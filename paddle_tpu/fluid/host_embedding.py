"""Host-offloaded sharded embedding tables (massive-sparse capability).

Capability parity: reference `framework/fleet/fleet_wrapper.h:59-137`
(PullSparseVarsSync / PushSparseVarsWithLabelAsync against the external
pslib parameter server) driven by `framework/downpour_worker.cc` — tables
larger than device memory live outside the accelerator; each step pulls
only the touched rows and pushes their gradients.

TPU-first redesign: the table lives in HOST RAM as a numpy array, row-
sharded across processes (row r belongs to process r % nproc — the DCN
shard layout).  Per step:

  1. pull  — np.unique over the batch's ids, gather those rows from the
             host shards, pad to a power-of-two bucket (bounded recompiles),
             feed as a small dense `W@PULLED` [P, D] device array;
  2. compute — the graph's lookup_table gathers from the PULLED table with
             batch-local remapped ids; the backward produces a dense
             [P, D] gradient (P is tiny vs the table);
  3. push  — the host applies the optimizer update (sgd / adagrad, state
             also host-resident) to exactly the touched rows.

The device never sees more than the touched rows — the table can exceed
HBM by orders of magnitude.  `layers.embedding(..., is_distributed=True)`
builds this path automatically.

Three engines drive the cycle (recsys-scale online learning, SURVEY
§2.1/§2.3 — the DownpourWorker FillSparseValue -> train -> push_sparse
overlap):

* `HostEmbeddingSession` — the synchronous reference path (blocking
  pull -> device step -> blocking push), the parity oracle;
* `PipelinedHostEmbeddingSession` — a background host worker prefetches
  batch t+1's rows and applies batch t-1's push WHILE the device
  computes batch t (double-buffered).  Exactness is preserved: FIFO
  ordering means the prefetched pull can miss at most the immediately-
  preceding push, so rows touched twice in flight (uniq(t) ∩
  uniq(t-1)) are detected and re-gathered after that push lands — a
  barrier for only the conflicting rows, bit-identical to the
  synchronous path (``exact=False`` trades that patch for bounded
  one-step staleness on the conflicting rows);
* `HotRowCache` — an HBM-resident LFU cache of the hottest rows with
  batch-local remap: cache hits skip the host exchange entirely,
  evicted dirty rows write back to the host shard, `flush()` runs
  before every checkpoint snapshot.

Multi-process exchange is owner-partitioned request/response (traffic
∝ unique pulled rows, not nproc²·P): round 1 all-gathers the id
requests, round 2 each owner publishes one deduped response row per
unique owned request; every rank derives each owner's response ordering
locally from round 1, so no index traffic moves.  Duplicate gradients
merge through one flattened `np.bincount` pass.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..observability import locks as _locks

__all__ = [
    "HostEmbedding",
    "HostEmbeddingSession",
    "PipelinedHostEmbeddingSession",
    "HotRowCache",
    "HostEmbeddingStats",
]


def _bucket(n):
    """Next power of two >= n (>=8): bounds the distinct PULLED shapes."""
    b = 8
    while b < n:
        b *= 2
    return b


def _global_bucket(n):
    """Bucket size agreed across ALL processes: allgather each rank's
    count and bucket the max, so every rank pads its exchange buffers to
    the same shape (process_allgather requires identical per-process
    shapes; ranks with uneven batches would otherwise hang)."""
    import jax

    if jax.process_count() == 1:
        return _bucket(n)
    from jax.experimental import multihost_utils

    counts = np.asarray(multihost_utils.process_allgather(
        np.asarray([n], np.int64)))
    return _bucket(int(counts.max()))


def _npz_path(path):
    """`np.savez` silently appends ``.npz`` when the path lacks it; every
    save/load site routes through this one helper so the writer and the
    reader always agree on the real filename."""
    p = str(path)
    return p if p.endswith(".npz") else p + ".npz"


def _bincount_merge(pos, grads, n_rows, dim):
    """Sum duplicate gradient rows: `pos` maps each grad row to its
    merged row index; one flattened `np.bincount` pass does the whole
    [N, D] scatter-add.  Accumulation is float64 inside bincount, cast
    back to f32 — deterministic regardless of duplicate order."""
    pos = np.asarray(pos, np.int64)
    idx = (pos[:, None] * dim + np.arange(dim, dtype=np.int64)[None, :])
    return np.bincount(
        idx.ravel(), weights=np.asarray(grads, np.float64).ravel(),
        minlength=int(n_rows) * dim).reshape(
            int(n_rows), dim).astype(np.float32)


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

_LBL = ("table",)


class HostEmbeddingStats:
    """Always-on labeled metrics for one host table: PR-4 registry
    families with ``table=<instance>`` label children (the
    io.stats.PipelineStats pattern — every table is visible at /metrics
    while each instance keeps independent series)."""

    def __init__(self, name, registry=None):
        from ..observability.metrics import (default_registry,
                                             unique_instance_label)

        reg = registry or default_registry()
        self.registry = reg
        self.instance_label = unique_instance_label(name)
        lab = (self.instance_label,)
        self.pull_ms = reg.histogram(
            "hostemb_pull_ms", "Host-embedding pull wall time (ms)",
            labelnames=_LBL).labels(*lab)
        self.push_ms = reg.histogram(
            "hostemb_push_ms", "Host-embedding push wall time (ms)",
            labelnames=_LBL).labels(*lab)
        self.exchange_ms = reg.histogram(
            "hostemb_exchange_ms",
            "Host shard-exchange (gather/scatter) wall time (ms)",
            labelnames=_LBL).labels(*lab)
        self.exchange_bytes = reg.counter(
            "hostemb_exchange_bytes_total",
            "Bytes moved through the host row exchange (pull rows + "
            "pushed gradient rows + id traffic)",
            labelnames=_LBL).labels(*lab)
        self.unique_ratio = reg.histogram(
            "hostemb_unique_ratio",
            "Unique ids / batch ids per pull (low = heavy reuse)",
            labelnames=_LBL,
            buckets=(0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
        ).labels(*lab)
        self.cache_hits = reg.counter(
            "hostemb_cache_hits_total",
            "Pulled rows served by the hot-row device cache",
            labelnames=_LBL).labels(*lab)
        self.cache_misses = reg.counter(
            "hostemb_cache_misses_total",
            "Pulled rows that went through the host exchange",
            labelnames=_LBL).labels(*lab)
        self.cache_hit_rate = reg.gauge(
            "hostemb_cache_hit_rate",
            "Lifetime hit fraction of the hot-row cache",
            labelnames=_LBL).labels(*lab)
        self.cache_staleness = reg.histogram(
            "hostemb_cache_staleness_steps",
            "Steps since a hit row was last touched (refresh age)",
            labelnames=_LBL,
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, float("inf"))
        ).labels(*lab)
        self.pipeline_conflicts = reg.counter(
            "hostemb_pipeline_conflicts_total",
            "Pipelined steps that re-gathered conflicting rows (uniq "
            "overlap with the in-flight push)",
            labelnames=_LBL).labels(*lab)

    def close(self):
        from ..observability.metrics import release_instance_label

        try:
            release_instance_label(self.instance_label)
        except Exception:
            pass


def _trace_span(name, **args):
    """A hostemb trace span on the PR-6 tracer; the disabled path is the
    tracer's shared no-op context (step_timer's lazy-import idiom)."""
    from ..observability import trace as _trace

    return _trace.default_tracer().span(name, cat="hostemb",
                                        args=args or None)


# ---------------------------------------------------------------------------
# hot-row device cache
# ---------------------------------------------------------------------------


class HotRowCache:
    """HBM-resident LFU cache of the hottest rows of one table.

    Cached rows live authoritatively in the cache (the host shard is
    STALE for them) — hits skip the host exchange entirely; the pulled
    buffer is assembled ON DEVICE from the resident [C+1, D] cache
    array plus a host buffer carrying only the miss rows, through one
    shape-stable gather+where (compile count bounded by the pull-bucket
    ladder, never by the hit pattern).  Updates land in the host mirror
    and the device copy is refreshed lazily as one [C+1, D] upload on
    the next assemble (TODO: scatter-refresh on TPU once pallas
    dynamic-update-slice is wired — the full refresh is the CPU-smoke
    trade).  Evicted rows write back to the host shard; `flush()`
    writes everything back (checkpoint snapshots call it).

    Single-process only: per-rank caches of peer-owned rows would need
    a coherence protocol the exchange does not speak yet.

    ``device_resident=None`` (default) keeps the [C+1, D] values array
    in device memory only on a real accelerator; on the CPU backend
    "device" and host are the same silicon, so hits are assembled from
    the host mirror directly (identical values, none of the fake-
    device dispatch overhead — the CPU-smoke measurement then isolates
    the exchange savings, which is what the cache is for).
    """

    def __init__(self, table, capacity, device_resident=None):
        if table.nproc > 1:
            raise ValueError(
                "HotRowCache requires a single-process table: per-rank "
                "caches of peer-owned rows would serve stale values "
                "without cross-rank invalidation")
        if device_resident is None:
            import jax

            device_resident = jax.default_backend() != "cpu"
        self.device_resident = bool(device_resident)
        self.table = table
        self.capacity = max(int(capacity), 1)
        # cross-lane coherence: the pull lane owns index mutation
        # (insert/evict, serial with itself), the push lane reads the
        # index and writes values — the lock keeps index+value reads
        # consistent and makes eviction write-back atomic vs peeks
        self.lock = _locks.named_rlock("host_embedding.table")
        C, D = self.capacity, table.dim
        self._ids = np.full(C, -1, np.int64)          # -1 = empty slot
        self._freq = np.zeros(C, np.int64)
        self._host = np.zeros((C + 1, D), table.dtype)  # [C]=zero sentinel
        # sorted-id index (vectorized lookups: a per-id python dict walk
        # costs more than the exchange it saves at recsys batch sizes)
        self._sorted_ids = np.zeros(0, np.int64)
        self._sorted_slots = np.zeros(0, np.int64)
        self._last_touch = np.zeros(C, np.int64)
        self._dev = None                              # lazy [C+1, D]
        self._dirty_dev = True
        self._step = 0
        self.hits = 0
        self.misses = 0

    # -- device copy -----------------------------------------------------
    def _device_values(self):
        import jax.numpy as jnp

        if self._dev is None or self._dirty_dev:
            self._dev = jnp.asarray(self._host)
            self._dirty_dev = False
        return self._dev

    @property
    def hit_rate(self):
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def _reindex(self):
        live = np.flatnonzero(self._ids >= 0)
        order = np.argsort(self._ids[live], kind="stable")
        self._sorted_ids = self._ids[live][order]
        self._sorted_slots = live[order]

    def _lookup(self, uniq):
        uniq = np.asarray(uniq, np.int64)
        pos = np.searchsorted(self._sorted_ids, uniq)
        pos_c = np.minimum(pos, max(len(self._sorted_ids) - 1, 0))
        hit = ((pos < len(self._sorted_ids))
               & (self._sorted_ids[pos_c] == uniq)
               if len(self._sorted_ids)
               else np.zeros(len(uniq), bool))
        slots = np.where(hit, self._sorted_slots[pos_c]
                         if len(self._sorted_slots)
                         else -1, -1)
        return hit, slots

    def _evict_for(self, need, protect):
        """Free `need` slots, preferring empty then lowest-freq slots
        not in `protect` (the current batch); evicted rows write back
        to the host shard.  Returns the freed slot indices."""
        empty = np.flatnonzero(self._ids < 0)
        if len(empty) >= need:
            return empty[:need]
        eligible = np.flatnonzero(
            (self._ids >= 0)
            & ~np.isin(self._ids, protect, assume_unique=False))
        order = eligible[np.argsort(self._freq[eligible], kind="stable")]
        victims = order[: need - len(empty)]
        if len(victims):
            vids = self._ids[victims]
            self.table._writeback_rows(vids, self._host[victims])
            self._ids[victims] = -1
            self._freq[victims] = 0
        return np.concatenate([empty, victims])

    def assemble(self, uniq, P, stats=None):
        """Pulled [P, D] device buffer for sorted-unique `uniq`: hits
        read the resident cache rows, misses go through the table's
        host exchange and are inserted (LFU eviction).  Pull-lane
        only (index mutation is single-threaded); the exchange runs
        OUTSIDE the lock so a concurrent push never waits on wire
        time."""
        import jax.numpy as jnp

        D = self.table.dim
        with self.lock:
            self._step += 1
            hit, slots = self._lookup(uniq)
            n_hit = int(hit.sum())
            if stats is not None and n_hit:
                stats.cache_hits.inc(n_hit)
                ages = self._step - self._last_touch[slots[hit]]
                stats.cache_staleness.observe(float(ages.mean()))
        n_miss = len(uniq) - n_hit
        if stats is not None and n_miss:
            stats.cache_misses.inc(n_miss)
        self.hits += n_hit
        self.misses += n_miss
        host_buf = np.zeros((P, D), self.table.dtype)
        if n_miss:
            miss_ids = uniq[~hit]
            rows = self.table._fetch_rows(miss_ids)
            host_buf[np.flatnonzero(~hit)] = rows
        with self.lock:
            if n_miss:
                # insert the misses so the NEXT pull of these rows
                # hits: at most `capacity` of them (a giant cold batch
                # cannot thrash the whole cache through itself), and
                # only as many as eviction could actually free (slots
                # holding rows of THIS batch are protected)
                freed = self._evict_for(min(n_miss, self.capacity),
                                        uniq)
                take = len(freed)
                ins_ids = miss_ids[:take]
                self._ids[freed] = ins_ids
                self._freq[freed] = 0
                # re-read the shard UNDER the lock: a concurrent push
                # may have updated these rows after the fetch above,
                # and the cache copy becomes authoritative on insert
                self._host[freed] = self.table._rows[
                    ins_ids // self.table.nproc]
                self._last_touch[freed] = self._step
                self._reindex()
                self._dirty_dev = True
                # the inserted rows now live in the cache; re-resolve
                # so they are served like any other hit
                hit, slots = self._lookup(uniq)
            self._freq[slots[hit]] += 1
            self._last_touch[slots[hit]] = self._step
            # sel[j] = cache slot of uniq[j], or C (zero sentinel) for
            # rows still outside the cache / padding
            sel = np.full(P, self.capacity, np.int64)
            sel[: len(uniq)][hit] = slots[hit]
            if self.device_resident:
                dev = self._device_values()
                sel_d = jnp.asarray(sel)
                pulled = jnp.where((sel_d < self.capacity)[:, None],
                                   jnp.take(dev, sel_d, axis=0),
                                   jnp.asarray(host_buf))
            else:
                # CPU-smoke assembly: hits read the host mirror in
                # place (same values the device array would carry)
                pulled = host_buf
                cached_pos = np.flatnonzero(sel < self.capacity)
                if cached_pos.size:
                    pulled[cached_pos] = self._host[sel[cached_pos]]
        if stats is not None:
            stats.cache_hit_rate.set(self.hit_rate)
        return pulled

    # -- update/write-back ----------------------------------------------
    def cached_mask(self, ids):
        with self.lock:
            mask, _ = self._lookup(np.asarray(ids, np.int64))
        return mask

    def read_rows(self, ids):
        with self.lock:
            _mask, slots = self._lookup(np.asarray(ids, np.int64))
            return self._host[slots]    # caller guarantees all cached

    def update_rows(self, ids, values):
        with self.lock:
            _mask, slots = self._lookup(np.asarray(ids, np.int64))
            self._host[slots] = values
            self._last_touch[slots] = self._step
            self._dirty_dev = True

    def flush(self):
        """Write every cached row back to the host shard (rows stay
        cached and become clean — `table._rows` equals the mirror)."""
        with self.lock:
            live = np.flatnonzero(self._ids >= 0)
            if len(live):
                self.table._writeback_rows(self._ids[live],
                                           self._host[live])

    def metrics(self):
        return {"capacity": self.capacity, "hits": self.hits,
                "misses": self.misses, "hit_rate": self.hit_rate,
                "resident": int((self._ids >= 0).sum())}


# ---------------------------------------------------------------------------
# the table
# ---------------------------------------------------------------------------


class HostEmbedding:
    """One host-resident row-sharded table + its optimizer state."""

    def __init__(self, name, num_rows, dim, dtype="float32",
                 optimizer="adagrad", lr=0.05, init_scale=0.01, seed=0,
                 epsilon=1e-6, padding_idx=None, transport_latency_s=0.0,
                 transport_bw_bytes_s=None):
        import jax

        self.name = name
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.epsilon = float(epsilon)
        self.nproc = jax.process_count()
        self.rank = jax.process_index()
        # single-process drills/benches can model the DCN pull/push RPC
        # of a real multi-host exchange: a flat per-exchange round-trip
        # latency plus a bytes/bandwidth term (GIL-released sleep, so a
        # pipelined worker genuinely overlaps it).  Cache hits never pay
        # either — they never exchange.
        self.transport_latency_s = float(transport_latency_s)
        self.transport_bw_bytes_s = (float(transport_bw_bytes_s)
                                     if transport_bw_bytes_s else None)
        # padding row: always reads zeros, never updates (reference
        # lookup_table padding_idx semantics carried into the host table)
        self.padding_idx = (None if padding_idx is None
                            else int(padding_idx) % self.num_rows)
        # owned rows: r with r % nproc == rank, stored compactly at r//nproc
        n_owned = (self.num_rows - self.rank + self.nproc - 1) // self.nproc
        rs = np.random.RandomState(seed + self.rank)
        self._rows = (init_scale * rs.randn(n_owned, self.dim)).astype(
            self.dtype)
        if optimizer == "adagrad":
            self._accum = np.zeros((n_owned, self.dim), np.float32)
        elif optimizer != "sgd":
            raise ValueError("host optimizer must be sgd or adagrad")
        self.cache = None
        self.stats = None
        # global ids whose rows changed since the last delta checkpoint
        # (streaming.DeltaCheckpointer drains this via collect_touched).
        # Tracking is OPT-IN: without a consumer draining the set, a
        # long trainer would accumulate one id array per push forever
        self.track_touched = False
        self._touched_chunks = []

    # -- observability ---------------------------------------------------
    def enable_stats(self, registry=None):
        """Attach (or re-attach) the PR-4 metric families."""
        if self.stats is not None:
            self.stats.close()
        self.stats = HostEmbeddingStats(self.name, registry=registry)
        return self.stats

    def attach_cache(self, capacity):
        """Attach an LFU hot-row device cache (single-process only)."""
        self.cache = HotRowCache(self, capacity)
        return self.cache

    def flush_cache(self):
        """Write cached rows back to the host shard; checkpoint
        snapshots call this so `_rows` is always the full truth."""
        if self.cache is not None:
            self.cache.flush()

    def _note_touched(self, uniq):
        if not self.track_touched:
            return
        self._touched_chunks.append(np.asarray(uniq, np.int64).copy())
        if len(self._touched_chunks) > 64:
            # compact: memory stays O(unique touched), not O(steps)
            self._touched_chunks = [
                np.unique(np.concatenate(self._touched_chunks))]

    def collect_touched(self, reset=True):
        """Sorted unique global row ids pushed since the last collect."""
        if not self._touched_chunks:
            return np.zeros(0, np.int64)
        out = np.unique(np.concatenate(self._touched_chunks))
        if reset:
            self._touched_chunks = []
        return out

    def _simulate_transport(self, nbytes=0):
        if self.nproc != 1:
            return
        wait = self.transport_latency_s
        if self.transport_bw_bytes_s:
            wait += nbytes / self.transport_bw_bytes_s
        if wait > 0:
            time.sleep(wait)

    def _validate_ids(self, ids, what):
        if ids.size and (ids.min() < 0 or ids.max() >= self.num_rows):
            raise IndexError(
                "embedding id out of range [0, %d) in %s of %s"
                % (self.num_rows, what, self.name))

    # -- sharded row access ---------------------------------------------
    @staticmethod
    def _owner_requests(all_req, nproc):
        """Each owner's deduped sorted response id list, derived
        identically on every rank from the round-1 request gather."""
        flat = all_req.reshape(-1)
        valid = flat[flat >= 0]
        return [np.unique(valid[valid % nproc == r]) for r in range(nproc)]

    def _exchange_pull(self, uniq):
        """Owner-partitioned pull (sorted unique ids -> [len, D]).

        Round 1 all-gathers the (padded) id requests — bytes ∝
        requested ids.  Round 2: each owner answers one row per unique
        owned requested id, padded to the bucketed max owner load —
        bytes ∝ unique pulled rows.  The old exchange all-gathered a
        [nproc, nproc·P, D] answers-for-everyone matrix (O(nproc²·P·D));
        this one derives every owner's response ordering locally, so
        only the rows themselves move."""
        from jax.experimental import multihost_utils

        P = _global_bucket(len(uniq))
        req = np.full((P,), -1, np.int64)
        req[: len(uniq)] = uniq
        all_req = np.asarray(multihost_utils.process_allgather(req))
        per_owner = self._owner_requests(all_req, self.nproc)
        Q = _bucket(max(max((len(x) for x in per_owner), default=1), 1))
        resp = np.zeros((Q, self.dim), self.dtype)
        mine = per_owner[self.rank]
        resp[: len(mine)] = self._rows[mine // self.nproc]
        all_resp = np.asarray(multihost_utils.process_allgather(resp))
        out = np.empty((len(uniq), self.dim), self.dtype)
        owners = uniq % self.nproc
        for r in range(self.nproc):
            sel = owners == r
            if sel.any():
                pos = np.searchsorted(per_owner[r], uniq[sel])
                out[sel] = all_resp[r][pos]
        if self.stats is not None:
            self.stats.exchange_bytes.inc(
                self.nproc * (P * 8 + Q * self.dim * self.dtype.itemsize))
        return out

    def _fetch_rows(self, uniq):
        """Current values of sorted-unique global ids from the host
        shards (the exchange path — the part a cache hit skips).  Does
        NOT consult the cache: callers route cached ids elsewhere."""
        t0 = time.perf_counter()
        self._simulate_transport(
            int(uniq.size) * (8 + self.dim * self.dtype.itemsize))
        if self.nproc == 1:
            rows = self._rows[uniq]
            if self.stats is not None:
                self.stats.exchange_bytes.inc(
                    int(uniq.size) * (8 + self.dim * self.dtype.itemsize))
        else:
            rows = self._exchange_pull(uniq)
        if self.stats is not None:
            self.stats.exchange_ms.observe(
                (time.perf_counter() - t0) * 1e3)
        return rows

    def _peek_rows(self, uniq, simulate_transport=True):
        """Current values honoring the cache: cached rows read the
        mirror, the rest the shard.  The pipelined conflict re-gather
        passes ``simulate_transport=False``: the rows it refetches are
        exactly the ones THIS rank just pushed, and a real owner-
        partitioned push RPC returns the updated values in its response
        (push-and-refetch) — no extra round trip to model."""
        uniq = np.asarray(uniq, np.int64)
        if self.cache is None:
            if self.nproc == 1 and not simulate_transport:
                return self._rows[uniq]      # advanced indexing: a copy
            return self._fetch_rows(uniq)
        with self.cache.lock:
            mask = self.cache.cached_mask(uniq)
            out = np.empty((len(uniq), self.dim), self.dtype)
            if mask.any():
                out[mask] = self.cache.read_rows(uniq[mask])
            if (~mask).any():
                miss = uniq[~mask]
                out[~mask] = (self._rows[miss]
                              if self.nproc == 1
                              and not simulate_transport
                              else self._fetch_rows(miss))
        return out

    def _writeback_rows(self, ids, values):
        """Scatter evicted/flushed cache rows into the owned shard."""
        ids = np.asarray(ids, np.int64)
        own = ids % self.nproc == self.rank
        self._rows[ids[own] // self.nproc] = values[own]

    # -- step API --------------------------------------------------------
    def pull(self, ids):
        """ids: int array [...] -> (pulled [P, D], local_ids like ids,
        uniq).  local_ids index into pulled.  `pulled` is a numpy array
        on the plain path and a device-resident jax array when a
        HotRowCache is attached (Executor feeds both without copies)."""
        t0 = time.perf_counter()
        ids = np.asarray(ids)
        uniq, inv = np.unique(ids, return_inverse=True)
        self._validate_ids(uniq, "pull")
        P = _bucket(max(len(uniq), 1))
        with _trace_span("hostemb.pull", table=self.name,
                         uniq=int(uniq.size), bucket=P):
            if self.cache is not None:
                pulled = self.cache.assemble(uniq, P, stats=self.stats)
                if uniq.size and self.padding_idx is not None:
                    pad = np.flatnonzero(uniq == self.padding_idx)
                    if pad.size:
                        if isinstance(pulled, np.ndarray):
                            pulled[pad] = 0
                        else:
                            import jax.numpy as jnp

                            pulled = pulled.at[pad].set(jnp.zeros(
                                (pad.size, self.dim), pulled.dtype))
            else:
                pulled = np.zeros((P, self.dim), self.dtype)
                if uniq.size or self.nproc > 1:
                    # nproc>1: join the exchange even with zero local
                    # ids — peers are blocked in the same collective and
                    # a rank that skipped it would hang them
                    rows = self._fetch_rows(uniq)
                    if uniq.size:
                        pulled[: len(uniq)] = rows
                        if self.padding_idx is not None:
                            pulled[: len(uniq)][uniq == self.padding_idx] = 0
        if self.stats is not None:
            self.stats.pull_ms.observe((time.perf_counter() - t0) * 1e3)
            if ids.size:
                self.stats.unique_ratio.observe(uniq.size / ids.size)
        return pulled, inv.reshape(ids.shape).astype(np.int64), uniq

    def _exchange_push(self, uniq, g):
        """Owner-partitioned gradient scatter: all-gather (id, grad)
        rows — bytes ∝ pushed rows — then each owner keeps its own and
        merges duplicates with one bincount pass."""
        from jax.experimental import multihost_utils

        P = _global_bucket(len(uniq))
        req = np.full((P,), -1, np.int64)
        req[: len(uniq)] = uniq
        gpad = np.zeros((P, self.dim), np.float32)
        gpad[: len(uniq)] = g
        all_req = np.asarray(multihost_utils.process_allgather(req))
        all_g = np.asarray(multihost_utils.process_allgather(gpad))
        flat = all_req.reshape(-1)
        flatg = all_g.reshape(-1, self.dim)
        mine = (flat >= 0) & (flat % self.nproc == self.rank)
        ids_mine, g_mine = flat[mine], flatg[mine]
        merged_ids = np.unique(ids_mine)
        pos = np.searchsorted(merged_ids, ids_mine)
        merged = _bincount_merge(pos, g_mine, len(merged_ids), self.dim)
        if self.stats is not None:
            self.stats.exchange_bytes.inc(
                self.nproc * P * (8 + self.dim * 4))
        return merged_ids, merged

    def push(self, uniq, grad_rows, lr=None):
        """Apply the host-side optimizer to the touched rows.  grad_rows:
        [len(uniq), D] dense gradient for the pulled rows."""
        t0 = time.perf_counter()
        lr = self.lr if lr is None else float(lr)
        uniq = np.asarray(uniq, np.int64)
        self._validate_ids(uniq, "push")
        g = np.asarray(grad_rows, np.float32)[: len(uniq)]
        with _trace_span("hostemb.push", table=self.name,
                         uniq=int(uniq.size)):
            self._push_impl(uniq, g, lr)
        if self.stats is not None:
            self.stats.push_ms.observe((time.perf_counter() - t0) * 1e3)

    def _push_impl(self, uniq, g, lr):
        own = uniq % self.nproc == self.rank
        cache = self.cache
        if self.nproc > 1:
            t0 = time.perf_counter()
            uniq, g = self._exchange_push(uniq, g)
            if self.stats is not None:
                self.stats.exchange_ms.observe(
                    (time.perf_counter() - t0) * 1e3)
            own = np.ones(len(uniq), bool)
        else:
            # only UNCACHED rows cross the modeled link: cached rows
            # are authoritative in the cache (write-back on eviction)
            cached_all = (cache.cached_mask(uniq)
                          if cache is not None else None)
            n_wire = int(uniq.size if cached_all is None
                         else (~cached_all).sum())
            if n_wire:
                self._simulate_transport(n_wire * (8 + self.dim * 4))
        if self.padding_idx is not None:
            own = own & (uniq != self.padding_idx)
        ids = uniq[own]
        local = ids // self.nproc
        gl = g[own]
        self._note_touched(ids)
        if cache is not None:
            # the authoritative mask is re-read INSIDE the lock and the
            # whole read-modify-write holds it: a concurrent pull-lane
            # insert/evict between mask and write would otherwise strand
            # this update in a dead slot (the wire-billing mask above
            # may legitimately be a step stale; this one may not be)
            with cache.lock:
                self._apply_update(ids, local, gl, lr,
                                   cache.cached_mask(ids), cache)
        else:
            self._apply_update(ids, local, gl, lr,
                               np.zeros(len(ids), bool), None)

    def _apply_update(self, ids, local, gl, lr, cached, cache):
        # current values: cached rows read the (authoritative) mirror,
        # the rest the shard — the update math is identical either way,
        # so cache on/off stays bit-identical
        cur = np.empty((len(ids), self.dim), self.dtype)
        if cached.any():
            cur[cached] = cache.read_rows(ids[cached])
        if (~cached).any():
            cur[~cached] = self._rows[local[~cached]]
        if self.optimizer == "adagrad":
            self._accum[local] += gl * gl
            new = cur - (lr * gl / (np.sqrt(self._accum[local])
                                    + self.epsilon)).astype(self.dtype)
        else:  # sgd
            new = cur - (lr * gl).astype(self.dtype)
        if cached.any():
            cache.update_rows(ids[cached], new[cached])
        if (~cached).any():
            self._rows[local[~cached]] = new[~cached]

    # -- persistence (fleet SaveModel capability) ------------------------
    def save(self, path):
        self.flush_cache()
        np.savez(_npz_path(path), rows=self._rows,
                 accum=getattr(self, "_accum", np.zeros(0)),
                 meta=np.asarray([self.num_rows, self.dim, self.rank,
                                  self.nproc]))

    def load(self, path):
        d = np.load(_npz_path(path))
        meta = d["meta"] if "meta" in d.files else None
        if meta is not None and int(meta[3]) != self.nproc:
            raise ValueError(
                "host-embedding shard %r was saved with nproc=%d but this "
                "table runs with nproc=%d — the row layout differs; load "
                "all old shards through load_resharded (or the elastic "
                "restore path in HostEmbeddingCheckpoint)"
                % (str(path), int(meta[3]), self.nproc))
        self._rows = d["rows"]
        if self.optimizer == "adagrad" and d["accum"].size:
            self._accum = d["accum"]
        self._drop_cache_values()

    def _drop_cache_values(self):
        """After a load/restore the shard is the truth; a live cache
        would serve pre-restore values, so re-seed it empty."""
        if self.cache is not None:
            self.cache = HotRowCache(
                self, self.cache.capacity,
                device_resident=self.cache.device_resident)

    def load_resharded(self, shard_paths):
        """Elastic restore: rebuild THIS rank's rows from the complete
        set of shards saved by an old group of any size.  `shard_paths`:
        {old_rank: path} covering every old rank."""
        from ..distributed.elastic.reshard import reshard_host_embedding_rows

        shards = {}
        old_nranks = None
        for old_rank, p in shard_paths.items():
            d = np.load(_npz_path(p))
            shards[int(old_rank)] = (d["rows"], d["accum"])
            if "meta" in d.files:
                saved = int(d["meta"][3])
                if old_nranks not in (None, saved):
                    raise ValueError(
                        "host-embedding shards disagree on the save-time "
                        "nproc (%d vs %d) — they are not from one commit"
                        % (old_nranks, saved))
                old_nranks = saved
        rows, accum = reshard_host_embedding_rows(
            shards, self.rank, self.nproc, old_nranks=old_nranks)
        if rows.shape[0] != self._rows.shape[0]:
            raise ValueError(
                "resharded row count %d does not match this table's owned "
                "rows %d (num_rows=%d nproc=%d rank=%d)"
                % (rows.shape[0], self._rows.shape[0], self.num_rows,
                   self.nproc, self.rank))
        self._rows = rows.astype(self.dtype, copy=False)
        if self.optimizer == "adagrad" and accum.size:
            self._accum = accum.astype(np.float32, copy=False)
        self._drop_cache_values()

    def export_rows(self):
        """The FULL [num_rows, D] table (all shards), for materializing
        a dense serving copy of a small/test table or an export slice.
        Production push-to-serving ships delta rows to an embedding
        service instead — this is the drill/bench-scale path."""
        self.flush_cache()
        if self.nproc == 1:
            return self._rows.copy()
        from jax.experimental import multihost_utils

        n_max = (self.num_rows + self.nproc - 1) // self.nproc
        pad = np.zeros((n_max, self.dim), self.dtype)
        pad[: self._rows.shape[0]] = self._rows
        shards = np.asarray(multihost_utils.process_allgather(pad))
        full = np.zeros((self.num_rows, self.dim), self.dtype)
        for r in range(self.nproc):
            n_r = (self.num_rows - r + self.nproc - 1) // self.nproc
            full[r::self.nproc] = shards[r][:n_r]
        return full

    # -- delta persistence (streaming online learning) -------------------
    def _read_owned_rows(self, own):
        """Current values of OWNED ids, honoring the cache mirror — a
        pure local read: no exchange, no simulated transport, no
        exchange metrics (this is a checkpoint read, not a pull)."""
        rows = self._rows[own // self.nproc]     # advanced indexing: copy
        if self.cache is not None and own.size:
            with self.cache.lock:
                mask = self.cache.cached_mask(own)
                if mask.any():
                    rows[mask] = self.cache.read_rows(own[mask])
        return rows

    def delta_payload(self, touched=None):
        """(own_ids, rows, accum, meta) for the touched rows — the one
        delta format both `save_delta` and the streaming
        DeltaCheckpointer serialize."""
        ids = (np.asarray(touched, np.int64) if touched is not None
               else self.collect_touched(reset=False))
        own = ids[ids % self.nproc == self.rank]
        vals = (self._read_owned_rows(own) if own.size
                else np.zeros((0, self.dim), self.dtype))
        accum = (self._accum[own // self.nproc].copy()
                 if hasattr(self, "_accum") and own.size
                 else np.zeros((0, self.dim), np.float32))
        meta = np.asarray([self.num_rows, self.dim, self.rank,
                           self.nproc])
        return own, vals, accum, meta

    def apply_delta_arrays(self, ids, rows, accum, saved_nproc=None):
        """Replay one delta payload: scatter its rows into the shard.
        Validates the save-time layout — deltas do not reshard."""
        if saved_nproc is not None and int(saved_nproc) != self.nproc:
            raise ValueError(
                "delta for table %r was saved with nproc=%d but this "
                "run has nproc=%d — deltas do not reshard; restart "
                "from the chain's full snapshot on the old topology"
                % (self.name, int(saved_nproc), self.nproc))
        ids = np.asarray(ids, np.int64)
        if ids.size:
            self._writeback_rows(ids, rows)
            if hasattr(self, "_accum") and accum.size:
                self._accum[ids // self.nproc] = accum
        self._drop_cache_values()
        return int(ids.size)

    def save_delta(self, path, touched=None):
        """Persist only the touched rows (ids + values + accum) —
        the streaming delta-checkpoint payload.  Returns the id count."""
        own, vals, accum, meta = self.delta_payload(touched)
        np.savez(_npz_path(path), ids=own, rows=vals, accum=accum,
                 meta=meta)
        return int(own.size)

    def apply_delta(self, path):
        """Replay one delta file saved by `save_delta`."""
        d = np.load(_npz_path(path))
        saved = d["meta"][3] if "meta" in d.files else None
        return self.apply_delta_arrays(d["ids"], d["rows"], d["accum"],
                                       saved_nproc=saved)


# ---------------------------------------------------------------------------
# sessions
# ---------------------------------------------------------------------------


class _HostEmbeddingSessionBase:
    """Shared wiring: locate the program's tables, materialize the
    pulled-buffer gradients once (the param backward sweep does not
    necessarily produce them: PULLED is a data var)."""

    def __init__(self, exe, program, loss=None):
        self._exe = exe
        self._program = program
        self._tables = getattr(program, "_host_embeddings", {})
        if not self._tables:
            raise ValueError(
                "program has no host embeddings; build one with "
                "layers.embedding(..., is_distributed=True)")
        self._grad_names = []
        if loss is not None:
            from . import framework
            from .backward import gradients

            block = program.global_block
            pulled_vars = [
                block.var(w + "@PULLED") for w in self._tables
            ]
            need = [
                v for v in pulled_vars
                if not block.has_var(v.name + framework.GRAD_SUFFIX)
            ]
            if need:
                with framework.program_guard(program):
                    gradients(loss, need)
            self._grad_names = [
                w + "@PULLED" + framework.GRAD_SUFFIX for w in self._tables
            ]

    def tables(self):
        return [t for t, _slot in self._tables.values()]

    def _pull_feed(self, feed):
        """(extra_feed, recs): pull every table for one batch."""
        extra = {}
        recs = []
        for wname, (table, ids_slot) in self._tables.items():
            pulled, local, uniq = table.pull(np.asarray(feed[ids_slot]))
            extra[wname + "@PULLED"] = pulled
            extra[ids_slot + "@LOCAL"] = local
            recs.append((table, uniq))
        return extra, recs

    def _push_grads(self, recs, grads, lr):
        for (table, uniq), g in zip(recs, grads):
            table.push(uniq, g, lr=lr)


class HostEmbeddingSession(_HostEmbeddingSessionBase):
    """Wraps Executor.run with the SYNCHRONOUS pull/compute/push cycle
    for every HostEmbedding registered on the program (DownpourWorker
    parity: `downpour_worker.cc` FillSparseValue -> train ->
    push_sparse).  The parity oracle for the pipelined engine."""

    def run(self, feed, fetch_list=None, lr=None, **kw):
        fetch_list = list(fetch_list or [])
        extra, recs = self._pull_feed(feed)
        outs = self._exe.run(
            self._program, feed={**feed, **extra},
            fetch_list=fetch_list + self._grad_names, **kw)
        n = len(fetch_list)
        self._push_grads(recs, outs[n:], lr)
        return outs[:n]


class _WorkerOp:
    __slots__ = ("kind", "payload", "result", "error", "done",
                 "early", "early_result")

    def __init__(self, kind, payload):
        self.kind = kind
        self.payload = payload
        self.result = None
        self.error = None
        self.done = threading.Event()
        # push ops: set after the CONFLICT phase (the rows the next
        # step needs) with their post-push values in early_result —
        # the device step starts while the rest of the push drains
        self.early = None
        self.early_result = None

    def wait(self):
        self.done.wait()
        if self.error is not None:
            raise self.error
        return self.result

    def wait_early(self):
        self.early.wait()
        if self.error is not None:
            raise self.error
        return self.early_result


class _Lane:
    """One background op lane: a FIFO + worker thread.  Ops execute
    strictly in submission order WITHIN a lane; the two lanes (pull,
    push) run concurrently, so a prefetch's wire time overlaps a
    writeback's — the async pull/push worker pair of the reference's
    DownpourWorker, with exactness restored by the epoch protocol in
    `PipelinedHostEmbeddingSession`.

    An op error lands on ``op.error`` for any waiter AND on
    ``on_error`` — push ops usually have no waiter (only conflicting
    steps ever wait one), and a silently lost gradient push would let
    training sail on over a corrupt table."""

    def __init__(self, name, handler, on_error=None):
        self._handler = handler
        self._on_error = on_error
        self._ops = []
        self._cv = _locks.named_condition("host_embedding.worker")
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    def submit(self, op):
        with self._cv:
            self._ops.append(op)
            self._cv.notify()
        return op

    def _loop(self):
        while True:
            with self._cv:
                while not self._ops:
                    self._cv.wait()
                op = self._ops.pop(0)
            try:
                if op.kind == "stop":
                    op.result = True
                    return
                if op.kind == "drain":
                    op.result = True
                else:
                    self._handler(op)
            except BaseException as e:  # delivered to the waiter
                op.error = e
                if self._on_error is not None:
                    self._on_error(e)
            finally:
                if op.early is not None:
                    op.early.set()     # never leave a waiter hanging
                op.done.set()


class PipelinedHostEmbeddingSession(_HostEmbeddingSessionBase):
    """Async pull-prefetch / push-writeback around the device step.

    TWO background lanes (the reference DownpourWorker's async
    pull/push pair): the PULL lane prefetches batch t+1's rows while
    the device computes batch t, and the PUSH lane applies batch t-1's
    gradients — pull wire time, push wire time and device compute all
    overlap.

    Exactness is an epoch protocol, not queue order: every pull op
    records how many pushes were FULLY APPLIED when its gather started
    (its epoch).  At step t, any push not provably applied before
    pull(t)'s gather is *suspect*; rows in `uniq(t) ∩ uniq(suspect)`
    are the only ones whose pulled values can be stale (or torn — a
    gather racing an update), and exactly those rows are re-patched
    before the device step:

    * the newest push (t-1, deferred-enqueued at step t's start once
      `uniq(t)` is known) runs CONFLICT-SPLIT — the push lane applies
      the conflicting rows first (their wire bytes + row updates
      only), hands their post-push values back through an early
      event (the push-and-refetch RPC response of a real
      owner-partitioned exchange), then drains the remainder while
      the device computes;
    * older suspect pushes (already enqueued, normally already done)
      are waited and their conflict rows re-read in place.

    With ``exact=True`` (default) the result is bit-identical to
    `HostEmbeddingSession` — the parity drill in
    tests/test_streaming.py proves it.  ``exact=False`` skips the
    patches: conflicting rows are served one step stale (bounded
    staleness, recsys-style).

    Rows outside every in-flight push's uniq set are never written
    concurrently, so their gathers are always clean; `HotRowCache`
    coherence across the two lanes rides the cache's internal lock.

    Single-process only: cross-host pipelining needs every rank to
    take the same conflict decisions, which requires a coordination
    the exchange does not carry yet.
    """

    def __init__(self, exe, program, loss=None, exact=True):
        super().__init__(exe, program, loss=loss)
        import jax

        if jax.process_count() > 1:
            raise ValueError(
                "PipelinedHostEmbeddingSession is single-process: the "
                "conflict barrier is a per-step local decision and "
                "ranks would diverge on it; use HostEmbeddingSession "
                "under multi-host launch")
        self.exact = bool(exact)
        self._next = None              # prefetched PULL op
        self._pending_push = None      # created, not yet enqueued
        self._push_log = []            # [(seq, {wname: uniq}, op)]
        self._push_seq = 0
        self._pushes_applied = 0       # advanced by the push lane
        self._closed = False
        self._async_error = None
        self._pull_lane = _Lane("hostemb-pull", self._handle_pull,
                                on_error=self._note_async_error)
        self._push_lane = _Lane("hostemb-push", self._handle_push,
                                on_error=self._note_async_error)

    def _note_async_error(self, e):
        self._async_error = e

    def _check_async_error(self):
        """Surface a background-lane failure at the NEXT session call:
        a push op usually has no waiter, and training past a lost
        gradient update would checkpoint a corrupt table."""
        e = self._async_error
        if e is not None:
            self._async_error = None
            raise RuntimeError(
                "a background host-embedding pull/push failed; the "
                "table state is not trustworthy past this step") from e

    # -- lane handlers ---------------------------------------------------
    def _handle_pull(self, op):
        # the epoch is sampled BEFORE the gather touches any row: a
        # push counted here is fully applied, anything later is the
        # caller's suspect set
        epoch = self._pushes_applied
        extra, recs = self._pull_feed(op.payload)
        op.result = (extra, recs, epoch)

    def _handle_push(self, op):
        """Apply one push, conflict subset first when the op carries
        one: the conflicting rows' updates land and their new values
        are handed back through ``early`` BEFORE the remainder's wire
        time — so the in-flight step serializes on only the rows it
        actually shares."""
        recs, grads, lr, conflicts = op.payload
        if conflicts:
            sels = {}
            early = {}
            for (table, uniq), g, wname in zip(recs, grads,
                                               self._tables):
                ids = conflicts.get(wname)
                if ids is None or not len(ids):
                    continue
                sel = np.isin(uniq, ids, assume_unique=True)
                sels[wname] = sel
                g_rows = np.asarray(g)[: len(uniq)]
                table.push(uniq[sel], g_rows[sel], lr=lr)
                early[wname] = table._peek_rows(
                    ids, simulate_transport=False)
            op.early_result = early
            op.early.set()
            for (table, uniq), g, wname in zip(recs, grads,
                                               self._tables):
                sel = sels.get(wname)
                if sel is None:
                    table.push(uniq, g, lr=lr)
                else:
                    g_rows = np.asarray(g)[: len(uniq)]
                    table.push(uniq[~sel], g_rows[~sel], lr=lr)
        else:
            self._push_grads(recs, grads, lr)
        self._pushes_applied += 1

    # -- submission ------------------------------------------------------
    def _submit_pull(self, feed):
        if self._closed:
            raise RuntimeError("session is closed")
        return self._pull_lane.submit(_WorkerOp("pull", feed))

    def _flush_pending(self, conflicts=None):
        """Enqueue the deferred push (conflict-split when `conflicts`
        — {wname: sorted ids} — is given)."""
        op = self._pending_push
        self._pending_push = None
        if op is None:
            return None
        if conflicts:
            op.payload = op.payload[:3] + (conflicts,)
        if self._closed:
            raise RuntimeError("session is closed")
        self._push_lane.submit(op)
        return op

    # -- API -------------------------------------------------------------
    def prefetch(self, feed):
        """Enqueue the pull for the NEXT batch (run() does this itself
        when given ``next_feed``/an iterator; explicit calls are for
        custom loops)."""
        if self._next is not None:
            raise RuntimeError("a prefetched batch is already pending")
        self._flush_pending()
        self._next = self._submit_pull(feed)

    def _patch_plan(self, recs, suspects):
        """{wname: (table, older_ids, newest_ids)} — the rows of this
        step's pull that a suspect push may have made stale.  Rows
        conflicting with BOTH an older suspect and the pending push
        land in newest_ids only: the pending push's early refetch
        reads after the push lane applied everything older (lane
        FIFO), so its values are already post-everything."""
        plan = {}
        for (table, uniq), wname in zip(recs, self._tables):
            if not len(uniq):
                continue
            older_parts = []
            newest = None
            for _seq, umap, op in suspects:
                pu = umap.get(wname)
                if pu is None or not len(pu):
                    continue
                c = np.intersect1d(uniq, pu, assume_unique=True)
                if not len(c):
                    continue
                if op is self._pending_push:
                    newest = c
                else:
                    older_parts.append(c)
            older = (np.unique(np.concatenate(older_parts))
                     if older_parts else None)
            if older is not None and newest is not None:
                older = np.setdiff1d(older, newest, assume_unique=True)
                if not len(older):
                    older = None
            if older is not None or newest is not None:
                plan[wname] = (table, older, newest)
        return plan

    def run(self, feed, fetch_list=None, lr=None, next_feed=None, **kw):
        """One pipelined step.  Pass ``next_feed`` (the t+1 batch) to
        start its pull before the device computes batch t; without it
        the step degrades to the synchronous order."""
        fetch_list = list(fetch_list or [])
        self._check_async_error()
        cur = self._next
        self._next = None
        if cur is not None and cur.payload is not feed:
            # stale prefetch: a caller loop that stopped early (e.g.
            # StreamingTrainer.run(max_steps=...)) left batch t+1's
            # pull queued, and this run() is for a DIFFERENT batch —
            # training on the prefetched rows would pair them with
            # this feed's labels.  Discard it (the gather had no side
            # effects) and pull fresh.
            try:
                cur.wait()
            except Exception:
                pass            # its batch will never train anyway
            cur = None
        if cur is None:
            self._flush_pending()
            cur = self._submit_pull(feed)
        extra, recs, epoch = cur.wait()
        if next_feed is not None:
            # overlaps everything below, including the conflict wait
            self._next = self._submit_pull(next_feed)
        self._push_log = [e for e in self._push_log if e[0] >= epoch]
        plan = (self._patch_plan(recs, self._push_log)
                if self.exact else {})
        newest_map = {w: n for w, (_t, _o, n) in plan.items()
                      if n is not None}
        pending_op = self._flush_pending(conflicts=newest_map or None)
        if plan:
            early_vals = None
            if pending_op is not None and newest_map:
                # implies every older suspect applied: the push lane
                # is FIFO and the pending op is its newest entry
                early_vals = pending_op.wait_early()
            else:
                for _seq, _u, op in self._push_log:
                    if op is not pending_op:
                        op.wait()
            uniq_by = {w: u for (t, u), w in zip(recs, self._tables)}
            for wname, (table, older, newest) in plan.items():
                # patch pulled buffers as host copies: an .at[].set
                # with a per-step-varying index shape would recompile
                # every step
                buf = extra[wname + "@PULLED"]
                if not isinstance(buf, np.ndarray):
                    buf = np.array(buf)   # device -> writable copy
                if older is not None:
                    buf[np.searchsorted(uniq_by[wname], older)] = \
                        table._peek_rows(older, simulate_transport=False)
                if newest is not None and early_vals is not None:
                    buf[np.searchsorted(uniq_by[wname], newest)] = \
                        early_vals[wname]
                extra[wname + "@PULLED"] = buf
                if table.stats is not None:
                    table.stats.pipeline_conflicts.inc()
        outs = self._exe.run(
            self._program, feed={**feed, **extra},
            fetch_list=fetch_list + self._grad_names, **kw)
        n = len(fetch_list)
        op = _WorkerOp("push", (recs, outs[n:], lr, None))
        op.early = threading.Event()
        self._pending_push = op
        self._push_log.append((self._push_seq, {
            wname: uniq for (t, uniq), wname in zip(recs, self._tables)
        }, op))
        self._push_seq += 1
        return outs[:n]

    def run_stream(self, feeds, fetch_list=None, lr=None, **kw):
        """Drive an iterable of feed dicts with automatic one-batch
        lookahead; yields each step's fetches."""
        it = iter(feeds)
        try:
            cur = next(it)
        except StopIteration:
            return
        while cur is not None:
            nxt = next(it, None)
            yield self.run(cur, fetch_list=fetch_list, lr=lr,
                           next_feed=nxt, **kw)
            cur = nxt

    def drain(self):
        """Block until every queued pull/push has been applied (call
        before reading table state — checkpoints, eval, parity)."""
        self._flush_pending()
        push_d = self._push_lane.submit(_WorkerOp("drain", None))
        pull_d = self._pull_lane.submit(_WorkerOp("drain", None))
        push_d.wait()
        pull_d.wait()
        self._check_async_error()

    def close(self):
        if self._closed:
            return
        self.drain()
        self._push_lane.submit(_WorkerOp("stop", None)).wait()
        self._pull_lane.submit(_WorkerOp("stop", None)).wait()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
