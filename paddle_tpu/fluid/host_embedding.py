"""Host-offloaded sharded embedding tables (massive-sparse capability).

Capability parity: reference `framework/fleet/fleet_wrapper.h:59-137`
(PullSparseVarsSync / PushSparseVarsWithLabelAsync against the external
pslib parameter server) driven by `framework/downpour_worker.cc` — tables
larger than device memory live outside the accelerator; each step pulls
only the touched rows and pushes their gradients.

TPU-first redesign: the table lives in HOST RAM as a numpy array, row-
sharded across processes (row r belongs to process r % nproc — the DCN
shard layout).  Per step:

  1. pull  — np.unique over the batch's ids, gather those rows from the
             host shards, pad to a power-of-two bucket (bounded recompiles),
             feed as a small dense `W@PULLED` [P, D] device array;
  2. compute — the graph's lookup_table gathers from the PULLED table with
             batch-local remapped ids; the backward produces a dense
             [P, D] gradient (P is tiny vs the table);
  3. push  — the host applies the optimizer update (sgd / adagrad, state
             also host-resident) to exactly the touched rows.

The device never sees more than the touched rows — the table can exceed
HBM by orders of magnitude.  `layers.embedding(..., is_distributed=True)`
builds this path automatically; drive steps through
:class:`HostEmbeddingSession`.
"""

from __future__ import annotations

import numpy as np


def _bucket(n):
    """Next power of two >= n (>=8): bounds the distinct PULLED shapes."""
    b = 8
    while b < n:
        b *= 2
    return b


def _global_bucket(n):
    """Bucket size agreed across ALL processes: allgather each rank's
    count and bucket the max, so every rank pads its exchange buffers to
    the same shape (process_allgather requires identical per-process
    shapes; ranks with uneven batches would otherwise hang)."""
    import jax

    if jax.process_count() == 1:
        return _bucket(n)
    from jax.experimental import multihost_utils

    counts = np.asarray(multihost_utils.process_allgather(
        np.asarray([n], np.int64)))
    return _bucket(int(counts.max()))


class HostEmbedding:
    """One host-resident row-sharded table + its optimizer state."""

    def __init__(self, name, num_rows, dim, dtype="float32",
                 optimizer="adagrad", lr=0.05, init_scale=0.01, seed=0,
                 epsilon=1e-6, padding_idx=None):
        import jax

        self.name = name
        self.num_rows = int(num_rows)
        self.dim = int(dim)
        self.dtype = np.dtype(dtype)
        self.optimizer = optimizer
        self.lr = float(lr)
        self.epsilon = float(epsilon)
        self.nproc = jax.process_count()
        self.rank = jax.process_index()
        # padding row: always reads zeros, never updates (reference
        # lookup_table padding_idx semantics carried into the host table)
        self.padding_idx = (None if padding_idx is None
                            else int(padding_idx) % self.num_rows)
        # owned rows: r with r % nproc == rank, stored compactly at r//nproc
        n_owned = (self.num_rows - self.rank + self.nproc - 1) // self.nproc
        rs = np.random.RandomState(seed + self.rank)
        self._rows = (init_scale * rs.randn(n_owned, self.dim)).astype(
            self.dtype)
        if optimizer == "adagrad":
            self._accum = np.zeros((n_owned, self.dim), np.float32)
        elif optimizer != "sgd":
            raise ValueError("host optimizer must be sgd or adagrad")

    # -- sharded row access ---------------------------------------------
    def _gather_rows(self, uniq):
        """uniq (sorted unique global row ids) -> [len(uniq), D].

        Multi-process: every process owns rows r % nproc == rank; the
        exchange all-gathers each rank's request and each rank's owned
        responses (traffic = total pulled rows — the pslib pull RPC
        without a transport layer)."""
        if self.nproc == 1:
            return self._rows[uniq]
        from jax.experimental import multihost_utils

        # 1 round: gather every rank's (padded) request list
        P = _global_bucket(len(uniq))
        req = np.full((P,), -1, np.int64)
        req[: len(uniq)] = uniq
        all_req = np.asarray(multihost_utils.process_allgather(req))
        # answer what we own, for all requests
        flat = all_req.reshape(-1)
        mine = (flat >= 0) & (flat % self.nproc == self.rank)
        ans = np.zeros((flat.shape[0], self.dim), self.dtype)
        ans[mine] = self._rows[flat[mine] // self.nproc]
        all_ans = np.asarray(multihost_utils.process_allgather(ans))
        # rows for MY request: sum over the responder axis (only the owner
        # wrote non-zero), slice my block
        summed = all_ans.sum(axis=0).reshape(all_req.shape + (self.dim,))
        return summed[self.rank][: len(uniq)]

    # -- step API --------------------------------------------------------
    def pull(self, ids):
        """ids: int array [...] -> (pulled [P, D], local_ids like ids,
        uniq).  local_ids index into pulled."""
        ids = np.asarray(ids)
        uniq, inv = np.unique(ids, return_inverse=True)
        if uniq.size and (uniq[0] < 0 or uniq[-1] >= self.num_rows):
            raise IndexError(
                "embedding id out of range [0, %d) in %s"
                % (self.num_rows, self.name))
        P = _bucket(max(len(uniq), 1))
        pulled = np.zeros((P, self.dim), self.dtype)
        if uniq.size or self.nproc > 1:
            # nproc>1: join the exchange even with zero local ids — peers
            # are blocked in the same collective and a rank that skipped
            # it would hang them
            rows = self._gather_rows(uniq)
            if uniq.size:
                pulled[: len(uniq)] = rows
                if self.padding_idx is not None:
                    pulled[: len(uniq)][uniq == self.padding_idx] = 0
        return pulled, inv.reshape(ids.shape).astype(np.int64), uniq

    def push(self, uniq, grad_rows, lr=None):
        """Apply the host-side optimizer to the touched rows.  grad_rows:
        [len(uniq), D] dense gradient for the pulled rows."""
        lr = self.lr if lr is None else float(lr)
        uniq = np.asarray(uniq)
        g = np.asarray(grad_rows, np.float32)[: len(uniq)]
        own = uniq % self.nproc == self.rank
        if self.nproc > 1:
            # every rank computed the same grads for its batch only; sum
            # contributions across ranks for shared rows
            from jax.experimental import multihost_utils

            # exchange (uniq, grad) pairs via the same gather trick
            P = _global_bucket(len(uniq))
            req = np.full((P,), -1, np.int64)
            req[: len(uniq)] = uniq
            gpad = np.zeros((P, self.dim), np.float32)
            gpad[: len(uniq)] = g
            all_req = np.asarray(multihost_utils.process_allgather(req))
            all_g = np.asarray(multihost_utils.process_allgather(gpad))
            flat = all_req.reshape(-1)
            flatg = all_g.reshape(-1, self.dim)
            mine = (flat >= 0) & (flat % self.nproc == self.rank)
            uniq, g = flat[mine], flatg[mine]
            # merge duplicate global rows
            uniq, inv = np.unique(uniq, return_inverse=True)
            merged = np.zeros((len(uniq), self.dim), np.float32)
            np.add.at(merged, inv, g)
            g = merged
            own = np.ones(len(uniq), bool)
        if self.padding_idx is not None:
            own = own & (uniq != self.padding_idx)
        local = uniq[own] // self.nproc
        gl = g[own]
        if self.optimizer == "adagrad":
            self._accum[local] += gl * gl
            self._rows[local] -= (
                lr * gl / (np.sqrt(self._accum[local]) + self.epsilon)
            ).astype(self.dtype)
        else:  # sgd
            self._rows[local] -= (lr * gl).astype(self.dtype)

    # -- persistence (fleet SaveModel capability) ------------------------
    def save(self, path):
        np.savez(path, rows=self._rows,
                 accum=getattr(self, "_accum", np.zeros(0)),
                 meta=np.asarray([self.num_rows, self.dim, self.rank,
                                  self.nproc]))

    def load(self, path):
        d = np.load(path if str(path).endswith(".npz") else str(path) + ".npz")
        meta = d["meta"] if "meta" in d.files else None
        if meta is not None and int(meta[3]) != self.nproc:
            raise ValueError(
                "host-embedding shard %r was saved with nproc=%d but this "
                "table runs with nproc=%d — the row layout differs; load "
                "all old shards through load_resharded (or the elastic "
                "restore path in HostEmbeddingCheckpoint)"
                % (str(path), int(meta[3]), self.nproc))
        self._rows = d["rows"]
        if self.optimizer == "adagrad" and d["accum"].size:
            self._accum = d["accum"]

    def load_resharded(self, shard_paths):
        """Elastic restore: rebuild THIS rank's rows from the complete
        set of shards saved by an old group of any size.  `shard_paths`:
        {old_rank: path} covering every old rank."""
        from ..distributed.elastic.reshard import reshard_host_embedding_rows

        shards = {}
        old_nranks = None
        for old_rank, p in shard_paths.items():
            d = np.load(p if str(p).endswith(".npz") else str(p) + ".npz")
            shards[int(old_rank)] = (d["rows"], d["accum"])
            if "meta" in d.files:
                saved = int(d["meta"][3])
                if old_nranks not in (None, saved):
                    raise ValueError(
                        "host-embedding shards disagree on the save-time "
                        "nproc (%d vs %d) — they are not from one commit"
                        % (old_nranks, saved))
                old_nranks = saved
        rows, accum = reshard_host_embedding_rows(
            shards, self.rank, self.nproc, old_nranks=old_nranks)
        if rows.shape[0] != self._rows.shape[0]:
            raise ValueError(
                "resharded row count %d does not match this table's owned "
                "rows %d (num_rows=%d nproc=%d rank=%d)"
                % (rows.shape[0], self._rows.shape[0], self.num_rows,
                   self.nproc, self.rank))
        self._rows = rows.astype(self.dtype, copy=False)
        if self.optimizer == "adagrad" and accum.size:
            self._accum = accum.astype(np.float32, copy=False)


class HostEmbeddingSession:
    """Wraps Executor.run with the pull/compute/push cycle for every
    HostEmbedding registered on the program (DownpourWorker parity:
    `downpour_worker.cc` FillSparseValue -> train -> push_sparse)."""

    def __init__(self, exe, program, loss=None):
        self._exe = exe
        self._program = program
        self._tables = getattr(program, "_host_embeddings", {})
        if not self._tables:
            raise ValueError(
                "program has no host embeddings; build one with "
                "layers.embedding(..., is_distributed=True)")
        # materialize grads of the pulled tables once (the param backward
        # sweep does not necessarily produce them: PULLED is a data var)
        self._grad_names = []
        if loss is not None:
            from . import framework
            from .backward import gradients

            block = program.global_block
            pulled_vars = [
                block.var(w + "@PULLED") for w in self._tables
            ]
            need = [
                v for v in pulled_vars
                if not block.has_var(v.name + framework.GRAD_SUFFIX)
            ]
            if need:
                with framework.program_guard(program):
                    gradients(loss, need)
            self._grad_names = [
                w + "@PULLED" + framework.GRAD_SUFFIX for w in self._tables
            ]

    def run(self, feed, fetch_list=None, lr=None, **kw):
        fetch_list = list(fetch_list or [])
        extra = {}
        recs = []
        for wname, (table, ids_slot) in self._tables.items():
            pulled, local, uniq = table.pull(np.asarray(feed[ids_slot]))
            extra[wname + "@PULLED"] = pulled
            extra[ids_slot + "@LOCAL"] = local
            recs.append((table, uniq))
        outs = self._exe.run(
            self._program, feed={**feed, **extra},
            fetch_list=fetch_list + self._grad_names, **kw)
        n = len(fetch_list)
        for (table, uniq), g in zip(recs, outs[n:]):
            table.push(uniq, g, lr=lr)
        return outs[:n]
