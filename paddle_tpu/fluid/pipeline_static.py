"""Static-graph pipeline parallelism: device_guard sections -> GPipe SPMD.

Capability parity: reference `PipelineOptimizer` (`optimizer.py:3632-4482`)
splits a Program into per-device sections by `device_guard` annotations and
`SectionWorker` threads (`framework/section_worker.cc:142`) push microbatch
scopes through them over in-memory queues.

TPU-first redesign — the sections become ONE SPMD program on the `pp` mesh
axis:

  * the forward ops that are ancestors of the loss are partitioned into
    stages by their `op_device` stage index (untagged ops inherit the
    current stage; stage indices must be non-decreasing in program order);
  * a `lax.scan` over GPipe ticks runs every stage in lockstep; each tick
    `ppermute` hands the boundary activations (the union of all vars that
    cross any stage boundary — skip-connections ride through untouched)
    to the next stage over ICI; every shard dynamically indexes its own
    microbatch feeds, so late-stage feeds (labels) need no threading;
  * `jax.grad` through the scan yields the reverse schedule automatically
    (ppermute transposes to the reverse permutation) — the program's
    appended backward ops (op_role=backward) are NOT executed on this
    path; the appended optimizer ops (op_role=optimize) ARE, fed with the
    pipeline-computed grads under the program's own @GRAD names, so the
    user's optimizer/LR-schedule semantics are preserved verbatim.

Persistable vars written by forward stages (batch_norm running stats)
are threaded through the scan as carries — microbatch-SEQUENTIAL, the
reference SectionWorker's order (`framework/section_worker.cc:142`) —
and the owning stage's final value is delta-psum'd to every shard, so
pipelined CNNs with batch norm train with the same running-stat
trajectory as a single device stepping microbatches in order.

Limitations (explicit, erroring): the local batch must divide
num_microbatches.  Full-batch parity holds for mean- AND sum-reduction
losses: the loss reduction is detected from the program
(`_loss_reduction_kind`) and microbatch losses are averaged or summed
accordingly; unrecognized reductions default to mean.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .framework import GRAD_SUFFIX, device_stage_index


def _loss_ancestors(ops, loss_name):
    """Indices of forward ops that are ancestors of loss_name."""
    needed = {loss_name}
    keep = []
    for i in range(len(ops) - 1, -1, -1):
        op = ops[i]
        if any(n in needed for n in op.all_output_names()):
            keep.append(i)
            needed.update(op.all_input_names())
    return set(keep)


def split_forward_stages(ops, loss_name, n_stages):
    """Partition forward ops into pipeline stages.

    Returns (stage_ops, aux_forward_ops, opt_ops, boundary_names) where
    boundary_names are the vars produced in some stage and consumed in a
    LATER stage (the ppermute payload, in deterministic order)."""
    fwd_idx = [i for i, op in enumerate(ops)
               if op.attrs.get("op_role") not in ("backward", "optimize")]
    opt_ops = [op for op in ops if op.attrs.get("op_role") == "optimize"]
    anc = _loss_ancestors([ops[i] for i in fwd_idx], loss_name)
    anc_idx = [fwd_idx[i] for i in range(len(fwd_idx)) if i in anc]
    aux_ops = [ops[i] for i in fwd_idx if i not in set(anc_idx)]

    stage_ops = [[] for _ in range(n_stages)]
    cur = 0
    for i in anc_idx:
        op = ops[i]
        s = device_stage_index(op.attrs.get("op_device"))
        if s is None:
            s = cur
        if s < cur:
            raise ValueError(
                "device_guard stage indices must be non-decreasing in "
                "program order: op %r is tagged stage %d after stage %d"
                % (op.type, s, cur))
        if s >= n_stages:
            raise ValueError(
                "op %r tagged for stage %d but the pp mesh axis has only "
                "%d shards" % (op.type, s, n_stages))
        cur = s
        stage_ops[s].append(op)
    if not stage_ops[0] or sum(1 for so in stage_ops if so) < 2:
        raise ValueError(
            "pipeline program needs >= 2 device_guard stages with ops "
            "(got %d); annotate the forward with fluid.device_guard"
            % sum(1 for so in stage_ops if so))

    produced_at = {}
    for s, sops in enumerate(stage_ops):
        for op in sops:
            for n in op.all_output_names():
                produced_at[n] = s
    boundary = []
    for s, sops in enumerate(stage_ops):
        for op in sops:
            for n in op.all_input_names():
                p = produced_at.get(n)
                if p is not None and p < s and n not in boundary:
                    boundary.append(n)
    return stage_ops, aux_ops, opt_ops, boundary, produced_at


def _loss_reduction_kind(ops, loss_name):
    """'mean' or 'sum': how the program reduces the per-example loss.

    Full-batch parity of the microbatched schedule depends on it: for a
    mean loss, mean-of-microbatch-losses == full-batch loss (equal
    microbatches); for a sum loss the microbatch losses must be SUMMED or
    the loss/grads shrink by 1/num_microbatches.  Walks back from the
    loss var through reduction-neutral ops (scale/cast/assign) to the
    first reducing op; unrecognized producers default to 'mean' (the
    overwhelmingly common convention)."""
    produced_by = {}
    for op in ops:
        for n in op.all_output_names():
            produced_by[n] = op
    name = loss_name
    for _ in range(16):                       # bounded walk-back
        op = produced_by.get(name)
        if op is None:
            break
        if op.type in ("mean", "reduce_mean"):
            return "mean"
        if op.type == "reduce_sum":
            return "sum"
        if op.type in ("scale", "cast", "assign", "share_data"):
            ins = op.all_input_names()
            if not ins:
                break
            name = ins[0]
            continue
        break
    return "mean"


def _stateful_forward_vars(stage_ops, block, scope):
    """Persistable vars WRITTEN by forward stage ops (batch_norm running
    stats).  The reference's SectionWorker carries these sequentially
    across microbatches (`framework/section_worker.cc:142`); here they
    become scan carries — microbatch m+1's stage sees microbatch m's
    update, the SectionWorker order exactly."""
    out = []
    for sops in stage_ops:
        for op in sops:
            for n in op.all_output_names():
                v = block._find_var_recursive(n)
                if ((v is not None and v.persistable) or scope.has(n)) \
                        and n not in out:
                    out.append(n)
    return out


def build_pipeline_jit(program, block, ops, feed_names, feed_shapes,
                       fetch_names, state_in, state_out, state_donate,
                       state_ro, scope, mesh, n_micro, loss_name, is_test):
    """Returns a jitted (feed_vals, donate_state, ro_state, rng_key) ->
    (fetches, new_state) with GPipe stage parallelism over the pp axis."""
    from jax.sharding import PartitionSpec as P

    from .core.block_eval import run_ops
    from .core.registry import LowerContext

    n_stages = mesh.axis_size("pp")
    stage_ops, aux_ops, opt_ops, boundary, produced_at = \
        split_forward_stages(ops, loss_name, n_stages)
    stat_names = _stateful_forward_vars(stage_ops, block, scope)
    loss_reduction = _loss_reduction_kind(ops, loss_name)

    # prune aux (non-loss-ancestor) ops nothing consumes, then reject the
    # survivors that read stage activations with a targeted diagnostic
    # (per-microbatch activations are not exposed outside the schedule)
    needed = set(fetch_names)
    for op in opt_ops:
        needed.update(op.all_input_names())
    kept_aux = []
    for op in reversed(aux_ops):
        if any(n in needed for n in op.all_output_names()) \
                or op.attrs.get("op_role") is None and op.type in ("print",):
            kept_aux.append(op)
            needed.update(op.all_input_names())
    aux_ops = list(reversed(kept_aux))
    for op in aux_ops:
        for n in op.all_input_names():
            if n in produced_at:
                raise ValueError(
                    "op %r (not an ancestor of the loss) reads %r, which "
                    "is computed inside pipeline stage %d: per-microbatch "
                    "activations are not exposed outside the pipeline "
                    "schedule.  Fetch the loss / persistable state / vars "
                    "independent of the staged forward, and compute side "
                    "metrics on the host from fetched values or as part "
                    "of the loss program itself" % (op.type, n,
                                                    produced_at[n]))
    # the stage that PRODUCES the loss accumulates it (trailing unannotated
    # stages, if any, just pass the boundary through)
    loss_stage = next(
        s for s, sops in enumerate(stage_ops)
        if any(loss_name in op.all_output_names() for op in sops))

    for n in fetch_names:
        if n != loss_name and n not in state_out and n in boundary:
            raise ValueError(
                "fetch var %r is a pipeline-internal activation; fetchable "
                "on the pipeline path: the loss, persistable state, and "
                "aux (non-loss) vars" % n)

    # grads wanted by the optimizer ops (program's own @GRAD naming)
    grad_params = []
    for op in opt_ops:
        for n in op.all_input_names():
            if n.endswith(GRAD_SUFFIX):
                p = n[: -len(GRAD_SUFFIX)]
                if p not in grad_params:
                    grad_params.append(p)

    # --- shape work (outside jit): boundary structs at microbatch size ---
    def _mb_feed_struct(n):
        shp = tuple(feed_shapes[n])
        if not shp or shp[0] % n_micro != 0:
            raise ValueError(
                "pipeline: feed %r local batch %s must divide "
                "num_microbatches=%d" % (n, shp[:1], n_micro))
        from .framework import np_dtype_of

        v = block._find_var_recursive(n)
        return jax.ShapeDtypeStruct(
            (shp[0] // n_micro,) + shp[1:], np_dtype_of(v))

    mb_structs = {n: _mb_feed_struct(n) for n in feed_names}

    def _state_struct(n):
        v = scope.find_var(n)
        return jax.ShapeDtypeStruct(v.shape, v.dtype)

    state_structs = {n: _state_struct(n) for n in state_in}

    def _fwd_all(env):
        ctx = LowerContext(base_key=jax.random.PRNGKey(0), is_test=True)
        for sops in stage_ops:
            run_ops(sops, env, ctx)
        return {n: env[n] for n in boundary}

    bnd_structs = jax.eval_shape(
        lambda e: _fwd_all(dict(e)), {**mb_structs, **state_structs})

    jmesh = mesh.mesh
    perm = [(i, i + 1) for i in range(n_stages - 1)]

    # SPMD forward: per-shard GPipe schedule over the pp axis.  The loss
    # comes back psum'd (identical on every shard, out_spec P()) so that
    # jax.grad wraps the WHOLE shard_map from outside — shard_map's
    # collective transposes then produce exact gradients (differentiating
    # an in-body psum per shard and psum'ing grads again double-counts
    # by the pp size).
    def pp_forward(train_params, const_params, mb_feeds, rng_key):
        from .core.jax_compat import pvary

        s = jax.lax.axis_index("pp")
        env_base = dict(const_params)
        env_base.update(train_params)
        # persistable vars written by forward stages (BN running stats)
        # ride the scan carry: microbatch-SEQUENTIAL, like SectionWorker
        stats0 = {n: env_base[n] for n in stat_names}

        def tick(carry, t):
            bnd, acc, stats = carry
            bnd = jax.tree.map(
                lambda a: jax.lax.ppermute(a, "pp", perm), bnd)
            mb = jnp.clip(t - s, 0, n_micro - 1)
            valid = (t - s >= 0) & (t - s < n_micro)
            feeds_t = {
                n: jax.lax.dynamic_index_in_dim(
                    a, mb, axis=0, keepdims=False)
                for n, a in mb_feeds.items()
            }

            def run_stage(si):
                def f(operand):
                    bnd_in, stats_in = operand
                    env = dict(env_base)
                    env.update(feeds_t)
                    env.update(stats_in)     # carried stats win
                    env.update(bnd_in)
                    ctx = LowerContext(
                        base_key=jax.random.fold_in(
                            jax.random.fold_in(rng_key, mb), si),
                        is_test=is_test)
                    run_ops(stage_ops[si], env, ctx)
                    # every switch branch must produce the same
                    # replication type: mark all branch outputs varying
                    # on pp (they are — each shard ran its own stage)
                    out = {n: pvary(env.get(n, bnd_in[n]), "pp")
                           for n in boundary}
                    lv = (env[loss_name].astype(jnp.float32)
                          if si == loss_stage else jnp.float32(0))
                    new_stats = {
                        n: pvary(jax.lax.stop_gradient(
                            env.get(n, stats_in[n])), "pp")
                        for n in stat_names
                    }
                    return (out,
                            pvary(jnp.asarray(lv, jnp.float32).reshape(()),
                                  "pp"),
                            new_stats)
                return f

            new_bnd, lv, new_stats = jax.lax.switch(
                s, [run_stage(i) for i in range(n_stages)], (bnd, stats))
            new_bnd = jax.tree.map(
                lambda new, old: jnp.where(valid, new, old),
                new_bnd, bnd)
            new_stats = jax.tree.map(
                lambda new, old: jnp.where(valid, new, old),
                new_stats, stats)
            acc = acc + jnp.where(valid, lv, 0.0)
            return (new_bnd, acc, new_stats), None

        bnd0 = jax.tree.map(
            lambda sd: jnp.zeros(sd.shape, sd.dtype), dict(bnd_structs))
        (_, acc, stats_end), _ = jax.lax.scan(
            tick, (bnd0, jnp.float32(0), stats0),
            jnp.arange(n_micro + n_stages - 1))
        # only the last stage accumulated; the psum broadcasts the total.
        # mean losses average over microbatches (== full-batch mean);
        # sum losses just sum (== full-batch sum) — see _loss_reduction_kind
        total = jax.lax.psum(acc, "pp")
        # each stat var was updated only on its owning stage's shard; the
        # delta-psum replicates the owner's final value everywhere
        stats_final = {
            n: stats0[n] + jax.lax.psum(stats_end[n] - stats0[n], "pp")
            for n in stat_names
        }
        loss = total / n_micro if loss_reduction == "mean" else total
        return loss, stats_final

    from .core.jax_compat import shard_map as _shard_map

    sharded_loss = _shard_map(
        pp_forward,
        mesh=jmesh,
        in_specs=(P(), P(), P(), P()),
        out_specs=(P(), {n: P() for n in stat_names}),
        check=False,
    )

    def step(feed_vals, donate_state, ro_state, rng_key):
        params = {}
        params.update(donate_state)
        params.update(ro_state)
        mb_feeds = {
            n: v.reshape((n_micro, v.shape[0] // n_micro) + v.shape[1:])
            for n, v in feed_vals.items()
        }

        # aux forward ops (LR schedules etc.): replicated, full-batch env
        aux_env = dict(params)
        aux_env.update(feed_vals)
        aux_ctx = LowerContext(base_key=rng_key, is_test=is_test)
        run_ops(aux_ops, aux_env, aux_ctx)

        train_params = {n: params[n] for n in grad_params}
        const_params = {n: v for n, v in params.items()
                        if n not in train_params}
        if grad_params:
            (loss_val, stat_vals), grads = jax.value_and_grad(
                sharded_loss, has_aux=True)(
                train_params, const_params, mb_feeds, rng_key)
        else:  # eval clone: staged forward only, no updates
            loss_val, stat_vals = sharded_loss(
                train_params, const_params, mb_feeds, rng_key)
            grads = {}

        opt_env = dict(params)
        opt_env.update(aux_env)
        opt_env.update(stat_vals)        # carried running stats persist
        for p, g in grads.items():
            opt_env[p + GRAD_SUFFIX] = g.astype(params[p].dtype)
        opt_ctx = LowerContext(base_key=rng_key, is_test=is_test)
        run_ops(opt_ops, opt_env, opt_ctx)

        def fetch_of(n):
            if n == loss_name:
                return loss_val
            if n in opt_env:
                return opt_env[n]
            raise RuntimeError(
                "pipeline fetch %r not available (loss/state/aux only)" % n)

        fetches = [fetch_of(n) for n in fetch_names]
        new_state = {n: opt_env[n] for n in state_out}
        return fetches, new_state

    return jax.jit(step, donate_argnums=(1,))
