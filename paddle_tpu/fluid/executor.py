"""Executor: lower a Program block to ONE jitted XLA computation and run it.

Capability parity: reference `python/paddle/fluid/executor.py` (Executor:461,
run:890, _run_impl:1081) driving the C++ per-op interpreter
(`framework/executor.cc:184`, hot loop :470-476 with kernel dispatch at
`operator.cc:934`).  TPU-first redesign: there is no interpreter.  The whole
block — forward, backward, optimizer updates — traces into a single jaxpr and
compiles to one XLA executable; persistable state is threaded functionally
with donated buffers so parameter updates are in-place on device.  The
per-op GC, kernel chooser, and data-transfer machinery of the reference
collapse into XLA's memory planner and layout assignment.

Program-level executable cache keyed like the reference's program cache
(`executor.py:382` _get_program_cache_key): (program identity+version, feed
signature, fetch list, state signature).
"""

from __future__ import annotations

import numpy as np

from . import framework
from .core import dtypes as dtypes_mod
from .core.place import Place, default_place
from .core.registry import LowerContext, get_op_def
from .core.scope import Scope, global_scope


class _LoweredBlock:
    """A compiled (feed, state, key) -> (fetch, new_state) executable."""

    def __init__(self, program, block, feed_names, fetch_names, scope,
                 dp_devices=None, mesh=None, feed_shapes=None):
        import jax

        feed_shapes = feed_shapes or {}

        self.feed_names = list(feed_names)
        self.fetch_names = list(fetch_names)
        # single-process data parallel (CompiledProgram.with_data_parallel):
        # a 1-axis GSPMD mesh; feeds shard on dim 0, state replicates
        self.dp_mesh = None
        if dp_devices:
            import numpy as _np
            from jax.sharding import Mesh

            self.dp_mesh = Mesh(_np.array(dp_devices), ("dp",))
        # mesh mode (SPMD over a DeviceMesh, possibly multi-process): the
        # whole block runs under shard_map on the "dp" axis so transpiled
        # c_allreduce_* ops bind the axis and lower to real psum — the
        # execution story behind transpiler/collective.py (reference
        # ParallelExecutor multi-trainer semantics: each rank feeds its
        # LOCAL batch, gradients all-reduce across ranks).
        self.mesh = mesh
        ops = block.ops

        produced = set()
        state_in = []  # persistable inputs read from scope
        for op in ops:
            for name in op.all_input_names():
                if name in produced or name in feed_names or name in state_in:
                    continue
                v = block._find_var_recursive(name)
                if scope.has(name):
                    state_in.append(name)
                elif v is not None and v.persistable:
                    raise RuntimeError(
                        "persistable var '%s' read before initialization — "
                        "run the startup program first (fluid.default_startup_program())"
                        % name
                    )
                else:
                    raise RuntimeError(
                        "op %r reads var '%s' which is neither fed, produced, "
                        "nor found in scope" % (op, name)
                    )
            produced.update(op.all_output_names())

        # fetches must be materialized by the block (clear diagnostic when a
        # var was folded into a recompute_segment interior or never produced)
        produced_all = set(feed_names) | set(state_in)
        for op in ops:
            produced_all.update(op.all_output_names())
        for name in fetch_names:
            if name not in produced_all:
                inside_seg = any(
                    op.type == "recompute_segment"
                    and any(
                        name in od["outputs"].get(slot, [])
                        for od in op.attrs.get("ops", [])
                        for slot in od["outputs"]
                    )
                    for op in ops
                )
                if inside_seg:
                    raise RuntimeError(
                        "fetch var '%s' lives inside a recompute segment; "
                        "its value is rematerialized (not stored). Add it to "
                        "the RecomputeOptimizer checkpoints to fetch it."
                        % name
                    )
                raise RuntimeError(
                    "fetch var '%s' is not produced by this program" % name
                )

        # persistable outputs -> write back to scope after the step
        state_out = []
        for op in ops:
            for name in op.all_output_names():
                v = block._find_var_recursive(name)
                if (v is not None and v.persistable) or scope.has(name):
                    if name not in state_out:
                        state_out.append(name)
        self.state_in = state_in
        self.state_out = state_out
        # print ops emit host callbacks; the executor must flush them so
        # output appears before run() returns (including prints serialized
        # into cond/while/recompute sub-op attrs)
        def _has_print(op_seq):
            for o in op_seq:
                o_type = o["type"] if isinstance(o, dict) else o.type
                o_attrs = o["attrs"] if isinstance(o, dict) else o.attrs
                if o_type == "print":
                    return True
                for key in ("ops", "true_ops", "false_ops", "cond_ops",
                            "body_ops", "step_ops"):
                    sub = o_attrs.get(key)
                    if isinstance(sub, list) and _has_print(sub):
                        return True
            return False

        self.has_print_effects = _has_print(ops)
        # Only state that is rewritten may be donated; read-only persistables
        # (e.g. params during eval) must keep their buffers alive in the scope.
        self.state_donate = [n for n in state_in if n in set(state_out)]
        self.state_ro = [n for n in state_in if n not in set(state_out)]

        is_test = program._is_test

        # static pipeline parallelism: a PipelineOptimizer-marked program
        # on a mesh with a pp axis runs device_guard stages in a GPipe
        # schedule (see fluid/pipeline_static.py)
        pp_meta = getattr(program, "_pipeline", None)
        if (pp_meta and mesh is not None and mesh.has_axis("pp")
                and mesh.axis_size("pp") > 1):
            from jax.sharding import PartitionSpec as _P

            from .pipeline_static import build_pipeline_jit

            self.gspmd = False
            self.is_pipeline = True
            # feeds replicate: every pp shard dynamically indexes its own
            # microbatches out of the full local batch
            self.feed_specs = {n: _P() for n in self.feed_names}
            self._jitted = build_pipeline_jit(
                program, block, ops, self.feed_names, feed_shapes,
                self.fetch_names, state_in, state_out, self.state_donate,
                self.state_ro, scope, mesh, pp_meta["n_micro"],
                pp_meta["loss"], is_test)
            return

        # GSPMD mode (program flagged by distributed.static_sharding):
        # ONE logical program jitted with per-var in/out shardings taken
        # from Variable.dist_attr — XLA partitions the computation and
        # inserts the collectives (grad psum for dp, row-parallel psum for
        # tp, ZeRO gather/scatter).  This is the static-graph answer to
        # ParallelExecutor + distribute_transpiler state sharding under one
        # roof: no program rewrite, no explicit c_* ops.
        self.gspmd = bool(getattr(program, "_gspmd", False)) and mesh is not None

        def run_block(feed_vals, donate_state, ro_state, rng_key):
            from .core.block_eval import run_ops

            env = dict(feed_vals)
            env.update(donate_state)
            env.update(ro_state)
            ctx = LowerContext(base_key=rng_key, is_test=is_test)
            run_ops(ops, env, ctx)
            fetches = [env[n] for n in self.fetch_names]
            new_state = {n: env[n] for n in self.state_out}
            return fetches, new_state

        if self.gspmd:
            from jax.sharding import NamedSharding, PartitionSpec as P

            jmesh = mesh.mesh
            repl = NamedSharding(jmesh, P())
            nproc = jax.process_count()

            def _sharding_for(name):
                v = block._find_var_recursive(name)
                spec = getattr(v, "dist_attr", None) if v is not None else None
                return NamedSharding(jmesh, P(*spec)) if spec else repl

            dp_total = mesh.axis_size("dp")
            self.feed_shardings = {}
            for n in feed_names:
                shp = feed_shapes.get(n, ())
                global0 = shp[0] * nproc if len(shp) >= 1 else 0
                if (mesh.has_axis("dp") and global0 > 0
                        and global0 % dp_total == 0):
                    self.feed_shardings[n] = NamedSharding(jmesh, P("dp"))
                else:
                    if (mesh.has_axis("dp") and dp_total > 1 and nproc > 1
                            and len(shp) >= 1 and global0 > 0):
                        # a replicated feed is stitched by treating each
                        # process's LOCAL value as the full global value —
                        # correct only when every rank feeds identical
                        # data (constant tables etc.); warn about the
                        # contract rather than silently corrupt
                        import warnings

                        warnings.warn(
                            "GSPMD feed %r (local shape %s) cannot be "
                            "sharded over the dp axis (global dim0 %d %% "
                            "dp %d != 0); treating it as REPLICATED from "
                            "this process's local value — every rank must "
                            "feed identical data for this to be consistent"
                            % (n, shp, global0, dp_total), stacklevel=3)
                    self.feed_shardings[n] = repl
            self.state_shardings = {
                n: _sharding_for(n)
                for n in set(state_in) | set(state_out)
            }

            self._jitted = jax.jit(
                run_block,
                in_shardings=(
                    dict(self.feed_shardings),
                    {n: self.state_shardings[n] for n in self.state_donate},
                    {n: self.state_shardings[n] for n in self.state_ro},
                    repl,
                ),
                out_shardings=(
                    [repl] * len(self.fetch_names),
                    {n: self.state_shardings[n] for n in self.state_out},
                ),
                donate_argnums=(1,),
            )
        elif mesh is None:
            # donate_state (arg 1): optimizer updates reuse param buffers.
            self._jitted = jax.jit(run_block, donate_argnums=(1,))
        else:
            import jax.numpy as jnp
            from jax.sharding import PartitionSpec as P

            jmesh = mesh.mesh
            ndev = jmesh.devices.size
            nproc = jax.process_count()
            local_dev = max(1, ndev // nproc)
            # per-feed spec: shard dim 0 over dp when this process's LOCAL
            # feed divides over its addressable devices; otherwise
            # replicate (same fallback as the dp_devices path)
            # a mesh without a "dp" axis (e.g. a pure-pp mesh reused for
            # an unannotated program) replicates feeds and maps fetches
            # over its first axis instead of crashing on the dp name
            rank_axis = "dp" if mesh.has_axis("dp") else mesh.axis_names[0]
            self.feed_specs = {}
            for n in feed_names:
                shp = feed_shapes.get(n, ())
                if (mesh.has_axis("dp") and len(shp) >= 1 and shp[0] > 0
                        and shp[0] % local_dev == 0):
                    self.feed_specs[n] = P("dp")
                else:
                    self.feed_specs[n] = P()
            # Per-rank RNG: a startup program (no feeds, no backward/
            # optimize ops) must init identically on every rank — the XLA
            # analogue of the reference's param broadcast
            # (parallel_executor.cc:740 BCastParamsToDevices).  Training/
            # eval programs fold the rank in so dropout masks decorrelate
            # across ranks (reference: per-device CUDA RNG states).
            fold_rank = bool(feed_names) or any(
                op.attrs.get("op_role") in ("backward", "optimize")
                for op in ops
            )

            def run_block_sharded(feed_vals, donate_state, ro_state, rng_key):
                from .core.block_eval import run_ops

                if fold_rank:
                    rng_key = jax.random.fold_in(
                        rng_key, jax.lax.axis_index(rank_axis)
                    )
                env = dict(feed_vals)
                env.update(donate_state)
                env.update(ro_state)
                ctx = LowerContext(base_key=rng_key, is_test=is_test)
                run_ops(ops, env, ctx)
                # fetches gain a leading per-rank dim (shard_map needs a
                # mapped output dim; per-rank values like the local loss
                # genuinely differ across ranks)
                fetches = [jnp.expand_dims(env[n], 0) for n in self.fetch_names]
                new_state = {n: env[n] for n in self.state_out}
                return fetches, new_state

            from .core.jax_compat import shard_map as _shard_map

            sharded = _shard_map(
                run_block_sharded,
                mesh=jmesh,
                in_specs=(
                    dict(self.feed_specs),
                    P(),  # state replicated (identical after psum'd grads)
                    P(),
                    P(),
                ),
                out_specs=([P(rank_axis)] * len(fetch_names), P()),
                check=False,
            )
            self._jitted = jax.jit(sharded, donate_argnums=(1,))

    def __call__(self, feed_vals, donate_state, ro_state, rng_key):
        return self._jitted(feed_vals, donate_state, ro_state, rng_key)


class Executor:
    """cf. reference fluid.Executor — run(program, feed, fetch_list)."""

    def __init__(self, place: Place = None, mesh=None):
        """mesh: a distributed.DeviceMesh with a "dp" axis switches the
        executor into SPMD mesh mode — every run executes the block under
        shard_map over dp, feeds are PER-RANK local batches (stitched into
        one global array across processes), and transpiled c_allreduce_*
        ops perform real cross-rank reductions.  This is the execution
        engine the collective transpiler targets (reference
        ParallelExecutor / test_dist_base multi-trainer semantics)."""
        self.place = place if place is not None else default_place()
        self.mesh = mesh
        self._cache = {}
        self._rng_counter = 0
        self._run_hist = None  # cached executor_run_ms child (hot path)
        # program -> versions FLAGS_verify_program already checked — weakly
        # keyed (no id-reuse collisions) and independent of _cache so
        # use_program_cache=False loops still verify each program version
        # exactly once, not every step
        import weakref

        self._verified_programs = weakref.WeakKeyDictionary()

    def close(self):
        self._cache.clear()

    # ------------------------------------------------------------------
    def run(
        self,
        program: framework.Program = None,
        feed: dict = None,
        fetch_list=None,
        scope: Scope = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        """Telemetry wrapper around `_run_impl`: the whole call's wall
        time is split compile-vs-compute via the jax.monitoring compile
        accumulator (`observability.step_timer`) and recorded into the
        always-on registry histograms plus the active StepTimer record,
        if a training loop armed one on this thread."""
        import time

        from ..observability import step_timer as _telemetry

        _telemetry.install_jax_compile_hooks()
        t0 = time.perf_counter()
        comp0 = _telemetry.thread_compile_seconds()
        try:
            return self._run_impl(
                program, feed, fetch_list, scope, return_numpy,
                use_program_cache,
            )
        finally:
            t1 = time.perf_counter()
            wall = t1 - t0
            dcomp = min(_telemetry.thread_compile_seconds() - comp0, wall)
            _telemetry.record_component("compile", dcomp)
            _telemetry.record_component("compute", max(wall - dcomp, 0.0))
            from ..observability import trace as _trace

            tracer = _trace.default_tracer()
            if tracer.enabled:
                tracer.complete(
                    "executor.run", t0, t1, cat="executor",
                    args={"compile_ms": round(dcomp * 1e3, 3),
                          "compute_ms": round((wall - dcomp) * 1e3, 3),
                          "fetches": len(fetch_list or [])})
            if self._run_hist is None:
                self._run_hist = _telemetry.default_registry().histogram(
                    "executor_run_ms",
                    "Executor.run wall time: placement + dispatch + "
                    "device execution + fetch materialization (ms)")
            self._run_hist.observe(wall * 1e3)

    def _run_impl(
        self,
        program: framework.Program = None,
        feed: dict = None,
        fetch_list=None,
        scope: Scope = None,
        return_numpy: bool = True,
        use_program_cache: bool = True,
    ):
        import jax

        program = program or framework.default_main_program()
        # CompiledProgram facade (compiler.py) unwraps to its program + config
        dp_devices = None
        facade = None
        if hasattr(program, "_unwrap_for_executor"):
            facade = program
            if hasattr(program, "_dp_devices"):
                dp_devices = program._dp_devices()
            program = program._unwrap_for_executor()
        feed = dict(feed or {})
        scope = scope or global_scope()
        fetch_names = []
        for f in fetch_list or []:
            fetch_names.append(f.name if isinstance(f, framework.Variable) else str(f))

        block = program.global_block

        # -- convert feeds -------------------------------------------------
        # jax.Arrays (an io.DevicePrefetcher feed) stay device-resident:
        # np.asarray on them would round-trip device->host->device and
        # throw away exactly the overlap the prefetcher bought
        feed_vals = {}
        for name, value in feed.items():
            v = block._find_var_recursive(name)
            if isinstance(value, jax.Array):
                arr = value
                if v is not None and \
                        dtypes_mod.to_jnp(v.dtype) != arr.dtype.type:
                    arr = arr.astype(dtypes_mod.to_jnp(v.dtype))
            else:
                arr = np.asarray(value)
                if v is not None and \
                        dtypes_mod.to_jnp(v.dtype) != arr.dtype.type:
                    arr = arr.astype(dtypes_mod.to_str(v.dtype))
            feed_vals[name] = arr

        # CompiledProgram.with_autotune: first run searches (or loads
        # from the tuning cache) the winning pass pipeline for THIS
        # program version at the live feed shapes; later runs execute
        # the cached tuned clone (same var names, so scope state and
        # feeds carry over unchanged)
        if (facade is not None
                and getattr(facade, "_autotune", None) and fetch_names):
            program = facade._ensure_tuned(
                feed_vals, fetch_names, mesh=self.mesh)
            block = program.global_block

        feed_sig = tuple(
            (n, feed_vals[n].shape, str(feed_vals[n].dtype)) for n in sorted(feed_vals)
        )
        from .flags import get_flags

        key = (
            id(program),
            program._version,
            feed_sig,
            tuple(fetch_names),
            id(scope),
            tuple(id(d) for d in dp_devices) if dp_devices else None,
            id(self.mesh) if self.mesh is not None else None,
            # the NaN guard is baked into the traced program, so the flag
            # must participate in the cache key
            bool(get_flags(["FLAGS_check_nan_inf"])["FLAGS_check_nan_inf"]),
            bool(getattr(program, "_gspmd", False)),
        )
        from .core import monitor
        from ..observability import step_timer as _telemetry

        entry = self._cache.get(key) if use_program_cache else None
        if entry is None:
            # FLAGS_verify_program: opt-in static verification the first
            # time each program version is run — a mutated or hand-built
            # program fails here with a structured diagnostic naming the
            # op/var instead of an XLA trace error below
            seen = self._verified_programs.get(program)
            if (seen is None or program._version not in seen) and \
                    get_flags(["FLAGS_verify_program"])["FLAGS_verify_program"]:
                from ..analysis import assert_program_valid

                assert_program_valid(
                    program, feed_names=list(feed_vals),
                    fetch_names=fetch_names,
                    what="program handed to Executor.run "
                         "(FLAGS_verify_program)")
                self._verified_programs.setdefault(
                    program, set()).add(program._version)
            # cache miss: the lowering/trace below plus the XLA compile
            # inside the first jitted call are "compile" time.  The
            # jax.monitoring hooks catch the XLA side; the lowering wall
            # time is pushed into the same thread accumulator (minus any
            # compile events that already fired inside it) so the run
            # wrapper attributes it to compile, not compute.
            import time as _time

            t_lower = _time.perf_counter()
            c_lower = _telemetry.thread_compile_seconds()
            entry = _LoweredBlock(
                program, block, list(feed_vals), fetch_names, scope,
                dp_devices=dp_devices, mesh=self.mesh,
                feed_shapes={n: a.shape for n, a in feed_vals.items()},
            )
            t_lower1 = _time.perf_counter()
            lower_secs = t_lower1 - t_lower
            lower_evt = _telemetry.thread_compile_seconds() - c_lower
            _telemetry.add_thread_compile_seconds(lower_secs - lower_evt)
            from ..observability import trace as _trace

            _tracer = _trace.default_tracer()
            if _tracer.enabled:
                _tracer.complete(
                    "executor.lower", t_lower, t_lower1, cat="executor",
                    args={"program_version": program._version,
                          "feeds": sorted(feed_vals)})
            monitor.stat_add("STAT_executor_programs_compiled")
            _telemetry.default_registry().histogram(
                "executor_lowering_ms",
                "Program lowering (trace + jit build) wall time (ms)"
            ).observe(lower_secs * 1e3)
            if use_program_cache:
                self._cache[key] = entry
            self._maybe_warn_unused_vars(block, fetch_names)
        monitor.stat_add("STAT_executor_runs")

        donate_state = {n: scope.find_var(n) for n in entry.state_donate}
        ro_state = {n: scope.find_var(n) for n in entry.state_ro}
        if entry.mesh is not None and entry.gspmd:
            # GSPMD: feeds are per-process LOCAL batches stitched into one
            # global batch-sharded array; state is placed per its dist_attr
            # sharding (a resharding device_put is a no-op when the scope
            # value already lands right, e.g. coming out of the last step)
            def _place(n, v):
                tgt = entry.state_shardings[n]
                return v if getattr(v, "sharding", None) == tgt \
                    else jax.device_put(v, tgt)

            def _to_global(a, sharding):
                if getattr(a, "sharding", None) == sharding:
                    return a
                if isinstance(a, jax.Array):
                    # device-resident with a different layout: reshard on
                    # device (np.asarray would fail on a multi-host
                    # global array, and would mislabel global shape as
                    # process-local data)
                    return jax.device_put(a, sharding)
                return jax.make_array_from_process_local_data(
                    sharding, np.asarray(a))

            feed_dev = {
                n: _to_global(a, entry.feed_shardings[n])
                for n, a in feed_vals.items()
            }
            donate_state = {n: _place(n, v) for n, v in donate_state.items()}
            ro_state = {n: _place(n, v) for n, v in ro_state.items()}
        elif entry.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            jmesh = entry.mesh.mesh
            repl = NamedSharding(jmesh, P())

            def _stitch(a, sharding):
                # per-process local data -> one global array (works single-
                # process too, where local IS global); already-placed
                # device arrays pass through or reshard on device
                if getattr(a, "sharding", None) == sharding:
                    return a
                if isinstance(a, jax.Array):
                    return jax.device_put(a, sharding)
                return jax.make_array_from_process_local_data(
                    sharding, np.asarray(a)
                )

            def _ensure_repl(d):
                return {
                    n: v if getattr(v, "sharding", None) == repl
                    else _stitch(v, repl)
                    for n, v in d.items()
                }

            feed_dev = {
                n: _stitch(a, NamedSharding(jmesh, entry.feed_specs[n]))
                for n, a in feed_vals.items()
            }
            donate_state = _ensure_repl(donate_state)
            ro_state = _ensure_repl(ro_state)
        elif entry.dp_mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            mesh = entry.dp_mesh
            ndev = mesh.devices.size
            repl = NamedSharding(mesh, P())

            def _put_feed(a):
                if a.ndim >= 1 and a.shape[0] > 0 and a.shape[0] % ndev == 0:
                    return jax.device_put(a, NamedSharding(mesh, P("dp")))
                return jax.device_put(a, repl)

            feed_dev = {n: _put_feed(a) for n, a in feed_vals.items()}
            donate_state = {
                n: jax.device_put(v, repl) for n, v in donate_state.items()
            }
            ro_state = {n: jax.device_put(v, repl) for n, v in ro_state.items()}
        else:
            device = self.place.get_device()
            feed_dev = {
                n: jax.device_put(a, device) for n, a in feed_vals.items()
            }

        seed = program.random_seed
        if seed is None:
            self._rng_counter += 1
            seed_val = self._rng_counter
        else:
            seed_val = seed + self._rng_counter
            self._rng_counter += 1
        rng_key = jax.random.PRNGKey(seed_val)

        fetches, new_state = entry(feed_dev, donate_state, ro_state, rng_key)
        if entry.has_print_effects:
            jax.effects_barrier()

        for n, val in new_state.items():
            scope.set(n, val)

        if (entry.mesh is not None and not entry.gspmd
                and not getattr(entry, "is_pipeline", False)):
            # fetches carry a leading per-rank dim; a process can only read
            # its addressable shards, so return the LOCAL ranks' values
            # (shape [n_local_ranks, ...]) — reference multi-trainer
            # semantics: each trainer sees its own fetch results.
            out = []
            for f in fetches:
                shards = sorted(
                    f.addressable_shards, key=lambda s: s.index[0].start or 0
                )
                loc = np.concatenate([np.asarray(s.data) for s in shards], 0)
                out.append(loc if return_numpy else jax.numpy.asarray(loc))
            return out

        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return fetches

    @staticmethod
    def _maybe_warn_unused_vars(block, fetch_names):
        """FLAGS_enable_unused_var_check (reference
        `framework/unused_var_check.cc`): warn about op outputs nothing
        consumes — usually a sign of a mis-built program."""
        from .flags import get_flags

        if not get_flags(["FLAGS_enable_unused_var_check"]).get(
            "FLAGS_enable_unused_var_check"
        ):
            return
        consumed = set(fetch_names)
        for op in block.ops:
            consumed.update(op.all_input_names())
        unused = []
        for op in block.ops:
            for n in op.all_output_names():
                v = block._find_var_recursive(n)
                persistable = v is not None and getattr(
                    v, "persistable", False
                )
                if (n not in consumed and not persistable
                        and "@GRAD@JUNK" not in n):
                    # @GRAD@JUNK: deliberate cotangent sinks (backward.py)
                    unused.append("%s (from %s)" % (n, op.type))
        if unused:
            import warnings

            warnings.warn(
                "unused op outputs (FLAGS_enable_unused_var_check): %s"
                % ", ".join(unused[:20])
            )

    # ------------------------------------------------------------------
    # Dataset trainer path (cf. reference Executor.train_from_dataset
    # executor.py:1448 -> _run_from_dataset:1323 -> TrainerDesc +
    # MultiTrainer/HogwildWorker threads, trainer.h:38).  TPU-first
    # redesign: the per-thread interpreter workers collapse into the one
    # jitted block — the native C++ engine parses/shuffles in its own
    # threads while XLA executes the previous batch, and ragged slots are
    # padded to the program's declared static shapes (bucketed otherwise)
    # so recompiles stay bounded.
    # ------------------------------------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """One full pass over `dataset` driving `program` batch-by-batch
        with no Python reader.  fetch_list vars are printed every
        `print_period` batches when `debug` (reference PrintFetchVars
        semantics, device_worker.h)."""
        return self._run_from_dataset(
            program, dataset, scope, fetch_list, fetch_info,
            print_period, debug,
        )

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Same loop as train_from_dataset but gradient/optimizer ops DO
        NOT run (reference contract, executor.py:1519): the program is
        pruned via clone(for_test=True), cached per program version."""
        program = program or framework.default_main_program()
        key = (id(program), program._version)
        cache = getattr(self, "_infer_clone_cache", None)
        if cache is None:
            cache = self._infer_clone_cache = {}
        clone = cache.get(key)
        if clone is None:
            if len(cache) > 8:
                cache.clear()
            clone = cache[key] = program.clone(for_test=True)
        return self._run_from_dataset(
            clone, dataset, scope, fetch_list, fetch_info,
            print_period, debug,
        )

    def _run_from_dataset(self, program, dataset, scope, fetch_list,
                          fetch_info, print_period, debug):
        if dataset is None:
            raise ValueError("train_from_dataset needs a dataset")
        from .dataset import pad_batch

        program = program or framework.default_main_program()
        block = program.global_block
        fetch_names = [
            f.name if isinstance(f, framework.Variable) else str(f)
            for f in (fetch_list or [])
        ]
        labels = list(fetch_info or fetch_names)
        last_fetch = None
        for step, batch in enumerate(dataset):
            feed = {}
            for name, _is_float in dataset._slots:
                vals, lod = batch[name]
                lod = np.asarray(lod)
                lens = lod[1:] - lod[:-1]
                v = block._find_var_recursive(name)
                vshape = v.shape if v is not None and v.shape else None
                if (np.all(lens == 1) and vshape is not None
                        and len(vshape) >= 2 and vshape[-1] == 1):
                    # one value per sample: dense column (CTR labels)
                    feed[name] = vals.reshape(-1, 1)
                    continue
                # ragged slot -> padded dense [B, T]; T from the program's
                # declared dim, else bucketed to the next power of two so
                # the executor cache sees few distinct shapes
                T = None
                if vshape is not None and len(vshape) >= 2 and vshape[1] > 0:
                    T = int(vshape[1])
                dense, _mask = pad_batch(vals, lod, max_len=T)
                if T is None and dense.shape[1] > 0:
                    L = 1
                    while L < dense.shape[1]:
                        L *= 2
                    if L != dense.shape[1]:
                        pad = np.zeros(
                            (dense.shape[0], L - dense.shape[1]),
                            dense.dtype)
                        dense = np.concatenate([dense, pad], axis=1)
                feed[name] = dense
                lname = name + "_length"
                if block._find_var_recursive(lname) is not None:
                    feed[lname] = lens.astype(np.int64)
            out = self.run(program, feed=feed, fetch_list=fetch_names,
                           scope=scope)
            last_fetch = out
            if debug and fetch_names and step % max(print_period, 1) == 0:
                msg = ", ".join(
                    "%s=%s" % (lbl, np.asarray(val).reshape(-1)[:4])
                    for lbl, val in zip(labels, out)
                )
                print("[train_from_dataset] step %d: %s" % (step, msg))
        return last_fetch

    # convenience used by tests/io
    def run_startup(self, startup_program=None, scope=None):
        startup_program = startup_program or framework.default_startup_program()
        return self.run(startup_program, feed={}, fetch_list=[], scope=scope)


def scope_guard(scope):
    """cf. fluid.scope_guard."""
    import contextlib

    @contextlib.contextmanager
    def _guard():
        from .core import scope as scope_mod

        old = scope_mod._global_scope
        scope_mod._global_scope = scope
        try:
            yield
        finally:
            scope_mod._global_scope = old

    return _guard()
