"""Program visualization + pretty printing.

Capability parity: reference `python/paddle/fluid/debugger.py:1`
(`draw_block_graphviz` — ops and vars as a dot graph;
`pprint_program_codes` — C-like program listing) and
`framework/ir/graph_viz_pass.cc` (the pass-pipeline dot dumper)."""

from __future__ import annotations

from . import framework


def _esc(s):
    return str(s).replace('"', r"\"")


def draw_block_graphviz(block, highlights=None, path="./temp.dot"):
    """Write `block` as a graphviz dot file: op nodes (boxes) wired to var
    nodes (ellipses; parameters shaded).  Returns the path."""
    highlights = set(highlights or ())
    lines = [
        "digraph G {",
        "  rankdir=TB;",
        '  node [fontsize=10, fontname="Helvetica"];',
    ]
    var_nodes = {}

    def var_node(name):
        if name not in var_nodes:
            nid = "var_%d" % len(var_nodes)
            var_nodes[name] = nid
            v = block._find_var_recursive(name)
            shape = ""
            if v is not None and v.shape is not None:
                shape = r"\n%s %s" % (v.dtype, list(v.shape))
            style = 'style=filled, fillcolor="lightgrey", ' if (
                v is not None and getattr(v, "persistable", False)
            ) else ""
            extra = 'color="red", penwidth=2, ' if name in highlights else ""
            lines.append(
                '  %s [%s%sshape=ellipse, label="%s%s"];'
                % (nid, style, extra, _esc(name), shape)
            )
        return var_nodes[name]

    for i, op in enumerate(block.ops):
        op_id = "op_%d" % i
        lines.append(
            '  %s [shape=box, style=filled, fillcolor="lightblue", '
            'label="%s"];' % (op_id, _esc(op.type))
        )
        for name in op.all_input_names():
            lines.append("  %s -> %s;" % (var_node(name), op_id))
        for name in op.all_output_names():
            lines.append("  %s -> %s;" % (op_id, var_node(name)))
    lines.append("}")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def draw(program, path="./program.dot"):
    """Convenience: dot-dump a Program's global block."""
    if hasattr(program, "global_block"):
        return draw_block_graphviz(program.global_block, path=path)
    return draw_block_graphviz(program, path=path)


def pprint_program_codes(program):
    """C-like listing of every block (cf. reference pprint_program_codes);
    returns the string (the reference prints)."""
    out = []
    for blk in getattr(program, "blocks", [program.global_block]):
        out.append("block_%d {" % getattr(blk, "idx", 0))
        for v in sorted(getattr(blk, "vars", {}).values(),
                        key=lambda v: v.name):
            out.append(
                "  var %s : %s%s%s" % (
                    v.name, v.dtype,
                    list(v.shape) if v.shape is not None else "?",
                    "  // param" if getattr(v, "persistable", False) else "",
                )
            )
        for op in blk.ops:
            ins = ", ".join(
                "%s=%s" % (k, v) for k, v in sorted(op.inputs.items())
            )
            outs = ", ".join(
                "%s=%s" % (k, v) for k, v in sorted(op.outputs.items())
            )
            out.append("  %s := %s(%s)" % (outs, op.type, ins))
        out.append("}")
    return "\n".join(out)
