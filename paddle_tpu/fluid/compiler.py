"""CompiledProgram: opt-in compilation config + single-process data parallel.

Capability parity: reference `python/paddle/fluid/compiler.py` —
`CompiledProgram:87`, `with_data_parallel:160`, `_compile_data_parallel:310`
which constructs a `core.ParallelExecutor` (`parallel_executor.cc:443`): the
program is cloned per GPU, a build-strategy pass pipeline inserts per-grad
allreduce ops, and an SSA-graph executor drives the clones.

TPU-first redesign: there is nothing to clone and no allreduce to insert.
`with_data_parallel` marks the program for GSPMD batch sharding — the
executor device_puts every feed with a `NamedSharding` over a 1-axis "dp"
mesh of the local devices and lets XLA partition the one compiled program;
gradient reduction falls out of the partitioner (the mean over the global
batch becomes a psum), so the numerics are bit-identical to the same global
batch on one device.  BuildStrategy/ExecutionStrategy knobs that steer the
reference's pass pipeline are recorded for API parity; the ones that have an
XLA equivalent are honored, the transport-level ones are no-ops by design.
"""

from __future__ import annotations


class BuildStrategy:
    """cf. reference `details/build_strategy.cc`. Knobs with an XLA analogue
    are honored (memory_optimize/enable_inplace => donation, already the
    executor default); transport knobs are recorded, not emulated."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = (
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        )
        self.memory_optimize = True
        self.enable_inplace = True
        self.fuse_all_reduce_ops = False
        self.fuse_broadcast_ops = False
        self.fuse_elewise_add_act_ops = False
        self.sync_batch_norm = False
        self.num_trainers = 1
        self.trainer_id = 0

    def __repr__(self):
        return "BuildStrategy(%s)" % ", ".join(
            "%s=%r" % kv for kv in sorted(vars(self).items())
        )


class ExecutionStrategy:
    """cf. reference ExecutionStrategy (pybind.cc): thread counts and scope
    drop cadence.  XLA owns scheduling, so these only gate diagnostics."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 100
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False

    def __repr__(self):
        return "ExecutionStrategy(%s)" % ", ".join(
            "%s=%r" % kv for kv in sorted(vars(self).items())
        )


class CompiledProgram:
    """cf. reference `compiler.py:87`.

    Usage parity::

        compiled = fluid.CompiledProgram(main).with_data_parallel(
            loss_name=loss.name)
        exe.run(compiled, feed=..., fetch_list=[...])
    """

    def __init__(self, program_or_graph, build_strategy=None):
        from . import framework

        if not isinstance(program_or_graph, framework.Program):
            raise TypeError(
                "CompiledProgram expects a Program, got %r"
                % type(program_or_graph)
            )
        self._program = program_or_graph
        self._build_strategy = build_strategy or BuildStrategy()
        self._exec_strategy = None
        self._loss_name = None
        self._is_data_parallel = False
        self._places = None
        self._share_vars_from = None
        self._autotune = None          # with_autotune() config dict
        # (program version, fetch tuple, feed signature) -> tuned clone.
        # A tuned pipeline is only valid for the fetch set it was
        # searched with (DCE "keep" protects exactly those fetches) AND
        # the feed shapes it was timed at; a dict (not a single slot)
        # so loops alternating fetch sets reuse stable clone objects —
        # the executor's jit cache keys on id(program), so a fresh
        # clone per run would retrace every step
        self._tuned_programs = {}
        self._tune_report = None       # last SearchReport, for operators

    # -- configuration --------------------------------------------------
    def with_data_parallel(
        self,
        loss_name=None,
        build_strategy=None,
        exec_strategy=None,
        share_vars_from=None,
        places=None,
    ):
        if self._is_data_parallel:
            raise RuntimeError("with_data_parallel() called twice")
        self._is_data_parallel = True
        self._loss_name = loss_name
        if build_strategy is not None:
            self._build_strategy = build_strategy
        self._exec_strategy = exec_strategy or ExecutionStrategy()
        self._share_vars_from = share_vars_from
        self._places = places
        return self

    def with_autotune(self, cache_dir=None, budget_s=None, space=None,
                      k=3, warmup=1, use_cache=True):
        """Opt-in measured autotuning (``paddle_tpu.tune``): the FIRST
        Executor.run of this program searches pass pipelines (pruned by
        the static cost model, verified per pass, compiled-and-timed)
        and every later run executes the winning rewrite.  Winners
        persist in the tuning cache (keyed by program hash + mesh + chip
        + jax version), so a second process skips the search entirely.

        The search runs synchronously inside that first run —
        ``budget_s`` bounds it.  Donation/sharding are fixed to the
        executor's own conventions; the searched axis here is the
        pipeline."""
        self._autotune = {
            "cache_dir": cache_dir, "budget_s": budget_s, "space": space,
            "k": k, "warmup": warmup, "use_cache": use_cache,
        }
        return self

    def _ensure_tuned(self, feed_vals, fetch_names, mesh=None):
        """Run (or load) the search once per program version; returns
        the tuned clone the executor should run.  Called by
        Executor._run_impl on the autotune-enabled facade."""
        prog = self._program
        memo_key = (
            prog._version, tuple(fetch_names),
            tuple(sorted((n, tuple(a.shape), str(a.dtype))
                         for n, a in feed_vals.items())))
        cached = self._tuned_programs.get(memo_key)
        if cached is not None:
            return cached
        from ..tune import SearchSpace, search, tuned_program

        cfg = self._autotune
        space = cfg["space"] or SearchSpace(
            donate=(True,),    # the executor always donates state
            sharding=False)    # sharding comes from dist_attr/mesh setup
        report = search(
            prog, list(fetch_names),
            feed_specs={n: (a.shape, a.dtype)
                        for n, a in feed_vals.items()},
            mesh=mesh, space=space, k=cfg["k"], warmup=cfg["warmup"],
            budget_s=cfg["budget_s"], use_cache=cfg["use_cache"],
            cache_dir=cfg["cache_dir"])
        self._tune_report = report
        tuned = (tuned_program(prog, report, fetch_list=fetch_names)
                 if report.winner is not None else prog)
        if len(self._tuned_programs) >= 32:
            # evict stale program versions first, then oldest-inserted —
            # never a wholesale clear: live entries must keep their
            # object identity or the executor's id-keyed jit cache
            # retraces every alternating-shape step
            for k in [k for k in self._tuned_programs
                      if k[0] != prog._version]:
                del self._tuned_programs[k]
            while len(self._tuned_programs) >= 32:
                self._tuned_programs.pop(
                    next(iter(self._tuned_programs)))
        self._tuned_programs[memo_key] = tuned
        return tuned

    # -- executor protocol ----------------------------------------------
    def _unwrap_for_executor(self):
        return self._program

    def _dp_devices(self):
        """Resolve the local device list for batch sharding (None => off)."""
        if not self._is_data_parallel:
            return None
        import jax

        places = self._places
        if places is None:
            devs = list(jax.local_devices())
        else:
            all_devs = list(jax.local_devices())
            devs = [
                all_devs[p] if isinstance(p, int) else p.get_device()
                for p in places
            ]
        return devs if len(devs) > 1 else None

    def __getattr__(self, item):
        # transparent read-through so code written against Program attrs
        # (random_seed, blocks, clone, ...) keeps working on the facade
        return getattr(self.__dict__["_program"], item)
