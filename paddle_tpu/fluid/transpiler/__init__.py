"""Program transpilers (distributed program rewriting).

Capability parity: reference `python/paddle/fluid/transpiler/` —
collective.py (NCCL DP rewrite), distribute_transpiler.py (PS topology,
subsumed by GSPMD sharding — see distributed/sharding.py), and the
deprecated memory_optimization_transpiler (subsumed by XLA).
"""

from .collective import Collective, GradAllReduce, LocalSGD  # noqa: F401
