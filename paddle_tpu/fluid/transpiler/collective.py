"""Collective transpilers: rewrite a single-process program into an SPMD
data-parallel program.

Capability parity: reference `python/paddle/fluid/transpiler/collective.py`
— `Collective:36` (transpile: init rings + broadcast params),
`GradAllReduce:178` (insert c_allreduce_sum per grad + scale),
`LocalSGD:270` (periodic param averaging).

TPU-first: ring init and param broadcast are unnecessary (the executor
places replicated state once); what remains is the op rewrite itself.  The
rewritten program runs under the executor's mesh mode (shard_map over the
`dp` axis) where c_allreduce_sum lowers to `lax.psum` on ICI.
"""

from __future__ import annotations

from .. import framework
from ..framework import GRAD_SUFFIX, Operator


def _params_grads_of(block):
    """Find (param, grad_name) pairs: grads written by backward-role ops."""
    params = {p.name for p in block.all_parameters() if p.trainable}
    out = []
    for op in block.ops:
        if op.attrs.get("op_role") != "backward":
            continue
        for name in op.all_output_names():
            if name.endswith(GRAD_SUFFIX) and name[: -len(GRAD_SUFFIX)] in params:
                if name not in [g for _, g in out]:
                    out.append((name[: -len(GRAD_SUFFIX)], name))
    return out


class Collective:
    """Base rewriter (cf. reference Collective:36)."""

    def __init__(self, nrings=1):
        self.nrings = nrings
        self.nranks = 1

    def transpile(self, startup_program=None, main_program=None, rank=0,
                  endpoints=None, current_endpoint=None, wait_port=True):
        self.startup_program = startup_program or framework.default_startup_program()
        self.main_program = main_program or framework.default_main_program()
        eps = endpoints or ["127.0.0.1:6170"]
        self.nranks = len(eps)
        self.rank = rank
        self._transpile_startup_program()
        self._transpile_main_program()
        return self.main_program

    def _transpile_startup_program(self):
        # reference inits NCCL rings + broadcasts params here; under XLA the
        # executor's replicated placement covers both — nothing to emit.
        pass

    def _transpile_main_program(self):
        raise NotImplementedError


class GradAllReduce(Collective):
    """Insert grad scaling + c_allreduce_sum before the optimizer ops
    (cf. reference GradAllReduce:178 _insert_scale_loss_grad_ops +
    _insert_allreduce_ops)."""

    def _transpile_main_program(self):
        block = self.main_program.global_block
        if self.nranks <= 1:
            return
        pairs = _params_grads_of(block)
        if not pairs:
            return
        # insertion point: before the first optimize-role op
        insert_at = len(block.ops)
        for i, op in enumerate(block.ops):
            if op.attrs.get("op_role") == "optimize":
                insert_at = i
                break
        new_ops = []
        for _p, g in pairs:
            new_ops.append(Operator(
                block, "scale",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"scale": 1.0 / self.nranks, "op_role": "backward"},
            ))
            new_ops.append(Operator(
                block, "c_allreduce_sum",
                inputs={"X": [g]}, outputs={"Out": [g]},
                attrs={"ring_id": 0, "op_role": "backward"},
            ))
        block.ops[insert_at:insert_at] = new_ops
        self.main_program._bump()


class LocalSGD(Collective):
    """k-step local updates + periodic parameter averaging
    (cf. reference LocalSGD:270).  Emitted as a param-averaging program the
    caller runs every k steps (the reference weaves step-conditionals into
    the main program; a separate compiled program is the XLA-friendly
    equivalent — same capability, one extra executable)."""

    def __init__(self, nrings=1, k_steps=1):
        super().__init__(nrings)
        self.k_steps = k_steps

    def _transpile_main_program(self):
        # main program runs unmodified (local SGD); build the averaging
        # program on the side.
        avg = framework.Program()
        block = self.main_program.global_block
        ab = avg.global_block
        for p in block.all_parameters():
            ab.create_var(name=p.name, shape=p.shape, dtype=p.dtype,
                          persistable=True, stop_gradient=True)
            ab.ops.append(Operator(
                ab, "scale", inputs={"X": [p.name]}, outputs={"Out": [p.name]},
                attrs={"scale": 1.0 / self.nranks},
            ))
            ab.ops.append(Operator(
                ab, "c_allreduce_sum",
                inputs={"X": [p.name]}, outputs={"Out": [p.name]},
                attrs={"ring_id": 0},
            ))
        self.avg_program = avg
