"""Program / Block / Operator / Variable — the serializable graph IR.

Capability parity: reference `paddle/fluid/framework/framework.proto:42-178`
(ProgramDesc{BlockDesc{VarDesc, OpDesc}}) and the Python graph builder
`python/paddle/fluid/framework.py` (Variable:835, Operator:1822, Block:2391,
Program:3852, program_guard:5287).

TPU-first redesign:
  * An Operator does NOT carry a kernel; its type names an :class:`OpDef`
    in the registry whose lowering is a pure JAX function.  A whole Block
    lowers to one jaxpr and compiles to one XLA executable (executor.py).
  * Shape/dtype inference at graph-build time runs `jax.eval_shape` over the
    lowering — no per-op InferShape duplication.  Dynamic (batch) dims are
    declared as -1 and substituted with a sentinel extent during inference.
  * Serialization is a plain JSON document instead of protobuf; structure
    mirrors the proto (program -> blocks -> vars/ops) so tooling parity
    (save_inference_model, program printing) is straightforward.
"""

from __future__ import annotations

import contextlib
import copy
import json

import numpy as np

from . import unique_name
from .core import dtypes as dtypes_mod
from .core.registry import LowerContext, get_op_def

# Sentinel extent substituted for -1 dims during graph-time shape inference;
# a large prime so it never collides with a real layer dimension, letting us
# map it back to -1 in inferred output shapes.
_DYN_SENTINEL = 1031

GRAD_SUFFIX = "@GRAD"

# Op callsite provenance (cf. reference OpDesc "op_callstack" attr written
# by append_op): when enabled — set_flags({"FLAGS_op_callstack": True}) or
# analysis.provenance — every append_op records the user frames that built
# the op, so verifier/lint diagnostics and _infer_op errors point at the
# line of model code instead of framework internals.
_capture_op_callstack = False
OP_CALLSTACK_ATTR = "op_callstack"
_PKG_ROOT = None


def set_op_callstack_capture(enabled):
    """Toggle op provenance capture; returns the previous setting."""
    global _capture_op_callstack
    old = _capture_op_callstack
    _capture_op_callstack = bool(enabled)
    return old


def op_callstack_capture_enabled():
    return _capture_op_callstack


def _user_callsite(limit=3):
    """First `limit` stack frames OUTSIDE paddle_tpu, innermost first —
    the Python line(s) of user code that built the current op."""
    import os
    import sys

    global _PKG_ROOT
    if _PKG_ROOT is None:
        # .../paddle_tpu — every frame under it is framework internals
        _PKG_ROOT = os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))) + os.sep
    frames = []
    f = sys._getframe(2)
    while f is not None and len(frames) < limit:
        fn = f.f_code.co_filename
        if not fn.startswith(_PKG_ROOT):
            frames.append(
                "%s:%d (%s)" % (fn, f.f_lineno, f.f_code.co_name))
        f = f.f_back
    return frames


def _format_callsite(op):
    stack = op.attrs.get(OP_CALLSTACK_ATTR)
    if not stack:
        return ""
    return "\n  op built at: " + " <- ".join(stack)


def _format_op_input_structs(block, op):
    """'slot=[name(shape, dtype), ...]' summary for inference errors."""
    parts = []
    for slot, names in op.inputs.items():
        descs = []
        for n in names:
            v = block._find_var_recursive(n)
            if v is None:
                descs.append("%s(<undefined>)" % n)
            else:
                descs.append("%s(%s, %s)" % (n, v.shape, v.dtype))
        parts.append("%s=[%s]" % (slot, ", ".join(descs)))
    return "; ".join(parts) if parts else "<no inputs>"


class Variable:
    """A named tensor in a Block (cf. reference framework.py:835 / VarDesc)."""

    def __init__(
        self,
        block,
        name,
        shape=None,
        dtype="float32",
        persistable=False,
        stop_gradient=False,
        is_data=False,
    ):
        self.block = block
        self.name = name
        self.shape = tuple(int(s) for s in shape) if shape is not None else None
        self.dtype = dtypes_mod.to_str(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_data = is_data
        # GSPMD sharding annotation: a tuple of mesh axis names / None per
        # dim (PartitionSpec entries), or None = replicated.  The TPU-native
        # dist_attr: where the reference slices persistable vars into
        # VarBlocks across pservers (distribute_transpiler.py:80
        # slice_variable), this framework annotates the var and lets GSPMD
        # place the shards (honored by the mesh-mode Executor).
        self.dist_attr = None

    def set_dist_attr(self, *spec):
        """Annotate this var with a PartitionSpec-style sharding, e.g.
        `w.set_dist_attr(None, "tp")` = shard dim 1 over the tp mesh axis."""
        self.dist_attr = tuple(spec) if spec else None
        # annotations participate in compilation: invalidate cached
        # executables built from the old shardings
        self.block.program._bump()
        return self

    # -- helpers ------------------------------------------------------------
    def __bool__(self):
        # a static Variable has no value at build time; silently defaulting
        # to True would bake one branch of `if tensor:` into the program
        raise RuntimeError(
            "Cannot use a static-graph Variable '%s' as a Python bool. "
            "Use layers.cond / layers.while_loop, or decorate the function "
            "with @declarative so data-dependent control flow converts "
            "automatically." % self.name
        )

    @property
    def ndim(self):
        return len(self.shape) if self.shape is not None else None

    def astype(self, dtype):
        from .layers import tensor as tensor_layers

        return tensor_layers.cast(self, dtype)

    def _sds(self):
        """ShapeDtypeStruct with dynamic dims substituted (for eval_shape)."""
        import jax

        shape = tuple(_DYN_SENTINEL if s == -1 else s for s in (self.shape or ()))
        return jax.ShapeDtypeStruct(shape, dtypes_mod.to_jnp(self.dtype))

    def to_dict(self):
        d = {
            "name": self.name,
            "shape": list(self.shape) if self.shape is not None else None,
            "dtype": self.dtype,
            "persistable": self.persistable,
            "stop_gradient": self.stop_gradient,
            "is_data": self.is_data,
            "kind": "param" if isinstance(self, Parameter) else "var",
        }
        if isinstance(self, Parameter):
            d["trainable"] = self.trainable
            d["optimize_attr"] = self.optimize_attr
            d["need_clip"] = self.need_clip
        return d

    def __repr__(self):
        return "Variable(%s, shape=%s, dtype=%s%s)" % (
            self.name,
            self.shape,
            self.dtype,
            ", persistable" if self.persistable else "",
        )

    # Python operator sugar (cf. reference math_op_patch.py) -----------------
    def _binary(self, other, fn, reverse=False):
        from .layers import math_op_patch

        return math_op_patch.binary(self, other, fn, reverse)

    def __add__(self, o):
        return self._binary(o, "elementwise_add")

    def __radd__(self, o):
        return self._binary(o, "elementwise_add", True)

    def __sub__(self, o):
        return self._binary(o, "elementwise_sub")

    def __rsub__(self, o):
        return self._binary(o, "elementwise_sub", True)

    def __mul__(self, o):
        return self._binary(o, "elementwise_mul")

    def __rmul__(self, o):
        return self._binary(o, "elementwise_mul", True)

    def __truediv__(self, o):
        return self._binary(o, "elementwise_div")

    def __rtruediv__(self, o):
        return self._binary(o, "elementwise_div", True)

    def __pow__(self, o):
        return self._binary(o, "elementwise_pow")

    def __neg__(self):
        from .layers import ops as _ops

        return _ops.scale(self, scale=-1.0)

    def __matmul__(self, o):
        from .layers import nn as _nn

        return _nn.matmul(self, o)

    # comparisons build compare ops (needed by dygraph_to_static rewritten
    # conditions; __eq__ deliberately stays identity so Variables keep
    # working in sets/dicts — use layers.equal for elementwise equality)
    def _compare(self, other, op_type):
        from .layers import tensor as _t

        if not isinstance(other, Variable):
            # keep float operands exact even against int tensors (the
            # compare lowering promotes dtypes like numpy)
            if isinstance(other, float) and "int" in self.dtype:
                dt = "float32"
            elif isinstance(other, bool):
                dt = "bool"
            else:
                dt = self.dtype
            other = _t.fill_constant([1], dt, float(other))
        from .layers.common import append_simple_op

        return append_simple_op(
            op_type, {"X": self, "Y": other}, dtype="bool", stop_gradient=True
        )

    def __lt__(self, o):
        return self._compare(o, "less_than")

    def __le__(self, o):
        return self._compare(o, "less_equal")

    def __gt__(self, o):
        return self._compare(o, "greater_than")

    def __ge__(self, o):
        return self._compare(o, "greater_equal")


class Parameter(Variable):
    """Persistable trainable variable (cf. reference framework.py:4962)."""

    def __init__(self, block, name, shape, dtype="float32", **kw):
        self.trainable = kw.pop("trainable", True)
        self.optimize_attr = kw.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kw.pop("regularizer", None)
        self.need_clip = kw.pop("need_clip", True)
        self.is_distributed = kw.pop("is_distributed", False)
        super().__init__(
            block,
            name,
            shape=shape,
            dtype=dtype,
            persistable=True,
            stop_gradient=not self.trainable,
        )


class OpInputResolutionError(RuntimeError):
    """An op input name resolves to no Variable (raised during shape
    inference so callers can tell it apart from lowering failures)."""


class Operator:
    """One op invocation (cf. reference framework.py:1822 / OpDesc).

    inputs / outputs: {slot_name: [var_name, ...]} keyed by the OpDef's
    declared slots.  attrs: JSON-serializable static attributes.
    """

    def __init__(self, block, type, inputs=None, outputs=None, attrs=None):
        self.block = block
        self.type = type
        self.inputs = {k: list(v) for k, v in (inputs or {}).items()}
        self.outputs = {k: list(v) for k, v in (outputs or {}).items()}
        self.attrs = dict(attrs or {})

    def input(self, slot):
        return self.inputs.get(slot, [])

    def output(self, slot):
        return self.outputs.get(slot, [])

    def all_input_names(self):
        return [n for ns in self.inputs.values() for n in ns]

    def all_output_names(self):
        return [n for ns in self.outputs.values() for n in ns]

    def attr(self, name, default=None):
        return self.attrs.get(name, default)

    def to_dict(self):
        return {
            "type": self.type,
            "inputs": self.inputs,
            "outputs": self.outputs,
            "attrs": self.attrs,
        }

    def __repr__(self):
        return "{%s: (%s) -> (%s)}" % (
            self.type,
            ", ".join("%s=%s" % kv for kv in self.inputs.items()),
            ", ".join("%s=%s" % kv for kv in self.outputs.items()),
        )


class Block:
    """Ordered ops + var table (cf. reference framework.py:2391 / BlockDesc)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars: dict[str, Variable] = {}
        self.ops: list[Operator] = []

    # -- vars ---------------------------------------------------------------
    def create_var(self, name=None, **kw):
        name = name or unique_name.generate("tmp")
        if name in self.vars:
            return self.vars[name]
        v = Variable(self, name, **kw)
        self.vars[name] = v
        self.program._bump()
        return v

    def create_parameter(self, name, shape, dtype="float32", **kw):
        p = Parameter(self, name, shape, dtype=dtype, **kw)
        self.vars[name] = p
        self.program._bump()
        return p

    def var(self, name) -> Variable:
        v = self._find_var_recursive(name)
        if v is None:
            raise KeyError("variable '%s' not found in block %d" % (name, self.idx))
        return v

    def has_var(self, name):
        return self._find_var_recursive(name) is not None

    def _find_var_recursive(self, name):
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = self.program.blocks[b.parent_idx] if b.parent_idx >= 0 else None
        return None

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]

    # -- ops ----------------------------------------------------------------
    def append_op(self, type, inputs=None, outputs=None, attrs=None, infer=True):
        op = Operator(self, type, inputs, outputs, attrs)
        if _current_device is not None and "op_device" not in op.attrs:
            op.attrs["op_device"] = _current_device
        if _capture_op_callstack and OP_CALLSTACK_ATTR not in op.attrs:
            op.attrs[OP_CALLSTACK_ATTR] = _user_callsite()
        self.ops.append(op)
        if infer:
            self._infer_op(op)
        self.program._bump()
        return op

    def _prepend_op(self, type, inputs=None, outputs=None, attrs=None):
        op = Operator(self, type, inputs, outputs, attrs)
        self.ops.insert(0, op)
        self._infer_op(op)
        self.program._bump()
        return op

    def _eval_op_structs(self, op):
        """jax.eval_shape over the op's lowering: {out_slot: [SDS, ...]}.

        Shared by build-time `_infer_op` and the analysis verifier's
        whole-program shape re-inference (paddle_tpu.analysis.verifier) —
        one inference implementation, replayable over mutated programs."""
        import jax

        opdef = get_op_def(op.type)
        in_structs = {}
        for slot, names in op.inputs.items():
            structs = []
            for n in names:
                v = self._find_var_recursive(n)
                if v is None:
                    raise OpInputResolutionError(
                        "op '%s' reads var '%s' (slot %s) which is not "
                        "defined in block %d or its ancestors%s"
                        % (op.type, n, slot, self.idx, _format_callsite(op)))
                structs.append(v._sds())
            in_structs[slot] = structs

        def f(ins):
            ctx = LowerContext(base_key=None, is_test=True)
            # eval_shape never executes, so fake rng keys are fine:
            if opdef.needs_rng:
                ctx._base_key = jax.random.PRNGKey(0)
            return opdef.lower(ctx, ins, op.attrs)

        return jax.eval_shape(f, in_structs)

    def _infer_op(self, op):
        """Graph-time shape/dtype inference via jax.eval_shape on the lowering."""
        try:
            out_structs = self._eval_op_structs(op)
        except OpInputResolutionError:
            raise  # already carries the op/var/callsite context
        except Exception as e:
            raise RuntimeError(
                "shape inference failed for op %r: %s\n  with inputs: %s%s"
                % (op, e, _format_op_input_structs(self, op),
                   _format_callsite(op))
            ) from e

        for slot, names in op.outputs.items():
            if slot not in out_structs:
                raise RuntimeError(
                    "op '%s' lowering produced no slot '%s'" % (op.type, slot)
                )
            structs = out_structs[slot]
            for name, st in zip(names, structs):
                shape = tuple(-1 if s == _DYN_SENTINEL else s for s in st.shape)
                v = self._find_var_recursive(name)
                if v is None:
                    v = Variable(self, name)
                    self.vars[name] = v
                if v.shape is None or not v.persistable:
                    v.shape = shape
                    v.dtype = dtypes_mod.to_str(st.dtype)

    def to_dict(self):
        return {
            "idx": self.idx,
            "parent_idx": self.parent_idx,
            "vars": [v.to_dict() for v in self.vars.values()],
            "ops": [o.to_dict() for o in self.ops],
        }


class Program:
    """A serializable multi-block program (cf. reference framework.py:3852)."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.current_block_idx = 0
        self._version = 0
        self.random_seed = None
        self._is_test = False

    # -- structure ----------------------------------------------------------
    @property
    def global_block(self) -> Block:
        return self.blocks[0]

    def current_block(self) -> Block:
        return self.blocks[self.current_block_idx]

    def _create_block(self, parent_idx=None):
        parent = self.current_block_idx if parent_idx is None else parent_idx
        b = Block(self, len(self.blocks), parent)
        self.blocks.append(b)
        self.current_block_idx = b.idx
        return b

    def _rollback(self):
        self.current_block_idx = self.blocks[self.current_block_idx].parent_idx

    def _bump(self):
        self._version += 1

    def all_parameters(self):
        return [p for b in self.blocks for p in b.all_parameters()]

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    # -- transforms ---------------------------------------------------------
    def clone(self, for_test=False):
        """Deep copy; for_test=True flips is_test attrs and prunes optimizer
        ops (cf. reference Program.clone(for_test=True))."""
        p = Program.__new__(Program)
        p.blocks = []
        p.current_block_idx = self.current_block_idx
        p._version = 0
        p.random_seed = self.random_seed
        p._is_test = for_test or self._is_test
        # eval clones must keep the GSPMD execution mode (dist_attr carries
        # over via copy.copy below; the flag must follow it)
        if getattr(self, "_gspmd", False):
            p._gspmd = True
        # pipeline marker carries over too: an eval clone on a pp mesh
        # still runs the staged forward (no grads/updates)
        if getattr(self, "_pipeline", None):
            p._pipeline = dict(self._pipeline)
        from .ops import OPTIMIZER_OP_TYPES

        for b in self.blocks:
            nb = Block(p, b.idx, b.parent_idx)
            for v in b.vars.values():
                nv = copy.copy(v)
                nv.block = nb
                nb.vars[v.name] = nv
            for o in b.ops:
                # prune backward + optimize ops (cf. reference clone(for_test)
                # docstring / OpRole tagging); OPTIMIZER_OP_TYPES is a
                # fallback for hand-appended update ops without a role attr
                if for_test and (
                    o.attrs.get("op_role") in ("backward", "optimize")
                    or o.type in OPTIMIZER_OP_TYPES
                ):
                    continue
                no = Operator(nb, o.type, o.inputs, o.outputs, o.attrs)
                if for_test and "is_test" in no.attrs:
                    no.attrs["is_test"] = True
                nb.ops.append(no)
            p.blocks.append(nb)
        if for_test:
            # pruning backward/optimizer ops strands their grad vars; drop
            # entries no kept op references so eval clones stay
            # orphan-clean (shared sweep matching the verifier's
            # orphan-var exemptions)
            from ..analysis import opgraph

            opgraph.drop_orphan_vars(p)
        p._bump()
        return p

    # -- serialization ------------------------------------------------------
    def to_dict(self):
        return {
            "version": 1,
            "blocks": [b.to_dict() for b in self.blocks],
            "random_seed": self.random_seed,
        }

    def to_json(self):
        return json.dumps(self.to_dict())

    @staticmethod
    def from_dict(d) -> "Program":
        p = Program.__new__(Program)
        p.blocks = []
        p.current_block_idx = 0
        p._version = 0
        p.random_seed = d.get("random_seed")
        p._is_test = False
        for bd in d["blocks"]:
            b = Block(p, bd["idx"], bd["parent_idx"])
            for vd in bd["vars"]:
                kw = dict(
                    shape=vd["shape"],
                    dtype=vd["dtype"],
                )
                if vd.get("kind") == "param":
                    v = Parameter(
                        b,
                        vd["name"],
                        trainable=vd.get("trainable", True),
                        optimize_attr=vd.get("optimize_attr", {"learning_rate": 1.0}),
                        need_clip=vd.get("need_clip", True),
                        **kw,
                    )
                else:
                    v = Variable(
                        b,
                        vd["name"],
                        persistable=vd["persistable"],
                        stop_gradient=vd["stop_gradient"],
                        is_data=vd.get("is_data", False),
                        **kw,
                    )
                b.vars[vd["name"]] = v
            for od in bd["ops"]:
                b.ops.append(
                    Operator(b, od["type"], od["inputs"], od["outputs"], od["attrs"])
                )
            p.blocks.append(b)
        return p

    @staticmethod
    def from_json(s) -> "Program":
        return Program.from_dict(json.loads(s))

    def __str__(self):
        lines = []
        for b in self.blocks:
            lines.append("-- block %d (parent %d) --" % (b.idx, b.parent_idx))
            for v in b.vars.values():
                lines.append("  " + repr(v))
            for o in b.ops:
                lines.append("  " + repr(o))
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Default-program machinery (cf. reference framework.py:5287 program_guard)
# ---------------------------------------------------------------------------

_main_program = Program()
_startup_program = Program()


def default_main_program() -> Program:
    return _main_program


def default_startup_program() -> Program:
    return _startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    global _main_program, _startup_program
    old_main, old_startup = _main_program, _startup_program
    _main_program = main_program
    if startup_program is not None:
        _startup_program = startup_program
    try:
        yield
    finally:
        _main_program = old_main
        _startup_program = old_startup


def reset_default_programs():
    """Fresh default programs (test helper; cf. unique_name.guard usage)."""
    global _main_program, _startup_program
    _main_program = Program()
    _startup_program = Program()


_current_device = None


@contextlib.contextmanager
def device_guard(device=None):
    """Tag ops appended inside with an `op_device` attr (cf. reference
    framework.py:5420).  Accepted forms mirror the reference: "cpu",
    "gpu:N" (and "tpu:N" as the native spelling) — the pipeline
    partitioner reads the :N suffix as the STAGE index; the executor
    itself places nothing (XLA owns placement), so the annotation is
    purely a partitioning directive."""
    global _current_device
    if device is not None and device != "cpu":
        dev, _, idx = device.partition(":")
        if dev not in ("gpu", "tpu", "xpu") or not idx.isdigit():
            raise ValueError(
                "device_guard expects 'cpu' or '<gpu|tpu|xpu>:<index>', "
                "got %r" % device)
    old = _current_device
    _current_device = device
    try:
        yield
    finally:
        _current_device = old


def device_stage_index(op_device):
    """Stage index from an op_device annotation, or None."""
    if not op_device or op_device == "cpu":
        return None
    _, _, idx = op_device.partition(":")
    return int(idx) if idx.isdigit() else None


_dygraph_tracer = None


def in_dygraph_mode():
    return _dygraph_tracer is not None


def grad_var_name(name):
    return name + GRAD_SUFFIX


def np_dtype_of(var):
    return np.dtype(dtypes_mod.to_str(var.dtype))
