"""Runtime flag system: set_flags / get_flags.

Capability parity: reference gflags plumbing — `platform/flags.cc` (26
DEFINEs), `fluid.set_flags/get_flags` (`framework.py:5480,5503`) via
`pybind/global_value_getter_setter.cc`, env seeding by `InitGflags`
(`init.cc:63`).

TPU mapping: numeric-debug flags wire into jax config (debug_nans covers
FLAGS_check_nan_inf, cf. `details/nan_inf_utils_detail.cc`); allocator and
GPU-memory knobs are accepted and recorded — XLA owns device memory, so
they are observability no-ops (documented per flag).
"""

from __future__ import annotations

import os

# flag -> (default, handler or None)
_HANDLERS = {}
_VALUES = {
    # numerics / debugging
    "FLAGS_check_nan_inf": False,           # -> jax_debug_nans
    "FLAGS_enable_unused_var_check": False,
    "FLAGS_benchmark": False,
    # static analysis (paddle_tpu.analysis)
    "FLAGS_verify_program": False,   # Executor.run verifies on first run
    "FLAGS_op_callstack": False,     # append_op records user callsites
    "FLAGS_verify_io_programs": True,  # save/load_inference_model verify
    # memory knobs (XLA BFC owns memory; recorded, no-op)
    "FLAGS_fraction_of_gpu_memory_to_use": 0.92,
    "FLAGS_allocator_strategy": "auto_growth",
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_fast_eager_deletion_mode": True,
    "FLAGS_memory_fraction_of_eager_deletion": 1.0,
    # execution
    "FLAGS_use_mkldnn": False,
    "FLAGS_paddle_num_threads": 1,
    "FLAGS_inner_op_parallelism": 0,
    # rng
    "FLAGS_cudnn_deterministic": True,
}


def _set_debug_nans(value):
    import jax

    jax.config.update("jax_debug_nans", bool(value))


_HANDLERS["FLAGS_check_nan_inf"] = _set_debug_nans


def _set_op_callstack(value):
    from . import framework

    framework.set_op_callstack_capture(bool(value))


_HANDLERS["FLAGS_op_callstack"] = _set_op_callstack


def set_flags(flags: dict):
    """cf. fluid.set_flags (framework.py:5480)."""
    for name, value in flags.items():
        if name not in _VALUES:
            raise ValueError("unknown flag %r (known: %s...)"
                             % (name, sorted(_VALUES)[:8]))
        _VALUES[name] = value
        h = _HANDLERS.get(name)
        if h is not None:
            h(value)


def get_flags(flags):
    """cf. fluid.get_flags (framework.py:5503)."""
    if isinstance(flags, str):
        flags = [flags]
    out = {}
    for name in flags:
        if name not in _VALUES:
            raise ValueError("unknown flag %r" % name)
        out[name] = _VALUES[name]
    return out


def init_from_env():
    """Seed flags from the environment (cf. InitGflags init.cc:63)."""
    for name in _VALUES:
        if name in os.environ:
            raw = os.environ[name]
            cur = _VALUES[name]
            if isinstance(cur, bool):
                val = raw.lower() in ("1", "true", "yes")
            elif isinstance(cur, float):
                val = float(raw)
            elif isinstance(cur, int):
                val = int(raw)
            else:
                val = raw
            set_flags({name: val})


init_from_env()
