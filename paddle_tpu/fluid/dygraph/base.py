"""Dygraph mode switches: guard, to_variable, no_grad, enable/disable.

Capability parity: reference `python/paddle/fluid/dygraph/base.py`
(`guard`, `to_variable`, `no_grad`, `enabled`).
"""

from __future__ import annotations

import contextlib
import functools

import numpy as np

from .. import framework
from .tracer import Tracer
from .varbase import VarBase


def enabled():
    return framework.in_dygraph_mode()


def enable_dygraph(place=None):
    if framework._dygraph_tracer is None:
        framework._dygraph_tracer = Tracer()


def disable_dygraph():
    framework._dygraph_tracer = None


@contextlib.contextmanager
def guard(place=None):
    """cf. fluid.dygraph.guard — activates eager mode within the block."""
    old = framework._dygraph_tracer
    framework._dygraph_tracer = Tracer()
    try:
        yield
    finally:
        framework._dygraph_tracer = old


def to_variable(value, name=None, zero_copy=None, stop_gradient=True):
    """numpy/jax array -> eager VarBase (cf. reference base.py to_variable)."""
    if isinstance(value, VarBase):
        return value
    if isinstance(value, framework.Variable):
        raise TypeError("to_variable expects an array, got a static Variable")
    return VarBase(np.asarray(value) if not hasattr(value, "dtype") else value,
                   name=name, stop_gradient=stop_gradient)


class no_grad:
    """Context-manager AND decorator disabling tape recording
    (cf. reference dygraph.base.no_grad)."""

    def __enter__(self):
        tracer = framework._dygraph_tracer
        self._old = tracer._has_grad if tracer is not None else None
        if tracer is not None:
            tracer._has_grad = False
        return self

    def __exit__(self, *exc):
        tracer = framework._dygraph_tracer
        if tracer is not None and self._old is not None:
            tracer._has_grad = self._old
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*a, **kw):
            with no_grad():
                return fn(*a, **kw)

        return wrapper
