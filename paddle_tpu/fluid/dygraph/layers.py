"""Layer: the dygraph module base class.

Capability parity: reference `python/paddle/fluid/dygraph/layers.py:60`
(Layer: lazy parameter dict, sublayer tree, hooks, state_dict,
train/eval, `__call__:583`).

Works in BOTH modes (the 2.0 design): parameters are eager ParamBase in
dygraph mode and static Parameters otherwise, created through LayerHelper;
forward() composes fluid.layers functions which dispatch per mode.  A
dygraph Layer's forward is jax-traceable, so `jax.jit(layer)` and
`functional_call` (params-as-pytree application, used by the distributed
train-step builder) both work.
"""

from __future__ import annotations

import collections

import jax.numpy as jnp
import numpy as np

from .. import framework
from ..layer_helper import LayerHelper, ParamAttr
from .varbase import ParamBase, VarBase


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self._full_name = name_scope or type(self).__name__.lower()
        self._dtype = dtype
        self._parameters = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self.training = True

    # -- mode ------------------------------------------------------------
    def train(self):
        self.training = True
        tracer = framework._dygraph_tracer
        if tracer is not None:
            tracer.train_mode = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        tracer = framework._dygraph_tracer
        if tracer is not None:
            tracer.train_mode = False
        for l in self.sublayers():
            l.training = False
        return self

    def full_name(self):
        return self._full_name

    # -- parameter creation ---------------------------------------------
    def create_parameter(
        self,
        shape,
        attr=None,
        dtype=None,
        is_bias=False,
        default_initializer=None,
    ):
        helper = LayerHelper(self._full_name)
        return helper.create_parameter(
            attr,
            list(shape),
            dtype=dtype or self._dtype,
            is_bias=is_bias,
            default_initializer=default_initializer,
        )

    def register_buffer(self, name, value, persistable=True):
        if not isinstance(value, VarBase) and value is not None:
            value = VarBase(value, stop_gradient=True, persistable=persistable)
        self._buffers[name] = value
        return value

    # -- tree ------------------------------------------------------------
    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def add_parameter(self, name, parameter):
        self._parameters[str(name)] = parameter
        return parameter

    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, (ParamBase, framework.Parameter)):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            table = self.__dict__.get(d)
            if table is not None and name in table:
                return table[name]
        raise AttributeError(
            "'%s' object has no attribute '%s'" % (type(self).__name__, name)
        )

    def __delattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            table = self.__dict__.get(d)
            if table is not None and name in table:
                del table[name]
                return
        object.__delattr__(self, name)

    def children(self):
        yield from self._sub_layers.values()

    def named_children(self):
        yield from self._sub_layers.items()

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for l in self._sub_layers.values():
            out.extend(l.sublayers(include_self=True))
        return out

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(include_sublayers)]

    def named_parameters(self, include_sublayers=True, prefix=""):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + name if not prefix else prefix + "." + name), p
        if include_sublayers:
            for lname, l in self._sub_layers.items():
                sub_prefix = prefix + "." + lname if prefix else lname
                for n, p in l.named_parameters(True, sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield n, p

    def named_buffers(self, prefix=""):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + "." + name if prefix else name), b
        for lname, l in self._sub_layers.items():
            sub_prefix = prefix + "." + lname if prefix else lname
            yield from l.named_buffers(sub_prefix)

    # -- hooks -----------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = _HookHandle(self._forward_pre_hooks, len(self._forward_pre_hooks))
        self._forward_pre_hooks[handle.idx] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = _HookHandle(self._forward_post_hooks, len(self._forward_post_hooks))
        self._forward_post_hooks[handle.idx] = hook
        return handle

    # -- run -------------------------------------------------------------
    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, outputs)
            if result is not None:
                outputs = result
        return outputs

    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    # -- state dict ------------------------------------------------------
    def state_dict(self, include_sublayers=True):
        d = collections.OrderedDict()
        for name, p in self.named_parameters(include_sublayers):
            d[name] = p
        for name, b in self.named_buffers():
            d[name] = b
        return d

    def set_state_dict(self, state_dict, include_sublayers=True):
        own = self.state_dict(include_sublayers)
        missing = [k for k in own if k not in state_dict]
        if missing:
            import warnings

            warnings.warn(
                "set_state_dict: %d parameter(s) missing from the "
                "checkpoint were left at their current values: %s%s"
                % (len(missing), ", ".join(missing[:5]),
                   "..." if len(missing) > 5 else ""),
                stacklevel=2,
            )
        for name, var in own.items():
            if name not in state_dict:
                continue
            value = state_dict[name]
            arr = value.data if isinstance(value, VarBase) else np.asarray(value)
            if tuple(arr.shape) != tuple(var.shape):
                raise ValueError(
                    "shape mismatch for '%s': checkpoint %s vs layer %s"
                    % (name, tuple(arr.shape), tuple(var.shape))
                )
            if isinstance(var, VarBase):
                var.data = jnp.asarray(arr, dtype=var.data.dtype)
            else:  # static-mode Parameter: write into the scope
                from ..core.scope import global_scope

                global_scope().set(var.name, jnp.asarray(arr))
        return missing

    # reference aliases
    set_dict = set_state_dict
    load_dict = set_state_dict

    def clear_gradients(self):
        for p in self.parameters():
            if isinstance(p, VarBase):
                p.clear_gradient()

    # -- functional application (TPU-native extension) -------------------
    def functional_call(self, params, *args, **kwargs):
        """Run forward with parameter arrays taken from ``params``
        ({name: array}, as produced by ``{k: v.data for k, v in
        layer.state_dict().items()}``).  Pure w.r.t. the layer's own state,
        so it is safe to `jax.jit` / `jax.grad` over: used by the
        distributed train-step builder (parallel/ package)."""
        sd = self.state_dict()
        saved = {}
        try:
            for name, arr in params.items():
                var = sd.get(name)
                if var is None:
                    raise KeyError("unknown parameter '%s'" % name)
                saved[name] = var.data
                var.data = arr
            return self(*args, **kwargs)
        finally:
            for name, arr in saved.items():
                sd[name].data = arr


class _HookHandle:
    def __init__(self, table, idx):
        self._table = table
        self.idx = idx

    def remove(self):
        self._table.pop(self.idx, None)
