"""Dygraph learning-rate schedulers.

Capability parity: reference `python/paddle/fluid/dygraph/
learning_rate_scheduler.py` — LearningRateDecay base (step() per call),
NoamDecay, PiecewiseDecay, NaturalExpDecay, ExponentialDecay,
InverseTimeDecay, PolynomialDecay, CosineDecay, LinearLrWarmup,
ReduceLROnPlateau.

The optimizer accepts an instance as `learning_rate`; each minimize() call
reads the current value (step advances when the user calls
scheduler.step() — reference epoch-driven semantics — or automatically per
minimize for the step-driven decays, matching reference step_num
bookkeeping).
"""

from __future__ import annotations

import math


class LearningRateDecay:
    def __init__(self, begin=0, step=1):
        self.step_num = begin
        self.step_size = step

    def step(self):
        """Advance (cf. reference: called once per optimizer step/epoch)."""
        self.step_num += self.step_size

    def __call__(self):
        """Advance-and-read (reference __call__ semantics: the optimizer
        invokes this once per minimize)."""
        self.step_num += self.step_size
        return float(self.get_lr())

    def get_lr(self):
        raise NotImplementedError


class NoamDecay(LearningRateDecay):
    """cf. reference NoamDecay: lr = d^-0.5 * min(n^-0.5, n * warmup^-1.5)."""

    def __init__(self, d_model, warmup_steps, learning_rate=1.0, begin=1, step=1):
        super().__init__(begin=max(begin, 1), step=step)
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        self.base = learning_rate

    def get_lr(self):
        n = max(self.step_num, 1)
        return (self.base * self.d_model ** -0.5
                * min(n ** -0.5, n * self.warmup_steps ** -1.5))


class PiecewiseDecay(LearningRateDecay):
    def __init__(self, boundaries, values, begin=0, step=1):
        super().__init__(begin, step)
        self.boundaries = list(boundaries)
        self.values = list(values)

    def get_lr(self):
        for b, v in zip(self.boundaries, self.values):
            if self.step_num < b:
                return v
        return self.values[len(self.boundaries)]


class ExponentialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, decay_rate,
                 staircase=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr0, self.decay_steps = learning_rate, decay_steps
        self.decay_rate, self.staircase = decay_rate, staircase

    def get_lr(self):
        p = self.step_num / self.decay_steps
        if self.staircase:
            p = math.floor(p)
        return self.lr0 * self.decay_rate ** p


class NaturalExpDecay(ExponentialDecay):
    def get_lr(self):
        p = self.step_num / self.decay_steps
        if self.staircase:
            p = math.floor(p)
        return self.lr0 * math.exp(-self.decay_rate * p)


class InverseTimeDecay(ExponentialDecay):
    def get_lr(self):
        p = self.step_num / self.decay_steps
        if self.staircase:
            p = math.floor(p)
        return self.lr0 / (1 + self.decay_rate * p)


class PolynomialDecay(LearningRateDecay):
    def __init__(self, learning_rate, decay_steps, end_learning_rate=1e-4,
                 power=1.0, cycle=False, begin=0, step=1):
        super().__init__(begin, step)
        self.lr0, self.decay_steps = learning_rate, decay_steps
        self.end_lr, self.power, self.cycle = end_learning_rate, power, cycle

    def get_lr(self):
        n = self.step_num
        steps = self.decay_steps
        if self.cycle:
            div = max(1.0, math.ceil(n / steps))
            steps = steps * div
        else:
            n = min(n, steps)
        return ((self.lr0 - self.end_lr)
                * (1 - n / steps) ** self.power + self.end_lr)


class CosineDecay(LearningRateDecay):
    def __init__(self, learning_rate, step_each_epoch, epochs, begin=0, step=1):
        super().__init__(begin, step)
        self.lr0 = learning_rate
        self.step_each_epoch = step_each_epoch
        self.epochs = epochs

    def get_lr(self):
        epoch = self.step_num // self.step_each_epoch
        return self.lr0 / 2 * (math.cos(epoch * math.pi / self.epochs) + 1)


class LinearLrWarmup(LearningRateDecay):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 begin=0, step=1):
        super().__init__(begin, step)
        self.wrapped = learning_rate  # float or LearningRateDecay
        self.warmup_steps = warmup_steps
        self.start_lr, self.end_lr = start_lr, end_lr

    def get_lr(self):
        if self.step_num < self.warmup_steps:
            return (self.start_lr
                    + (self.end_lr - self.start_lr)
                    * self.step_num / self.warmup_steps)
        if isinstance(self.wrapped, LearningRateDecay):
            return self.wrapped.get_lr()
        return float(self.wrapped)


class ReduceLROnPlateau(LearningRateDecay):
    """cf. reference ReduceLROnPlateau: shrink lr when a metric stalls."""

    def __init__(self, learning_rate, mode="min", decay_rate=0.1, patience=10,
                 threshold=1e-4, cooldown=0, min_lr=0.0):
        super().__init__()
        self.lr = float(learning_rate)
        self.mode = mode
        self.decay_rate = decay_rate
        self.patience = patience
        self.threshold = threshold
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0

    def get_lr(self):
        return self.lr

    def __call__(self):
        return self.lr  # advances only via step(metric)

    def step(self, metric=None):
        if metric is None:
            return
        metric = float(metric)
        better = (
            self.best is None
            or (self.mode == "min" and metric < self.best - self.threshold)
            or (self.mode == "max" and metric > self.best + self.threshold)
        )
        if better:
            self.best = metric
            self.num_bad = 0
        elif self.cooldown_counter > 0:
            self.cooldown_counter -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.lr = max(self.lr * self.decay_rate, self.min_lr)
                self.cooldown_counter = self.cooldown
                self.num_bad = 0
