"""save_dygraph / load_dygraph: eager state-dict checkpointing.

Capability parity: reference `python/paddle/fluid/dygraph/checkpoint.py`
(save_dygraph -> .pdparams / .pdopt npz-style files, load_dygraph returns
(param_dict, opt_dict)).
"""

from __future__ import annotations

import os
import pickle

import numpy as np

from .varbase import VarBase


def _to_numpy_dict(state_dict):
    out = {}
    for k, v in state_dict.items():
        out[k] = np.asarray(v.data) if isinstance(v, VarBase) else np.asarray(v)
    return out


def save_dygraph(state_dict, model_path):
    """cf. reference save_dygraph: writes <path>.pdparams (or .pdopt when the
    dict looks like optimizer state)."""
    base = str(model_path)
    if base.endswith(".pdparams") or base.endswith(".pdopt"):
        base = base.rsplit(".", 1)[0]
    is_opt = any(not isinstance(v, VarBase) and not hasattr(v, "shape")
                 for v in state_dict.values())
    suffix = ".pdopt" if is_opt else ".pdparams"
    d = os.path.dirname(base)
    if d:
        os.makedirs(d, exist_ok=True)
    payload = {}
    for k, v in state_dict.items():
        payload[k] = np.asarray(v.data) if isinstance(v, VarBase) else v
    with open(base + suffix, "wb") as f:
        pickle.dump(payload, f, protocol=2)


def load_dygraph(model_path):
    """cf. reference load_dygraph -> (param_dict, opt_dict)."""
    base = str(model_path)
    if base.endswith(".pdparams") or base.endswith(".pdopt"):
        base = base.rsplit(".", 1)[0]
    params, opt = None, None
    if os.path.exists(base + ".pdparams"):
        with open(base + ".pdparams", "rb") as f:
            params = pickle.load(f)
    if os.path.exists(base + ".pdopt"):
        with open(base + ".pdopt", "rb") as f:
            opt = pickle.load(f)
    if params is None and opt is None:
        raise ValueError("no checkpoint found at '%s(.pdparams|.pdopt)'" % base)
    return params, opt
