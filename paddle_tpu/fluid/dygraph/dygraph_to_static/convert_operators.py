"""Runtime dispatchers the AST transformer targets (`_jst.*` calls).

Capability parity: reference
`python/paddle/fluid/dygraph/dygraph_to_static/convert_operators.py`
(convert_ifelse, convert_while_loop, convert_logical_*) — each decides at
RUNTIME whether the rewritten construct sees a tensor (→ emit
layers.cond / layers.while_loop into the program) or a plain Python value
(→ keep native Python semantics), so one transformed source serves both.
"""

from __future__ import annotations

from ... import framework
from ...framework import Variable


class _Undefined:
    """Sentinel for names possibly unbound before a branch assigns them
    (reference UndefinedVar, `dygraph_to_static/utils.py`)."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        raise NameError(
            "variable is undefined on this control-flow path (assigned in "
            "only one branch of a converted if/loop)"
        )


UNDEF = _Undefined()


def _is_tensor(x):
    from ..varbase import VarBase

    return isinstance(x, (Variable, VarBase))


def _as_py_bool(x):
    from ..varbase import VarBase

    if isinstance(x, VarBase):
        return bool(x.numpy())
    return bool(x)


def convert_ifelse(pred, true_fn, false_fn, names, orig_vals):
    """`if` rewritten by IfElseTransformer.  true_fn/false_fn take the
    current values of `names` (the union of names either branch assigns)
    and return one value per name."""
    if isinstance(pred, Variable) and not framework.in_dygraph_mode():
        from ...layers import control_flow

        holder = {}

        def _is_var_tuple(v):
            # a tuple/list slot carrying at least one tensor (mixed
            # tensor/python-scalar tuples count: the scalars must agree
            # between branches, checked at stitch time)
            return (isinstance(v, (tuple, list)) and v
                    and any(isinstance(e, Variable) for e in v))

        def wrap(fn, tag, lift):
            def inner():
                vals = list(fn(*orig_vals))
                if lift:
                    vals = [
                        _lift_scalar(v)
                        if isinstance(v, (bool, int, float)) else v
                        for v in vals
                    ]
                holder[tag] = vals
                flat = []
                for v in vals:
                    if isinstance(v, Variable):
                        flat.append(v)
                    elif _is_var_tuple(v):
                        # a structured slot (e.g. `return a, b` merged by
                        # the return rewrite): its tensors ride the cond
                        # outputs and the structure rebuilds at stitch
                        flat.extend(e for e in v if isinstance(e, Variable))
                return flat

            return inner

        try:
            outs = control_flow.cond(
                pred, wrap(true_fn, "t", False), wrap(false_fn, "f", False)
            )
        except ValueError:
            # a slot is a python scalar in one branch but a tensor in the
            # other (e.g. an already-promoted break flag): lift scalars and
            # trace again so both branches return matching structures
            try:
                outs = control_flow.cond(
                    pred, wrap(true_fn, "t", True), wrap(false_fn, "f", True)
                )
            except ValueError as e:
                raise TypeError(
                    "@declarative: branches of a data-dependent `if` "
                    "produce incompatible values for %s — a variable is "
                    "likely undefined or non-scalar in exactly one branch"
                    % (names,)
                ) from e
        if isinstance(outs, Variable):
            outs = [outs]
        outs = list(outs) if outs is not None else []
        t_vals, f_vals = holder["t"], holder["f"]
        # stitch: tensor slots take the cond output; python slots must agree
        # between branches (they were computed at trace time, not runtime)
        result, oi = [], 0
        for i, name in enumerate(names):
            tv, fv = t_vals[i], f_vals[i]
            t_tensor, f_tensor = isinstance(tv, Variable), isinstance(fv, Variable)
            if _is_var_tuple(tv) or _is_var_tuple(fv):
                ok = (type(tv) is type(fv)
                      and _is_var_tuple(tv) and _is_var_tuple(fv)
                      and len(tv) == len(fv)
                      and all(isinstance(a, Variable)
                              == isinstance(b, Variable)
                              for a, b in zip(tv, fv)))
                if ok:
                    def _same(a, b):
                        if a is b:
                            return True
                        try:
                            return bool(a == b)
                        except Exception:
                            return False   # ambiguous (e.g. ndarray)

                    rebuilt = []
                    for a, b in zip(tv, fv):
                        if isinstance(a, Variable):
                            rebuilt.append(outs[oi])
                            oi += 1
                        elif _same(a, b):  # python element: must agree
                            rebuilt.append(a)
                        else:
                            ok = False
                            break
                if not ok:
                    raise TypeError(
                        "@declarative: variable '%s' is a tensor "
                        "tuple/list of mismatched structure between "
                        "branches of a data-dependent `if` (%r vs %r); "
                        "tensor positions and python elements must match"
                        % (name, tv, fv)
                    )
                result.append(type(tv)(rebuilt))
                continue
            if t_tensor != f_tensor:
                raise TypeError(
                    "@declarative: variable '%s' is a tensor in one branch "
                    "of a data-dependent `if` but not the other — both "
                    "branches must produce the same kind" % name
                )
            if t_tensor:
                # cond emitted both branches; outputs align in true-branch
                # tensor order, which equals false-branch order here
                result.append(outs[oi])
                oi += 1
            else:
                if tv is UNDEF and fv is UNDEF:
                    result.append(UNDEF)
                elif (
                    isinstance(tv, (bool, int, float))
                    and isinstance(fv, (bool, int, float))
                    and tv != fv
                ):
                    # differing python scalars under a tensor pred (e.g. a
                    # break flag): lift to a runtime select
                    result.append(
                        control_flow.cond(
                            pred,
                            lambda v=tv: _lift_scalar(v),
                            lambda v=fv: _lift_scalar(v),
                        )
                    )
                elif tv is UNDEF or fv is UNDEF or tv != fv:
                    raise TypeError(
                        "@declarative: non-tensor variable '%s' differs "
                        "between branches of a data-dependent `if` (%r vs "
                        "%r); make it a tensor or hoist it out" % (name, tv, fv)
                    )
                else:
                    result.append(tv)
        return tuple(result)
    # python / eager path: real short-circuit semantics
    return tuple(
        true_fn(*orig_vals) if _as_py_bool(pred) else false_fn(*orig_vals)
    )


def convert_while_loop(cond_fn, body_fn, loop_vars, names):
    """`while` rewritten by LoopTransformer.

    A loop may PROMOTE mid-trace: iterations run in Python while the
    condition stays a Python bool, and the moment it becomes a tensor
    (e.g. a break flag set inside a data-dependent `if`) the remaining
    iterations compile to one while_loop op from the current state."""
    vals = list(loop_vars)
    while True:
        c = cond_fn(*vals)
        if isinstance(c, Variable) and not framework.in_dygraph_mode():
            from ...layers import control_flow

            lifted = []
            for name, v in zip(names, vals):
                if isinstance(v, Variable):
                    lifted.append(v)
                elif isinstance(v, (bool, int, float)):
                    lifted.append(_lift_scalar(v))
                else:
                    raise TypeError(
                        "@declarative: loop variable '%s' of a "
                        "data-dependent `while` must be a tensor or scalar "
                        "(got %r)" % (name, type(v).__name__)
                    )
            outs = control_flow.while_loop(cond_fn, body_fn, lifted)
            return tuple(outs)
        if not _as_py_bool(c):
            return tuple(vals)
        out = body_fn(*vals)
        vals = list(out) if isinstance(out, (list, tuple)) else [out]


class _Lazy:
    """Deferred operand of a rewritten `and`/`or` (keeps Python
    short-circuit semantics for non-tensor left operands)."""

    __slots__ = ("fn",)

    def __init__(self, fn):
        self.fn = fn


def lazy(fn):
    return _Lazy(fn)


def _force(v):
    return v.fn() if isinstance(v, _Lazy) else v


def convert_logical_and(x, y):
    if not _is_tensor(x):
        if not _as_py_bool(x):
            return x  # short-circuit: y is never evaluated
        y = _force(y)
        if not _is_tensor(y):
            return y  # python `and` returns the second operand
    else:
        y = _force(y)
    from ...layers import tensor as t

    return t.logical_and(_to_bool_tensor(x), _to_bool_tensor(y))


def convert_logical_or(x, y):
    if not _is_tensor(x):
        if _as_py_bool(x):
            return x  # short-circuit
        y = _force(y)
        if not _is_tensor(y):
            return y
    else:
        y = _force(y)
    from ...layers import tensor as t

    return t.logical_or(_to_bool_tensor(x), _to_bool_tensor(y))


def convert_logical_not(x):
    if _is_tensor(x):
        from ...layers import tensor as t

        return t.logical_not(_to_bool_tensor(x))
    return not x


def convert_range_cond(i, stop, step):
    """Bound test of a converted `for i in range(...)`: direction follows
    the sign of step (range(3, 0, -1) iterates downward)."""
    if not any(_is_tensor(v) for v in (i, stop, step)):
        return i < stop if step > 0 else i > stop
    if isinstance(step, (bool, int, float)):  # static step: pick statically
        return i < stop if step > 0 else i > stop
    # tensor step: (step > 0 and i < stop) or (step < 0 and i > stop)
    from ...layers import tensor as t

    up = t.logical_and(_to_bool_tensor(step > 0), _to_bool_tensor(i < stop))
    dn = t.logical_and(_to_bool_tensor(step < 0), _to_bool_tensor(i > stop))
    return t.logical_or(up, dn)


def _lift_scalar(v):
    """Python scalar -> [1] tensor: bool stays bool, numbers use float32
    (int loop counters survive `scale`-op arithmetic without dtype drift)."""
    from ...layers import tensor as t

    if isinstance(v, bool):
        return t.fill_constant([1], "bool", v)
    return t.fill_constant([1], "float32", float(v))


def _to_bool_tensor(x):
    from ...layers import tensor as t

    if not _is_tensor(x):
        return t.fill_constant([1], "bool", bool(x))
    if getattr(x, "dtype", "bool") != "bool":
        return t.cast(x, "bool")
    return x


# ---------------------------------------------------------------------------
# round-4 transformers' runtime targets: print / cast / len / assert /
# shape / append / call (reference print_transformer.py,
# cast_transformer.py, assert_transformer.py, tensor_shape_transformer.py,
# list_transformer.py, call_transformer.py)
# ---------------------------------------------------------------------------


def convert_print(*args):
    """Variables print from inside the compiled program (layers.Print);
    everything else prints natively.  Argument ORDER is preserved: each
    tensor's Print op carries the non-tensor args since the previous
    tensor as its message."""
    if not any(_is_tensor(a) for a in args):
        print(*args)
        return None
    from ...layers import tensor as tensor_layers

    pending = []
    for a in args:
        if _is_tensor(a):
            tensor_layers.Print(a, message=" ".join(pending))
            pending = []
        else:
            pending.append(str(a))
    if pending:
        print(*pending)
    return None


_CAST_PY = {"int64": int, "float32": float, "bool": bool}


def convert_cast(x, dtype):
    if _is_tensor(x):
        from ...layers import tensor as tensor_layers

        return tensor_layers.cast(x, dtype)
    return _CAST_PY[dtype](x)


def convert_len(x):
    if _is_tensor(x):
        d0 = x.shape[0]
        if d0 is not None and int(d0) >= 0:
            return int(d0)
        from ...layers import tensor as tensor_layers

        return tensor_layers.slice(tensor_layers.shape(x), [0], [0], [1])
    return len(x)


def convert_shape(x):
    """Static tuple when fully known; layers.shape tensor otherwise;
    non-Variables (numpy etc.) pass through to their own .shape."""
    if not _is_tensor(x):
        return x.shape
    dims = list(x.shape)
    if all(d is not None and int(d) >= 0 for d in dims):
        return tuple(int(d) for d in dims)
    from ...layers import tensor as tensor_layers

    return tensor_layers.shape(x)


def convert_assert(cond, msg=None):
    """msg may be a zero-arg lambda (lazy python semantics — evaluated
    only when needed: on failure, or at trace time for tensor conds)."""
    if _is_tensor(cond):
        from ...layers import control_flow as cf

        m = msg() if callable(msg) else msg
        return cf.Assert(cond, summarize=10,
                         message=str(m) if m is not None else "")
    if not cond:
        raise AssertionError(msg() if callable(msg) else msg)
    return None


def convert_append(lst, x):
    """Plain appendables mutate IN PLACE and return themselves (the
    rebinding the transformer emits then preserves aliasing while still
    marking the name as loop-carried); tensor arrays
    (layers.create_array) get array_write-at-length append."""
    if hasattr(lst, "append"):
        lst.append(x)
        return lst
    from ...layers import control_flow as cf

    cf.array_write(x, cf.array_length(lst), lst)
    return lst


import weakref

_CALL_CACHE: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def convert_call(fn):
    """Recursively AST-convert a called user function (reference
    convert_call, `dygraph_to_static/convert_call_func.py`): functions
    with retrievable source transform once (cached per function OBJECT,
    so distinct closures of one def stay distinct); bound methods unwrap
    to their __func__ and rebind; builtins, layer APIs, framework
    internals, and classes pass through untouched."""
    import types

    if isinstance(fn, types.MethodType):
        conv = convert_call(fn.__func__)
        if conv is fn.__func__:
            return fn
        return types.MethodType(conv, fn.__self__)
    if not isinstance(fn, types.FunctionType):
        return fn  # builtins, classes, arbitrary callables
    mod = getattr(fn, "__module__", "") or ""
    if mod.startswith("paddle_tpu") or mod.startswith("jax") \
            or mod.startswith("numpy"):
        return fn
    if getattr(fn, "__dy2st_source__", None):
        return fn  # already transformed
    try:
        hit = _CALL_CACHE.get(fn)
    except TypeError:
        hit = None
    if hit is not None:
        return hit
    from .ast_transformer import transform_function

    try:
        new_fn = transform_function(fn)
    except Exception:
        new_fn = None
    out = new_fn or fn
    try:
        _CALL_CACHE[fn] = out
    except TypeError:
        pass
    return out
