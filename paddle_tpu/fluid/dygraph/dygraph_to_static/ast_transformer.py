"""Dygraph-to-static AST rewriting.

Capability parity: reference `dygraph_to_static/ast_transformer.py:1` +
`program_translator.py:1` (20 files of transformers).  The same pipeline
idea, TPU-sized: rewrite data-dependent Python control flow into calls on
`_jst` (convert_operators), which pick `layers.cond`/`layers.while_loop`
when the condition is a tensor — those lower to native XLA `lax.cond`/
`lax.while_loop`, the compiler-friendly control flow the platform wants —
and keep plain Python semantics otherwise.

Passes, in order (each output is plain AST the next pass understands):
  1. BreakContinueTransformer — break/continue become boolean flag vars;
     statements downstream of a possible interrupt are guarded by `if`.
  2. ForToWhileTransformer — `for i in range(...)` becomes a counter
     `while` (other iterables stay Python: they unroll at trace time).
  3. LoopTransformer — `while` becomes cond_fn/body_fn + convert_while_loop
     over the loop-carried names.
  4. IfElseTransformer — `if` becomes true_fn/false_fn + convert_ifelse
     over the union of names either branch assigns.
  (BoolOpTransformer runs inside passes 3/4 on test expressions only:
  and/or/not there become convert_logical_* calls with lazy operands —
  tensors have no Python truthiness, while pure-Python guards keep
  short-circuit semantics.)

Early `return` (pass 0, ReturnTransformer) is rewritten to
assign-then-return — a return-value var + taken-flag, downstream
statements guarded, loops broken — so data-dependent returns under
tensor conditions become cond outputs like any other assignment
(reference return_transformer.py).
"""

from __future__ import annotations

import ast
import inspect
import textwrap

_JST = "_jst"


# ---------------------------------------------------------------------------
# analysis helpers
# ---------------------------------------------------------------------------


def _target_names(target):
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out = []
        for e in target.elts:
            out.extend(_target_names(e))
        return out
    return []  # attribute/subscript stores mutate objects, not names


def _assigned_names(stmts):
    """Names bound by a statement list (incl. nested blocks)."""
    names = []

    class V(ast.NodeVisitor):
        def visit_Assign(self, node):
            for t in node.targets:
                names.extend(_target_names(t))
            self.generic_visit(node)

        def visit_AugAssign(self, node):
            names.extend(_target_names(node.target))
            self.generic_visit(node)

        def visit_AnnAssign(self, node):
            names.extend(_target_names(node.target))
            self.generic_visit(node)

        def visit_For(self, node):
            names.extend(_target_names(node.target))
            self.generic_visit(node)

        def visit_FunctionDef(self, node):
            # function objects cannot thread through cond/while outputs;
            # a def stays local to its branch/body (do not descend either)
            pass

        def visit_Lambda(self, node):
            pass

    v = V()
    for s in stmts:
        v.visit(s)
    # stable order, unique
    seen, out = set(), []
    for n in names:
        if n not in seen:
            seen.add(n)
            out.append(n)
    return out


def _walk_shallow(root):
    """ast.walk that does NOT descend into nested function/lambda bodies
    (their returns/breaks belong to them, not the enclosing block)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                continue
            stack.append(child)


def _contains(stmts, node_types, stop_at_loops=False):
    for s in stmts:
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue  # a nested def's returns/breaks are its own
        for node in _walk_shallow(s):
            if isinstance(node, node_types):
                if stop_at_loops and _inside_nested_loop(s, node):
                    continue
                return True
    return False


def _inside_nested_loop(root, node):
    """True if `node` sits inside a loop nested under `root` (that loop
    owns the break/continue)."""
    # walk with explicit parent tracking
    stack = [(root, False)]
    while stack:
        cur, in_loop = stack.pop()
        if cur is node:
            return in_loop
        for child in ast.iter_child_nodes(cur):
            stack.append(
                (child, in_loop or isinstance(cur, (ast.For, ast.While)))
            )
    return False


def _has_return(stmts):
    return _contains(stmts, (ast.Return,))


def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _jst_call(fn_name, args):
    return ast.Call(
        func=ast.Attribute(value=_name(_JST), attr=fn_name, ctx=ast.Load()),
        args=args,
        keywords=[],
    )


def _ensure_defined(names):
    """`try: x\nexcept NameError: x = _jst.UNDEF` per name — makes branch
    functions well-defined when a name is only assigned on one path."""
    out = []
    for n in names:
        out.append(
            ast.Try(
                body=[ast.Expr(value=_name(n))],
                handlers=[
                    ast.ExceptHandler(
                        type=_name("NameError"),
                        name=None,
                        body=[
                            ast.Assign(
                                targets=[_name(n, ast.Store())],
                                value=ast.Attribute(
                                    value=_name(_JST), attr="UNDEF",
                                    ctx=ast.Load(),
                                ),
                            )
                        ],
                    ),
                    ast.ExceptHandler(
                        type=_name("UnboundLocalError"),
                        name=None,
                        body=[
                            ast.Assign(
                                targets=[_name(n, ast.Store())],
                                value=ast.Attribute(
                                    value=_name(_JST), attr="UNDEF",
                                    ctx=ast.Load(),
                                ),
                            )
                        ],
                    ),
                ],
                orelse=[],
                finalbody=[],
            )
        )
    return out


# ---------------------------------------------------------------------------
# pass 0: early return -> assign-then-return
# ---------------------------------------------------------------------------

RET_VAL = "__dy2st_ret_val"


class _ReturnUnsupported(Exception):
    pass


class ReturnTransformer:
    """cf. reference return_transformer.py: early `return` becomes
    assign-then-return.  The rewrite is continuation-style so EVERY path
    assigns the return var (convert_ifelse then merges real values, never
    a None placeholder):

    * `if t: return A` followed by more statements -> the remaining
      statements move into the else-continuation; both branches end
      assigning `__dy2st_ret_val`, and ONE `return __dy2st_ret_val`
      remains at the end of the function.
    * `return A` inside a loop -> a per-return flag + `break` (the
      BreakContinue pass folds the break into the loop condition); after
      the loop a dispatch chain evaluates A under `if flag:` — sound
      because break exits immediately, so the loop-carried names still
      hold their values from the breaking iteration.
    * a path that falls off the function end assigns None (merging None
      with a tensor under a TENSOR condition then raises the cond
      structural-mismatch guidance, the same restriction as any
      diverging branch outputs).

    Returns nested under a second loop level — or functions whose guard
    nesting would blow the continuation duplication past a size cap —
    fall back to the untouched function (plain tracing; a tensor
    condition there raises the Variable.__bool__ guidance error).

    Runs FIRST.  transform_function applies one instance per FunctionDef
    node, outer AND nested: a nested def's source is unavailable to
    convert_call once the outer function re-execs from transformed
    source, so its returns must rewrite here."""

    # continuation statements may duplicate into both if-branches; cap
    # the total copies so guard-clause-heavy functions can't go
    # exponential (past the cap: pristine-function fallback)
    MAX_COPIED_STMTS = 2000

    def __init__(self):
        self._uid = 0
        self._copied = 0

    def _fresh(self):
        self._uid += 1
        return "__dy2st_retflag_%d" % self._uid

    def transform(self, fdef):
        import copy

        body = fdef.body
        early = False
        for i, s in enumerate(body):
            if isinstance(s, ast.Return) and i == len(body) - 1:
                continue               # single trailing return: fine as is
            if _has_return([s]):
                early = True
                break
        if not early:
            return fdef
        # rewrite a COPY: _rw_block mutates nodes in place, and the
        # unsupported-fallback must trace the pristine original
        try:
            fdef.body = self._rw_block(copy.deepcopy(body)) + [
                ast.Return(value=_name(RET_VAL))
            ]
        except _ReturnUnsupported:
            pass                       # plain tracing fallback
        return fdef

    def _rw_block(self, stmts):
        """Rewrite so every path through `stmts` assigns RET_VAL."""
        import copy

        out = []
        for idx, s in enumerate(stmts):
            rest = stmts[idx + 1:]
            if isinstance(s, ast.Return):
                out.append(ast.Assign(
                    targets=[_name(RET_VAL, ast.Store())],
                    value=s.value or ast.Constant(value=None)))
                return out             # rest is unreachable
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    or not _has_return([s]):
                out.append(s)
                continue
            if isinstance(s, ast.If):
                # each branch gets its OWN copy of the continuation:
                # later in-place passes must not see aliased nodes
                self._copied += 2 * sum(
                    1 for r in rest for _ in ast.walk(r))
                if self._copied > self.MAX_COPIED_STMTS:
                    raise _ReturnUnsupported    # exponential guard chain
                s.body = self._rw_block(list(s.body)
                                        + copy.deepcopy(rest))
                s.orelse = self._rw_block(list(s.orelse)
                                          + copy.deepcopy(rest))
                out.append(s)
                return out
            if isinstance(s, (ast.While, ast.For)):
                flags = self._rw_loop(s)
                out.extend(
                    ast.Assign(targets=[_name(f, ast.Store())],
                               value=ast.Constant(value=False))
                    for f, _ in flags)
                out.append(s)
                # post-loop dispatch: which return (if any) fired?
                node = self._rw_block(rest)
                for f, value in reversed(flags):
                    node = [ast.If(
                        test=_name(f),
                        body=[ast.Assign(
                            targets=[_name(RET_VAL, ast.Store())],
                            value=value)],
                        orelse=node)]
                out.extend(node)
                return out
            if isinstance(s, ast.With):
                # a return under `with` would skip __exit__ ordering in
                # the rewrite; keep Python semantics via fallback
                raise _ReturnUnsupported
            out.append(s)
        out.append(ast.Assign(targets=[_name(RET_VAL, ast.Store())],
                              value=ast.Constant(value=None)))
        return out

    def _rw_loop(self, loop):
        """Replace each `return A` in the loop body (one loop level) with
        `flag = True; break`; returns [(flag, A)] in source order."""
        flags = []

        def rw(stmts, depth):
            out = []
            for s in stmts:
                if isinstance(s, ast.Return):
                    if depth > 0:
                        raise _ReturnUnsupported   # nested-loop return
                    f = self._fresh()
                    flags.append((f, s.value or ast.Constant(value=None)))
                    out.append(ast.Assign(
                        targets=[_name(f, ast.Store())],
                        value=ast.Constant(value=True)))
                    out.append(ast.Break())
                    continue
                if isinstance(s,
                              (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        or not _has_return([s]):
                    out.append(s)
                    continue
                if isinstance(s, ast.If):
                    s.body = rw(s.body, depth)
                    s.orelse = rw(s.orelse, depth)
                elif isinstance(s, (ast.While, ast.For)):
                    s.body = rw(s.body, depth + 1)
                else:
                    raise _ReturnUnsupported
                out.append(s)
            return out

        loop.body = rw(loop.body, 0)
        return flags


# ---------------------------------------------------------------------------
# pass 1: break/continue -> flags
# ---------------------------------------------------------------------------


class BreakContinueTransformer(ast.NodeTransformer):
    """Rewrite break/continue into boolean flag assignments; guard the
    statements that would have been skipped (reference
    break_continue_transformer.py)."""

    def __init__(self):
        self._uid = 0

    def _fresh(self, tag):
        self._uid += 1
        return "__dy2st_%s_%d" % (tag, self._uid)

    def visit_While(self, node):
        self.generic_visit(node)  # inner loops first
        return self._rewrite_loop(node, is_for=False)

    def visit_For(self, node):
        self.generic_visit(node)
        return self._rewrite_loop(node, is_for=True)

    def _rewrite_loop(self, node, is_for):
        has_brk = _contains(node.body, (ast.Break,), stop_at_loops=True)
        has_cont = _contains(node.body, (ast.Continue,), stop_at_loops=True)
        if not (has_brk or has_cont):
            return node
        # flags are only honored by loops the later passes convert; a
        # non-range for, or any loop with an else clause, keeps REAL
        # break/continue (Python semantics; a tensor condition around them
        # then raises the Variable.__bool__ guidance error)
        if node.orelse or (is_for and not _is_convertible_for(node)):
            return node
        brk = self._fresh("brk") if has_brk else None
        cont = self._fresh("cont") if has_cont else None

        new_body = []
        if cont:
            new_body.append(
                ast.Assign(
                    targets=[_name(cont, ast.Store())],
                    value=ast.Constant(value=False),
                )
            )
        new_body.extend(self._guard_block(node.body, brk, cont))
        node.body = new_body

        if brk:
            # flag init before the loop + loop condition &= not brk
            init = ast.Assign(
                targets=[_name(brk, ast.Store())],
                value=ast.Constant(value=False),
            )
            if isinstance(node, ast.While):
                node.test = _jst_call(
                    "convert_logical_and",
                    [node.test, _jst_call("convert_logical_not", [_name(brk)])],
                )
            else:
                # For: ForToWhile pass will fold the flag into its test
                node._dy2st_break_flag = brk
            return [init, node]
        return node

    def _guard_block(self, stmts, brk, cont):
        """Replace break/continue with flag-sets; wrap statements after a
        possible interrupt in `if not (brk or cont):`."""
        out = []
        pending_guard = None  # names of flags that may be set so far
        for s in stmts:
            if isinstance(s, ast.Break):
                repl = ast.Assign(
                    targets=[_name(brk, ast.Store())],
                    value=ast.Constant(value=True),
                )
                out.append(self._wrap(repl, pending_guard))
                pending_guard = self._merge(pending_guard, [brk])
                continue
            if isinstance(s, ast.Continue):
                repl = ast.Assign(
                    targets=[_name(cont, ast.Store())],
                    value=ast.Constant(value=True),
                )
                out.append(self._wrap(repl, pending_guard))
                pending_guard = self._merge(pending_guard, [cont])
                continue
            # recurse into if/with bodies (loops already handled themselves;
            # try/finally falls back to plain tracing at compile time)
            if isinstance(s, (ast.If, ast.With)) and (
                _contains([s], (ast.Break, ast.Continue), stop_at_loops=True)
            ):
                s.body = self._guard_block(s.body, brk, cont)
                if isinstance(s, ast.If):
                    s.orelse = self._guard_block(s.orelse, brk, cont)
                flags = [f for f in (brk, cont) if f is not None]
                out.append(self._wrap(s, pending_guard))
                pending_guard = self._merge(pending_guard, flags)
                continue
            out.append(self._wrap(s, pending_guard))
        return out

    def _merge(self, guard, flags):
        cur = list(guard or [])
        for f in flags:
            if f and f not in cur:
                cur.append(f)
        return cur

    def _wrap(self, stmt, guard):
        if not guard:
            return stmt
        test = _name(guard[0])
        for g in guard[1:]:
            test = _jst_call("convert_logical_or", [test, _name(g)])
        return ast.If(
            test=_jst_call("convert_logical_not", [test]),
            body=[stmt],
            orelse=[],
        )


# ---------------------------------------------------------------------------
# pass 2: for-range -> while
# ---------------------------------------------------------------------------


def _is_convertible_for(node):
    """The for-loops ForToWhileTransformer rewrites: `for <name> in
    range(...)` with no else clause."""
    it = node.iter
    return (
        isinstance(it, ast.Call)
        and isinstance(it.func, ast.Name)
        and it.func.id == "range"
        and isinstance(node.target, ast.Name)
        and not node.orelse
    )


class ForToWhileTransformer(ast.NodeTransformer):
    """`for i in range(...)` -> counter while (other iterables unroll at
    trace time, which is the right call for Python lists under XLA)."""

    def __init__(self):
        self._uid = 0

    def visit_For(self, node):
        self.generic_visit(node)
        if not _is_convertible_for(node):
            return node
        it = node.iter
        self._uid += 1
        a = it.args
        start = a[0] if len(a) >= 2 else ast.Constant(value=0)
        stop = a[1] if len(a) >= 2 else a[0]
        step = a[2] if len(a) >= 3 else ast.Constant(value=1)
        i = node.target.id
        # internal counter: the user's loop variable is assigned at the TOP
        # of each iteration, so after the loop it holds the last iterated
        # value (Python semantics), not `stop`
        it_name = "__dy2st_it_%d" % self._uid

        init = ast.Assign(targets=[_name(it_name, ast.Store())], value=start)
        # step-sign-aware bound check (range(3,0,-1) iterates downward)
        test = _jst_call(
            "convert_range_cond", [_name(it_name), stop, step]
        )
        flag = getattr(node, "_dy2st_break_flag", None)
        if flag:
            test = _jst_call(
                "convert_logical_and",
                [test, _jst_call("convert_logical_not", [_name(flag)])],
            )
        set_i = ast.Assign(
            targets=[_name(i, ast.Store())], value=_name(it_name)
        )
        incr = ast.AugAssign(
            target=_name(it_name, ast.Store()), op=ast.Add(), value=step
        )
        w = ast.While(
            test=test, body=[set_i] + list(node.body) + [incr], orelse=[]
        )
        return [init, w]


# ---------------------------------------------------------------------------
# pass 3: while -> convert_while_loop
# ---------------------------------------------------------------------------


class LoopTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def visit_While(self, node):
        self.generic_visit(node)
        if _has_return(node.body) or node.orelse:
            return node  # python semantics (tensor cond raises guidance)
        node.test = _rewrite_test(node.test)
        self._uid += 1
        assigned = _assigned_names(node.body)
        # loop-carried names: assigned in the body and visible outside
        loop_names = assigned
        if not loop_names:
            return node
        cond_name = "__dy2st_cond_%d" % self._uid
        body_name = "__dy2st_body_%d" % self._uid

        args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in loop_names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[],
        )
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None,
        )
        body_fn = ast.FunctionDef(
            name=body_name, args=args,
            body=list(node.body)
            + [
                ast.Return(
                    value=ast.Tuple(
                        elts=[_name(n) for n in loop_names], ctx=ast.Load()
                    )
                )
            ],
            decorator_list=[], returns=None,
        )
        call = ast.Assign(
            targets=[
                ast.Tuple(
                    elts=[_name(n, ast.Store()) for n in loop_names],
                    ctx=ast.Store(),
                )
            ],
            value=_jst_call(
                "convert_while_loop",
                [
                    _name(cond_name),
                    _name(body_name),
                    ast.Tuple(
                        elts=[_name(n) for n in loop_names], ctx=ast.Load()
                    ),
                    ast.Constant(value=tuple(loop_names)),
                ],
            ),
        )
        return _ensure_defined(loop_names) + [cond_fn, body_fn, call]


# ---------------------------------------------------------------------------
# pass 4: if -> convert_ifelse
# ---------------------------------------------------------------------------


class IfElseTransformer(ast.NodeTransformer):
    def __init__(self):
        self._uid = 0

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_return(node.body) or _has_return(node.orelse):
            return node  # python semantics (tensor cond raises guidance)
        if _contains(node.body + node.orelse, (ast.Break, ast.Continue),
                     stop_at_loops=True):
            # a surviving real break/continue (unconvertible enclosing
            # loop): converting this if would move it into a function and
            # make it a SyntaxError — keep Python semantics
            return node
        node.test = _rewrite_test(node.test)
        names = _assigned_names(node.body + node.orelse)
        self._uid += 1
        t_name = "__dy2st_true_%d" % self._uid
        f_name = "__dy2st_false_%d" % self._uid
        ret = ast.Return(
            value=ast.Tuple(elts=[_name(n) for n in names], ctx=ast.Load())
        )
        # branch fns take the assigned names as PARAMETERS (a name both
        # read and re-assigned in a branch would otherwise be an unbound
        # local of the branch function)
        fn_args = ast.arguments(
            posonlyargs=[], args=[ast.arg(arg=n) for n in names],
            vararg=None, kwonlyargs=[], kw_defaults=[], kwarg=None,
            defaults=[],
        )
        true_fn = ast.FunctionDef(
            name=t_name, args=fn_args,
            body=list(node.body) + [ret],
            decorator_list=[], returns=None,
        )
        false_fn = ast.FunctionDef(
            name=f_name, args=fn_args,
            body=(list(node.orelse) or [ast.Pass()]) + [ret],
            decorator_list=[], returns=None,
        )
        orig_vals = ast.Tuple(
            elts=[_name(n) for n in names], ctx=ast.Load()
        )
        call_args = [
            node.test, _name(t_name), _name(f_name),
            ast.Constant(value=tuple(names)), orig_vals,
        ]
        if names:
            tgt = [
                ast.Tuple(
                    elts=[_name(n, ast.Store()) for n in names],
                    ctx=ast.Store(),
                )
            ]
            call_stmt = ast.Assign(
                targets=tgt, value=_jst_call("convert_ifelse", call_args)
            )
        else:
            call_stmt = ast.Expr(
                value=_jst_call("convert_ifelse", call_args)
            )
        return _ensure_defined(names) + [true_fn, false_fn, call_stmt]


# ---------------------------------------------------------------------------
# pass 5: and/or/not -> convert_logical_*
# ---------------------------------------------------------------------------


class BoolOpTransformer(ast.NodeTransformer):
    """Applied ONLY to `if`/`while` test expressions (tensors have no
    Python truthiness there); `and`/`or` elsewhere keep native semantics.
    Later operands are wrapped `_jst.lazy(lambda: ...)` so pure-Python
    guards keep short-circuit behavior."""

    def visit_BoolOp(self, node):
        self.generic_visit(node)
        fn = (
            "convert_logical_and"
            if isinstance(node.op, ast.And)
            else "convert_logical_or"
        )
        expr = node.values[0]
        for v in node.values[1:]:
            lazy_v = _jst_call(
                "lazy",
                [
                    ast.Lambda(
                        args=ast.arguments(
                            posonlyargs=[], args=[], vararg=None,
                            kwonlyargs=[], kw_defaults=[], kwarg=None,
                            defaults=[],
                        ),
                        body=v,
                    )
                ],
            )
            expr = _jst_call(fn, [expr, lazy_v])
        return expr

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return _jst_call("convert_logical_not", [node.operand])
        return node

    # do not descend into nested statements: tests only
    def visit_FunctionDef(self, node):
        return node

    def visit_Lambda(self, node):
        return node


def _rewrite_test(expr):
    return BoolOpTransformer().visit(expr)


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


class ListTransformer(ast.NodeTransformer):
    """cf. reference list_transformer.py: `l.append(x)` statements become
    `l = _jst.convert_append(l, x)` — the reassignment makes `l` a
    loop-carried var for LoopTransformer, and convert_append picks plain
    list vs tensor-array semantics at trace time.  MUST run before the
    loop passes.

    In a NESTED def, only appends to the def's OWN locals rewrite; a
    free (closed-over) name keeps the real `.append` call — the
    reassignment would turn it into an unbound local (closure mutation
    needs `nonlocal`), while genuine Python append on the closure cell
    works at trace time."""

    def __init__(self):
        self._locals = None      # None = outer function (always rewrite)

    def _nested_locals(self, node):
        args = node.args
        names = set(_assigned_names(node.body))
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            names.add(a.arg)
        if args.vararg:
            names.add(args.vararg.arg)
        if args.kwarg:
            names.add(args.kwarg.arg)
        return names

    def visit_FunctionDef(self, node):
        if self._locals is None:       # the function being transformed
            self._locals = False
            self.generic_visit(node)
            self._locals = None
        else:
            prev = self._locals
            self._locals = self._nested_locals(node)
            self.generic_visit(node)
            self._locals = prev
        return node

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Expr(self, node):
        self.generic_visit(node)
        call = node.value
        if (isinstance(call, ast.Call)
                and isinstance(call.func, ast.Attribute)
                and call.func.attr == "append"
                and isinstance(call.func.value, ast.Name)
                and len(call.args) == 1 and not call.keywords):
            tgt = call.func.value.id
            if isinstance(self._locals, set) and tgt not in self._locals:
                return node            # free name in a nested def
            return ast.Assign(
                targets=[_name(tgt, ast.Store())],
                value=_jst_call("convert_append",
                                [_name(tgt), call.args[0]]),
            )
        return node


class PrintTransformer(ast.NodeTransformer):
    """cf. reference print_transformer.py: print(...) -> _jst.convert_print
    (layers.Print for Variables — visible from inside the compiled
    program — plain print otherwise)."""

    def visit_Call(self, node):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name) and node.func.id == "print"
                and not node.keywords):
            return _jst_call("convert_print", list(node.args))
        return node


class CastTransformer(ast.NodeTransformer):
    """cf. reference cast_transformer.py: int(x)/float(x)/bool(x) on
    Variables become layers.cast; len(x) becomes convert_len."""

    _MAP = {"int": "int64", "float": "float32", "bool": "bool"}

    def visit_Call(self, node):
        self.generic_visit(node)
        if (isinstance(node.func, ast.Name) and not node.keywords
                and len(node.args) == 1):
            if node.func.id in self._MAP:
                return _jst_call(
                    "convert_cast",
                    [node.args[0],
                     ast.Constant(value=self._MAP[node.func.id])])
            if node.func.id == "len":
                return _jst_call("convert_len", [node.args[0]])
        return node


class AssertTransformer(ast.NodeTransformer):
    """cf. reference assert_transformer.py."""

    def visit_Assert(self, node):
        self.generic_visit(node)
        args = [node.test]
        if node.msg is not None:
            # lazy message (python semantics: only evaluated on failure)
            args.append(ast.Lambda(
                args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[]),
                body=node.msg))
        else:
            args.append(ast.Constant(value=None))
        return ast.Expr(value=_jst_call("convert_assert", args))


class TensorShapeTransformer(ast.NodeTransformer):
    """cf. reference tensor_shape_transformer.py: `x.shape` reads go
    through convert_shape (static tuple when fully known, layers.shape
    tensor when any dim is dynamic; non-Variables pass through)."""

    def visit_Attribute(self, node):
        self.generic_visit(node)
        if node.attr == "shape" and isinstance(node.ctx, ast.Load):
            return _jst_call("convert_shape", [node.value])
        return node


class CallTransformer(ast.NodeTransformer):
    """cf. reference call_transformer.py: user-function calls route
    through _jst.convert_call, which AST-transforms the callee
    recursively (so `if tensor:`-style control flow inside helpers also
    converts); builtins / fluid APIs pass through untouched at runtime.
    Runs LAST so the other passes' generated calls are recognizable."""

    _SKIP = {"print", "len", "int", "float", "bool", "range", "super",
             "isinstance", "getattr", "setattr", "hasattr", "enumerate",
             "zip", "list", "tuple", "dict", "min", "max", "abs", "sum",
             "type", "id", "repr", "str"}

    def _is_jst(self, func):
        return (isinstance(func, ast.Attribute)
                and isinstance(func.value, ast.Name)
                and func.value.id == _JST)

    def visit_Call(self, node):
        self.generic_visit(node)
        f = node.func
        if self._is_jst(f):
            return node
        if isinstance(f, ast.Name):
            if f.id in self._SKIP or f.id.startswith("_"):
                return node
            node.func = _jst_call("convert_call", [f])
            return node
        if isinstance(f, ast.Attribute) and not f.attr.startswith("_"):
            # method-style calls (self.helper(x), module.fn(x)) convert
            # too; convert_call leaves non-convertibles untouched
            node.func = _jst_call("convert_call", [f])
            return node
        return node


def transform_function(fn):
    """Source-rewrite `fn` through the pass pipeline; returns the new
    callable (or None when source is unavailable — builtins, lambdas from
    exec, etc. — the caller then falls back to plain tracing)."""
    try:
        src = textwrap.dedent(inspect.getsource(fn))
    except (OSError, TypeError):
        return None
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None

    def _is_declarative(dec):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = getattr(target, "attr", None) or getattr(target, "id", None)
        return name in ("declarative", "to_static")

    # strip ONLY @declarative/@to_static; other stacked decorators re-apply
    # when the transformed source is exec'd
    fdef.decorator_list = [
        d for d in fdef.decorator_list if not _is_declarative(d)
    ]

    # pass 0 applies per function DEF — the outer one and every nested
    # def (a nested def's source is unavailable to convert_call once the
    # outer function is re-exec'd from transformed source, so its
    # control flow must convert IN PLACE here; the later passes already
    # descend into nested defs).  Children first: the outer restructure
    # may duplicate a nested def node, and a second transform of an
    # already-rewritten def is a no-op.
    for fd in reversed([n for n in ast.walk(fdef)
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))]):
        ReturnTransformer().transform(fd)

    for pass_cls in (
        ListTransformer,          # append->assign BEFORE loop-var capture
        BreakContinueTransformer,
        ForToWhileTransformer,
        LoopTransformer,
        IfElseTransformer,
        # BoolOp rewriting happens inside Loop/IfElse on test exprs only
        PrintTransformer,
        CastTransformer,
        AssertTransformer,
        TensorShapeTransformer,
        CallTransformer,          # LAST: wraps remaining user calls
    ):
        tree = pass_cls().visit(tree)
    ast.fix_missing_locations(tree)

    from . import convert_operators

    glb = dict(getattr(fn, "__globals__", {}))
    glb[_JST] = convert_operators
    # closure cells become plain globals of the transformed function
    # (values snapshot at transform time; cf. reference
    # program_translator function wrapping)
    freevars = getattr(fn.__code__, "co_freevars", ())
    for name, cell in zip(freevars, fn.__closure__ or ()):
        try:
            glb[name] = cell.cell_contents
        except ValueError:
            pass
    try:
        code = compile(tree, filename="<dygraph_to_static %s>" % fn.__name__,
                       mode="exec")
    except SyntaxError:
        # e.g. break under try/finally survived into a generated function —
        # fall back to plain tracing (tensor conds then raise guidance)
        return None
    ns = {}
    exec(code, glb, ns)
    new_fn = ns[fdef.name]
    new_fn.__dy2st_source__ = ast.unparse(tree)
    return new_fn
