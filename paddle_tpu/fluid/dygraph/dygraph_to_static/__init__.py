"""Dygraph-to-static conversion (reference `dygraph_to_static/` package)."""

from .ast_transformer import transform_function  # noqa: F401
from .convert_operators import (  # noqa: F401
    UNDEF,
    convert_ifelse,
    convert_logical_and,
    convert_logical_not,
    convert_logical_or,
    convert_while_loop,
)
