"""Sequential / LayerList / ParameterList containers.

Capability parity: reference `python/paddle/fluid/dygraph/container.py`.
"""

from __future__ import annotations

from .layers import Layer


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and not isinstance(
            layers[0], Layer
        ):
            layers = layers[0]
        for i, item in enumerate(layers):
            if isinstance(item, (list, tuple)):
                name, layer = item
            else:
                name, layer = str(i), item
            self.add_sublayer(name, layer)

    def __getitem__(self, idx):
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def forward(self, input):
        for layer in self._sub_layers.values():
            input = layer(input)
        return input


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        for i, l in enumerate(sublayers or []):
            self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return list(self._sub_layers.values())[idx]
        return self._sub_layers[str(idx if idx >= 0 else len(self) + idx)]

    def __setitem__(self, idx, sublayer):
        self._sub_layers[str(idx)] = sublayer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        for i, p in enumerate(parameters or []):
            self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx if idx >= 0 else len(self) + idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())
