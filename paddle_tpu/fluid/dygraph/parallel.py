"""Dygraph data parallelism.

Capability parity: reference `python/paddle/fluid/dygraph/parallel.py` —
`ParallelEnv:56`, `DataParallel:225` (`scale_loss:292`,
`apply_collective_grads:384`: coalesce grads, NCCL allreduce).

TPU-first: single-PROCESS multi-device dygraph runs each step on one chip
(eager jax); the scalable path is to jit the train step over a dp mesh
(distributed.ShardedTrainStep), where grad reduction is compiler-inserted.
DataParallel here keeps the reference API: on a 1-process world it is the
documented no-op passthrough (reference behavior with one trainer); its
`train_step` helper upgrades the wrapped layer to the sharded SPMD step.
"""

from __future__ import annotations

import numpy as np

from .. import framework
from ...distributed.parallel import ParallelEnv  # noqa: F401  (re-export)
from .layers import Layer


def prepare_context(strategy=None):
    """cf. reference prepare_context: collective bootstrap — handled by
    distributed.init_parallel_env (jax.distributed) on multi-host."""
    from ...distributed.parallel import init_parallel_env

    return init_parallel_env()


class DataParallel(Layer):
    """cf. reference DataParallel(layers, strategy)."""

    def __init__(self, layers, strategy=None):
        super().__init__()
        self._layers = layers
        self._strategy = strategy
        self._env = ParallelEnv()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    @property
    def nranks(self):
        return max(self._env.world_size, 1)

    def scale_loss(self, loss):
        """cf. reference scale_loss:292 — divide by trainer count so the
        summed allreduce averages."""
        if self.nranks <= 1:
            return loss
        return loss * (1.0 / self.nranks)

    def apply_collective_grads(self):
        """cf. reference apply_collective_grads:384.  Eager cross-process
        collectives don't exist under the XLA runtime — grad reduction
        belongs inside the jitted step (ShardedTrainStep).  With one
        process this is the reference no-op; multi-process use raises with
        guidance rather than silently training un-synced replicas."""
        if self.nranks <= 1:
            return
        raise RuntimeError(
            "eager multi-process gradient allreduce is not supported on the "
            "XLA runtime; wrap the model in distributed.ShardedTrainStep "
            "(one jitted SPMD step, grads reduced on ICI) instead"
        )

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def clear_gradients(self):
        self._layers.clear_gradients()
