"""Imperative (dygraph) mode.

Capability parity: reference `python/paddle/fluid/dygraph/` — eager
execution with taped autograd (imperative/tracer.cc, basic_engine.cc),
Layer/nn/containers, to_variable/guard/no_grad, save/load_dygraph.
"""

from . import base, container, layers, nn  # noqa: F401
from .base import (  # noqa: F401
    disable_dygraph,
    enable_dygraph,
    enabled,
    guard,
    no_grad,
    to_variable,
)
from .checkpoint import load_dygraph, save_dygraph  # noqa: F401
from .container import LayerList, ParameterList, Sequential  # noqa: F401
from .layers import Layer  # noqa: F401
from .nn import (  # noqa: F401
    BatchNorm,
    Conv2D,
    Dropout,
    Embedding,
    GroupNorm,
    LayerNorm,
    Linear,
    Pool2D,
)
from . import learning_rate_scheduler  # noqa: F401
from .jit import TracedLayer, declarative, to_static  # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    CosineDecay,
    ExponentialDecay,
    InverseTimeDecay,
    LinearLrWarmup,
    NaturalExpDecay,
    NoamDecay,
    PiecewiseDecay,
    PolynomialDecay,
    ReduceLROnPlateau,
)
from .parallel import DataParallel, ParallelEnv, prepare_context  # noqa: F401
from .tracer import Tracer  # noqa: F401
from .varbase import ParamBase, VarBase  # noqa: F401
