"""Dygraph-to-static: TracedLayer + @declarative program capture.

Capability parity: reference `python/paddle/fluid/dygraph/jit.py`
(TracedLayer.trace -> static program capture + save_inference_model) and
`dygraph_to_static/program_translator.py` (`@declarative` — reference
AST-rewrites Python source into program-building code, cached per input
signature).

TPU-first redesign: no AST rewriting is needed.  Every layer/op in this
framework is dual-mode — the SAME Python builds a static Program when no
tracer is active — so "to static" is: switch the mode off, replay the
callable against placeholder data vars, collect the Program.  Python
control flow over tensors must use layers.cond/while_loop (which trace
into lax control flow); data-dependent `if x:` raises the same guidance
error the reference translator gives for unsupported constructs.
"""

from __future__ import annotations

import functools

import numpy as np

from .. import framework, unique_name
from ..core import dtypes as dtypes_mod
from .varbase import VarBase


class _InputSpec:
    def __init__(self, shape, dtype):
        self.shape = tuple(-1 if s is None else int(s) for s in shape)
        self.dtype = dtypes_mod.to_str(dtype)

    def key(self):
        return (self.shape, self.dtype)


def _spec_of(value):
    arr = value.data if isinstance(value, VarBase) else np.asarray(value)
    return _InputSpec(arr.shape, arr.dtype)


class TracedLayer:
    """cf. reference TracedLayer: a captured (program, feeds, fetches)
    triple runnable without the original Python."""

    def __init__(self, program, startup, feed_names, fetch_vars, scope):
        self.program = program
        self.startup = startup
        self.feed_names = feed_names
        self.fetch_vars = fetch_vars
        self._scope = scope
        self._exe = None

    @staticmethod
    def trace(layer, inputs):
        """Build the static program by replaying `layer` on placeholder
        vars (cf. reference TracedLayer.trace signature; returns
        (outputs, traced_layer))."""
        outs, traced = _trace_callable(
            layer, [_spec_of(v) for v in inputs], params_from=[layer]
        )
        return outs, traced

    def __call__(self, inputs):
        from ..executor import Executor, scope_guard

        if self._exe is None:
            self._exe = Executor()
        feed = {
            n: (v.data if isinstance(v, VarBase) else np.asarray(v))
            for n, v in zip(self.feed_names, inputs)
        }
        with scope_guard(self._scope):
            return self._exe.run(
                self.program, feed=feed, fetch_list=self.fetch_vars
            )

    def save_inference_model(self, dirname, feed=None, fetch=None):
        """cf. reference TracedLayer.save_inference_model."""
        from .. import io
        from ..executor import Executor, scope_guard

        exe = Executor()
        with scope_guard(self._scope):
            io.save_inference_model(
                dirname, self.feed_names, self.fetch_vars, exe, self.program
            )


def _trace_callable(fn, specs, params_from=None):
    """Replay a dual-mode callable in static mode -> (eager outs, TracedLayer).

    The layer's eager parameter values are copied into the capture scope so
    the traced program computes with the trained weights.
    """
    from ..core.scope import Scope
    from ..executor import Executor, scope_guard
    from ..layers import tensor as tensor_layers

    old_tracer = framework._dygraph_tracer
    program, startup = framework.Program(), framework.Program()
    scope = Scope()
    framework._dygraph_tracer = None  # static mode
    try:
        with framework.program_guard(program, startup):
            # materialize the layers' eager parameters as program Parameters
            # FIRST, so forward's by-name references resolve during capture
            for lyr in params_from or []:
                for _qual, vb in lyr.state_dict().items():
                    if not program.global_block.has_var(vb.name):
                        program.global_block.create_parameter(
                            vb.name, list(vb.shape), dtype=vb.dtype,
                            trainable=not vb.stop_gradient,
                        )
            feed_vars = []
            for spec in specs:
                name = unique_name.generate("traced_in")
                feed_vars.append(
                    tensor_layers.data(
                        name, list(spec.shape), dtype=spec.dtype,
                        append_batch_size=False,
                    )
                )
            outs = fn(*feed_vars)
        if isinstance(outs, framework.Variable):
            outs = [outs]
        outs = list(outs)
    finally:
        framework._dygraph_tracer = old_tracer

    # transplant trained eager weights into the capture scope
    exe = Executor()
    with scope_guard(scope):
        exe.run_startup(startup)
        for lyr in params_from or []:
            for _qual, vb in lyr.state_dict().items():
                scope.set(vb.name, vb.data)
    traced = TracedLayer(
        program, startup, [v.name for v in feed_vars], outs, scope
    )
    return outs, traced


def _closure_layers(fn):
    """Layers captured in the function's closure (common @declarative
    pattern: a free function closing over model objects)."""
    from .layers import Layer

    found = []
    for cell in getattr(fn, "__closure__", None) or ():
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if isinstance(v, Layer):
            found.append(v)
    return found


class _DeclarativeFunction:
    """cf. reference program_translator.StaticFunction: AST-transform the
    source (dygraph_to_static/) so data-dependent Python control flow
    becomes layers.cond / layers.while_loop in the captured program, with
    a per-signature program cache + executor dispatch."""

    def __init__(self, fn):
        self._fn = fn
        self._transformed = None
        self._transform_tried = False
        self._cache = {}
        functools.update_wrapper(self, fn)

    def _static_fn(self):
        """The AST-rewritten function (falls back to the original when
        source is unavailable — plain trace capture, control flow baked)."""
        if not self._transform_tried:
            self._transform_tried = True
            from .dygraph_to_static import transform_function

            self._transformed = transform_function(self._fn)
        return self._transformed or self._fn

    @property
    def code(self):
        """Rewritten source (reference StaticFunction.code) for debugging."""
        fn = self._static_fn()
        return getattr(fn, "__dy2st_source__", None)

    def __get__(self, obj, objtype=None):
        # decorating Layer.forward: bind like a method (per-instance cache
        # lives on this shared object, keyed also by instance id)
        if obj is None:
            return self
        return functools.partial(self.__call__, obj)

    def __call__(self, *args):
        from .layers import Layer

        bound_self = None
        if args and isinstance(args[0], Layer):
            bound_self, args = args[0], args[1:]

        static_fn = self._static_fn()

        def call_fn(*xs):
            return static_fn(bound_self, *xs) if bound_self is not None \
                else static_fn(*xs)

        if framework._dygraph_tracer is None:
            return call_fn(*args)  # already static: plain build
        key = (id(bound_self), tuple(_spec_of(a).key() for a in args))
        traced = self._cache.get(key)
        if traced is None:
            param_layers = [bound_self] if bound_self is not None else []
            param_layers += _closure_layers(self._fn)
            _, traced = _trace_callable(
                call_fn, [_spec_of(a) for a in args], params_from=param_layers
            )
            self._cache[key] = traced
        outs = [VarBase(o, stop_gradient=True) for o in traced(list(args))]
        return outs[0] if len(outs) == 1 else outs

    @property
    def program_cache(self):
        return self._cache


def declarative(fn):
    """cf. reference @declarative / @paddle.jit.to_static."""
    return _DeclarativeFunction(fn)


to_static = declarative
