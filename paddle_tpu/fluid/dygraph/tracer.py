"""Eager op execution with taped reverse-mode autograd.

Capability parity: reference `paddle/fluid/imperative/tracer.cc:45`
(Tracer::TraceOp creates + runs an op immediately, then CreateGradOpNode
tapes it) and `imperative/basic_engine.cc:159` (reverse sweep with dependency
counting and gradient accumulation, `gradient_accumulator.cc`).

TPU-first redesign: there is no separate grad-op registry.  Every registered
op lowering is a pure JAX function, so the tape stores (opdef, inputs, attrs,
rng key) and the backward sweep calls `jax.vjp` on the forward lowering
itself.  RNG ops (dropout...) replay the exact key used in forward, so the
recomputed mask is identical — no Mask plumbing needed.  Because lowerings
are jax-traceable, a dygraph forward also traces cleanly under `jax.jit`
(the tape then records tracers, which is fine: it is trace-time only).
"""

from __future__ import annotations

import weakref

import jax
import jax.numpy as jnp
import numpy as np

from ..core import dtypes as dtypes_mod
from ..core.registry import LowerContext, get_op_def


def _is_float(arr):
    return jnp.issubdtype(arr.dtype, jnp.floating)


class _TapeEntry:
    __slots__ = ("opdef", "attrs", "ins", "outs", "base_key", "is_test")

    def __init__(self, opdef, attrs, ins, outs, base_key, is_test):
        self.opdef = opdef
        self.attrs = attrs
        self.ins = ins  # {slot: [VarBase]}
        self.outs = outs  # {slot: [VarBase]}
        self.base_key = base_key
        self.is_test = is_test


class Tracer:
    """cf. reference imperative::Tracer + the Python tracer wrapper
    (`python/paddle/fluid/dygraph/tracer.py`)."""

    def __init__(self, seed=0):
        self._vars = weakref.WeakValueDictionary()  # name -> VarBase
        self._tape: list[_TapeEntry] = []
        self._has_grad = True
        self.train_mode = True
        self._base_key = jax.random.PRNGKey(seed)
        self._op_count = 0

    # -- var table (lets static-graph layer code run eagerly by name) -------
    def register_var(self, vb):
        self._vars[vb.name] = vb

    def lookup(self, name):
        return self._vars.get(name)

    # ------------------------------------------------------------------
    def eager_run(self, op_type, ins, attrs, out_slots=None):
        """Run one op immediately on VarBases/arrays.

        ins: {slot: [VarBase | array-like]}.  Returns {slot: [VarBase]}.
        """
        from .varbase import VarBase

        opdef = get_op_def(op_type)
        attrs = dict(attrs or {})
        in_vbs = {}
        arrs = {}
        for slot, vs in ins.items():
            vbs = []
            vals = []
            for v in vs:
                if not isinstance(v, VarBase):
                    v = VarBase(jnp.asarray(v), stop_gradient=True)
                vbs.append(v)
                vals.append(v.data)
            in_vbs[slot] = vbs
            arrs[slot] = vals

        self._op_count += 1
        op_key = jax.random.fold_in(self._base_key, self._op_count)
        ctx = LowerContext(base_key=op_key, is_test=not self.train_mode)
        outs = opdef.lower(ctx, arrs, attrs)

        slots = out_slots or [s for s in opdef.output_slots if s in outs]
        if not slots:
            slots = list(outs)
        out_vbs = {}
        for slot in slots:
            out_vbs[slot] = [VarBase(v, stop_gradient=True) for v in outs[slot]]

        # -- tape ----------------------------------------------------------
        record = (
            self._has_grad
            and opdef.grad_maker is not None
            and any(
                not vb.stop_gradient and _is_float(vb.data)
                for slot, vbs in in_vbs.items()
                if slot not in opdef.no_grad_slots
                for vb in vbs
            )
        )
        if record:
            for slot, vbs in out_vbs.items():
                if slot in opdef.stateful_out_slots:
                    continue
                for vb in vbs:
                    if _is_float(vb.data):
                        vb.stop_gradient = False
                        vb._produced = True
            self._tape.append(
                _TapeEntry(opdef, attrs, in_vbs, out_vbs, op_key, not self.train_mode)
            )
        return out_vbs

    # ------------------------------------------------------------------
    def trace_op(self, op_type, inputs, outputs, attrs):
        """Name-keyed entry point used by LayerHelper in dygraph mode.

        inputs/outputs: {slot: [var_name]} — names resolve through the var
        table, so the static-graph layer functions work unchanged in eager
        mode (cf. reference where one layer API serves both modes).
        """
        from .varbase import VarBase

        ins = {}
        for slot, names in (inputs or {}).items():
            vbs = []
            for n in names:
                vb = self.lookup(n)
                if vb is None:
                    raise RuntimeError(
                        "dygraph: input var '%s' of op '%s' not found in "
                        "tracer table" % (n, op_type)
                    )
                vbs.append(vb)
            ins[slot] = vbs

        out_names = {slot: list(ns) for slot, ns in (outputs or {}).items()}
        # honor explicit stop_gradient=True placeholders (e.g. masks)
        out_vbs = self.eager_run(op_type, ins, attrs, out_slots=list(out_names))
        results = {}
        for slot, names in out_names.items():
            res = []
            for name, src in zip(names, out_vbs[slot]):
                dst = self.lookup(name)
                if dst is None:
                    src.name = name
                    self.register_var(src)
                    dst = src
                else:
                    dst.data = src.data
                    if not src.stop_gradient:
                        dst.stop_gradient = False
                        dst._produced = True
                        # re-point the tape at the caller's placeholder
                        if self._tape and self._tape[-1].outs.get(slot):
                            outs = self._tape[-1].outs[slot]
                            for i, o in enumerate(outs):
                                if o is src:
                                    outs[i] = dst
                    elif not dst.persistable:
                        # in-place state writes (optimizer ParamOut, running
                        # stats) must NOT flip a parameter to stop_gradient
                        dst.stop_gradient = True
                res.append(dst)
            results[slot] = res
        return results

    # -- backward ------------------------------------------------------
    def backward(self, root, retain_graph=False):
        """Reverse sweep (cf. BasicEngine::Execute basic_engine.cc:159)."""
        grads = {}  # id(VarBase) -> cotangent array
        alive = {}  # id -> VarBase (keep alive during sweep)
        grads[id(root)] = jnp.ones_like(root.data)
        alive[id(root)] = root

        for entry in reversed(self._tape):
            opdef, attrs = entry.opdef, entry.attrs
            # cotangents for this op's differentiable outputs
            diff_outs = []
            for slot, vbs in entry.outs.items():
                if slot in opdef.stateful_out_slots:
                    continue
                for vb in vbs:
                    if _is_float(vb.data):
                        diff_outs.append(vb)
            if not any(id(vb) in grads for vb in diff_outs):
                continue

            diff_index = []  # (slot, i)
            primals = []
            for slot, vbs in entry.ins.items():
                if slot in opdef.no_grad_slots:
                    continue
                for i, vb in enumerate(vbs):
                    if not vb.stop_gradient and _is_float(vb.data):
                        diff_index.append((slot, i))
                        primals.append(vb.data)
            if not primals:
                continue

            in_arrs = {s: [vb.data for vb in vbs] for s, vbs in entry.ins.items()}
            out_struct = [
                (slot, len(vbs))
                for slot, vbs in entry.outs.items()
                if slot not in opdef.stateful_out_slots
            ]

            def fwd(*dvals):
                rebuilt = {s: list(vs) for s, vs in in_arrs.items()}
                for (slot, i), v in zip(diff_index, dvals):
                    rebuilt[slot][i] = v
                ctx = LowerContext(base_key=entry.base_key, is_test=entry.is_test)
                outs = opdef.lower(ctx, rebuilt, attrs)
                flat = []
                for slot, n in out_struct:
                    for v in outs[slot][:n]:
                        if jnp.issubdtype(v.dtype, jnp.floating):
                            flat.append(v)
                return tuple(flat)

            _, vjp_fn = jax.vjp(fwd, *primals)
            cots = []
            for vb in diff_outs:
                g = grads.get(id(vb))
                cots.append(g if g is not None else jnp.zeros_like(vb.data))
            in_grads = vjp_fn(tuple(cots))

            for (slot, i), g in zip(diff_index, in_grads):
                vb = entry.ins[slot][i]
                prev = grads.get(id(vb))
                grads[id(vb)] = g if prev is None else prev + g
                alive[id(vb)] = vb

            # free output cotangents (no longer needed once consumed)
            for vb in diff_outs:
                grads.pop(id(vb), None)
                alive.pop(id(vb), None)

        # materialize leaf gradients (params & requires-grad inputs),
        # accumulating across backward calls (reference semantics)
        for vid, g in grads.items():
            vb = alive.get(vid)
            if vb is None:
                continue
            if not getattr(vb, "_produced", False):
                vb._grad = g if vb._grad is None else vb._grad + g

        if not retain_graph:
            self._tape.clear()


def _np(value):
    return np.asarray(value)
