"""Parameter-holding layer classes (dygraph *and* static capable).

Capability parity: reference `python/paddle/fluid/dygraph/nn.py` (Conv2D,
Linear, Pool2D, BatchNorm, Embedding, LayerNorm, Dropout, GroupNorm, PRelu,
Conv2DTranspose...).  Parameters are created once in ``__init__``; forward
composes the shared op layer (`layers/common.py`) which dispatches eagerly
in dygraph mode and appends program ops in static mode — so the same model
class serves both the imperative milestone (ResNet-50 dygraph) and the
static flagship path.
"""

from __future__ import annotations

from .. import framework
from ..initializer import ConstantInitializer
from ..layer_helper import ParamAttr
from ..layers.common import append_simple_op
from .layers import Layer


def _pair(v):
    return [v, v] if isinstance(v, int) else list(v)


class Linear(Layer):
    """cf. reference dygraph/nn.py Linear."""

    def __init__(self, input_dim, output_dim, param_attr=None, bias_attr=None,
                 act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._act = act
        self.weight = self.create_parameter(
            [input_dim, output_dim], attr=param_attr, dtype=dtype
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter(
                [output_dim], attr=bias_attr, dtype=dtype, is_bias=True
            )
        )

    def forward(self, input):
        out = append_simple_op(
            "mul",
            {"X": input, "Y": self.weight},
            {"x_num_col_dims": len(input.shape) - 1, "y_num_col_dims": 1},
        )
        if self.bias is not None:
            out = append_simple_op(
                "elementwise_add",
                {"X": out, "Y": self.bias},
                {"axis": len(input.shape) - 1},
            )
        if self._act:
            out = append_simple_op(self._act, {"X": out}, {})
        return out


class Conv2D(Layer):
    """cf. reference dygraph/nn.py Conv2D."""

    def __init__(self, num_channels, num_filters, filter_size, stride=1,
                 padding=0, dilation=1, groups=1, param_attr=None,
                 bias_attr=None, use_cudnn=True, act=None, dtype="float32",
                 data_format="NCHW"):
        super().__init__(dtype=dtype)
        self._act = act
        self._data_format = data_format
        self._stride = _pair(stride)
        self._padding = _pair(padding)
        self._dilation = _pair(dilation)
        self._groups = groups or 1
        fs = _pair(filter_size)
        import math

        from ..initializer import NormalInitializer

        fan_in = (num_channels // self._groups) * fs[0] * fs[1]
        std = math.sqrt(2.0 / fan_in)
        self.weight = self.create_parameter(
            [num_filters, num_channels // self._groups] + fs,
            attr=param_attr,
            dtype=dtype,
            default_initializer=NormalInitializer(0.0, std),
        )
        self.bias = (
            None
            if bias_attr is False
            else self.create_parameter(
                [num_filters], attr=bias_attr, dtype=dtype, is_bias=True
            )
        )

    def forward(self, input):
        out = append_simple_op(
            "conv2d",
            {"Input": input, "Filter": self.weight},
            {
                "strides": self._stride,
                "paddings": self._padding,
                "dilations": self._dilation,
                "groups": self._groups,
                "data_format": self._data_format,
            },
            out_slots=("Output",),
        )
        if self.bias is not None:
            axis = 1 if self._data_format == "NCHW" else 3
            out = append_simple_op(
                "elementwise_add", {"X": out, "Y": self.bias}, {"axis": axis}
            )
        if self._act:
            out = append_simple_op(self._act, {"X": out}, {})
        return out


class Pool2D(Layer):
    """cf. reference dygraph/nn.py Pool2D."""

    def __init__(self, pool_size=-1, pool_type="max", pool_stride=1,
                 pool_padding=0, global_pooling=False, use_cudnn=True,
                 ceil_mode=False, exclusive=True, data_format="NCHW"):
        super().__init__()
        self._attrs = {
            "pooling_type": pool_type,
            "ksize": _pair(pool_size),
            "strides": _pair(pool_stride),
            "paddings": _pair(pool_padding),
            "global_pooling": global_pooling,
            "ceil_mode": ceil_mode,
            "exclusive": exclusive,
            "data_format": data_format,
        }

    def forward(self, input):
        return append_simple_op("pool2d", {"X": input}, dict(self._attrs))


class BatchNorm(Layer):
    """cf. reference dygraph/nn.py BatchNorm; running stats are buffers
    updated in place by the op's stateful outputs."""

    def __init__(self, num_channels, act=None, is_test=False, momentum=0.9,
                 epsilon=1e-5, param_attr=None, bias_attr=None,
                 dtype="float32", data_layout="NCHW", use_global_stats=False,
                 trainable_statistics=False):
        super().__init__(dtype=dtype)
        self._act = act
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_layout = data_layout
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            [num_channels], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter(
            [num_channels], attr=bias_attr, dtype=dtype, is_bias=True
        )
        self._mean = self.create_parameter(
            [num_channels], attr=ParamAttr(trainable=False), dtype=dtype,
            default_initializer=ConstantInitializer(0.0),
        )
        self._variance = self.create_parameter(
            [num_channels], attr=ParamAttr(trainable=False), dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )

    def forward(self, input):
        from ..layers import nn as static_nn

        is_test = (not self.training) or self._use_global_stats
        if framework.in_dygraph_mode():
            tracer = framework._dygraph_tracer
            outs = tracer.eager_run(
                "batch_norm",
                {
                    "X": [input],
                    "Scale": [self.weight],
                    "Bias": [self.bias],
                    "Mean": [self._mean],
                    "Variance": [self._variance],
                },
                {
                    "momentum": self._momentum,
                    "epsilon": self._epsilon,
                    "is_test": is_test,
                    "data_layout": self._data_layout,
                },
            )
            # write back running stats (MeanOut aliases Mean in reference)
            self._mean.data = outs["MeanOut"][0].data
            self._variance.data = outs["VarianceOut"][0].data
            out = outs["Y"][0]
        else:
            out, *_ = append_simple_op(
                "batch_norm",
                {
                    "X": input,
                    "Scale": self.weight,
                    "Bias": self.bias,
                    "Mean": self._mean,
                    "Variance": self._variance,
                },
                {
                    "momentum": self._momentum,
                    "epsilon": self._epsilon,
                    "is_test": is_test,
                    "data_layout": self._data_layout,
                },
                out_slots=("Y", "SavedMean", "SavedVariance"),
                n_outs=None,
            )
            # alias the running-stat outputs onto the persistable params
            self.block_alias_running_stats()
        if self._act:
            out = append_simple_op(self._act, {"X": out}, {})
        return out

    def block_alias_running_stats(self):
        """In static mode the op just appended has fresh MeanOut/VarianceOut
        temp names; rewrite them to alias the persistable stats so the
        executor writes running statistics back to the scope."""
        block = framework.default_main_program().current_block()
        op = block.ops[-1]
        if op.type == "batch_norm":
            op.outputs["MeanOut"] = [self._mean.name]
            op.outputs["VarianceOut"] = [self._variance.name]


class Embedding(Layer):
    """cf. reference dygraph/nn.py Embedding (lookup_table)."""

    def __init__(self, size, is_sparse=False, is_distributed=False,
                 padding_idx=None, param_attr=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._size = list(size)
        if padding_idx is None:
            self._padding_idx = -1
        elif padding_idx < 0:
            self._padding_idx = int(size[0]) + padding_idx
        else:
            self._padding_idx = padding_idx
        self.weight = self.create_parameter(self._size, attr=param_attr, dtype=dtype)

    def forward(self, input):
        return append_simple_op(
            "lookup_table",
            {"W": self.weight, "Ids": input},
            {"padding_idx": self._padding_idx},
            dtype=self._dtype,
        )


class LayerNorm(Layer):
    """cf. reference dygraph/nn.py LayerNorm."""

    def __init__(self, normalized_shape, scale=True, shift=True, epsilon=1e-5,
                 param_attr=None, bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        self._act = act
        n = 1
        for s in self._normalized_shape:
            n *= int(s)
        self.weight = (
            self.create_parameter(
                [n], attr=param_attr, dtype=dtype,
                default_initializer=ConstantInitializer(1.0),
            )
            if scale
            else None
        )
        self.bias = (
            self.create_parameter([n], attr=bias_attr, dtype=dtype, is_bias=True)
            if shift
            else None
        )

    def forward(self, input):
        bna = len(input.shape) - len(self._normalized_shape)
        ins = {"X": input}
        if self.weight is not None:
            ins["Scale"] = self.weight
        if self.bias is not None:
            ins["Bias"] = self.bias
        out, _, _ = append_simple_op(
            "layer_norm",
            ins,
            {"begin_norm_axis": bna, "epsilon": self._epsilon},
            out_slots=("Y", "Mean", "Variance"),
        )
        if self._act:
            out = append_simple_op(self._act, {"X": out}, {})
        return out


class Dropout(Layer):
    """cf. reference dygraph/nn.py Dropout."""

    def __init__(self, p=0.5, dropout_implementation="downgrade_in_infer",
                 is_test=False):
        super().__init__()
        self._p = p
        self._impl = dropout_implementation

    def forward(self, input):
        out, _ = append_simple_op(
            "dropout",
            {"X": input},
            {
                "dropout_prob": self._p,
                "is_test": not self.training,
                "dropout_implementation": self._impl,
            },
            out_slots=("Out", "Mask"),
        )
        return out


class GroupNorm(Layer):
    """cf. reference dygraph/nn.py GroupNorm."""

    def __init__(self, channels, groups, epsilon=1e-5, param_attr=None,
                 bias_attr=None, act=None, dtype="float32"):
        super().__init__(dtype=dtype)
        self._groups = groups
        self._epsilon = epsilon
        self._act = act
        self.weight = self.create_parameter(
            [channels], attr=param_attr, dtype=dtype,
            default_initializer=ConstantInitializer(1.0),
        )
        self.bias = self.create_parameter(
            [channels], attr=bias_attr, dtype=dtype, is_bias=True
        )

    def forward(self, input):
        out, _, _ = append_simple_op(
            "group_norm",
            {"X": input, "Scale": self.weight, "Bias": self.bias},
            {"groups": self._groups, "epsilon": self._epsilon},
            out_slots=("Y", "Mean", "Variance"),
        )
        if self._act:
            out = append_simple_op(self._act, {"X": out}, {})
        return out
