"""VarBase / ParamBase: eager tensors backed by jax.Array.

Capability parity: reference `paddle/fluid/imperative/layer.h:56` (VarBase =
tensor + grad var + stop_gradient) and the Python-side patch methods
(`dygraph/varbase_patch_methods.py` — backward:127, gradient, numpy).

Subclasses :class:`framework.Variable` so every static-graph layer function
(isinstance checks, `.name/.dtype/.shape` access, operator sugar) accepts
eager tensors unchanged.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import framework, unique_name
from ..core import dtypes as dtypes_mod


class _EagerBlockShim:
    """Duck-typed Block for program-rewrite utilities (clip, regularizer)
    that reach vars through ``grad.block`` — resolves names via the active
    tracer so those utilities run eagerly unchanged."""

    def create_var(self, name=None, shape=None, dtype="float32",
                   stop_gradient=True, **kw):
        return VarBase(None, name=name, stop_gradient=stop_gradient)

    def append_op(self, type, inputs=None, outputs=None, attrs=None, infer=False):
        return framework._dygraph_tracer.trace_op(type, inputs, outputs, attrs)

    def var(self, name):
        vb = framework._dygraph_tracer.lookup(name)
        if vb is None:
            raise KeyError("eager var '%s' not found" % name)
        return vb

    def has_var(self, name):
        return framework._dygraph_tracer.lookup(name) is not None


_eager_block_shim = _EagerBlockShim()


class VarBase(framework.Variable):
    def __init__(self, data, name=None, stop_gradient=True, persistable=False):
        # NOTE: deliberately does NOT call Variable.__init__ — an eager tensor
        # belongs to no Block; shape/dtype derive from the live array.
        self.name = name or unique_name.generate("eager_tmp")
        self.data = None if data is None else jnp.asarray(data)
        self.stop_gradient = stop_gradient
        self.persistable = persistable
        self.is_data = False
        self._grad = None
        self._produced = False  # True once an op on the tape wrote this var
        tracer = framework._dygraph_tracer
        if tracer is not None:
            tracer.register_var(self)

    @property
    def block(self):
        return _eager_block_shim if framework._dygraph_tracer is not None else None

    @block.setter
    def block(self, _):
        pass

    # -- array-facing ----------------------------------------------------
    @property
    def shape(self):
        return tuple(int(s) for s in self.data.shape) if self.data is not None else None

    @shape.setter
    def shape(self, _):
        pass  # shape always derives from data

    @property
    def dtype(self):
        return dtypes_mod.to_str(self.data.dtype) if self.data is not None else "float32"

    @dtype.setter
    def dtype(self, _):
        pass

    def numpy(self):
        return np.asarray(self.data)

    def item(self):
        return self.numpy().item()

    def __float__(self):
        return float(self.numpy())

    def __bool__(self):
        return bool(self.numpy())  # eager: true data-dependent truthiness

    def __len__(self):
        return int(self.data.shape[0])

    def __getitem__(self, idx):
        # slicing is differentiable; route through the tape when needed
        tracer = framework._dygraph_tracer
        if (
            tracer is not None
            and not self.stop_gradient
            and jnp.issubdtype(self.data.dtype, jnp.floating)
        ):
            return _tape_getitem(tracer, self, idx)
        return VarBase(self.data[idx], stop_gradient=True)

    # -- autograd --------------------------------------------------------
    def backward(self, retain_graph=False):
        tracer = framework._dygraph_tracer
        if tracer is None:
            raise RuntimeError("backward() requires dygraph mode (fluid.dygraph.guard)")
        tracer.backward(self, retain_graph=retain_graph)

    def gradient(self):
        return None if self._grad is None else np.asarray(self._grad)

    @property
    def grad(self):
        return self._grad

    def clear_gradient(self):
        self._grad = None

    def detach(self):
        return VarBase(self.data, stop_gradient=True)

    def astype(self, dtype):
        out = VarBase(self.data.astype(dtypes_mod.to_jnp(dtype)))
        out.stop_gradient = self.stop_gradient
        return out

    def __repr__(self):
        return "VarBase(name=%s, shape=%s, dtype=%s, stop_gradient=%s)\n%s" % (
            self.name,
            self.shape,
            self.dtype,
            self.stop_gradient,
            self.data,
        )


def _tape_getitem(tracer, vb, idx):
    """Record x[idx] on the tape as a one-off op via jax.vjp in backward."""
    from ..core.registry import LowerContext, OpDef

    def lower(ctx, ins, attrs):
        return {"Out": [ins["X"][0][idx]]}

    opdef = OpDef("__getitem__", lower, ["X"], ["Out"])
    out_data = vb.data[idx]
    out = VarBase(out_data, stop_gradient=False)
    out._produced = True
    from .tracer import _TapeEntry

    tracer._tape.append(
        _TapeEntry(opdef, {}, {"X": [vb]}, {"Out": [out]}, None, True)
    )
    return out


class ParamBase(VarBase):
    """Eager trainable parameter (cf. reference ParamBase / dygraph Parameter)."""

    def __init__(self, data, name=None, trainable=True, **kw):
        self.trainable = trainable
        self.optimize_attr = kw.pop("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kw.pop("regularizer", None)
        self.need_clip = kw.pop("need_clip", True)
        self.is_distributed = kw.pop("is_distributed", False)
        super().__init__(
            data, name=name, stop_gradient=not trainable, persistable=True
        )
