"""Encrypted model IO (capability parity: reference
`paddle/fluid/framework/io/crypto/` — AES-GCM cipher + CipherFactory used
to encrypt `__model__`/params files for deployment).

This environment ships no AES library, so the cipher is an HMAC-SHA256
counter-mode stream (PRF keystream XOR) with an HMAC integrity tag —
same interface and deployment flow (encrypt the saved model directory,
decrypt at load), documented as not AES-interoperable with the
reference's files.
"""

from __future__ import annotations

import hashlib
import hmac
import os

_MAGIC = b"PTPUENC1"


def _keystream(key: bytes, nonce: bytes, n: int):
    out = bytearray()
    counter = 0
    while len(out) < n:
        block = hmac.new(
            key, nonce + counter.to_bytes(8, "big"), hashlib.sha256
        ).digest()
        out.extend(block)
        counter += 1
    return bytes(out[:n])


def _norm_key(key) -> bytes:
    if isinstance(key, str):
        key = key.encode()
    return hashlib.sha256(key).digest()


def _xor(data: bytes, ks: bytes) -> bytes:
    import numpy as np

    return np.bitwise_xor(
        np.frombuffer(data, np.uint8), np.frombuffer(ks, np.uint8)
    ).tobytes()


def encrypt_bytes(data: bytes, key) -> bytes:
    k = _norm_key(key)
    nonce = os.urandom(16)
    ct = _xor(data, _keystream(k, nonce, len(data)))
    tag = hmac.new(k, nonce + ct, hashlib.sha256).digest()
    return _MAGIC + nonce + tag + ct


def decrypt_bytes(blob: bytes, key) -> bytes:
    if not blob.startswith(_MAGIC):
        raise ValueError("not an encrypted model blob")
    k = _norm_key(key)
    nonce = blob[8:24]
    tag = blob[24:56]
    ct = blob[56:]
    if not hmac.compare_digest(
            tag, hmac.new(k, nonce + ct, hashlib.sha256).digest()):
        raise ValueError("wrong key or corrupted encrypted model")
    return _xor(ct, _keystream(k, nonce, len(ct)))


def encrypt_file(path, key, out_path=None):
    with open(path, "rb") as f:
        blob = encrypt_bytes(f.read(), key)
    with open(out_path or path, "wb") as f:
        f.write(blob)


def decrypt_file(path, key, out_path=None):
    with open(path, "rb") as f:
        data = decrypt_bytes(f.read(), key)
    with open(out_path or path, "wb") as f:
        f.write(data)


def encrypt_inference_model(dirname, key):
    """Encrypt every file of a save_inference_model directory in place
    (reference deploy flow: ship only ciphertext)."""
    for name in os.listdir(dirname):
        encrypt_file(os.path.join(dirname, name), key)


def decrypt_inference_model(dirname, key, out_dirname=None):
    out_dirname = out_dirname or dirname
    os.makedirs(out_dirname, exist_ok=True)
    for name in os.listdir(dirname):
        decrypt_file(os.path.join(dirname, name), key,
                     os.path.join(out_dirname, name))
