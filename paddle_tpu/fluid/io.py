"""Checkpointing & deployment export.

Capability parity: reference `python/paddle/fluid/io.py` — save_vars:224,
save_params, save_persistables:598, load_vars, load_persistables,
save_inference_model:1093 (prunes to the feed/fetch subgraph + serialized
program), load_inference_model:1303, unified save:1598/load:1662
(.pdparams/.pdopt), load_program_state:1833 / set_program_state.

TPU-first: values are host numpy arrays saved via npz (no save/load ops in
the program — the executor scope is the source of truth); the serialized
program is the JSON IR from framework.py.
"""

import os
import pickle

import numpy as np

from . import framework
from .core.scope import global_scope


def _collect_vars(program, predicate):
    return [v for v in program.list_vars() if predicate(v)]


def _is_persistable(v):
    return v.persistable and not v.is_data


def _is_param(v):
    return isinstance(v, framework.Parameter)


def _save_var_dict(dirname, var_values, filename=None):
    os.makedirs(dirname, exist_ok=True)
    if filename:
        np.savez(os.path.join(dirname, filename), **var_values)
    else:
        for name, val in var_values.items():
            np.save(os.path.join(dirname, name.replace("/", "__slash__")), val)


def save_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    program = main_program or framework.default_main_program()
    if vars is None:
        vars = _collect_vars(program, predicate or _is_persistable)
    scope = global_scope()
    values = {}
    for v in vars:
        val = scope.find_var(v.name)
        if val is None:
            raise RuntimeError("variable %s has no value in scope" % v.name)
        values[v.name] = np.asarray(val)
    _save_var_dict(dirname, values, filename)


def save_params(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_param, filename=filename)


def save_persistables(executor, dirname, main_program=None, filename=None):
    save_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


def load_vars(executor, dirname, main_program=None, vars=None, predicate=None,
              filename=None):
    program = main_program or framework.default_main_program()
    if vars is None:
        vars = _collect_vars(program, predicate or _is_persistable)
    scope = global_scope()
    if filename:
        data = np.load(os.path.join(dirname, filename), allow_pickle=False)
        get = lambda name: data[name]
    else:
        def get(name):
            path = os.path.join(dirname, name.replace("/", "__slash__") + ".npy")
            return np.load(path)

    import jax

    for v in vars:
        arr = get(v.name)
        scope.set(v.name, jax.device_put(arr))


def load_params(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_param, filename=filename)


def load_persistables(executor, dirname, main_program=None, filename=None):
    load_vars(executor, dirname, main_program, predicate=_is_persistable,
              filename=filename)


# ---------------------------------------------------------------------------
# graph pruning for inference export
# ---------------------------------------------------------------------------

def _prune_program(program, feed_names, target_names):
    """Keep only ops backward-reachable from targets, stopping at feeds
    (cf. reference Program._prune_with_input used by save_inference_model)."""
    pruned = program.clone(for_test=True)
    block = pruned.global_block
    needed = set(target_names)
    keep = []
    for op in reversed(block.ops):
        outs = op.all_output_names()
        if any(n in needed for n in outs):
            keep.append(op)
            for n in op.all_input_names():
                if n not in feed_names:
                    needed.add(n)
    keep.reverse()
    block.ops = keep
    # drop vars not referenced anymore (keep feeds + referenced)
    referenced = set(feed_names) | set(target_names)
    for op in keep:
        referenced.update(op.all_input_names())
        referenced.update(op.all_output_names())
    block.vars = {k: v for k, v in block.vars.items() if k in referenced}
    for name in feed_names:
        if name in block.vars:
            block.vars[name].is_data = True
    pruned._bump()
    return pruned


def _verify_io_program(program, feed_names, fetch_names, what):
    """Static verification gate on the export/load paths
    (FLAGS_verify_io_programs, default on): a pruned-wrong or corrupted
    serialized program fails HERE with structured diagnostics instead of
    surfacing as an opaque trace error at serving time.  Structural
    invariants only — cheap enough for in-loop saves; full shape
    re-inference stays available via analysis.verify_program /
    FLAGS_verify_program / tools/program_lint.py."""
    from .flags import get_flags

    if not get_flags(["FLAGS_verify_io_programs"])["FLAGS_verify_io_programs"]:
        return
    from ..analysis import assert_program_valid

    assert_program_valid(program, feed_names=feed_names,
                         fetch_names=fetch_names, check_shapes=False,
                         what=what)


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None):
    """cf. reference io.py:1093 — prune to the inference subgraph, serialize
    the program + parameters.  The pruned program is statically verified
    before anything is written (FLAGS_verify_io_programs)."""
    program = main_program or framework.default_main_program()
    target_names = [
        t.name if isinstance(t, framework.Variable) else t for t in target_vars
    ]
    pruned = _prune_program(program, list(feeded_var_names), target_names)
    _verify_io_program(
        pruned, list(feeded_var_names), target_names,
        "pruned inference program (save_inference_model would export a "
        "broken model)")
    os.makedirs(dirname, exist_ok=True)
    model_path = os.path.join(dirname, model_filename or "__model__.json")
    with open(model_path, "w") as f:
        f.write(pruned.to_json())
    meta = {"feed_names": list(feeded_var_names), "fetch_names": target_names}
    with open(os.path.join(dirname, "__meta__.pkl"), "wb") as f:
        pickle.dump(meta, f)
    save_vars(
        executor, dirname, pruned,
        predicate=lambda v: v.persistable and not v.is_data,
        filename=params_filename,
    )
    return target_names


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None):
    """Returns [program, feed_names, fetch_vars] (reference signature)."""
    model_path = os.path.join(dirname, model_filename or "__model__.json")
    with open(model_path) as f:
        program = framework.Program.from_json(f.read())
    with open(os.path.join(dirname, "__meta__.pkl"), "rb") as f:
        meta = pickle.load(f)
    _verify_io_program(
        program, list(meta.get("feed_names", [])),
        list(meta.get("fetch_names", [])),
        "deserialized inference program %r" % model_path)
    load_vars(
        executor, dirname, program,
        predicate=lambda v: v.persistable and not v.is_data,
        filename=params_filename,
    )
    fetch_vars = [program.global_block.var(n) for n in meta["fetch_names"]]
    return [program, meta["feed_names"], fetch_vars]


# ---------------------------------------------------------------------------
# unified save/load (.pdparams / .pdopt) — cf. reference io.py:1598
# ---------------------------------------------------------------------------

def save(program, model_path):
    scope = global_scope()
    params = {}
    opt = {}
    for v in program.list_vars():
        if not v.persistable or v.is_data:
            continue
        val = scope.find_var(v.name)
        if val is None:
            continue
        (params if _is_param(v) else opt)[v.name] = np.asarray(val)
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f)
    with open(model_path + ".pdopt", "wb") as f:
        pickle.dump(opt, f)
    with open(model_path + ".pdmodel", "w") as f:
        f.write(program.to_json())


def load(program, model_path, executor=None):
    state = load_program_state(model_path)
    set_program_state(program, state)


def load_program_state(model_path):
    state = {}
    for suffix in (".pdparams", ".pdopt"):
        path = model_path + suffix
        if os.path.exists(path):
            with open(path, "rb") as f:
                state.update(pickle.load(f))
    return state


def set_program_state(program, state_dict):
    import jax

    scope = global_scope()
    missing = []
    for v in program.list_vars():
        if not v.persistable or v.is_data:
            continue
        if v.name in state_dict:
            scope.set(v.name, jax.device_put(np.asarray(state_dict[v.name])))
        else:
            missing.append(v.name)
    return missing
