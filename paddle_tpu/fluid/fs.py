"""File-system shell abstraction for fleet checkpoints & datasets.

Capability parity: reference `framework/io/fs.{h,cc}` + `shell.{h,cc}`
(popen-based local/HDFS ops behind one interface) and the Python fleet
side `incubate/fleet/utils/fs.py` (LocalFS / BDFS clients with
ls_dir/is_dir/upload/download/mkdirs/delete).

LocalFS is complete; HDFSClient shells out to the `hadoop fs` CLI when
one is configured (the reference does exactly this through shell.cc) and
raises with guidance otherwise — checkpoint code written against the
interface ports unchanged between backends."""

from __future__ import annotations

import os
import shutil
import subprocess


class FS:
    """Interface (cf. reference fs.h function table)."""

    def ls_dir(self, path):
        raise NotImplementedError

    def is_dir(self, path):
        raise NotImplementedError

    def is_file(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError

    def mkdirs(self, path):
        raise NotImplementedError

    def delete(self, path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mv(self, src, dst):
        raise NotImplementedError

    def touch(self, path):
        raise NotImplementedError


class LocalFS(FS):
    """cf. reference LocalFS (fs.cc localfs_* functions)."""

    def ls_dir(self, path):
        if not os.path.exists(path):
            return [], []
        dirs, files = [], []
        for name in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, name)) else files
             ).append(name)
        return dirs, files

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def is_exist(self, path):
        return os.path.exists(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def upload(self, local_path, fs_path):
        self.mkdirs(os.path.dirname(fs_path) or ".")
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def mv(self, src, dst):
        shutil.move(src, dst)

    def touch(self, path):
        self.mkdirs(os.path.dirname(path) or ".")
        open(path, "a").close()


class HDFSClient(FS):
    """cf. reference HDFSClient (fs.cc hdfs_* via popen `hadoop fs`)."""

    def __init__(self, hadoop_home=None, configs=None):
        self._hadoop = (
            os.path.join(hadoop_home, "bin", "hadoop")
            if hadoop_home else shutil.which("hadoop")
        )
        self._configs = configs or {}

    def _cmd(self, *args):
        if self._hadoop is None or not os.path.exists(self._hadoop):
            raise RuntimeError(
                "HDFSClient needs a hadoop CLI (hadoop_home=...) — "
                "none found; use LocalFS or mount the DFS locally"
            )
        pre = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            pre += ["-D%s=%s" % (k, v)]
        return subprocess.run(
            pre + list(args), capture_output=True, text=True, timeout=300
        )

    def _cmd_checked(self, *args):
        """Mutating ops must FAIL LOUDLY (reference raises ExecuteError on
        nonzero exit) — a silently lost checkpoint is data loss."""
        r = self._cmd(*args)
        if r.returncode != 0:
            raise RuntimeError(
                "hadoop fs %s failed (rc=%d): %s"
                % (" ".join(args), r.returncode, r.stderr.strip()[:500])
            )
        return r

    def ls_dir(self, path):
        r = self._cmd("-ls", path)
        dirs, files = [], []
        for line in r.stdout.splitlines():
            parts = line.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, path):
        return self._cmd("-test", "-e", path).returncode == 0

    def is_dir(self, path):
        return self._cmd("-test", "-d", path).returncode == 0

    def is_file(self, path):
        return self.is_exist(path) and not self.is_dir(path)

    def mkdirs(self, path):
        self._cmd_checked("-mkdir", "-p", path)

    def delete(self, path):
        self._cmd_checked("-rm", "-r", "-f", path)

    def upload(self, local_path, fs_path):
        self._cmd_checked("-put", "-f", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._cmd_checked("-get", fs_path, local_path)

    def mv(self, src, dst):
        self._cmd_checked("-mv", src, dst)

    def touch(self, path):
        self._cmd_checked("-touchz", path)
