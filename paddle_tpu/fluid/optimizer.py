"""Optimizers: minimize() = append_backward + optimization pass.

Capability parity: reference `python/paddle/fluid/optimizer.py` — base
Optimizer:55 (minimize = append_backward + _create_optimization_pass, global
LR var, per-param accumulators as persistable vars), SGD:918, Momentum:1012,
LarsMomentum:1562, Adagrad:1676, Adam:1792, Adamax:2058, Dpsgd:2230,
DecayedAdagrad:2325, Adadelta:2435, RMSProp:2554, Ftrl:2742, Lamb:2901.

The update math itself is in ops/optimizer_ops.py; state (accumulators) are
persistable vars initialized by the startup program, so checkpoint/resume of
optimizer state is automatic (reference behavior).
"""

from __future__ import annotations

from . import framework, unique_name
from .backward import append_backward
from .framework import Variable, default_startup_program
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper


class Optimizer:
    def __init__(self, learning_rate, regularization=None, grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or unique_name.generate(type(self).__name__.lower())
        self._accumulators = {}  # acc_name -> {param_name: Variable}
        self._lr_var = None
        self.helper = None

    # -- learning rate ------------------------------------------------------
    def _global_learning_rate(self):
        if self._lr_var is not None:
            return self._lr_var
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return self._lr_var
        block = framework.default_main_program().global_block
        name = unique_name.generate("learning_rate")
        self._lr_var = block.create_var(
            name=name, shape=(1,), dtype="float32", persistable=True,
            stop_gradient=True,
        )
        sb = default_startup_program().global_block
        sb.create_var(name=name, shape=(1,), dtype="float32", persistable=True,
                      stop_gradient=True)
        sb.append_op(
            "fill_constant",
            outputs={"Out": [name]},
            attrs={"shape": [1], "value": float(self._learning_rate),
                   "dtype": "float32"},
            infer=False,
        )
        return self._lr_var

    def current_step_lr(self):
        from .core.scope import global_scope

        v = global_scope().find_var(self._global_learning_rate().name)
        return float(v[0]) if v is not None else float(self._learning_rate)

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype="float32"):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        shape = list(shape if shape is not None else param.shape)
        var_name = unique_name.generate(param.name + "_" + name)
        mb = framework.default_main_program().global_block
        v = mb.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        sb = default_startup_program().global_block
        sb.create_var(name=var_name, shape=shape, dtype=dtype, persistable=True,
                      stop_gradient=True)
        sb.append_op(
            "fill_constant",
            outputs={"Out": [var_name]},
            attrs={"shape": shape, "value": float(fill_value), "dtype": dtype},
            infer=False,
        )
        self._accumulators.setdefault(name, {})[param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- the per-op hook subclasses implement --------------------------------
    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, params_grads):
        pass

    # -- public API ---------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        block = framework.default_main_program().global_block
        first_op_idx = len(block.ops)
        # reference order (optimizer.py apply_gradients): clip the raw
        # gradients FIRST, then append weight-decay regularization unclipped
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        from .regularizer import append_regularization_ops

        params_grads = append_regularization_ops(params_grads, self.regularization)
        self._create_accumulators(block, [p for p, _ in params_grads])
        for pg in params_grads:
            self._append_optimize_op(block, pg)
        self._finish_update(block, params_grads)
        # tag for clone(for_test) pruning (cf. OpRole.Optimize)
        for op in block.ops[first_op_idx:]:
            op.attrs.setdefault("op_role", "optimize")
        return params_grads

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        self.apply_gradients(params_grads)
        return [], params_grads

    # helper for emitting update ops with the in-place convention
    def _emit(self, block, type, param, grad, extra_inputs, extra_outputs, attrs):
        inputs = {
            "Param": [param.name],
            "Grad": [grad.name],
            "LearningRate": [self._global_learning_rate().name],
        }
        for k, v in extra_inputs.items():
            inputs[k] = [v.name if isinstance(v, Variable) else v]
        outputs = {"ParamOut": [param.name]}
        for k, v in extra_outputs.items():
            outputs[k] = [v.name if isinstance(v, Variable) else v]
        block.append_op(type, inputs=inputs, outputs=outputs, attrs=attrs, infer=False)


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, pg):
        p, g = pg
        self._emit(block, "sgd", p, g, {}, {}, {})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        self._emit(
            block, "momentum", p, g,
            {"Velocity": v}, {"VelocityOut": v},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        self._emit(
            block, "lars_momentum", p, g,
            {"Velocity": v}, {"VelocityOut": v},
            {"mu": self._momentum, "lars_coeff": self._lars_coeff,
             "lars_weight_decay": self._lars_weight_decay},
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        self._emit(
            block, "adagrad", p, g, {"Moment": m}, {"MomentOut": m},
            {"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    _op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _extra_attrs(self):
        return {}

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        attrs = {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}
        attrs.update(self._extra_attrs())
        self._emit(
            block, self._op_type, p, g,
            {"Moment1": m1, "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p},
            {"Moment1Out": m1, "Moment2Out": m2, "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs,
        )


class AdamWOptimizer(AdamOptimizer):
    """Decoupled weight decay (2.0-era paddle.optimizer.AdamW parity)."""

    _op_type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _extra_attrs(self):
        return {"coeff": self._coeff}


class LambOptimizer(AdamOptimizer):
    """cf. reference optimizer.py Lamb:2901."""

    _op_type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)
        self._weight_decay = lamb_weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        self._emit(
            block, "adamax", p, g,
            {
                "Moment": self._get_accumulator("moment", p),
                "InfNorm": self._get_accumulator("inf_norm", p),
                "Beta1Pow": self._get_accumulator("beta1_pow_acc", p),
            },
            {
                "MomentOut": self._get_accumulator("moment", p),
                "InfNormOut": self._get_accumulator("inf_norm", p),
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block, params_grads):
        # beta1_pow *= beta1 each step (reference does this with a scale op)
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                "scale",
                inputs={"X": [b1p.name]},
                outputs={"Out": [b1p.name]},
                attrs={"scale": self._beta1},
                infer=False,
            )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        self._emit(
            block, "decayed_adagrad", p, g, {"Moment": m}, {"MomentOut": m},
            {"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        g2 = self._get_accumulator("avg_squared_grad", p)
        u2 = self._get_accumulator("avg_squared_update", p)
        block.append_op(
            "adadelta",
            inputs={
                "Param": [p.name], "Grad": [g.name],
                "AvgSquaredGrad": [g2.name], "AvgSquaredUpdate": [u2.name],
            },
            outputs={
                "ParamOut": [p.name],
                "AvgSquaredGradOut": [g2.name],
                "AvgSquaredUpdateOut": [u2.name],
            },
            attrs={"rho": self._rho, "epsilon": self._epsilon},
            infer=False,
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        self._emit(
            block, "rmsprop", p, g,
            {
                "Moment": self._get_accumulator("momentum", p),
                "MeanSquare": self._get_accumulator("mean_square", p),
                "MeanGrad": self._get_accumulator("mean_grad", p),
            },
            {
                "MomentOut": self._get_accumulator("momentum", p),
                "MeanSquareOut": self._get_accumulator("mean_square", p),
                "MeanGradOut": self._get_accumulator("mean_grad", p),
            },
            {"decay": self._rho, "epsilon": self._epsilon,
             "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        block.append_op(
            "ftrl",
            inputs={
                "Param": [p.name],
                "SquaredAccumulator": [sq.name],
                "LinearAccumulator": [lin.name],
                "Grad": [g.name],
                "LearningRate": [self._global_learning_rate().name],
            },
            outputs={
                "ParamOut": [p.name],
                "SquaredAccumOut": [sq.name],
                "LinearAccumOut": [lin.name],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
            infer=False,
        )


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate, clip=10.0, batch_size=16.0, sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, pg):
        p, g = pg
        self._emit(
            block, "dpsgd", p, g, {}, {},
            {"clip": self._clip, "batch_size": self._batch_size, "sigma": self._sigma},
        )


# reference-style lowercase aliases (cf. optimizer.py bottom: SGD = SGDOptimizer)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
Dpsgd = DpsgdOptimizer
LarsMomentum = LarsMomentumOptimizer
