"""Optimizers: minimize() = append_backward + optimization pass.

Capability parity: reference `python/paddle/fluid/optimizer.py` — base
Optimizer:55 (minimize = append_backward + _create_optimization_pass, global
LR var, per-param accumulators as persistable vars), SGD:918, Momentum:1012,
LarsMomentum:1562, Adagrad:1676, Adam:1792, Adamax:2058, Dpsgd:2230,
DecayedAdagrad:2325, Adadelta:2435, RMSProp:2554, Ftrl:2742, Lamb:2901.

The update math itself is in ops/optimizer_ops.py; state (accumulators) are
persistable vars initialized by the startup program, so checkpoint/resume of
optimizer state is automatic (reference behavior).
"""

from __future__ import annotations

from . import framework, unique_name
from .backward import append_backward
from .framework import Operator, Variable, default_startup_program
from .initializer import ConstantInitializer
from .layer_helper import LayerHelper


class _EagerBlock:
    """Block shim: lets _append_optimize_op emit update ops through the
    dygraph tracer (name-resolved via the tracer var table) instead of a
    program block."""

    def __init__(self):
        self.ops = []

    def append_op(self, type, inputs=None, outputs=None, attrs=None, infer=False):
        framework._dygraph_tracer.trace_op(type, inputs, outputs, attrs)
        op = framework.Operator(self, type, inputs, outputs, attrs)
        self.ops.append(op)
        return op


class Optimizer:
    def __init__(self, learning_rate, regularization=None, grad_clip=None, name=None):
        self._learning_rate = learning_rate
        self.regularization = regularization
        self._grad_clip = grad_clip
        self._name = name or unique_name.generate(type(self).__name__.lower())
        self._accumulators = {}  # acc_name -> {param_name: Variable}
        self._lr_var = None
        self.helper = None

    # -- learning rate ------------------------------------------------------
    def _global_learning_rate(self):
        if self._lr_var is not None:
            return self._lr_var
        if isinstance(self._learning_rate, Variable):
            self._lr_var = self._learning_rate
            return self._lr_var
        if framework.in_dygraph_mode():
            from .dygraph.varbase import VarBase

            lr0 = self._learning_rate
            if callable(lr0):  # dygraph LR scheduler object
                lr0 = lr0.get_lr() if hasattr(lr0, "get_lr") else lr0()
            self._lr_var = VarBase(
                [float(lr0)],
                name=unique_name.generate("learning_rate"),
                stop_gradient=True,
                persistable=True,
            )
            return self._lr_var
        block = framework.default_main_program().global_block
        name = unique_name.generate("learning_rate")
        self._lr_var = block.create_var(
            name=name, shape=(1,), dtype="float32", persistable=True,
            stop_gradient=True,
        )
        sb = default_startup_program().global_block
        sb.create_var(name=name, shape=(1,), dtype="float32", persistable=True,
                      stop_gradient=True)
        sb.append_op(
            "fill_constant",
            outputs={"Out": [name]},
            attrs={"shape": [1], "value": float(self._learning_rate),
                   "dtype": "float32"},
            infer=False,
        )
        return self._lr_var

    def current_step_lr(self):
        lr = self._global_learning_rate()
        from .dygraph.varbase import VarBase

        if isinstance(lr, VarBase):
            return float(lr.numpy().reshape(-1)[0])
        from .core.scope import global_scope

        v = global_scope().find_var(lr.name)
        return float(v[0]) if v is not None else float(self._learning_rate)

    def set_lr(self, value):
        """cf. reference optimizer set_lr (dygraph) / scope write (static)."""
        import jax.numpy as jnp

        lr = self._global_learning_rate()
        from .dygraph.varbase import VarBase

        if isinstance(lr, VarBase):
            lr.data = jnp.asarray([float(value)], dtype=lr.data.dtype)
        else:
            from .core.scope import global_scope

            global_scope().set(lr.name, jnp.asarray([float(value)], jnp.float32))

    # -- accumulators -------------------------------------------------------
    def _add_accumulator(self, name, param, fill_value=0.0, shape=None, dtype="float32"):
        if name in self._accumulators and param.name in self._accumulators[name]:
            return self._accumulators[name][param.name]
        shape = list(shape if shape is not None else param.shape)
        if framework.in_dygraph_mode():
            import jax.numpy as jnp

            from .core import dtypes as dtypes_mod
            from .dygraph.varbase import VarBase

            v = VarBase(
                jnp.full(tuple(shape), float(fill_value),
                         dtype=dtypes_mod.to_jnp(dtype)),
                name=unique_name.generate(param.name + "_" + name),
                stop_gradient=True,
                persistable=True,
            )
            self._accumulators.setdefault(name, {})[param.name] = v
            return v
        var_name = unique_name.generate(param.name + "_" + name)
        mb = framework.default_main_program().global_block
        v = mb.create_var(
            name=var_name, shape=shape, dtype=dtype, persistable=True,
            stop_gradient=True,
        )
        sb = default_startup_program().global_block
        sb.create_var(name=var_name, shape=shape, dtype=dtype, persistable=True,
                      stop_gradient=True)
        sb.append_op(
            "fill_constant",
            outputs={"Out": [var_name]},
            attrs={"shape": shape, "value": float(fill_value), "dtype": dtype},
            infer=False,
        )
        self._accumulators.setdefault(name, {})[param.name] = v
        return v

    def _get_accumulator(self, name, param):
        return self._accumulators[name][param.name]

    # -- the per-op hook subclasses implement --------------------------------
    def _append_optimize_op(self, block, param_and_grad):
        raise NotImplementedError

    def _create_accumulators(self, block, parameters):
        pass

    def _finish_update(self, block, params_grads):
        pass

    # -- public API ---------------------------------------------------------
    def backward(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        return append_backward(loss, parameter_list, no_grad_set)

    def apply_gradients(self, params_grads):
        if framework.in_dygraph_mode():
            block = _EagerBlock()
        else:
            block = framework.default_main_program().global_block
        first_op_idx = len(block.ops)
        # SelectedRows-style sparse grads (marker .selected_rows) have no
        # clip/regularization lowering yet — refuse loudly rather than
        # silently skipping them (which would under-clip everything else
        # and drop the embedding's weight decay)
        sparse = [
            pg for pg in params_grads
            if getattr(pg[1], "selected_rows", None)
        ]
        params_grads = [
            pg for pg in params_grads
            if not getattr(pg[1], "selected_rows", None)
        ]
        if sparse:
            bad = [p.name for p, _ in sparse]
            if self._grad_clip is not None:
                raise NotImplementedError(
                    "gradient clipping is not implemented for sparse "
                    "(SelectedRows) gradients (%s); set is_sparse=False "
                    "or drop grad_clip" % bad
                )
            if self.regularization is not None or any(
                getattr(p, "regularizer", None) for p, _ in sparse
            ):
                raise NotImplementedError(
                    "weight-decay regularization is not implemented for "
                    "sparse (SelectedRows) gradients (%s); set "
                    "is_sparse=False or drop the regularizer" % bad
                )
        # reference order (optimizer.py apply_gradients): clip the raw
        # gradients FIRST, then append weight-decay regularization unclipped
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        from .regularizer import append_regularization_ops

        params_grads = append_regularization_ops(params_grads, self.regularization)
        params_grads = params_grads + sparse
        self._create_accumulators(block, [p for p, _ in params_grads])
        for pg in params_grads:
            self._append_optimize_op(block, pg)
        self._finish_update(block, params_grads)
        # tag for clone(for_test) pruning (cf. OpRole.Optimize)
        for op in block.ops[first_op_idx:]:
            op.attrs.setdefault("op_role", "optimize")
        return params_grads

    def apply_optimize(self, loss, startup_program, params_grads):
        return self.apply_gradients(params_grads)

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        if framework.in_dygraph_mode():
            return self._dygraph_minimize(loss, parameter_list)
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        self.apply_gradients(params_grads)
        return [], params_grads

    def _dygraph_minimize(self, loss, parameter_list):
        """Eager update path (cf. reference dygraph minimize): the user has
        called loss.backward(); apply the SAME optimizer ops eagerly through
        the tracer — updates land in-place on the ParamBase arrays."""
        if parameter_list is None:
            raise ValueError(
                "dygraph minimize() requires parameter_list "
                "(cf. reference optimizer parameter_list requirement)"
            )
        from .dygraph.varbase import VarBase

        # dygraph LR schedulers: refresh the lr var every step
        if callable(self._learning_rate) and not isinstance(
            self._learning_rate, Variable
        ):
            import jax.numpy as jnp

            lr_var = self._global_learning_rate()
            lr_var.data = jnp.asarray([float(self._learning_rate())],
                                      jnp.float32)

        params_grads = []
        for p in parameter_list:
            if getattr(p, "_grad", None) is None or not getattr(p, "trainable", True):
                continue
            g = VarBase(p._grad, name=p.name + "@GRAD", stop_gradient=True)
            params_grads.append((p, g))
        self.apply_gradients(params_grads)
        return [], params_grads

    # helper for emitting update ops with the in-place convention
    def _emit(self, block, type, param, grad, extra_inputs, extra_outputs, attrs):
        if getattr(grad, "selected_rows", None):
            raise NotImplementedError(
                "param '%s' has a sparse (SelectedRows) gradient but %s has "
                "no sparse update op — use SGD or Adam, or set "
                "is_sparse=False on the embedding" % (param.name, type)
            )
        inputs = {
            "Param": [param.name],
            "Grad": [grad.name],
            "LearningRate": [self._global_learning_rate().name],
        }
        for k, v in extra_inputs.items():
            inputs[k] = [v.name if isinstance(v, Variable) else v]
        outputs = {"ParamOut": [param.name]}
        for k, v in extra_outputs.items():
            outputs[k] = [v.name if isinstance(v, Variable) else v]
        block.append_op(type, inputs=inputs, outputs=outputs, attrs=attrs, infer=False)


class SGDOptimizer(Optimizer):
    def _append_optimize_op(self, block, pg):
        p, g = pg
        sr = getattr(g, "selected_rows", None)
        if sr is not None:
            rows, vals = sr
            block.append_op(
                "sgd_sparse",
                inputs={
                    "Param": [p.name], "Rows": [rows], "Values": [vals],
                    "LearningRate": [self._global_learning_rate().name],
                },
                outputs={"ParamOut": [p.name]},
                attrs={},
                infer=False,
            )
            return
        self._emit(block, "sgd", p, g, {}, {}, {})


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        self._emit(
            block, "momentum", p, g,
            {"Velocity": v}, {"VelocityOut": v},
            {"mu": self._momentum, "use_nesterov": self._use_nesterov},
        )


class LarsMomentumOptimizer(Optimizer):
    def __init__(self, learning_rate, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, **kw):
        super().__init__(learning_rate, **kw)
        self._momentum = momentum
        self._lars_coeff = lars_coeff
        self._lars_weight_decay = lars_weight_decay

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("velocity", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        v = self._get_accumulator("velocity", p)
        self._emit(
            block, "lars_momentum", p, g,
            {"Velocity": v}, {"VelocityOut": v},
            {"mu": self._momentum, "lars_coeff": self._lars_coeff,
             "lars_weight_decay": self._lars_weight_decay},
        )


class AdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, initial_accumulator_value=0.0, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon = epsilon
        self._initial = initial_accumulator_value

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p, fill_value=self._initial)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        self._emit(
            block, "adagrad", p, g, {"Moment": m}, {"MomentOut": m},
            {"epsilon": self._epsilon},
        )


class AdamOptimizer(Optimizer):
    _op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 lazy_mode=False, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment1", p)
            self._add_accumulator("moment2", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])
            self._add_accumulator("beta2_pow_acc", p, fill_value=self._beta2, shape=[1])

    def _extra_attrs(self):
        return {}

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m1 = self._get_accumulator("moment1", p)
        m2 = self._get_accumulator("moment2", p)
        b1p = self._get_accumulator("beta1_pow_acc", p)
        b2p = self._get_accumulator("beta2_pow_acc", p)
        attrs = {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon}
        attrs.update(self._extra_attrs())
        sr = getattr(g, "selected_rows", None)
        if sr is not None:
            if self._op_type != "adam":
                raise NotImplementedError(
                    "sparse (SelectedRows) gradients: only sgd/adam have "
                    "sparse update ops; got %s" % self._op_type
                )
            rows, vals = sr
            block.append_op(
                "adam_sparse",
                inputs={
                    "Param": [p.name], "Rows": [rows], "Values": [vals],
                    "LearningRate": [self._global_learning_rate().name],
                    "Moment1": [m1.name], "Moment2": [m2.name],
                    "Beta1Pow": [b1p.name], "Beta2Pow": [b2p.name],
                },
                outputs={
                    "ParamOut": [p.name], "Moment1Out": [m1.name],
                    "Moment2Out": [m2.name], "Beta1PowOut": [b1p.name],
                    "Beta2PowOut": [b2p.name],
                },
                attrs=attrs,
                infer=False,
            )
            return
        self._emit(
            block, self._op_type, p, g,
            {"Moment1": m1, "Moment2": m2, "Beta1Pow": b1p, "Beta2Pow": b2p},
            {"Moment1Out": m1, "Moment2Out": m2, "Beta1PowOut": b1p, "Beta2PowOut": b2p},
            attrs,
        )


class AdamWOptimizer(AdamOptimizer):
    """Decoupled weight decay (2.0-era paddle.optimizer.AdamW parity)."""

    _op_type = "adamw"

    def __init__(self, learning_rate=0.001, weight_decay=0.01, **kw):
        super().__init__(learning_rate, **kw)
        self._coeff = weight_decay

    def _extra_attrs(self):
        return {"coeff": self._coeff}


class LambOptimizer(AdamOptimizer):
    """cf. reference optimizer.py Lamb:2901."""

    _op_type = "lamb"

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, **kw):
        super().__init__(learning_rate, beta1=beta1, beta2=beta2, epsilon=epsilon, **kw)
        self._weight_decay = lamb_weight_decay

    def _extra_attrs(self):
        return {"weight_decay": self._weight_decay}


class AdamaxOptimizer(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)
            self._add_accumulator("inf_norm", p)
            self._add_accumulator("beta1_pow_acc", p, fill_value=self._beta1, shape=[1])

    def _append_optimize_op(self, block, pg):
        p, g = pg
        self._emit(
            block, "adamax", p, g,
            {
                "Moment": self._get_accumulator("moment", p),
                "InfNorm": self._get_accumulator("inf_norm", p),
                "Beta1Pow": self._get_accumulator("beta1_pow_acc", p),
            },
            {
                "MomentOut": self._get_accumulator("moment", p),
                "InfNormOut": self._get_accumulator("inf_norm", p),
            },
            {"beta1": self._beta1, "beta2": self._beta2, "epsilon": self._epsilon},
        )

    def _finish_update(self, block, params_grads):
        # beta1_pow *= beta1 each step (reference does this with a scale op)
        for p, _ in params_grads:
            b1p = self._get_accumulator("beta1_pow_acc", p)
            block.append_op(
                "scale",
                inputs={"X": [b1p.name]},
                outputs={"Out": [b1p.name]},
                attrs={"scale": self._beta1},
                infer=False,
            )


class DecayedAdagradOptimizer(Optimizer):
    def __init__(self, learning_rate, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self._decay, self._epsilon = decay, epsilon

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("moment", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        m = self._get_accumulator("moment", p)
        self._emit(
            block, "decayed_adagrad", p, g, {"Moment": m}, {"MomentOut": m},
            {"decay": self._decay, "epsilon": self._epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, rho=0.95, **kw):
        super().__init__(learning_rate, **kw)
        self._epsilon, self._rho = epsilon, rho

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("avg_squared_grad", p)
            self._add_accumulator("avg_squared_update", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        g2 = self._get_accumulator("avg_squared_grad", p)
        u2 = self._get_accumulator("avg_squared_update", p)
        block.append_op(
            "adadelta",
            inputs={
                "Param": [p.name], "Grad": [g.name],
                "AvgSquaredGrad": [g2.name], "AvgSquaredUpdate": [u2.name],
            },
            outputs={
                "ParamOut": [p.name],
                "AvgSquaredGradOut": [g2.name],
                "AvgSquaredUpdateOut": [u2.name],
            },
            attrs={"rho": self._rho, "epsilon": self._epsilon},
            infer=False,
        )


class RMSPropOptimizer(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, **kw):
        super().__init__(learning_rate, **kw)
        self._rho, self._epsilon = rho, epsilon
        self._momentum, self._centered = momentum, centered

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("momentum", p)
            self._add_accumulator("mean_square", p)
            self._add_accumulator("mean_grad", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        self._emit(
            block, "rmsprop", p, g,
            {
                "Moment": self._get_accumulator("momentum", p),
                "MeanSquare": self._get_accumulator("mean_square", p),
                "MeanGrad": self._get_accumulator("mean_grad", p),
            },
            {
                "MomentOut": self._get_accumulator("momentum", p),
                "MeanSquareOut": self._get_accumulator("mean_square", p),
                "MeanGradOut": self._get_accumulator("mean_grad", p),
            },
            {"decay": self._rho, "epsilon": self._epsilon,
             "momentum": self._momentum, "centered": self._centered},
        )


class FtrlOptimizer(Optimizer):
    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def _create_accumulators(self, block, parameters):
        for p in parameters:
            self._add_accumulator("squared", p)
            self._add_accumulator("linear", p)

    def _append_optimize_op(self, block, pg):
        p, g = pg
        sq = self._get_accumulator("squared", p)
        lin = self._get_accumulator("linear", p)
        block.append_op(
            "ftrl",
            inputs={
                "Param": [p.name],
                "SquaredAccumulator": [sq.name],
                "LinearAccumulator": [lin.name],
                "Grad": [g.name],
                "LearningRate": [self._global_learning_rate().name],
            },
            outputs={
                "ParamOut": [p.name],
                "SquaredAccumOut": [sq.name],
                "LinearAccumOut": [lin.name],
            },
            attrs={"l1": self._l1, "l2": self._l2, "lr_power": self._lr_power},
            infer=False,
        )


class DpsgdOptimizer(Optimizer):
    def __init__(self, learning_rate, clip=10.0, batch_size=16.0, sigma=1.0, **kw):
        super().__init__(learning_rate, **kw)
        self._clip, self._batch_size, self._sigma = clip, batch_size, sigma

    def _append_optimize_op(self, block, pg):
        p, g = pg
        self._emit(
            block, "dpsgd", p, g, {}, {},
            {"clip": self._clip, "batch_size": self._batch_size, "sigma": self._sigma},
        )


class RecomputeOptimizer(Optimizer):
    """Activation checkpointing (cf. reference optimizer.py
    RecomputeOptimizer:4483 + backward.py:629).

    `_set_checkpoints([vars])` marks segment boundaries; before backward the
    forward ops between consecutive checkpoints are folded into
    `recompute_segment` composite ops (backward.py) that lower under
    `jax.checkpoint`, so the backward pass rematerializes segment interiors
    instead of storing them — the XLA-native form of the reference's
    forward-op re-emission.
    """

    def __init__(self, optimizer):
        self._inner = optimizer
        self._checkpoints = None

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _set_checkpoints(self, checkpoints):
        self._checkpoints = [
            c.name if isinstance(c, Variable) else str(c) for c in (checkpoints or [])
        ]

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        if self._checkpoints:
            self._fold_segments(loss)
        return self._inner.backward(
            loss, startup_program, parameter_list, no_grad_set
        )

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        self._inner.apply_gradients(params_grads)
        return [], params_grads

    def _fold_segments(self, loss):
        from .core.registry import get_op_def

        block = loss.block
        ops = block.ops
        producer = {}
        for i, op in enumerate(ops):
            for n in op.all_output_names():
                producer[n] = i
        bounds = sorted(
            {producer[c] for c in self._checkpoints if c in producer}
        )
        if not bounds:
            return
        # segments: (start, end] between consecutive checkpoint producers;
        # the first begins at op 0, ops after the last checkpoint stay as-is
        segments = []
        prev = -1
        for b in bounds:
            if b - prev > 1:  # fold only multi-op spans
                segments.append((prev + 1, b))
            prev = b
        if not segments:
            return

        # var usage after each position (to compute segment boundary outputs)
        new_ops = []
        cursor = 0
        for start, end in segments:
            new_ops.extend(ops[cursor:start])
            seg_ops = ops[start:end + 1]
            seg_op_dicts = [o.to_dict() for o in seg_ops]
            produced = set()
            in_names = []
            for o in seg_ops:
                for n in o.all_input_names():
                    if n not in produced and n not in in_names:
                        in_names.append(n)
                produced.update(o.all_output_names())
            used_later = set()
            for o in ops[end + 1:]:
                used_later.update(o.all_input_names())
            out_names = []
            for o in seg_ops:
                for n in o.all_output_names():
                    v = block._find_var_recursive(n)
                    if n in used_later or (v is not None and v.persistable):
                        if n not in out_names:
                            out_names.append(n)
            new_ops.append(Operator(
                block, "recompute_segment",
                inputs={"X": in_names},
                outputs={"Out": out_names},
                attrs={
                    "ops": seg_op_dicts,
                    "in_names": in_names,
                    "out_names": out_names,
                    # static per-segment RNG seed: forward and VJP re-lowering
                    # derive the same key from it (see backward.py)
                    "segment_seed": len(segments) * 1000 + start,
                    "op_role": "forward",
                },
            ))
            cursor = end + 1
        new_ops.extend(ops[cursor:])
        block.ops[:] = new_ops
        block.program._bump()


class GradientMergeOptimizer(Optimizer):
    """k-step gradient accumulation (cf. `gradient_merge` strategy,
    distributed_strategy.proto:37-38; reference implements it with
    conditional blocks).

    XLA-friendly rewrite: grads accumulate into persistable buffers every
    step; the update ops run unconditionally but their state writes are
    select-masked (`where(cond, new, old)`) so parameters/moments only
    change every k-th step — branchless, fully fusable control flow.
    """

    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        self._inner = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = avg

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        return self._inner.backward(
            loss, startup_program, parameter_list, no_grad_set
        )

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        params_grads = self.backward(
            loss, startup_program, parameter_list, no_grad_set
        )
        self.apply_gradients(params_grads, startup_program)
        return [], params_grads

    def _state_var(self, name, shape, dtype, value, startup_program):
        mb = framework.default_main_program().global_block
        v = mb.create_var(name=name, shape=shape, dtype=dtype,
                          persistable=True, stop_gradient=True)
        sb = (startup_program or default_startup_program()).global_block
        sb.create_var(name=name, shape=shape, dtype=dtype, persistable=True,
                      stop_gradient=True)
        sb.append_op(
            "fill_constant", outputs={"Out": [name]},
            attrs={"shape": list(shape), "value": float(value), "dtype": dtype},
            infer=False,
        )
        return v

    def apply_gradients(self, params_grads, startup_program=None):
        block = framework.default_main_program().global_block
        k = self.k_steps
        # int32 counter: a float32 counter saturates at 2^24 steps and would
        # silently freeze updates on long runs
        step = self._state_var(
            unique_name.generate("grad_merge_step"), (1,), "int32", 0,
            startup_program,
        )
        block.append_op(
            "increment", inputs={"X": [step.name]}, outputs={"Out": [step.name]},
            attrs={"step": 1, "op_role": "optimize"}, infer=False,
        )
        kmod = unique_name.generate("grad_merge_mod")
        block.create_var(name=kmod, shape=(1,), dtype="int32", stop_gradient=True)
        kconst = unique_name.generate("grad_merge_k")
        block.create_var(name=kconst, shape=(1,), dtype="int32", stop_gradient=True)
        block.append_op(
            "fill_constant", outputs={"Out": [kconst]},
            attrs={"shape": [1], "value": k, "dtype": "int32",
                   "op_role": "optimize"},
            infer=False,
        )
        block.append_op(
            "elementwise_mod", inputs={"X": [step.name], "Y": [kconst]},
            outputs={"Out": [kmod]}, attrs={"op_role": "optimize"}, infer=False,
        )
        zero = unique_name.generate("grad_merge_zero")
        block.create_var(name=zero, shape=(1,), dtype="int32", stop_gradient=True)
        block.append_op(
            "fill_constant", outputs={"Out": [zero]},
            attrs={"shape": [1], "value": 0, "dtype": "int32",
                   "op_role": "optimize"},
            infer=False,
        )
        cond = unique_name.generate("grad_merge_cond")
        block.create_var(name=cond, shape=(1,), dtype="bool", stop_gradient=True)
        block.append_op(
            "equal", inputs={"X": [kmod], "Y": [zero]}, outputs={"Out": [cond]},
            attrs={"op_role": "optimize"}, infer=False,
        )

        # accumulate grads; feed the inner optimizer the averaged accumulator
        merged = []
        accs = []
        for p, g in params_grads:
            acc = self._state_var(
                unique_name.generate(p.name + "_grad_merge"), list(g.shape),
                g.dtype, 0.0, startup_program,
            )
            block.append_op(
                "sum", inputs={"X": [acc.name, g.name]},
                outputs={"Out": [acc.name]}, attrs={"op_role": "optimize"},
                infer=False,
            )
            eff = unique_name.generate(g.name + "_merged")
            block.create_var(name=eff, shape=g.shape, dtype=g.dtype,
                             stop_gradient=True)
            block.append_op(
                "scale", inputs={"X": [acc.name]}, outputs={"Out": [eff]},
                attrs={"scale": 1.0 / k if self.avg else 1.0,
                       "op_role": "optimize"},
                infer=False,
            )
            merged.append((p, block.var(eff)))
            accs.append(acc)

        first = len(block.ops)
        self._inner.apply_gradients(merged)

        # select-mask every persistable-state write in the update section
        appended = block.ops[first:]
        rebuilt = block.ops[:first]
        for op in appended:
            redirects = []  # (slot, idx, orig, tmp)
            for slot, names in op.outputs.items():
                for i, n in enumerate(names):
                    v = block._find_var_recursive(n)
                    if v is not None and v.persistable:
                        tmp = unique_name.generate(n + "_gm_new")
                        block.create_var(name=tmp, shape=v.shape, dtype=v.dtype,
                                         stop_gradient=True)
                        names[i] = tmp
                        redirects.append((slot, i, n, tmp))
            rebuilt.append(op)
            for _slot, _i, orig, tmp in redirects:
                rebuilt.append(Operator(
                    block, "where",
                    inputs={"Condition": [cond], "X": [tmp], "Y": [orig]},
                    outputs={"Out": [orig]},
                    attrs={"op_role": "optimize"},
                ))
        block.ops[:] = rebuilt

        # reset accumulators after an applied step
        for acc in accs:
            zname = unique_name.generate(acc.name + "_zeros")
            block.create_var(name=zname, shape=acc.shape, dtype=acc.dtype,
                             stop_gradient=True)
            block.append_op(
                "fill_zeros_like", inputs={"X": [acc.name]},
                outputs={"Out": [zname]}, attrs={"op_role": "optimize"},
                infer=False,
            )
            block.append_op(
                "where",
                inputs={"Condition": [cond], "X": [zname], "Y": [acc.name]},
                outputs={"Out": [acc.name]},
                attrs={"op_role": "optimize"},
                infer=False,
            )
        framework.default_main_program()._bump()


class ExponentialMovingAverage:
    """EMA of parameters (cf. reference optimizer.py EMA:3382).

    `update()` appends shadow-update ops to the main program (call after
    minimize); `apply(executor)` is a context manager that swaps EMA values
    into the parameters for evaluation and `restore()`s on exit — the swap
    is a scope operation, matching the reference's save/restore programs.
    """

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._name = name or "ema"
        self._pairs = []  # (param_name, shadow_name)
        self._backup = {}

    def update(self):
        block = framework.default_main_program().global_block
        sblock = default_startup_program().global_block
        for p in block.all_parameters():
            if not p.trainable:
                continue
            shadow = unique_name.generate(p.name + "@" + self._name)
            block.create_var(name=shadow, shape=p.shape, dtype=p.dtype,
                             persistable=True, stop_gradient=True)
            sblock.create_var(name=shadow, shape=p.shape, dtype=p.dtype,
                              persistable=True, stop_gradient=True)
            # shadow starts at the initial param value (reference behavior)
            sblock.append_op(
                "assign", inputs={"X": [p.name]}, outputs={"Out": [shadow]},
                infer=False,
            )
            # shadow = decay*shadow + (1-decay)*param
            tmp = unique_name.generate(shadow + "@scaled")
            block.create_var(name=tmp, shape=p.shape, dtype=p.dtype,
                             stop_gradient=True)
            block.append_op(
                "scale", inputs={"X": [shadow]}, outputs={"Out": [shadow]},
                attrs={"scale": self._decay, "op_role": "optimize"},
                infer=False,
            )
            block.append_op(
                "scale", inputs={"X": [p.name]}, outputs={"Out": [tmp]},
                attrs={"scale": 1.0 - self._decay, "op_role": "optimize"},
                infer=False,
            )
            block.append_op(
                "sum", inputs={"X": [shadow, tmp]}, outputs={"Out": [shadow]},
                attrs={"op_role": "optimize"}, infer=False,
            )
            self._pairs.append((p.name, shadow))

    import contextlib as _contextlib

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        from .core.scope import global_scope

        scope = global_scope()
        self._backup = {}
        for pname, shadow in self._pairs:
            self._backup[pname] = scope.find_var(pname)
            sv = scope.find_var(shadow)
            if sv is not None:
                scope.set(pname, sv)
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        from .core.scope import global_scope

        scope = global_scope()
        for pname, val in self._backup.items():
            scope.set(pname, val)
        self._backup = {}


class ModelAverage:
    """Windowed parameter averaging (cf. reference ModelAverage:3073;
    simplified to one running sum per window instead of the reference's
    three-tier sum_1/2/3 bookkeeping — same capability, simpler state)."""

    def __init__(self, average_window_rate=0.15, min_average_window=10000,
                 max_average_window=10000):
        self._pairs = []  # (param, sum_name, count_name)
        self._backup = {}

    def apply_program(self):
        """Append sum-accumulation ops after the optimizer ops."""
        block = framework.default_main_program().global_block
        sblock = default_startup_program().global_block
        for p in block.all_parameters():
            if not p.trainable:
                continue
            s = unique_name.generate(p.name + "@avg_sum")
            c = unique_name.generate(p.name + "@avg_cnt")
            for name, shape, dt in [(s, list(p.shape), p.dtype),
                                    (c, [1], "float32")]:
                block.create_var(name=name, shape=shape, dtype=dt,
                                 persistable=True, stop_gradient=True)
                sblock.create_var(name=name, shape=shape, dtype=dt,
                                  persistable=True, stop_gradient=True)
                sblock.append_op(
                    "fill_constant", outputs={"Out": [name]},
                    attrs={"shape": shape, "value": 0.0, "dtype": dt},
                    infer=False,
                )
            block.append_op(
                "sum", inputs={"X": [s, p.name]}, outputs={"Out": [s]},
                attrs={"op_role": "optimize"}, infer=False,
            )
            block.append_op(
                "increment", inputs={"X": [c]}, outputs={"Out": [c]},
                attrs={"step": 1.0, "op_role": "optimize"}, infer=False,
            )
            self._pairs.append((p.name, s, c))

    import contextlib as _contextlib

    @_contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        import numpy as _np

        from .core.scope import global_scope

        scope = global_scope()
        self._backup = {}
        for pname, s, c in self._pairs:
            self._backup[pname] = scope.find_var(pname)
            sv, cv = scope.find_var(s), scope.find_var(c)
            if sv is not None and cv is not None and float(_np.asarray(cv)[0]) > 0:
                scope.set(pname, sv / float(_np.asarray(cv)[0]))
        try:
            yield
        finally:
            if need_restore:
                self.restore(executor)

    def restore(self, executor=None):
        from .core.scope import global_scope

        scope = global_scope()
        for pname, val in self._backup.items():
            scope.set(pname, val)
        self._backup = {}


class LookaheadOptimizer:
    """Lookahead (cf. reference LookaheadOptimizer:4775): fast weights step
    every iteration; every k steps slow weights interpolate toward fast and
    fast resets to slow.  Branchless via select-masking (same pattern as
    GradientMergeOptimizer)."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5):
        assert 0.0 <= alpha <= 1.0
        self._inner = inner_optimizer
        self.alpha = alpha
        self.k = int(k)

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        result = self._inner.minimize(
            loss, startup_program, parameter_list, no_grad_set
        )
        block = framework.default_main_program().global_block
        sblock = (startup_program or default_startup_program()).global_block

        step = unique_name.generate("lookahead_step")
        for name, shape, dt, val in [(step, [1], "int32", 0)]:
            block.create_var(name=name, shape=shape, dtype=dt,
                             persistable=True, stop_gradient=True)
            sblock.create_var(name=name, shape=shape, dtype=dt,
                              persistable=True, stop_gradient=True)
            sblock.append_op(
                "fill_constant", outputs={"Out": [name]},
                attrs={"shape": shape, "value": val, "dtype": dt},
                infer=False,
            )
        block.append_op(
            "increment", inputs={"X": [step]}, outputs={"Out": [step]},
            attrs={"step": 1, "op_role": "optimize"}, infer=False,
        )
        kconst = unique_name.generate("lookahead_k")
        kmod = unique_name.generate("lookahead_mod")
        zero = unique_name.generate("lookahead_zero")
        cond = unique_name.generate("lookahead_cond")
        for name, val in [(kconst, self.k), (zero, 0)]:
            block.create_var(name=name, shape=(1,), dtype="int32",
                             stop_gradient=True)
            block.append_op(
                "fill_constant", outputs={"Out": [name]},
                attrs={"shape": [1], "value": val, "dtype": "int32",
                       "op_role": "optimize"},
                infer=False,
            )
        block.create_var(name=kmod, shape=(1,), dtype="int32", stop_gradient=True)
        block.append_op(
            "elementwise_mod", inputs={"X": [step], "Y": [kconst]},
            outputs={"Out": [kmod]}, attrs={"op_role": "optimize"}, infer=False,
        )
        block.create_var(name=cond, shape=(1,), dtype="bool", stop_gradient=True)
        block.append_op(
            "equal", inputs={"X": [kmod], "Y": [zero]}, outputs={"Out": [cond]},
            attrs={"op_role": "optimize"}, infer=False,
        )

        for p in block.all_parameters():
            if not p.trainable:
                continue
            slow = unique_name.generate(p.name + "@SLOW")
            block.create_var(name=slow, shape=p.shape, dtype=p.dtype,
                             persistable=True, stop_gradient=True)
            sblock.create_var(name=slow, shape=p.shape, dtype=p.dtype,
                              persistable=True, stop_gradient=True)
            sblock.append_op(
                "assign", inputs={"X": [p.name]}, outputs={"Out": [slow]},
                infer=False,
            )
            # slow_new = slow + alpha * (fast - slow); applied every k steps
            mix = unique_name.generate(p.name + "@MIX")
            sc1 = unique_name.generate(p.name + "@SC1")
            sc2 = unique_name.generate(p.name + "@SC2")
            for nm in (mix, sc1, sc2):
                block.create_var(name=nm, shape=p.shape, dtype=p.dtype,
                                 stop_gradient=True)
            block.append_op(
                "scale", inputs={"X": [p.name]}, outputs={"Out": [sc1]},
                attrs={"scale": self.alpha, "op_role": "optimize"}, infer=False,
            )
            block.append_op(
                "scale", inputs={"X": [slow]}, outputs={"Out": [sc2]},
                attrs={"scale": 1.0 - self.alpha, "op_role": "optimize"},
                infer=False,
            )
            block.append_op(
                "sum", inputs={"X": [sc1, sc2]}, outputs={"Out": [mix]},
                attrs={"op_role": "optimize"}, infer=False,
            )
            for target in (slow, p.name):
                block.append_op(
                    "where",
                    inputs={"Condition": [cond], "X": [mix], "Y": [target]},
                    outputs={"Out": [target]},
                    attrs={"op_role": "optimize"},
                    infer=False,
                )
        return result


# reference-style lowercase aliases (cf. optimizer.py bottom: SGD = SGDOptimizer)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adam = AdamOptimizer
AdamW = AdamWOptimizer
Adamax = AdamaxOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
Ftrl = FtrlOptimizer
Lamb = LambOptimizer
Dpsgd = DpsgdOptimizer
LarsMomentum = LarsMomentumOptimizer
