"""Dataset API over the native C++ engine.

Capability parity: reference `python/paddle/fluid/dataset.py` —
DatasetFactory, InMemoryDataset (load_into_memory / local_shuffle /
global_shuffle / release_memory / get_memory_data_size), QueueDataset —
over C++ `framework/data_set.cc` + MultiSlotDataFeed (`data_feed.cc`).

Slots declare the MultiSlot text schema via ``set_use_var``-style calls:
each sample line holds, per slot, "<count> v...".  Batches come back as
{slot_name: (values, lod)} where lod is the LoD offset vector — ragged
sequences batch without padding (the reference LoDTensor capability);
``pad_batch`` converts to dense [batch, max_len] + mask for the TPU path.
"""

from __future__ import annotations

import ctypes

import numpy as np


class DatasetFactory:
    """cf. reference DatasetFactory.create_dataset."""

    def create_dataset(self, datafeed_class="QueueDataset"):
        if datafeed_class == "InMemoryDataset":
            return InMemoryDataset()
        if datafeed_class == "QueueDataset":
            return QueueDataset()
        raise ValueError("unknown dataset class %r" % datafeed_class)


class DatasetBase:
    def __init__(self):
        self._filelist = []
        self._batch_size = 1
        self._thread_num = 1
        self._slots = []  # (name, is_float)
        self._handle = None

    # -- reference setters ----------------------------------------------
    def set_filelist(self, filelist):
        """The FULL file list (reference contract); each trainer loads its
        own shard — see set_trainer_info / global_shuffle."""
        self._filelist = list(filelist)
        if self._handle is not None:
            # filelist changed: rebuild the engine on next use
            self._lib.ds_destroy(self._handle)
            self._handle = None

    def set_trainer_info(self, trainer_id, trainer_num):
        """Shard the filelist across trainers (reference DatasetImpl
        SetTrainerNum / file dispatch in data_set.cc): trainer i loads
        files [i::trainer_num] of the (possibly shuffled) global list."""
        self._trainer_id = int(trainer_id)
        self._trainer_num = max(int(trainer_num), 1)

    def _my_files(self):
        tid = getattr(self, "_trainer_id", 0)
        tnum = getattr(self, "_trainer_num", 1)
        files = list(self._filelist)
        seed = getattr(self, "_file_perm_seed", None)
        if seed is not None:
            rs = np.random.RandomState(seed)
            rs.shuffle(files)
        return files[tid::tnum] if tnum > 1 else files

    def set_batch_size(self, batch_size):
        self._batch_size = int(batch_size)

    def set_thread(self, thread_num):
        self._thread_num = int(thread_num)

    def set_use_var(self, var_list):
        """Declare the slot schema from Variables (name + dtype), matching
        the reference's use of program vars to describe the feed."""
        from .core import dtypes as dtypes_mod

        self._slots = [
            (v.name, dtypes_mod.is_floating(v.dtype)) for v in var_list
        ]

    def set_pipe_command(self, cmd):
        """Preprocessing subprocess per file (reference pipe_command,
        data_feed.cc): the engine reads each file through `cmd < file`."""
        self._pipe_command = cmd
        if self._handle is not None:
            self._lib.ds_set_pipe_command(self._handle, cmd.encode())

    # -- engine ---------------------------------------------------------
    def _ensure_handle(self):
        from ..native import get_lib

        if self._handle is not None:
            return
        if not self._slots:
            raise RuntimeError("call set_use_var(...) to declare slots first")
        lib = get_lib()
        my_files = self._my_files()
        files = (ctypes.c_char_p * len(my_files))(
            *[f.encode() for f in my_files]
        )
        schema = (ctypes.c_int * len(self._slots))(
            *[1 if f else 0 for _, f in self._slots]
        )
        self._lib = lib
        self._handle = lib.ds_create(
            files, len(my_files), schema, len(self._slots),
            self._thread_num,
        )
        if getattr(self, "_pipe_command", None):
            lib.ds_set_pipe_command(self._handle, self._pipe_command.encode())

    def __del__(self):
        if getattr(self, "_handle", None):
            self._lib.ds_destroy(self._handle)
            self._handle = None

    # -- iteration ------------------------------------------------------
    def _next_batch(self):
        self._ensure_handle()
        lib = self._lib
        nslots = len(self._slots)
        counts = (ctypes.c_int64 * nslots)()
        actual = lib.ds_next_batch_sizes(self._handle, self._batch_size, counts)
        if actual == 0:
            return None
        bufs = []
        lods = []
        buf_ptrs = (ctypes.c_void_p * nslots)()
        lod_ptrs = (ctypes.POINTER(ctypes.c_int64) * nslots)()
        for s, (_name, is_float) in enumerate(self._slots):
            dtype = np.float32 if is_float else np.int64
            arr = np.empty(max(int(counts[s]), 1), dtype=dtype)
            lod = np.empty(actual + 1, dtype=np.int64)
            bufs.append(arr)
            lods.append(lod)
            buf_ptrs[s] = arr.ctypes.data_as(ctypes.c_void_p)
            lod_ptrs[s] = lod.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        lib.ds_fill_batch(self._handle, self._batch_size, buf_ptrs, lod_ptrs)
        out = {}
        for s, (name, _f) in enumerate(self._slots):
            out[name] = (bufs[s][: int(counts[s])], lods[s])
        return out

    def __iter__(self):
        self._ensure_handle()
        self._lib.ds_reset_cursor(self._handle)
        while True:
            b = self._next_batch()
            if b is None:
                return
            yield b


class InMemoryDataset(DatasetBase):
    """cf. reference InMemoryDataset."""

    def load_into_memory(self):
        self._ensure_handle()
        self._lib.ds_load_into_memory(self._handle)
        self._was_loaded = True

    def __iter__(self):
        # a set_filelist after load_into_memory rebuilds the engine; honor
        # the earlier load by reloading the new shard instead of silently
        # yielding zero batches
        self._ensure_handle()
        if (getattr(self, "_was_loaded", False)
                and self._lib.ds_memory_data_size(self._handle) == 0):
            self._lib.ds_load_into_memory(self._handle)
        yield from super().__iter__()

    def local_shuffle(self, seed=0):
        self._ensure_handle()
        self._lib.ds_local_shuffle(self._handle, seed)

    def global_shuffle(self, fleet=None, seed=0):
        """Cross-trainer sample redistribution (reference data_set.cc
        GlobalShuffle via gloo).  TPU-native: every trainer applies the
        SAME seeded permutation to the global filelist and reloads its new
        shard — samples move between trainers at file granularity with no
        transport layer — then local-shuffles within the shard.  With one
        trainer this degenerates to a local shuffle (reference behavior)."""
        tnum = getattr(self, "_trainer_num", 1)
        if fleet is not None and tnum == 1:
            try:
                self.set_trainer_info(fleet.worker_index(),
                                      fleet.worker_num())
                tnum = self._trainer_num
            except Exception as e:
                import warnings

                warnings.warn(
                    "global_shuffle could not read trainer identity from "
                    "fleet (%s); falling back to a LOCAL shuffle — no "
                    "cross-trainer redistribution will happen" % (e,),
                    stacklevel=2,
                )
        if tnum > 1:
            self._file_perm_seed = int(seed) + 1
            if self._handle is not None:
                self._lib.ds_destroy(self._handle)
                self._handle = None
            self._ensure_handle()
            self._lib.ds_load_into_memory(self._handle)
        self.local_shuffle(seed)

    def release_memory(self):
        if self._handle is not None:
            self._lib.ds_release_memory(self._handle)

    def get_memory_data_size(self, fleet=None):
        self._ensure_handle()
        return int(self._lib.ds_memory_data_size(self._handle))

    def get_error_line_count(self):
        self._ensure_handle()
        return int(self._lib.ds_error_line_count(self._handle))


class QueueDataset(DatasetBase):
    """cf. reference QueueDataset: TRUE streaming through the engine's
    bounded channel — reader threads parse files into a fixed-capacity
    queue while the trainer consumes, so resident memory is O(capacity +
    shuffle window) and the corpus may exceed RAM (reference
    InMemoryDataFeed channel architecture, data_feed.h:291)."""

    def set_queue_capacity(self, capacity):
        self._channel_capacity = int(capacity)

    def set_shuffle_window(self, window, seed=0):
        """Bounded window shuffle applied on the consumer side of the
        channel (streaming cannot globally sort; same trade as the
        reference's channel shuffle)."""
        self._stream_shuffle = (int(window), int(seed))

    def _next_stream_batch(self):
        lib = self._lib
        nslots = len(self._slots)
        counts = (ctypes.c_int64 * nslots)()
        actual = lib.ds_stream_next_batch_sizes(
            self._handle, self._batch_size, counts)
        if actual == 0:
            return None
        bufs = []
        lods = []
        buf_ptrs = (ctypes.c_void_p * nslots)()
        lod_ptrs = (ctypes.POINTER(ctypes.c_int64) * nslots)()
        for s, (_name, is_float) in enumerate(self._slots):
            dtype = np.float32 if is_float else np.int64
            arr = np.empty(max(int(counts[s]), 1), dtype=dtype)
            lod = np.empty(actual + 1, dtype=np.int64)
            bufs.append(arr)
            lods.append(lod)
            buf_ptrs[s] = arr.ctypes.data_as(ctypes.c_void_p)
            lod_ptrs[s] = lod.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        lib.ds_stream_fill_batch(self._handle, buf_ptrs, lod_ptrs)
        return {
            name: (bufs[s][: int(counts[s])], lods[s])
            for s, (name, _f) in enumerate(self._slots)
        }

    def __iter__(self):
        self._ensure_handle()
        lib = self._lib
        if getattr(self, "_stream_shuffle", None):
            win, seed = self._stream_shuffle
            lib.ds_set_shuffle_buffer(self._handle, win, seed)
        lib.ds_start_streaming(
            self._handle, getattr(self, "_channel_capacity", 1024))
        try:
            while True:
                batch = self._next_stream_batch()
                if batch is None:
                    return
                yield batch
        finally:
            lib.ds_stop_streaming(self._handle)


def pad_batch(values, lod, pad_value=0, max_len=None):
    """Ragged (values, lod) -> dense [batch, max_len] + float mask — the
    padding/packing bridge from LoD batches to static TPU shapes."""
    lod = np.asarray(lod)
    lens = lod[1:] - lod[:-1]
    b = len(lens)
    m = int(max_len or (lens.max() if b else 0))
    out = np.full((b, m), pad_value, dtype=values.dtype)
    mask = np.zeros((b, m), np.float32)
    for i in range(b):
        n = min(int(lens[i]), m)
        out[i, :n] = values[lod[i]:lod[i] + n]
        mask[i, :n] = 1.0
    return out, mask
