"""Input pipelines: DataLoader, Dataset, BatchSampler, reader decorators.

Capability parity: reference `python/paddle/fluid/reader.py` (DataLoader:101,
from_generator:361 double-buffered feed), `python/paddle/fluid/dataloader/`
(Dataset, BatchSampler, worker prefetch) and `python/paddle/reader/decorator.py`
(batch/shuffle/buffered composition).

TPU-first: the C++ BufferedReader/LoDTensorBlockingQueue
(`operators/reader/buffered_reader.cc`) becomes a host-side background-thread
prefetcher whose slots are `jax.device_put`-ahead batches — the XLA dispatch
queue overlaps H2D copies with compute, so one thread + a small queue gives
the same double-buffering.
"""

import itertools
import queue
import threading

import numpy as np


# ---------------------------------------------------------------------------
# reader decorators (cf. paddle.batch / paddle.reader.shuffle)
# ---------------------------------------------------------------------------

def batch(reader, batch_size, drop_last=False):
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return batched


def shuffle(reader, buf_size, seed=None):
    rs = np.random.RandomState(seed)

    def shuffled():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                rs.shuffle(buf)
                yield from buf
                buf = []
        rs.shuffle(buf)
        yield from buf

    return shuffled


def cache(reader):
    items = []

    def cached():
        if not items:
            for it in reader():
                items.append(it)
                yield it
        else:
            yield from items

    return cached


def firstn(reader, n):
    def limited():
        yield from itertools.islice(reader(), n)

    return limited


# ---------------------------------------------------------------------------
# Dataset / BatchSampler (cf. python/paddle/fluid/dataloader/)
# ---------------------------------------------------------------------------

class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError


class TensorDataset(Dataset):
    def __init__(self, *arrays):
        self.arrays = [np.asarray(a) for a in arrays]
        assert all(len(a) == len(self.arrays[0]) for a in self.arrays)

    def __getitem__(self, idx):
        return tuple(a[idx] for a in self.arrays)

    def __len__(self):
        return len(self.arrays[0])


class BatchSampler:
    def __init__(self, dataset=None, shuffle=False, batch_size=1, drop_last=False,
                 seed=None):
        self.n = len(dataset)
        self.shuffle = shuffle
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._rs = np.random.RandomState(seed)

    def __iter__(self):
        idx = np.arange(self.n)
        if self.shuffle:
            self._rs.shuffle(idx)
        for i in range(0, self.n, self.batch_size):
            b = idx[i : i + self.batch_size]
            if len(b) < self.batch_size and self.drop_last:
                return
            yield list(b)

    def __len__(self):
        if self.drop_last:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size


def _mp_worker_main(dataset, collate, task_q, res_q):
    """DataLoader worker entry (module-level: spawn pickles it).

    Persistent across epochs: tasks carry an epoch tag that is echoed
    back so the parent can discard results of an abandoned epoch."""
    while True:
        item = task_q.get()
        if item is None:
            return
        epoch, i, idx = item
        try:
            res_q.put((epoch, i, collate([dataset[j] for j in idx]), None))
        except Exception as e:  # surface in the parent
            res_q.put((epoch, i, None, "%s: %s" % (type(e).__name__, e)))


def default_collate(items):
    """Batch a list of samples: tuple/list samples -> tuple of stacked
    arrays; dict samples -> dict of stacked arrays (keys must agree
    across the batch).  Anything else raises — a clear error beats a
    silent mis-zip."""
    first = items[0]
    if isinstance(first, dict):
        keys = set(first)
        for i, it in enumerate(items):
            if not isinstance(it, dict) or set(it) != keys:
                raise TypeError(
                    "default_collate: dict samples must share one key set; "
                    "sample 0 has %s, sample %d has %s"
                    % (sorted(keys), i,
                       sorted(it) if isinstance(it, dict) else type(it)))
        return {
            k: np.stack([np.asarray(it[k]) for it in items]) for k in first
        }
    if isinstance(first, (tuple, list)):
        transposed = list(zip(*items))
        return tuple(
            np.stack([np.asarray(x) for x in col]) for col in transposed)
    raise TypeError(
        "default_collate supports tuple/list or dict samples, got %s; "
        "pass collate_fn= for anything else" % type(first).__name__)


class DataLoader:
    """Iterable over batches with background-thread prefetch.

    Two construction modes, mirroring the reference:
      * DataLoader(dataset, batch_size=..., shuffle=...) — map-style dataset.
      * DataLoader.from_generator(capacity=..., feed_list=...) then
        .set_sample_list_generator / .set_batch_generator — generator-fed.
    """

    def __init__(self, dataset=None, feed_list=None, batch_size=1, shuffle=False,
                 drop_last=False, collate_fn=None, num_workers=0, capacity=4,
                 batch_sampler=None, return_list=True):
        self.dataset = dataset
        self.feed_list = feed_list
        self.capacity = max(2, capacity)
        self.collate_fn = collate_fn or default_collate
        self.num_workers = max(0, int(num_workers))
        self._gen = None
        self._pool = None        # persistent mp worker pool (lazily started)
        self._mp_epoch = 0
        if dataset is not None:
            self.batch_sampler = batch_sampler or BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size, drop_last=drop_last
            )

    # -- generator-fed mode (cf. reader.py:361) -----------------------------
    @staticmethod
    def from_generator(feed_list=None, capacity=4, use_double_buffer=True,
                       iterable=True, return_list=False):
        return DataLoader(feed_list=feed_list, capacity=capacity)

    def set_sample_generator(self, generator, batch_size, drop_last=False,
                             places=None):
        """Feed from a per-sample generator, batching by `batch_size`.

        `drop_last` defaults to False, ALIGNED with the constructor's
        default (the reference defaulted this one method to True, so the
        same DataLoader dropped the tail batch or not depending on which
        entry point fed it — a silent data-loss footgun; pass
        drop_last=True explicitly for fixed-shape feeding)."""
        from .reader import batch as _batch  # self-module import for clarity

        self._gen = lambda: (
            self.collate_fn(samples)
            for samples in _batch(generator, batch_size, drop_last)()
        )
        return self

    def set_sample_list_generator(self, generator, places=None):
        self._gen = lambda: (self.collate_fn(samples) for samples in generator())
        return self

    def set_batch_generator(self, generator, places=None):
        self._gen = generator
        return self

    # -- iteration with prefetch -------------------------------------------
    def _batches(self):
        if self._gen is not None:
            yield from self._gen()
            return
        if self.num_workers > 0:
            yield from self._mp_batches()
            return
        for indices in self.batch_sampler:
            yield self.collate_fn([self.dataset[i] for i in indices])

    def _mp_batches(self):
        """Multiprocess map-style loading (reference dataloader_iter.py
        _DataLoaderIterMultiProcess capability): N spawned workers pull
        index lists from a task queue and push collated numpy batches
        back; the parent reassembles them IN ORDER.

        Spawn (not fork): the parent runs a multithreaded JAX runtime and
        forking it is the textbook deadlock; spawn requires the dataset /
        collate_fn to be picklable, same contract as the reference's
        multiprocess workers.  Tasks are issued through a bounded window
        so a straggler batch cannot let the others run arbitrarily far
        ahead (the in-order buffer stays <= window batches), and the
        result wait polls worker liveness so a killed worker raises
        instead of hanging the trainer.

        The worker pool PERSISTS across epochs (spawn + dataset pickling
        cost is paid once per DataLoader, not once per epoch); epochs are
        distinguished by a generation tag so results of an abandoned
        epoch are discarded, and close() tears the pool down.  Two
        consequences, both matching the reference's persistent workers:
        only ONE live iterator per DataLoader — starting a new iteration
        invalidates the previous one (it raises on next use) — and the
        dataset is pickled once at pool start, so mutating it between
        epochs has no effect on workers (call close() to force a
        respawn)."""
        import queue as _queue

        batches = list(self.batch_sampler)
        if not batches:
            return
        procs, task_q, res_q = self._ensure_pool()
        self._mp_epoch += 1
        epoch = self._mp_epoch
        window = max(2 * len(procs), self.capacity)
        issued = 0

        def issue_up_to(limit):
            nonlocal issued
            if self._mp_epoch != epoch:
                return                       # superseded: stop issuing work
            while issued < min(limit, len(batches)):
                task_q.put((epoch, issued, batches[issued]))
                issued += 1

        def check_live():
            if self._mp_epoch != epoch:
                raise RuntimeError(
                    "this DataLoader iterator was invalidated by a newer "
                    "iteration (one live iterator per DataLoader when "
                    "num_workers > 0)")

        issue_up_to(window)
        pending = {}
        next_i = 0
        received = 0
        stalled_polls = 0
        while received < len(batches):
            check_live()
            try:
                ep, i, b, e = res_q.get(timeout=5.0)
                stalled_polls = 0
                if ep != epoch:
                    if ep == self._mp_epoch:
                        # belongs to the iterator that invalidated us —
                        # hand it back before we raise at the loop top
                        res_q.put((ep, i, b, e))
                    continue         # stale result of an abandoned epoch
            except _queue.Empty:
                dead = sum(1 for p in procs if not p.is_alive())
                if dead == len(procs):
                    self.close()
                    raise RuntimeError(
                        "all DataLoader workers died without "
                        "delivering results (OOM-killed?)")
                if dead:
                    # a dead worker took its in-flight task with it;
                    # no result can ever unblock next_i — fail fast
                    # instead of hanging the trainer
                    stalled_polls += 1
                    if stalled_polls >= 2:
                        self.close()
                        raise RuntimeError(
                            "%d DataLoader worker(s) died and the "
                            "stream stalled (batch %d never arrived)"
                            % (dead, next_i))
                continue
            received += 1
            if e is not None:
                raise RuntimeError(
                    "DataLoader worker failed on batch %d: %s" % (i, e))
            pending[i] = b
            while next_i in pending:
                yield pending.pop(next_i)
                next_i += 1
                # a newer iterator may have invalidated us while we were
                # suspended at the yield — stop issuing and raise NOW, not
                # several buffered batches later
                check_live()
                issue_up_to(next_i + window)

    def _ensure_pool(self):
        """Start (once) and return the persistent worker pool."""
        if self._pool is not None:
            procs = self._pool[0]
            if all(p.is_alive() for p in procs):
                return self._pool
            self.close()                     # respawn a broken pool
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        task_q = ctx.Queue()
        res_q = ctx.Queue()
        procs = [
            ctx.Process(
                target=_mp_worker_main,
                args=(self.dataset, self.collate_fn, task_q, res_q),
                daemon=True,
            )
            for _ in range(self.num_workers)
        ]
        for p in procs:
            p.start()
        self._pool = (procs, task_q, res_q)
        return self._pool

    def close(self):
        """Tear down the persistent worker pool (idempotent)."""
        if self._pool is None:
            return
        procs, task_q, _ = self._pool
        self._pool = None
        for p in procs:
            if p.is_alive():
                task_q.put(None)
        for p in procs:
            p.join(timeout=1)
        for p in procs:
            if p.is_alive():
                p.terminate()
        for p in procs:
            p.join(timeout=5)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def _sampler_state(self):
        """The sampler's cursor, or None when it has none (a plain
        BatchSampler) or it is not meaningfully positional here."""
        sampler = getattr(self, "batch_sampler", None)
        if sampler is None or not hasattr(sampler, "state_dict"):
            return None
        try:
            return sampler.state_dict()
        except TypeError:
            return None

    def __iter__(self):
        q = queue.Queue(maxsize=self.capacity)
        sentinel = object()
        err = []
        # the background thread pulls the sampler up to capacity+1
        # batches ahead of the consumer: pair each batch with the
        # sampler cursor AS OF ITS PULL so state_dict() can report the
        # position of the batch the trainer actually received
        track = self.num_workers == 0 and self._gen is None

        def worker():
            try:
                for b in self._batches():
                    q.put((b, self._sampler_state() if track else None))
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=worker, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if err:
                    raise err[0]
                return
            item, state = item
            if state is not None:
                self._last_sampler_state = state
            if self.feed_list is not None:
                yield {
                    v.name if hasattr(v, "name") else v: arr
                    for v, arr in zip(self.feed_list, item)
                }
            else:
                yield item

    def __len__(self):
        if self._gen is not None:
            raise TypeError("generator-fed DataLoader has no length")
        return len(self.batch_sampler)

    # -- checkpointable iteration (paddle_tpu.io contract) ------------------
    def state_dict(self):
        """Sampler state aligned to YIELDED batches (the internal
        prefetch thread runs ahead; see __iter__) — exact for
        num_workers=0 map-style iteration with an io.ShardedBatchSampler.
        With num_workers>0 the batch list is drained upfront, so
        positional resume needs io.ResumableDataLoader instead."""
        state = getattr(self, "_last_sampler_state", None)
        if state is not None:
            return {"sampler": state}
        state = self._sampler_state()
        if state is None:
            raise TypeError(
                "this DataLoader's sampler has no state_dict(); use "
                "io.ResumableDataLoader (or io.ShardedBatchSampler) for "
                "checkpointable iteration")
        return {"sampler": state}

    def load_state_dict(self, state):
        sampler = getattr(self, "batch_sampler", None)
        if sampler is None or not hasattr(sampler, "load_state_dict"):
            raise TypeError(
                "this DataLoader's sampler has no load_state_dict(); use "
                "io.ResumableDataLoader (or io.ShardedBatchSampler) for "
                "checkpointable iteration")
        sampler.load_state_dict(state["sampler"])
        self._last_sampler_state = state["sampler"]

    def set_epoch(self, epoch):
        sampler = getattr(self, "batch_sampler", None)
        if sampler is not None and hasattr(sampler, "set_epoch"):
            sampler.set_epoch(epoch)


class DistributedBatchSampler(BatchSampler):
    """cf. reference `paddle.io.DistributedBatchSampler`: each rank
    iterates its own 1/nranks slice of the (optionally shuffled) index
    space, padded so every rank sees the same number of batches."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False, seed=None):
        super().__init__(dataset=dataset, shuffle=shuffle,
                         batch_size=batch_size, drop_last=drop_last,
                         seed=seed)
        if num_replicas is None or rank is None:
            import os

            num_replicas = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))
            rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        self.nranks = max(int(num_replicas), 1)
        self.rank = int(rank)
        self.epoch = 0
        self._seed_base = int(seed or 0)

    def set_epoch(self, epoch):
        """Reshuffle deterministically per epoch (reference contract)."""
        self.epoch = int(epoch)

    def state_dict(self):
        """Epoch-granular state (the permutation is a pure function of
        (seed, epoch)); `io.ShardedBatchSampler` extends this with the
        exact batch offset for mid-epoch resume."""
        return {"epoch": self.epoch, "seed": self._seed_base,
                "nranks": self.nranks, "rank": self.rank}

    def load_state_dict(self, state):
        self.epoch = int(state["epoch"])

    def _shard_batches(self, idx):
        """Permuted global indices -> this rank's batch list: pad
        (tiling if needed) to a multiple of nranks so every rank yields
        equally many batches even when pad > dataset size, take the
        rank-strided slice, split into batches.  Single-sourced: the
        resumable io.ShardedBatchSampler's offsets index into exactly
        this list.  Sized off `idx` (not self.n): an elastic resume
        hands in the epoch's unconsumed SUFFIX and only it may be
        sharded — tiling it back up to the dataset size would replay
        consumed samples."""
        per = (len(idx) + self.nranks - 1) // self.nranks
        padded = np.resize(idx, per * self.nranks)
        local = padded[self.rank::self.nranks]
        out = []
        for i in range(0, len(local), self.batch_size):
            b = local[i:i + self.batch_size]
            if len(b) < self.batch_size and self.drop_last:
                break
            out.append([int(j) for j in b])
        return out

    def __iter__(self):
        idx = np.arange(self.n)
        if self.shuffle:
            np.random.RandomState(
                (self._seed_base or 0) + self.epoch).shuffle(idx)
        yield from self._shard_batches(idx)

    def __len__(self):
        per = (self.n + self.nranks - 1) // self.nranks
        if self.drop_last:
            return per // self.batch_size
        return (per + self.batch_size - 1) // self.batch_size
