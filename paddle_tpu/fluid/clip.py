"""Gradient clipping (cf. reference python/paddle/fluid/clip.py:
GradientClipByValue, GradientClipByNorm, GradientClipByGlobalNorm)."""

import math

from . import framework, unique_name


class GradientClipBase:
    def __call__(self, params_grads):
        raise NotImplementedError


def _new_var_like(block, base, name_hint):
    name = unique_name.generate(name_hint)
    return block.create_var(
        name=name, shape=base.shape, dtype=base.dtype, stop_gradient=True
    )


class GradientClipByValue(GradientClipBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            block = g.block
            clipped = _new_var_like(block, g, g.name + "@CLIP")
            block.append_op(
                "clip", inputs={"X": [g.name]}, outputs={"Out": [clipped.name]},
                attrs={"min": self.min, "max": self.max}, infer=False,
            )
            out.append((p, clipped))
        return out


class GradientClipByNorm(GradientClipBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        from .layers.common import append_simple_op

        out = []
        for p, g in params_grads:
            if not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            block = g.block
            # norm = sqrt(sum(g^2)); g *= clip_norm / max(norm, clip_norm)
            sq = _new_var_like(block, g, g.name + "@SQN")
            sq.shape = (1,)
            block.append_op(
                "squared_l2_norm", inputs={"X": [g.name]}, outputs={"Out": [sq.name]},
                infer=False,
            )
            clipped = _new_var_like(block, g, g.name + "@CLIP")
            block.append_op(
                "clip_by_norm_apply",
                inputs={"X": [g.name], "SquaredNorm": [sq.name]},
                outputs={"Out": [clipped.name]},
                attrs={"clip_norm": self.clip_norm},
                infer=False,
            )
            out.append((p, clipped))
        return out


class GradientClipByGlobalNorm(GradientClipBase):
    """cf. reference clip.py GradientClipByGlobalNorm: scale all grads by
    clip_norm / max(global_norm, clip_norm)."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        block = None
        sq_names = []
        for p, g in params_grads:
            if not getattr(p, "need_clip", True):
                continue
            block = g.block
            sq = _new_var_like(block, g, g.name + "@SQN")
            sq.shape = (1,)
            block.append_op(
                "squared_l2_norm", inputs={"X": [g.name]}, outputs={"Out": [sq.name]},
                infer=False,
            )
            sq_names.append(sq.name)
        if block is None:
            return params_grads
        total = block.create_var(
            name=unique_name.generate("global_norm_sq"), shape=(1,),
            dtype="float32", stop_gradient=True,
        )
        block.append_op(
            "sum", inputs={"X": sq_names}, outputs={"Out": [total.name]}, infer=False
        )
        out = []
        for p, g in params_grads:
            if not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            clipped = _new_var_like(block, g, g.name + "@CLIP")
            block.append_op(
                "global_norm_clip_apply",
                inputs={"X": [g.name], "GlobalNormSq": [total.name]},
                outputs={"Out": [clipped.name]},
                attrs={"clip_norm": self.clip_norm},
                infer=False,
            )
            out.append((p, clipped))
        return out


# the two helper apply-ops
import jax.numpy as jnp  # noqa: E402

from .core.registry import register_op  # noqa: E402


@register_op("clip_by_norm_apply", inputs=["X", "SquaredNorm"], outputs=["Out"], grad=None)
def _clip_by_norm_apply(ctx, ins, attrs):
    g = ins["X"][0]
    norm = jnp.sqrt(ins["SquaredNorm"][0][0])
    clip_norm = attrs["clip_norm"]
    scale = clip_norm / jnp.maximum(norm, clip_norm)
    return {"Out": [(g * scale).astype(g.dtype)]}


@register_op("global_norm_clip_apply", inputs=["X", "GlobalNormSq"], outputs=["Out"], grad=None)
def _global_norm_clip_apply(ctx, ins, attrs):
    g = ins["X"][0]
    gn = jnp.sqrt(ins["GlobalNormSq"][0][0])
    clip_norm = attrs["clip_norm"]
    scale = clip_norm / jnp.maximum(gn, clip_norm)
    return {"Out": [(g * scale.astype(g.dtype)).astype(g.dtype)]}


# legacy API names
ClipByValue = GradientClipByValue
ClipByNorm = GradientClipByNorm
ClipByGlobalNorm = GradientClipByGlobalNorm


def set_gradient_clip(clip, param_list=None, program=None):
    raise NotImplementedError(
        "set_gradient_clip is deprecated in the reference too — pass "
        "grad_clip= to the optimizer instead"
    )
