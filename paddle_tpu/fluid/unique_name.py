"""Unique name generator (cf. python/paddle/fluid/unique_name.py)."""

import contextlib


class UniqueNameGenerator:
    def __init__(self, prefix=""):
        self.prefix = prefix
        self.ids = {}

    def __call__(self, key):
        if key not in self.ids:
            self.ids[key] = 0
        n = self.ids[key]
        self.ids[key] += 1
        return self.prefix + key + "_" + str(n)


generator = UniqueNameGenerator()


def generate(key):
    return generator(key)


@contextlib.contextmanager
def guard(new_prefix=""):
    """Fresh name space (used by tests and program cloning)."""
    global generator
    old = generator
    generator = UniqueNameGenerator(new_prefix)
    try:
        yield
    finally:
        generator = old


def switch(new_generator=None):
    global generator
    old = generator
    generator = new_generator or UniqueNameGenerator()
    return old
