"""Gradient checking utilities: first and second order.

Capability parity: reference
`python/paddle/fluid/tests/unittests/gradient_checker.py` — `grad_check`
(analytic grads from `gradients()` vs central finite differences) and
`double_grad_check` (builds grads-of-grads and numeric-checks them); the
reference ships it as a test helper, but it is genuinely user-facing for
custom-op authors, so it lives in the package here.
"""

from __future__ import annotations

import numpy as np

from . import backward
from .executor import Executor, scope_guard
from .core.place import CPUPlace
from .core.scope import Scope


def _run(program, feed, fetch, scope, exe):
    with scope_guard(scope):
        return exe.run(program, feed=feed, fetch_list=fetch)


def _numeric_grad(program, feed, x_name, y_names, scope, delta, exe):
    """d sum(ys) / d x by central differences."""
    base = {k: np.asarray(v).copy() for k, v in feed.items()}
    x = base[x_name]
    g = np.zeros_like(x, dtype=np.float64)
    flat, gf = x.reshape(-1), g.reshape(-1)

    def loss_of():
        outs = _run(program, base, list(y_names), scope, exe)
        return sum(float(np.sum(o)) for o in outs)

    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        lp = loss_of()
        flat[i] = orig - delta
        lm = loss_of()
        flat[i] = orig
        gf[i] = (lp - lm) / (2 * delta)
    return g


def grad_check(x, y, feed, program=None, place=None, scope=None,
               eps=1e-3, atol=1e-3, rtol=1e-2):
    """Check analytic d sum(y) / d x against finite differences.

    x, y: Variables (or lists); feed: {name: np.ndarray} covering every
    data input.  Raises AssertionError on mismatch; returns True.
    """
    from . import framework

    xs = x if isinstance(x, (list, tuple)) else [x]
    ys = y if isinstance(y, (list, tuple)) else [y]
    program = program or framework.default_main_program()
    scope = scope or Scope()

    with framework.program_guard(program):
        loss_parts = []
        from . import layers

        total = None
        for yv in ys:
            s = layers.reduce_sum(yv)
            total = s if total is None else total + s
        grads = backward.gradients(total, list(xs))

    missing = [xv.name for xv, g in zip(xs, grads) if g is None]
    if missing:
        raise ValueError(
            "no gradient path from targets to %s (stop_gradient or "
            "disconnected graph)" % missing
        )
    # ONE executor: its program cache makes the 2*numel finite-difference
    # evaluations reuse a single compile
    exe = Executor(CPUPlace())
    fetches = [g.name for g in grads]
    analytic = _run(program, feed, fetches, scope, exe)
    for xv, ga in zip(xs, analytic):
        gn = _numeric_grad(program, feed, xv.name,
                           [yv.name for yv in ys], scope, eps, exe)
        np.testing.assert_allclose(
            ga, gn, rtol=rtol, atol=atol,
            err_msg="grad_check failed for d(%s)/d(%s)"
            % ([yv.name for yv in ys], xv.name),
        )
    return True


def double_grad_check(x, y, feed, program=None, place=None, scope=None,
                      eps=1e-3, atol=1e-3, rtol=1e-2):
    """Check SECOND-order grads: build gx = dy/dx symbolically, then
    grad_check d sum(gx) / d x numerically (reference double_grad_check
    pattern via the differentiable vjp_grad op)."""
    from . import framework

    xs = x if isinstance(x, (list, tuple)) else [x]
    ys = y if isinstance(y, (list, tuple)) else [y]
    program = program or framework.default_main_program()
    scope = scope or Scope()

    with framework.program_guard(program):
        from . import layers

        total = None
        for yv in ys:
            s = layers.reduce_sum(yv)
            total = s if total is None else total + s
        first = backward.gradients(total, list(xs))
    missing = [xv.name for xv, g in zip(xs, first) if g is None]
    if missing:
        raise ValueError("no first-order grad for %s" % missing)
    return grad_check(xs, first, feed, program=program, scope=scope,
                      eps=eps, atol=atol, rtol=rtol)
