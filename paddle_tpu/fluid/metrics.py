"""Host-side metric accumulators.

Capability parity: reference `python/paddle/fluid/metrics.py` — MetricBase,
Accuracy, Precision, Recall, Auc, CompositeMetric, ChunkEvaluator (chunk
omitted: LoD-era sequence tagging; covered by the packing utilities).
"""

from __future__ import annotations

import numpy as np


class MetricBase:
    def __init__(self, name=None):
        self._name = name or type(self).__name__

    def reset(self):
        raise NotImplementedError

    def update(self, *args, **kwargs):
        raise NotImplementedError

    def eval(self):
        raise NotImplementedError

    def get_config(self):
        return {"name": self._name}


class Accuracy(MetricBase):
    """cf. reference metrics.Accuracy: running weighted mean of batch accs."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.value = 0.0
        self.weight = 0.0

    def update(self, value, weight=1):
        if weight < 0:
            raise ValueError("weight must be >= 0")
        self.value += float(value) * weight
        self.weight += weight

    def eval(self):
        if self.weight == 0:
            raise ValueError("Accuracy: no batches accumulated")
        return self.value / self.weight


class Precision(MetricBase):
    """Binary precision (cf. reference metrics.Precision)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fp += int(np.sum((preds == 1) & (labels == 0)))

    def eval(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0


class Recall(MetricBase):
    """Binary recall (cf. reference metrics.Recall)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        preds = np.rint(np.asarray(preds)).astype(np.int64).ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        self.tp += int(np.sum((preds == 1) & (labels == 1)))
        self.fn += int(np.sum((preds == 0) & (labels == 1)))

    def eval(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0


class Auc(MetricBase):
    """Histogram-bucketed ROC AUC (cf. reference metrics.Auc / auc_op.cc)."""

    def __init__(self, name=None, curve="ROC", num_thresholds=4095):
        super().__init__(name)
        self._num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, np.int64)

    def update(self, preds, labels):
        preds = np.asarray(preds)
        if preds.ndim == 2 and preds.shape[1] == 2:
            preds = preds[:, 1]
        preds = preds.ravel()
        labels = np.asarray(labels).astype(np.int64).ravel()
        idx = np.clip((preds * self._num_thresholds).astype(np.int64), 0,
                      self._num_thresholds)
        np.add.at(self._stat_pos, idx[labels == 1], 1)
        np.add.at(self._stat_neg, idx[labels == 0], 1)

    def eval(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_neg - tot_neg) * (new_pos + tot_pos) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0


class CompositeMetric(MetricBase):
    """cf. reference metrics.CompositeMetric."""

    def __init__(self, name=None):
        super().__init__(name)
        self._metrics = []

    def add_metric(self, metric):
        if not isinstance(metric, MetricBase):
            raise ValueError("add_metric expects a MetricBase")
        self._metrics.append(metric)

    def reset(self):
        for m in self._metrics:
            m.reset()

    def update(self, preds, labels):
        for m in self._metrics:
            m.update(preds, labels)

    def eval(self):
        return [m.eval() for m in self._metrics]


class ChunkEvaluator(MetricBase):
    """cf. reference metrics.py ChunkEvaluator: accumulates the chunk_eval
    op's (num_infer, num_label, num_correct) counts across batches and
    reports (precision, recall, f1)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.num_infer_chunks = 0
        self.num_label_chunks = 0
        self.num_correct_chunks = 0

    def update(self, num_infer_chunks, num_label_chunks,
               num_correct_chunks):
        self.num_infer_chunks += int(np.asarray(num_infer_chunks).sum())
        self.num_label_chunks += int(np.asarray(num_label_chunks).sum())
        self.num_correct_chunks += int(
            np.asarray(num_correct_chunks).sum())

    def eval(self):
        precision = (self.num_correct_chunks / self.num_infer_chunks
                     if self.num_infer_chunks else 0.0)
        recall = (self.num_correct_chunks / self.num_label_chunks
                  if self.num_label_chunks else 0.0)
        f1 = (2 * precision * recall / (precision + recall)
              if self.num_correct_chunks else 0.0)
        return precision, recall, f1


class EditDistance(MetricBase):
    """cf. reference metrics.py EditDistance: mean edit distance over all
    evaluated sequences + ratio of exactly-matched instances."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self.total_distance = 0.0
        self.seq_num = 0
        self.instance_error = 0

    def update(self, distances, seq_num):
        d = np.asarray(distances, np.float64).reshape(-1)
        self.total_distance += float(d.sum())
        self.seq_num += int(np.asarray(seq_num).sum())
        self.instance_error += int((d > 0).sum())

    def eval(self):
        if self.seq_num == 0:
            raise ValueError(
                "There is no data in EditDistance Metric. Please check "
                "layers.edit_distance output has been added to "
                "EditDistance.")
        avg_distance = self.total_distance / self.seq_num
        avg_instance_error = self.instance_error / float(self.seq_num)
        return avg_distance, avg_instance_error


class DetectionMAP(MetricBase):
    """cf. reference metrics.py DetectionMAP: accumulates the
    detection_map op's per-batch mAP (host-side average — the op computes
    a full matching per batch on device)."""

    def __init__(self, name=None):
        super().__init__(name)
        self.reset()

    def reset(self):
        self._total = 0.0
        self._count = 0

    def update(self, value, weight=1):
        self._total += float(np.asarray(value).sum()) * weight
        self._count += weight

    def eval(self):
        if self._count == 0:
            raise ValueError("DetectionMAP has no accumulated batches")
        return self._total / self._count
