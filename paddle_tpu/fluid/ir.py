"""IR pass framework: Pass / registry / pattern rewriting over Programs.

Capability parity: reference `framework/ir/` — `ir::Pass` (`ir/pass.h`),
`PassRegistry`, `GraphPatternDetector` (`ir/graph_pattern_detector.h`)
and the fusion passes built on them (`conv_bn_fuse_pass.cc`,
`fc_fuse_pass.cc`, ...).

TPU-first scope note: the reference's ~35k LoC of fusion passes exist to
hand-schedule kernels XLA fuses automatically (SURVEY §7 marks them
subsumed), so this framework keeps the PUBLIC machinery — write a Pass,
register it, match op patterns, rewrite the program — with a small set
of passes that are genuinely useful at the PROGRAM level (dead-op
elimination, op-level fusions that swap in fused ops the op library
really has).  Programs here are the JSON Program/Block/Op IR
(fluid/framework.py), so passes are plain Python over `block.ops`.
"""

from __future__ import annotations

from . import framework

_PASS_REGISTRY: dict = {}


class Pass:
    """cf. ir/pass.h: named transform over a Program; `set(...)` carries
    attributes (reference Pass::Set)."""

    name = None

    def __init__(self):
        self._attrs = {}

    def set(self, key, value):
        self._attrs[key] = value
        return self

    def get(self, key, default=None):
        return self._attrs.get(key, default)

    def apply(self, program):
        """Transform `program` IN PLACE and return it."""
        raise NotImplementedError


def register_pass(cls):
    """Decorator: register a Pass subclass by its `name`."""
    if not getattr(cls, "name", None):
        raise ValueError("a Pass must define a class-level `name`")
    _PASS_REGISTRY[cls.name] = cls
    return cls


def get_pass(name):
    """cf. PassRegistry::Instance().Get."""
    if name not in _PASS_REGISTRY:
        raise KeyError(
            "no pass named %r (registered: %s)"
            % (name, ", ".join(sorted(_PASS_REGISTRY))))
    return _PASS_REGISTRY[name]()


def apply_passes(program, names, verify=False):
    """Run a pass pipeline (cf. PassBuilder) over the program.

    verify=True re-runs the whole-program static verifier (structural
    invariants + shape re-inference + orphan-var check, see
    `paddle_tpu.analysis`) AFTER EACH pass and raises a
    ProgramVerificationError NAMING the offending pass — so a broken
    rewrite fails at the pass boundary, not as an XLA trace error deep
    inside Executor.run."""
    if verify:
        from ..analysis import assert_program_valid

        assert_program_valid(
            program, check_orphans=True,
            what="program handed to apply_passes (before any pass ran)")
    for n in names:
        p = n if isinstance(n, Pass) else get_pass(n)
        program = p.apply(program)
        if verify:
            from ..analysis import (
                ProgramVerificationError, assert_program_valid,
            )

            pass_name = getattr(p, "name", None) or type(p).__name__
            try:
                assert_program_valid(
                    program, check_orphans=True,
                    what="program after pass %r" % pass_name)
            except ProgramVerificationError as e:
                e.pass_name = pass_name
                raise
    return program


def clone_and_apply(program, names, verify=True):
    """Run a pass pipeline on a CLONE of `program` and return the clone
    — the candidate-evaluation primitive behind
    `analysis.perf.rank_pass_pipelines` (and the coming autotuner): the
    original program is never mutated, so any number of pipeline
    variants can be costed side by side."""
    return apply_passes(program.clone(), list(names), verify=verify)


# ---------------------------------------------------------------------------
# pattern detection (cf. ir/graph_pattern_detector.h, reduced to the
# op-chain patterns the JSON IR needs)
# ---------------------------------------------------------------------------


def consumers_of(block, var_name):
    """Ops reading var_name, with their indices."""
    out = []
    for i, op in enumerate(block.ops):
        if var_name in op.all_input_names():
            out.append((i, op))
    return out


def match_chain(block, types):
    """Find (i0, [op...]) chains where op_k's FIRST output feeds op_{k+1}
    as its only consumer — the linear patterns fusion passes match
    (cf. GraphPatternDetector chains)."""
    matches = []
    ops = block.ops
    for i, op in enumerate(ops):
        if op.type != types[0]:
            continue
        chain = [op]
        ok = True
        cur = op
        for want in types[1:]:
            outs = cur.all_output_names()
            if not outs:
                ok = False
                break
            link = outs[0]
            cons = consumers_of(block, link)
            if len(cons) != 1 or cons[0][1].type != want:
                ok = False
                break
            cur = cons[0][1]
            chain.append(cur)
        if ok:
            matches.append((i, chain))
    return matches


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------


@register_pass
class DeadOpEliminationPass(Pass):
    """Remove ops whose outputs are never consumed, fetched, or
    persistable (cf. the reference's eager-deletion/memory passes — at
    the program level the equivalent hygiene is deleting dead ops so the
    executor never lowers them).  Set("keep", [names]) protects extra
    vars (e.g. a fetch list known ahead of time).

    Liveness spans EVERY block plus the sub-block ops control flow and
    recompute serialize into attrs: a var consumed only inside a
    cond/while/static_rnn body (or referenced through a name-list attr
    like ``cap_names``) keeps its parent-block producer alive, and an op
    whose sub-block contains a side effect (e.g. a cond that prints) is
    never deleted.  Vars stranded by op removal are dropped from their
    block's var table so the pass leaves no orphans behind."""

    name = "dead_op_elimination"

    def apply(self, program):
        from ..analysis import opgraph

        keep = set(self.get("keep", []))
        changed = True
        while changed:
            changed = False
            live = set(keep)
            # reads from every real op in every block, every serialized
            # sub-op, and every name-list attr (sub-block alias bindings)
            for _b, _i, op in opgraph.iter_all_ops_deep(program):
                live.update(opgraph.input_names(op))
                for _k, vals in opgraph.attr_name_lists(op):
                    live.update(vals)
            for block in program.blocks:
                for v in block.vars.values():
                    if getattr(v, "persistable", False):
                        live.add(v.name)
            for block in program.blocks:
                kept_ops = []
                for op in block.ops:
                    outs = op.all_output_names()
                    if (opgraph.has_side_effects(op) or not outs
                            or any(o in live for o in outs)
                            or op.attrs.get("op_role") == "optimize"):
                        kept_ops.append(op)
                    else:
                        changed = True
                block.ops[:] = kept_ops
        # drop vars the removed ops stranded (orphan hygiene: the verifier
        # flags unreferenced entries, and a later pass must not trip over
        # stale shape metadata)
        opgraph.drop_orphan_vars(program, keep=keep)
        program._bump()
        return program


@register_pass
class BatchNormActFusePass(Pass):
    """batch_norm + act (sole consumer) -> fused_batch_norm_act — a real
    PatternDetector-style rewrite targeting an op the library ships
    (cf. reference fused_bn_activation and conv_bn_fuse_pass.cc
    machinery; the arithmetic fusion itself is XLA's job, this keeps the
    program one op shorter and the pattern API exercised)."""

    name = "batch_norm_act_fuse"

    _ACTS = ("relu", "sigmoid", "tanh")

    def apply(self, program):
        from ..analysis import opgraph

        block = program.current_block()
        rewired = []
        for act in self._ACTS:
            while True:
                matches = match_chain(block, ["batch_norm", act])
                if not matches:
                    break
                _, (bn, act_op) = matches[0]
                bn.type = "fused_batch_norm_act"
                bn.attrs["act_type"] = act
                # the fused op's Y takes the activation's output name
                act_out = act_op.all_output_names()[0]
                old_y = bn.outputs["Y"][0]
                bn.outputs["Y"] = [act_out]
                block.ops.remove(act_op)
                if old_y != act_out:
                    rewired.append(old_y)
        # the rewiring strands the original batch_norm Y name: drop it
        # from the var table (it held stale shape metadata and tripped
        # the orphan-var verifier rule) unless something else still
        # references it
        if rewired:
            opgraph.drop_orphan_vars(program, candidates=rewired)
        program._bump()
        return program


def _deep_read_counts(program):
    """{name: times read} over every real op in every block, every
    serialized sub-op, and every name-list attr.  A fusion may only
    consume an intermediate whose EVERY read it rewrites — a block-local
    consumer count would miss a cond body or a recompute segment reading
    the var.  Built ONCE per rewrite scan (one program walk) instead of
    per lookup, so a pass sweep stays linear in program size."""
    from ..analysis import opgraph

    counts = {}
    for _b, _i, op in opgraph.iter_all_ops_deep(program):
        for n in opgraph.input_names(op):
            counts[n] = counts.get(n, 0) + 1
        for _k, vals in opgraph.attr_name_lists(op):
            for n in vals:
                counts[n] = counts.get(n, 0) + 1
    return counts


@register_pass
class MatmulBiasActFusePass(Pass):
    """matmul/mul -> elementwise_add(1-D bias on the last dim) -> act
    (sole consumers throughout) -> ONE ``matmul_bias_act`` op — the
    rewrite for exactly the chains the ``unfused-epilogue`` perf-lint
    rule flags (its diagnostics carry ``fix="matmul_bias_act_fuse"``).
    On TPU the fused op lowers to the pallas fused-epilogue kernel
    (bias+activation applied on the f32 accumulator tile before the
    HBM writeback; custom-VJP backward fusing dact into the dX/dW
    GEMMs); elsewhere it lowers to the identical jnp composition.

    Also fuses the reshape-interposed variant the BERT FFN can emit
    (matmul -> reshape2* -> add -> act): the epilogue commutes with a
    reshape that preserves the bias (last) dim, so the activation moves
    into the matmul and the reshapes slide after it.  Chains whose
    bias is not a last-dim 1-D vector, whose intermediates have other
    consumers (anywhere, sub-blocks included), or whose activation the
    kernel lacks are left alone."""

    name = "matmul_bias_act_fuse"

    _ACTS = ("relu", "tanh", "gelu")

    def apply(self, program):
        from ..analysis import opgraph

        block = program.current_block()
        stranded = []
        changed = True
        while changed:
            changed = False
            # fresh read-count index per scan: each rewrite invalidates
            # it, and each scan performs at most one rewrite
            reads = _deep_read_counts(program)
            for op in block.ops:
                if op.type not in ("matmul", "mul"):
                    continue
                m = self._match(block, op, reads)
                if m is None:
                    continue
                self._rewrite(block, op, m, stranded)
                changed = True
                break
        if stranded:
            opgraph.drop_orphan_vars(program, candidates=stranded)
        program._bump()
        return program

    def _sole_consumer(self, block, name, reads):
        """The single op reading `name`, or None when the read count
        anywhere in the program is not exactly one."""
        if reads.get(name, 0) != 1:
            return None
        cons = consumers_of(block, name)
        return cons[0][1] if len(cons) == 1 else None

    def _var(self, block, name):
        return block._find_var_recursive(name)

    def _match(self, block, mm, reads):
        outs = mm.all_output_names()
        if not outs:
            return None
        out_v = self._var(block, outs[0])
        if out_v is None or not out_v.shape:
            return None
        last_dim = out_v.shape[-1]
        # walk through sole-consumer reshapes that keep the bias dim
        mids = []
        cur = outs[0]
        nxt = self._sole_consumer(block, cur, reads)
        # both registered reshape spellings — the lint's fixable guard
        # accepts the same set, so every fix-hinted chain really fuses
        while nxt is not None and nxt.type in ("reshape2", "reshape"):
            r_out = nxt.all_output_names()
            r_v = self._var(block, r_out[0]) if r_out else None
            if r_v is None or not r_v.shape or r_v.shape[-1] != last_dim:
                return None
            mids.append(nxt)
            cur = r_out[0]
            nxt = self._sole_consumer(block, cur, reads)
        add = nxt
        if add is None or add.type != "elementwise_add":
            return None
        # the chain value must be X (bias broadcasts ONTO it); bias is Y
        if add.inputs.get("X", [None])[0] != cur:
            return None
        bias_name = add.inputs.get("Y", [None])[0]
        bias_v = self._var(block, bias_name) if bias_name else None
        if (bias_v is None or bias_v.shape is None
                or len(bias_v.shape) != 1
                or int(bias_v.shape[0]) != int(last_dim)):
            return None
        chain_v = self._var(block, cur)
        axis = add.attrs.get("axis", -1)
        ndim = (len(chain_v.shape)
                if chain_v is not None and chain_v.shape else None)
        if ndim is None or axis not in (-1, ndim - 1):
            return None
        a_out = add.all_output_names()
        if not a_out:
            return None
        act = self._sole_consumer(block, a_out[0], reads)
        if act is None or act.type not in self._ACTS:
            return None
        act_out = act.all_output_names()
        if not act_out:
            return None
        return mids, add, act, bias_name

    def _rewrite(self, block, mm, match, stranded):
        mids, add, act, bias_name = match
        mm.type = "matmul_bias_act"
        mm.attrs["act_type"] = act.type
        if act.type == "gelu":
            mm.attrs["approximate"] = act.attrs.get("approximate", False)
        mm.inputs["Bias"] = [bias_name]
        act_out = act.all_output_names()[0]
        if mids:
            # epilogue moves into the matmul; the reshapes slide after
            # it, and the LAST reshape takes over the activation's
            # output name (its recorded shape already matches)
            last = mids[-1]
            stranded.append(last.outputs["Out"][0])
            last.outputs["Out"] = [act_out]
        else:
            stranded.append(mm.outputs["Out"][0])
            mm.outputs["Out"] = [act_out]
        stranded.append(add.all_output_names()[0])
        block.ops.remove(add)
        block.ops.remove(act)


@register_pass
class TransposeFoldPass(Pass):
    """Cancel inverse-permutation transpose pairs so relayout passes
    never hit HBM — the fix for the ``layout-transpose-hazard`` lint
    (its diagnostics carry ``fix="transpose_fold"``).  Three rewrites,
    most specific first:

    1. **flash-attention layout fold** — transpose([0,2,1,3]) on Q/K/V
       into a BHSD ``flash_attention`` whose output is transposed
       straight back: the kernel already reads BSHD natively
       (``layout`` attr), so the pass flips the attr and deletes all
       four transposes — the model never materializes
       [B,S,H,D]<->[B,H,S,D].
    2. **adjacent pair** — transpose(p1) -> transpose(p2) with
       p1∘p2 = identity (p1's out consumed only by p2): the second
       transpose becomes an ``assign`` (XLA elides it) and the first
       is deleted when nothing else reads it.  The assign keeps every
       downstream name — including fetch targets — produced.
    3. **matmul flag absorption** — a last-two-dims transpose consumed
       only by one matmul folds into its ``transpose_X``/``transpose_Y``
       attr (the MXU takes either operand order for free).

    Every rewrite is shape-neutral on recorded metadata, so
    ``apply_passes(verify=True)``'s re-inference stays green."""

    name = "transpose_fold"

    _T = ("transpose2", "transpose")

    def apply(self, program):
        from ..analysis import opgraph

        block = program.current_block()
        stranded = []
        changed = True
        while changed:
            # fresh read-count index per scan (each scan does at most
            # one rewrite, which invalidates it)
            reads = _deep_read_counts(program)
            changed = (self._fold_flash_layout(block, stranded, reads)
                       or self._fold_adjacent(block, stranded, reads)
                       or self._fold_into_matmul(block, stranded, reads))
        if stranded:
            opgraph.drop_orphan_vars(program, candidates=stranded)
        program._bump()
        return program

    @staticmethod
    def _perm(op):
        p = op.attrs.get("axis")
        return list(p) if isinstance(p, (list, tuple)) else None

    @staticmethod
    def _identity_compose(p1, p2):
        if p1 is None or p2 is None or len(p1) != len(p2):
            return False
        n = len(p1)
        return all(0 <= p2[j] < n and p1[p2[j]] == j for j in range(n))

    def _producer(self, block, name, before_idx):
        from ..analysis import opgraph

        return opgraph.producer_before(block, name, before_idx)

    def _delete_if_unread(self, block, op, stranded, reads):
        out = op.all_output_names()
        if out and reads.get(out[0], 0) == 0:
            v = block._find_var_recursive(out[0])
            if v is None or not getattr(v, "persistable", False):
                block.ops.remove(op)
                stranded.append(out[0])
                return True
        return False

    # -- rewrite 1: flash_attention BSHD layout fold -------------------
    _HEAD_SWAP = [0, 2, 1, 3]

    def _fold_flash_layout(self, block, stranded, reads):
        for fidx, f in enumerate(block.ops):
            if (f.type != "flash_attention"
                    or f.attrs.get("layout", "BHSD") != "BHSD"):
                continue
            slot_names = {s: f.inputs.get(s, [None])[0]
                          for s in ("Q", "K", "V")}
            ins = {}
            ok = True
            for slot, name in slot_names.items():
                found = (self._producer(block, name, fidx)
                         if name else None)
                # a shared transpose (e.g. K and V from one transposed
                # tensor) is foldable as long as EVERY read of its
                # output is one of THIS op's Q/K/V slots
                n_here = sum(1 for n in slot_names.values()
                             if n == name)
                if (found is None or found[1].type not in self._T
                        or self._perm(found[1]) != self._HEAD_SWAP
                        or reads.get(name, 0) != n_here):
                    ok = False
                    break
                ins[slot] = found[1]
            if not ok:
                continue
            out_name = f.all_output_names()[0]
            if reads.get(out_name, 0) != 1:
                continue
            t_out = next((op for _i, op in consumers_of(block, out_name)),
                         None)
            if (t_out is None or t_out.type not in self._T
                    or self._perm(t_out) != self._HEAD_SWAP):
                continue
            # dedup: a shared transpose appears under several slots but
            # must be deleted (and its out var stranded) only once
            tposes = {id(ins[s]): ins[s] for s in ins}
            for slot, t in ins.items():
                f.inputs[slot] = [t.inputs["X"][0]]
            f.attrs["layout"] = "BSHD"
            stranded.append(out_name)
            f.outputs["Out"] = [t_out.all_output_names()[0]]
            for t in tposes.values():
                stranded.append(t.all_output_names()[0])
                block.ops.remove(t)
            block.ops.remove(t_out)
            return True
        return False

    # -- rewrite 2: adjacent inverse pair ------------------------------
    def _fold_adjacent(self, block, stranded, reads):
        for idx, t2 in enumerate(block.ops):
            if t2.type not in self._T:
                continue
            p2 = self._perm(t2)
            name = t2.inputs.get("X", [None])[0]
            found = self._producer(block, name, idx) if name else None
            if found is None:
                continue
            t1 = found[1]
            if (t1.type not in self._T
                    or not self._identity_compose(self._perm(t1), p2)
                    or reads.get(name, 0) != 1):
                continue
            # t2 becomes a no-op copy of t1's input (keeps every
            # downstream name — fetch targets included — produced)
            t2.type = "assign"
            t2.inputs = {"X": [t1.inputs["X"][0]]}
            t2.attrs.pop("axis", None)
            reads[name] = 0    # t2 no longer reads t1's output
            self._delete_if_unread(block, t1, stranded, reads)
            return True
        return False

    # -- rewrite 3: fold a last-two-dims swap into matmul's flags ------
    @staticmethod
    def _is_last_two_swap(p):
        if p is None or len(p) < 2:
            return False
        n = len(p)
        return (p[:-2] == list(range(n - 2))
                and p[-2] == n - 1 and p[-1] == n - 2)

    def _fold_into_matmul(self, block, stranded, reads):
        for idx, t in enumerate(block.ops):
            if t.type not in self._T:
                continue
            if not self._is_last_two_swap(self._perm(t)):
                continue
            out = t.all_output_names()
            if not out or reads.get(out[0], 0) != 1:
                continue
            mm = next((op for _i, op in consumers_of(block, out[0])),
                      None)
            if mm is None or mm.type != "matmul":
                continue
            if mm.inputs.get("X", [None])[0] == out[0]:
                slot, flag = "X", "transpose_X"
            elif mm.inputs.get("Y", [None])[0] == out[0]:
                slot, flag = "Y", "transpose_Y"
            else:
                continue
            cur = mm.attrs.get(flag, mm.attrs.get(flag.lower(), False))
            mm.attrs[flag] = not cur
            mm.attrs.pop(flag.lower(), None)
            mm.inputs[slot] = [t.inputs["X"][0]]
            stranded.append(out[0])
            block.ops.remove(t)
            return True
        return False
