"""IR pass framework: Pass / registry / pattern rewriting over Programs.

Capability parity: reference `framework/ir/` — `ir::Pass` (`ir/pass.h`),
`PassRegistry`, `GraphPatternDetector` (`ir/graph_pattern_detector.h`)
and the fusion passes built on them (`conv_bn_fuse_pass.cc`,
`fc_fuse_pass.cc`, ...).

TPU-first scope note: the reference's ~35k LoC of fusion passes exist to
hand-schedule kernels XLA fuses automatically (SURVEY §7 marks them
subsumed), so this framework keeps the PUBLIC machinery — write a Pass,
register it, match op patterns, rewrite the program — with a small set
of passes that are genuinely useful at the PROGRAM level (dead-op
elimination, op-level fusions that swap in fused ops the op library
really has).  Programs here are the JSON Program/Block/Op IR
(fluid/framework.py), so passes are plain Python over `block.ops`.
"""

from __future__ import annotations

from . import framework

_PASS_REGISTRY: dict = {}


class Pass:
    """cf. ir/pass.h: named transform over a Program; `set(...)` carries
    attributes (reference Pass::Set)."""

    name = None

    def __init__(self):
        self._attrs = {}

    def set(self, key, value):
        self._attrs[key] = value
        return self

    def get(self, key, default=None):
        return self._attrs.get(key, default)

    def apply(self, program):
        """Transform `program` IN PLACE and return it."""
        raise NotImplementedError


def register_pass(cls):
    """Decorator: register a Pass subclass by its `name`."""
    if not getattr(cls, "name", None):
        raise ValueError("a Pass must define a class-level `name`")
    _PASS_REGISTRY[cls.name] = cls
    return cls


def get_pass(name):
    """cf. PassRegistry::Instance().Get."""
    if name not in _PASS_REGISTRY:
        raise KeyError(
            "no pass named %r (registered: %s)"
            % (name, ", ".join(sorted(_PASS_REGISTRY))))
    return _PASS_REGISTRY[name]()


def apply_passes(program, names, verify=False):
    """Run a pass pipeline (cf. PassBuilder) over the program.

    verify=True re-runs the whole-program static verifier (structural
    invariants + shape re-inference + orphan-var check, see
    `paddle_tpu.analysis`) AFTER EACH pass and raises a
    ProgramVerificationError NAMING the offending pass — so a broken
    rewrite fails at the pass boundary, not as an XLA trace error deep
    inside Executor.run."""
    if verify:
        from ..analysis import assert_program_valid

        assert_program_valid(
            program, check_orphans=True,
            what="program handed to apply_passes (before any pass ran)")
    for n in names:
        p = n if isinstance(n, Pass) else get_pass(n)
        program = p.apply(program)
        if verify:
            from ..analysis import (
                ProgramVerificationError, assert_program_valid,
            )

            pass_name = getattr(p, "name", None) or type(p).__name__
            try:
                assert_program_valid(
                    program, check_orphans=True,
                    what="program after pass %r" % pass_name)
            except ProgramVerificationError as e:
                e.pass_name = pass_name
                raise
    return program


def clone_and_apply(program, names, verify=True):
    """Run a pass pipeline on a CLONE of `program` and return the clone
    — the candidate-evaluation primitive behind
    `analysis.perf.rank_pass_pipelines` (and the coming autotuner): the
    original program is never mutated, so any number of pipeline
    variants can be costed side by side."""
    return apply_passes(program.clone(), list(names), verify=verify)


# ---------------------------------------------------------------------------
# pattern detection (cf. ir/graph_pattern_detector.h, reduced to the
# op-chain patterns the JSON IR needs)
# ---------------------------------------------------------------------------


def consumers_of(block, var_name):
    """Ops reading var_name, with their indices."""
    out = []
    for i, op in enumerate(block.ops):
        if var_name in op.all_input_names():
            out.append((i, op))
    return out


def match_chain(block, types):
    """Find (i0, [op...]) chains where op_k's FIRST output feeds op_{k+1}
    as its only consumer — the linear patterns fusion passes match
    (cf. GraphPatternDetector chains)."""
    matches = []
    ops = block.ops
    for i, op in enumerate(ops):
        if op.type != types[0]:
            continue
        chain = [op]
        ok = True
        cur = op
        for want in types[1:]:
            outs = cur.all_output_names()
            if not outs:
                ok = False
                break
            link = outs[0]
            cons = consumers_of(block, link)
            if len(cons) != 1 or cons[0][1].type != want:
                ok = False
                break
            cur = cons[0][1]
            chain.append(cur)
        if ok:
            matches.append((i, chain))
    return matches


# ---------------------------------------------------------------------------
# passes
# ---------------------------------------------------------------------------


@register_pass
class DeadOpEliminationPass(Pass):
    """Remove ops whose outputs are never consumed, fetched, or
    persistable (cf. the reference's eager-deletion/memory passes — at
    the program level the equivalent hygiene is deleting dead ops so the
    executor never lowers them).  Set("keep", [names]) protects extra
    vars (e.g. a fetch list known ahead of time).

    Liveness spans EVERY block plus the sub-block ops control flow and
    recompute serialize into attrs: a var consumed only inside a
    cond/while/static_rnn body (or referenced through a name-list attr
    like ``cap_names``) keeps its parent-block producer alive, and an op
    whose sub-block contains a side effect (e.g. a cond that prints) is
    never deleted.  Vars stranded by op removal are dropped from their
    block's var table so the pass leaves no orphans behind."""

    name = "dead_op_elimination"

    def apply(self, program):
        from ..analysis import opgraph

        keep = set(self.get("keep", []))
        changed = True
        while changed:
            changed = False
            live = set(keep)
            # reads from every real op in every block, every serialized
            # sub-op, and every name-list attr (sub-block alias bindings)
            for _b, _i, op in opgraph.iter_all_ops_deep(program):
                live.update(opgraph.input_names(op))
                for _k, vals in opgraph.attr_name_lists(op):
                    live.update(vals)
            for block in program.blocks:
                for v in block.vars.values():
                    if getattr(v, "persistable", False):
                        live.add(v.name)
            for block in program.blocks:
                kept_ops = []
                for op in block.ops:
                    outs = op.all_output_names()
                    if (opgraph.has_side_effects(op) or not outs
                            or any(o in live for o in outs)
                            or op.attrs.get("op_role") == "optimize"):
                        kept_ops.append(op)
                    else:
                        changed = True
                block.ops[:] = kept_ops
        # drop vars the removed ops stranded (orphan hygiene: the verifier
        # flags unreferenced entries, and a later pass must not trip over
        # stale shape metadata)
        opgraph.drop_orphan_vars(program, keep=keep)
        program._bump()
        return program


@register_pass
class BatchNormActFusePass(Pass):
    """batch_norm + act (sole consumer) -> fused_batch_norm_act — a real
    PatternDetector-style rewrite targeting an op the library ships
    (cf. reference fused_bn_activation and conv_bn_fuse_pass.cc
    machinery; the arithmetic fusion itself is XLA's job, this keeps the
    program one op shorter and the pattern API exercised)."""

    name = "batch_norm_act_fuse"

    _ACTS = ("relu", "sigmoid", "tanh")

    def apply(self, program):
        from ..analysis import opgraph

        block = program.current_block()
        rewired = []
        for act in self._ACTS:
            while True:
                matches = match_chain(block, ["batch_norm", act])
                if not matches:
                    break
                _, (bn, act_op) = matches[0]
                bn.type = "fused_batch_norm_act"
                bn.attrs["act_type"] = act
                # the fused op's Y takes the activation's output name
                act_out = act_op.all_output_names()[0]
                old_y = bn.outputs["Y"][0]
                bn.outputs["Y"] = [act_out]
                block.ops.remove(act_op)
                if old_y != act_out:
                    rewired.append(old_y)
        # the rewiring strands the original batch_norm Y name: drop it
        # from the var table (it held stale shape metadata and tripped
        # the orphan-var verifier rule) unless something else still
        # references it
        if rewired:
            opgraph.drop_orphan_vars(program, candidates=rewired)
        program._bump()
        return program
