"""append_backward: declarative reverse-mode AD by program rewriting.

Capability parity: reference `python/paddle/fluid/backward.py` —
append_backward:1193 (reverse-topological per-op grad emission),
_addup_repetitive_outputs_:372 (multi-consumer grad summation),
_remove_no_grad_branch_:454 (no_grad_set / stop_gradient pruning).

TPU-first redesign: the reference needs ~600 hand-written C++ GradOpMakers
(`grad_op_desc_maker.h`).  Here gradients come from ONE generic grad op,
``vjp_grad``, whose lowering calls `jax.vjp` on the forward op's own lowering
inside the same XLA compilation — the recomputed forward is eliminated by
XLA CSE, so the emitted HLO matches a hand-written grad kernel.  Ops where
VJP-of-lowering is wrong (RNG ops like dropout, whose grad must reuse the
forward mask) register a custom grad maker instead.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import framework
from .core import dtypes as dtypes_mod
from .core.registry import LowerContext, get_op_def, register_op

# ---------------------------------------------------------------------------
# The generic VJP grad op
# ---------------------------------------------------------------------------
# Slot naming convention inside a vjp_grad op:
#   inputs:  "X$<slot>"  forward inputs,  "DO$<slot>" output gradients
#   outputs: "DX$<slot>" input gradients
# attrs: fwd_type, fwd_attrs, fwd_in_slots (ordered), fwd_out_slots (ordered,
#        non-stateful), grad_in_slots (subset receiving gradients)


# grad="auto": differentiating a vjp_grad op (VJP of a VJP, both pure JAX)
# is how double-grad works — cf. reference double_grad makers
# (`imperative/partial_grad_engine.cc`, per-op *GradGrad ops).
@register_op("vjp_grad", inputs=[], outputs=[], grad="auto")
def _vjp_grad(ctx, ins, attrs):
    fwd_def = get_op_def(attrs["fwd_type"])
    fwd_attrs = attrs["fwd_attrs"]
    in_slots = attrs["fwd_in_slots"]
    out_slots = attrs["fwd_out_slots"]
    grad_slots = attrs["grad_in_slots"]

    fwd_ins = {slot: ins.get("X$" + slot, []) for slot in in_slots}

    # flatten the differentiable primals
    diff_index = []  # (slot, i)
    primals = []
    for slot in grad_slots:
        for i, v in enumerate(fwd_ins[slot]):
            diff_index.append((slot, i))
            primals.append(v)

    def fwd_flat(*diff_vals):
        rebuilt = {s: list(vs) for s, vs in fwd_ins.items()}
        for (slot, i), v in zip(diff_index, diff_vals):
            rebuilt[slot][i] = v
        sub = LowerContext(base_key=None, is_test=ctx.is_test)
        sub._base_key = ctx._base_key
        outs = fwd_def.lower(sub, rebuilt, fwd_attrs)
        flat = []
        for slot in out_slots:
            flat.extend(outs[slot])
        return flat

    out_primals, vjp_fn = jax.vjp(fwd_flat, *primals)

    # cotangents: provided output grads, zeros elsewhere
    cotangents = []
    counts = attrs["fwd_out_counts"]
    k = 0
    for slot, cnt in zip(out_slots, counts):
        slot_grads = ins.get("DO$" + slot, [])
        present = attrs["out_grad_present"][out_slots.index(slot)]
        gi = 0
        for j in range(cnt):
            if present[j]:
                g = slot_grads[gi]
                gi += 1
                cotangents.append(g.astype(out_primals[k].dtype))
            else:
                cotangents.append(jnp.zeros_like(out_primals[k]))
            k += 1

    grads = vjp_fn(list(cotangents))

    out = {}
    for (slot, i), g in zip(diff_index, grads):
        out.setdefault("DX$" + slot, []).append(g)
    return out


# ---------------------------------------------------------------------------
# Recompute segments (activation checkpointing)
# ---------------------------------------------------------------------------
# Capability parity: reference `backward.py:629`
# `_append_backward_ops_with_checkpoints_` re-emits forward ops between user
# checkpoints before their grads.  TPU-first: a segment becomes ONE composite
# op whose lowering runs the segment under `jax.checkpoint`; the generic VJP
# then differentiates the segment as a unit, so XLA stores only segment
# boundaries and rematerializes the interior in the backward pass (the
# reference's re-emission + our CSE-proofing in one primitive).


@register_op("recompute_segment", inputs=["X"], outputs=["Out"], grad="auto",
             needs_rng=True)
def _recompute_segment(ctx, ins, attrs):
    from .core.block_eval import run_ops

    seg_ops = attrs["ops"]  # serialized op dicts (framework.Operator.to_dict)
    in_names = attrs["in_names"]
    out_names = attrs["out_names"]
    needs_rng = any(get_op_def(od["type"]).needs_rng for od in seg_ops)
    # RNG key must be IDENTICAL between the primal lowering and the VJP
    # re-lowering (the grad path resets its sub-context counter), so derive
    # it from the program base key + a per-segment static seed — NOT from
    # ctx.rng(), whose counter differs between the two traversals.
    key = None
    if needs_rng:
        key = jax.random.fold_in(
            ctx._base_key, 0x5E6 ^ int(attrs.get("segment_seed", 0))
        )
    is_test = ctx.is_test

    def seg(key, xs):
        env = dict(zip(in_names, xs))
        sub = LowerContext(base_key=key, is_test=is_test)
        run_ops(seg_ops, env, sub)
        return [env[n] for n in out_names]

    seg = jax.checkpoint(seg)
    return {"Out": seg(key, list(ins["X"]))}


# ---------------------------------------------------------------------------
# Custom grad makers (ops whose grads can't come from plain VJP)
# ---------------------------------------------------------------------------

def _dropout_grad_maker(op, get_out_grad, new_grad_name, block):
    g = get_out_grad(op.output("Out")[0])
    if g is None:
        return []
    x = op.input("X")[0]
    gx = new_grad_name(x)
    return [
        (
            "dropout_grad",
            {"Mask": list(op.output("Mask")), "Out@GRAD": [g]},
            {"X@GRAD": [gx]},
            dict(op.attrs),
            {x: gx},
        )
    ]


def _lookup_table_grad_maker(op, get_out_grad, new_grad_name, block):
    """SelectedRows cover (reference `selected_rows.h:1`): with
    is_sparse=True the table's grad becomes a (Rows, Values) pair plus a
    marker grad Variable carrying `.selected_rows`; the optimizer emits a
    sparse scatter update instead of a dense one.  Dense mode (the default)
    returns None to fall through to the generic VJP path."""
    if not op.attrs.get("is_sparse"):
        return None
    g = get_out_grad(op.output("Out")[0])
    if g is None:
        return []
    w_name = op.input("W")[0]
    ids_name = op.input("Ids")[0]
    w = block._find_var_recursive(w_name)
    ids = block._find_var_recursive(ids_name)
    n = 1
    for s in ids.shape:
        if s == -1:
            n = -1
            break
        n *= int(s)
    gw = new_grad_name(w_name)
    rows_name, vals_name = gw + "@ROWS", gw + "@VALUES"
    block.create_var(name=rows_name, shape=(n,), dtype="int32",
                     stop_gradient=True)
    block.create_var(name=vals_name, shape=(n, int(w.shape[1])),
                     dtype=w.dtype, stop_gradient=True)
    # the grad var itself is a marker: no op produces it, the executor
    # errors loudly if anything tries to read it as a dense array
    block.var(gw).selected_rows = (rows_name, vals_name)
    return [
        (
            "lookup_table_sparse_grad",
            {"Ids": [ids_name], "OutGrad": [g]},
            {"Rows": [rows_name], "Values": [vals_name]},
            {"padding_idx": op.attrs.get("padding_idx", -1)},
            {},
        )
    ]


CUSTOM_GRAD_MAKERS = {
    "dropout_grad_maker": _dropout_grad_maker,
    "lookup_table_grad_maker": _lookup_table_grad_maker,
}


# ---------------------------------------------------------------------------
# append_backward
# ---------------------------------------------------------------------------

def append_backward(loss, parameter_list=None, no_grad_set=None, callbacks=None):
    """Emit grad ops for `loss` into its program; return [(param, grad)].

    Matches reference `backward.py:1193` semantics: honors stop_gradient and
    no_grad_set, sums multi-consumer gradients, names grads `<var>@GRAD`.
    """
    return _append_backward_for_targets(
        [loss], [None], parameter_list=parameter_list, no_grad_set=no_grad_set
    )


def _append_backward_for_targets(
    targets, target_gradients, parameter_list=None, no_grad_set=None,
    return_map=False,
):
    """Shared engine behind append_backward / gradients (reference
    `backward.py:1601` calc_gradient): seeds each target with the provided
    output gradient (or ones), then runs one reverse sweep."""
    loss = targets[0]
    block = loss.block
    program = block.program
    # all targets (and provided output gradients) must live in ONE block;
    # mixed-block inputs would silently build a wrong graph
    for t in targets[1:]:
        if t.block is not block:
            raise ValueError(
                "backward targets span different blocks: %r vs %r — "
                "compute gradients per block" % (loss.name, t.name))
    for tg in (target_gradients or []):
        if tg is not None and tg.block is not block:
            raise ValueError(
                "target_gradient %r lives in a different block than the "
                "targets" % tg.name)
    no_grad = set(no_grad_set or ())
    first_backward_op_idx = len(block.ops)

    # 1. ops relevant to the targets (backward data-flow reachability)
    needed = {t.name for t in targets}
    relevant = []
    for op in reversed(block.ops):
        if any(n in needed for n in op.all_output_names()):
            relevant.append(op)
            needed.update(op.all_input_names())
    # relevant is in reverse program order already

    # 2. partial-grad bookkeeping
    partials: dict[str, list[str]] = {}

    def new_grad_name(var_name):
        lst = partials.setdefault(var_name, [])
        base = framework.grad_var_name(var_name)
        # a fresh name when the canonical one is taken (second sweep for
        # double-grad, or multiple partials) — SSA, never redefine a var
        if not lst and not block.has_var(base):
            name = base
        else:
            name = framework.unique_name.generate(base + "@RENAME")
        lst.append(name)
        v = block._find_var_recursive(var_name)
        # stop_gradient=False: grad vars stay differentiable so a second
        # reverse sweep (double-grad) can chain through them.
        block.create_var(
            name=name, shape=v.shape, dtype=v.dtype, stop_gradient=False
        )
        return name

    def get_total_grad(var_name):
        lst = partials.get(var_name, [])
        if not lst:
            return None
        if len(lst) == 1:
            return lst[0]
        for pname in lst:
            pv = block._find_var_recursive(pname)
            if pv is not None and getattr(pv, "selected_rows", None):
                raise NotImplementedError(
                    "parameter '%s' receives multiple gradients and at "
                    "least one is sparse (SelectedRows) — a table used by "
                    "an is_sparse=True embedding cannot be shared with "
                    "other consumers; set is_sparse=False" % var_name
                )
        total = framework.grad_var_name(var_name) + "@SUM"
        if block.has_var(total):  # a previous sweep already used this name
            total = framework.unique_name.generate(total)
        v = block._find_var_recursive(var_name)
        block.create_var(name=total, shape=v.shape, dtype=v.dtype, stop_gradient=False)
        block.append_op(
            "sum", inputs={"X": list(lst)}, outputs={"Out": [total]}, infer=False
        )
        partials[var_name] = [total]
        return total

    # 3. seed each target: provided output grad, else d target/d target = 1
    for t, tg in zip(targets, target_gradients):
        if tg is not None:
            if tuple(tg.shape) != tuple(t.shape):
                raise ValueError(
                    "target_gradient %s shape %s does not match target %s "
                    "shape %s" % (tg.name, tg.shape, t.name, t.shape)
                )
            partials.setdefault(t.name, []).append(tg.name)
            continue
        t_grad = framework.grad_var_name(t.name)
        if not block.has_var(t_grad):
            block.create_var(
                name=t_grad, shape=t.shape, dtype=t.dtype, stop_gradient=True
            )
        block.append_op(
            "fill_constant",
            inputs={},
            outputs={"Out": [t_grad]},
            attrs={
                "shape": list(t.shape),
                "value": 1.0,
                "dtype": t.dtype,
            },
            infer=False,
        )
        partials.setdefault(t.name, []).append(t_grad)

    def wants_grad(var_name, slot, opdef):
        if slot in opdef.no_grad_slots or var_name in no_grad:
            return False
        v = block._find_var_recursive(var_name)
        if v is None or v.stop_gradient:
            return False
        return dtypes_mod.is_floating(v.dtype)

    # 4. reverse sweep
    for op in relevant:
        opdef = get_op_def(op.type)
        if opdef.grad_maker is None:
            continue

        # custom maker?  (returning None falls through to the generic path)
        if isinstance(opdef.grad_maker, str) and opdef.grad_maker != "auto":
            maker = CUSTOM_GRAD_MAKERS[opdef.grad_maker]
            specs = maker(op, get_total_grad, new_grad_name, block)
            if specs is not None:
                for type_, ins_, outs_, attrs_, _gradmap in specs:
                    block.append_op(type_, inputs=ins_, outputs=outs_,
                                    attrs=attrs_, infer=False)
                continue

        # generic vjp path
        grad_in_slots = []
        for slot, names in op.inputs.items():
            if any(wants_grad(n, slot, opdef) for n in names):
                grad_in_slots.append(slot)
        if not grad_in_slots:
            continue

        out_slots = [s for s in op.outputs if s not in opdef.stateful_out_slots]
        out_counts = [len(op.outputs[s]) for s in out_slots]
        out_grad_present = []
        do_inputs = {}
        any_grad = False
        for slot in out_slots:
            present = []
            slot_grads = []
            for name in op.outputs[slot]:
                g = get_total_grad(name)
                present.append(g is not None)
                if g is not None:
                    slot_grads.append(g)
                    any_grad = True
            out_grad_present.append(present)
            if slot_grads:
                do_inputs["DO$" + slot] = slot_grads
        if not any_grad:
            continue

        vjp_inputs = {"X$" + slot: list(op.inputs[slot]) for slot in op.inputs}
        vjp_inputs.update(do_inputs)
        vjp_outputs = {}
        for slot in grad_in_slots:
            gnames = []
            for n in op.inputs[slot]:
                if wants_grad(n, slot, opdef):
                    gnames.append(new_grad_name(n))
                else:
                    # vjp still returns a cotangent for every entry in the
                    # slot; route unwanted ones to throwaway vars
                    v = block._find_var_recursive(n)
                    junk = framework.unique_name.generate(n + "@GRAD@JUNK")
                    block.create_var(name=junk, shape=v.shape, dtype=v.dtype, stop_gradient=True)
                    gnames.append(junk)
            vjp_outputs["DX$" + slot] = gnames

        block.append_op(
            "vjp_grad",
            inputs=vjp_inputs,
            outputs=vjp_outputs,
            attrs={
                "fwd_type": op.type,
                "fwd_attrs": dict(op.attrs),
                "fwd_in_slots": list(op.inputs),
                "fwd_out_slots": out_slots,
                "fwd_out_counts": out_counts,
                "out_grad_present": out_grad_present,
                "grad_in_slots": grad_in_slots,
            },
            infer=False,
        )

    # 5. sum any remaining multi-partial leaf grads so `<var>@GRAD` is total
    #    (cf. reference _addup_repetitive_outputs_).  Skipped for the
    #    calc_gradient path (return_map): redefining the canonical name would
    #    clobber an earlier sweep's grads under double-grad.
    for var_name in [] if return_map else list(partials):
        if len(partials[var_name]) > 1:
            total = get_total_grad(var_name)
            # expose under the canonical @GRAD name
            canonical = framework.grad_var_name(var_name)
            if total != canonical:
                v = block._find_var_recursive(var_name)
                if not block.has_var(canonical):
                    block.create_var(
                        name=canonical, shape=v.shape, dtype=v.dtype, stop_gradient=True
                    )
                block.append_op(
                    "assign",
                    inputs={"X": [total]},
                    outputs={"Out": [canonical]},
                    infer=False,
                )
                partials[var_name] = [canonical]

    # tag everything emitted here for clone(for_test) pruning (cf. OpRole)
    for op in block.ops[first_backward_op_idx:]:
        op.attrs.setdefault("op_role", "backward")

    if return_map:
        gmap = {name: get_total_grad(name) for name in list(partials)}
        # tag the sum ops get_total_grad just emitted, too
        for op in block.ops[first_backward_op_idx:]:
            op.attrs.setdefault("op_role", "backward")
        return gmap

    # 6. collect (param, grad) pairs
    if parameter_list is not None:
        params = [
            block.var(p) if isinstance(p, str) else p for p in parameter_list
        ]
    else:
        params = [p for p in block.all_parameters() if p.trainable]

    result = []
    for p in params:
        g = get_total_grad(p.name)
        if g is None:
            continue
        result.append((p, block.var(g)))
    return result


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """cf. reference backward.py:1727 / calc_gradient:1601 — grads of
    (possibly several) targets w.r.t. inputs, with optional provided output
    gradients.  Calling it on the result of a previous call yields
    double-grad (the emitted vjp_grad ops are themselves differentiable).
    """
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    if target_gradients is None:
        target_gradients = [None] * len(targets)
    if not isinstance(target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    if len(target_gradients) != len(targets):
        raise ValueError(
            "gradients(): %d targets but %d target_gradients"
            % (len(targets), len(target_gradients))
        )
    block = targets[0].block
    grad_map = _append_backward_for_targets(
        list(targets), list(target_gradients),
        parameter_list=[], no_grad_set=no_grad_set,
        return_map=True,
    )
    out = []
    for iv in inputs:
        gname = grad_map.get(iv.name)
        out.append(block.var(gname) if gname is not None else None)
    return out
