"""Sequence packing: LoD batches -> fixed-shape packed rows + segment ids.

Capability parity: the reference carries variable-length batches as
LoDTensor offset tables end to end (`framework/lod_tensor.h:52,104`).
TPU-first redesign: XLA wants static shapes, so variable-length data is
*packed* — several sequences concatenated into one fixed-length row — and
the in-graph ops consume O(S) segment-id vectors instead of offset tables:
`flash_attention` (QSeg/KSeg) confines attention to a segment,
`segment_pool` pools per segment, positions restart per segment.  Packing
wastes far less compute than padding when lengths vary (the padding is only
the tail of each row, not per-sequence).

Host-side (numpy) — runs in the reader/data pipeline, not in-graph.
"""

from __future__ import annotations

import numpy as np

__all__ = ["pack_sequences", "PackedBatch"]


class PackedBatch:
    """data [B, S, ...], segment_ids [B, S] (1-based, 0 = padding),
    positions [B, S] (restart at 0 per segment), index: list per row of
    (sequence_index, start, length)."""

    def __init__(self, data, segment_ids, positions, index):
        self.data = data
        self.segment_ids = segment_ids
        self.positions = positions
        self.index = index

    def __repr__(self):
        return "PackedBatch(data=%s, rows=%d)" % (
            self.data.shape, len(self.index)
        )


def pack_sequences(sequences, seq_len, pad_value=0, max_rows=None):
    """Greedy first-fit-decreasing packing of variable-length sequences
    into rows of length ``seq_len``.

    sequences: list of 1-D (token ids) or 2-D ([T, D] features) arrays,
    each with len <= seq_len (longer raises — never silently truncate).
    Returns a :class:`PackedBatch`; segment ids are 1-based per row with 0
    marking the padded tail, so they can feed `flash_attention`'s
    QSeg/KSeg directly (padding attends only padding) and `segment_pool`
    after subtracting 1.
    """
    seqs = [np.asarray(s) for s in sequences]
    for i, s in enumerate(seqs):
        if s.shape[0] > seq_len:
            raise ValueError(
                "sequence %d has length %d > seq_len %d (packing never "
                "truncates; split or raise seq_len)" % (i, s.shape[0], seq_len)
            )
    order = sorted(range(len(seqs)), key=lambda i: -seqs[i].shape[0])
    rows = []  # each: [used, [(orig_idx, seq), ...]]
    for i in order:
        s = seqs[i]
        placed = False
        for row in rows:
            if row[0] + s.shape[0] <= seq_len:
                row[1].append((i, s))
                row[0] += s.shape[0]
                placed = True
                break
        if not placed:
            if max_rows is not None and len(rows) >= max_rows:
                raise ValueError(
                    "pack_sequences: need more than max_rows=%d rows"
                    % max_rows
                )
            rows.append([s.shape[0], [(i, s)]])

    feat_shape = seqs[0].shape[1:] if seqs and seqs[0].ndim > 1 else ()
    B = len(rows) if max_rows is None else max_rows
    data = np.full((B, seq_len) + feat_shape, pad_value,
                   dtype=seqs[0].dtype if seqs else np.int64)
    seg = np.zeros((B, seq_len), np.int32)
    pos = np.zeros((B, seq_len), np.int32)
    index = [[] for _ in range(B)]
    for r, (_, items) in enumerate(rows):
        cursor = 0
        for s_rank, (orig_idx, s) in enumerate(items, start=1):
            L = s.shape[0]
            data[r, cursor:cursor + L] = s
            seg[r, cursor:cursor + L] = s_rank
            pos[r, cursor:cursor + L] = np.arange(L)
            index[r].append((orig_idx, cursor, L))
            cursor += L
    return PackedBatch(data, seg, pos, index)
