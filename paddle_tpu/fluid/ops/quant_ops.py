"""Quantization ops (QAT simulation + int8 storage).

Capability parity: reference
`python/paddle/fluid/contrib/slim/quantization/quantization_pass.py:1` and
the C++ fake_quantize_op.cc / dequantize ops family:
- fake_quantize_dequantize_abs_max: QAT simulation with a per-tensor
  abs-max scale computed on the fly,
- fake_channel_wise_quantize_dequantize_abs_max: per-output-channel weight
  simulation,
- fake_quantize_dequantize_moving_average_abs_max: activation simulation
  with a running scale (persistable state),
- quantize_linear / dequantize_linear: real int8 storage conversion used
  by the freeze pass and post-training quantization.

TPU-first: the straight-through estimator is not a hand-written grad
kernel — the lowering is `x + stop_gradient(qdq(x) - x)`, so the generic
VJP differentiates it as identity inside the clip range for free, and XLA
folds the whole simulation into neighboring ops.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

QMAX = 127.0


def _qdq(x, scale):
    """quantize->dequantize to the int8 grid at `scale` (abs-max)."""
    s = jnp.maximum(scale, 1e-9)
    q = jnp.clip(jnp.round(x / s * QMAX), -QMAX, QMAX)
    return q * s / QMAX


def _ste(x, scale):
    # straight-through: forward = qdq(x), backward = identity
    return x + jax.lax.stop_gradient(_qdq(x, scale) - x)


@register_op("fake_quantize_dequantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], stateful_out_slots=("OutScale",))
def _fake_qdq_abs_max(ctx, ins, attrs):
    x = ins["X"][0]
    scale = jnp.max(jnp.abs(x))
    return {"Out": [_ste(x, scale)], "OutScale": [scale.reshape(1)]}


@register_op("fake_channel_wise_quantize_dequantize_abs_max", inputs=["X"],
             outputs=["Out", "OutScale"], stateful_out_slots=("OutScale",))
def _fake_qdq_channel(ctx, ins, attrs):
    """Per-output-channel weight simulation; quant_axis selects the channel
    dim (0 for conv filters [O,I,H,W], 1 for fc weights [in, out])."""
    x = ins["X"][0]
    axis = int(attrs.get("quant_axis", 0))
    red = tuple(i for i in range(x.ndim) if i != axis)
    scale = jnp.max(jnp.abs(x), axis=red, keepdims=True)
    return {
        "Out": [_ste(x, scale)],
        "OutScale": [scale.reshape(-1)],
    }


@register_op(
    "fake_quantize_dequantize_moving_average_abs_max",
    inputs=["X", "InScale"],
    outputs=["Out", "OutScale"],
    no_grad_slots=("InScale",),
    stateful_out_slots=("OutScale",),
)
def _fake_qdq_moving_avg(ctx, ins, attrs):
    """Activation simulation with EMA scale state (cf. fake_quantize_op.cc
    moving_average_abs_max): scale' = rho*scale + (1-rho)*absmax(x)."""
    x = ins["X"][0]
    in_scale = ins["InScale"][0]
    rho = float(attrs.get("moving_rate", 0.9))
    is_test = attrs.get("is_test", False) or ctx.is_test
    if is_test:
        scale = in_scale
    else:
        cur = jnp.max(jnp.abs(x)).reshape(1)
        # first step: running scale still zero -> adopt the batch scale
        scale = jnp.where(in_scale > 0, rho * in_scale + (1 - rho) * cur, cur)
    return {"Out": [_ste(x, scale)], "OutScale": [scale]}


@register_op("quantize_linear", inputs=["X", "Scale"], outputs=["Y"],
             grad=None)
def _quantize_linear(ctx, ins, attrs):
    """float -> int8 at the given abs-max scale (freeze / PTQ storage)."""
    x, scale = ins["X"][0], ins["Scale"][0]
    axis = attrs.get("quant_axis", -1)
    if axis >= 0 and scale.size > 1:
        shape = [1] * x.ndim
        shape[axis] = -1
        scale = scale.reshape(shape)
    s = jnp.maximum(scale, 1e-9)
    return {"Y": [jnp.clip(jnp.round(x / s * QMAX), -QMAX, QMAX)
                  .astype(jnp.int8)]}


@register_op("dequantize_linear", inputs=["X", "Scale"], outputs=["Y"],
             no_grad_slots=("Scale",))
def _dequantize_linear(ctx, ins, attrs):
    """int8 -> float: the only op a quantized program needs at run time;
    XLA fuses the multiply into the consuming matmul/conv so the weight is
    read from HBM as int8 (the bandwidth win)."""
    x, scale = ins["X"][0], ins["Scale"][0]
    axis = attrs.get("quant_axis", -1)
    if axis >= 0 and scale.size > 1:
        shape = [1] * x.ndim
        shape[axis] = -1
        scale = scale.reshape(shape)
    return {"Y": [x.astype(jnp.float32) * scale / QMAX]}
