"""Tensor manipulation ops: reshape/transpose/concat/..., fill, cast, compare.

Capability parity: reference `paddle/fluid/operators/` reshape_op.cc,
transpose_op.cc, concat_op.cc, split_op.cc, slice_op.cc, cast_op.cc,
fill_constant_op.cc, gather_op.cc, one_hot_op.cc, compare ops in
controlflow/, assign_op.cc, expand_op.cc, stack_op.cc.
"""

import jax
import jax.numpy as jnp

from ..core.dtypes import to_jnp
from ..core.registry import register_op


@register_op("reshape2", inputs=["X"], outputs=["Out"])
def _reshape(ctx, ins, attrs):
    x = ins["X"][0]
    shape = list(attrs["shape"])
    # paddle semantics: 0 means "copy input dim", -1 inferred
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return {"Out": [x.reshape(shape)]}


register_op("reshape", inputs=["X"], outputs=["Out"])(_reshape)


@register_op("transpose2", inputs=["X"], outputs=["Out"])
def _transpose(ctx, ins, attrs):
    return {"Out": [jnp.transpose(ins["X"][0], attrs["axis"])]}


register_op("transpose", inputs=["X"], outputs=["Out"])(_transpose)


@register_op("flatten2", inputs=["X"], outputs=["Out"])
def _flatten(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 1)
    lead = 1
    for s in x.shape[:axis]:
        lead *= int(s)
    return {"Out": [x.reshape((lead, -1))]}


register_op("flatten", inputs=["X"], outputs=["Out"])(_flatten)


@register_op("flatten_contiguous_range", inputs=["X"], outputs=["Out"])
def _flatten_range(ctx, ins, attrs):
    x = ins["X"][0]
    start = attrs.get("start_axis", 1)
    stop = attrs.get("stop_axis", -1)
    if stop < 0:
        stop += x.ndim
    mid = 1
    for s in x.shape[start : stop + 1]:
        mid *= int(s)
    return {"Out": [x.reshape(x.shape[:start] + (mid,) + x.shape[stop + 1 :])]}


@register_op("squeeze2", inputs=["X"], outputs=["Out"])
def _squeeze(ctx, ins, attrs):
    axes = attrs.get("axes", [])
    x = ins["X"][0]
    if not axes:
        return {"Out": [jnp.squeeze(x)]}
    axes = tuple(a if a >= 0 else a + x.ndim for a in axes)
    return {"Out": [jnp.squeeze(x, axis=axes)]}


register_op("squeeze", inputs=["X"], outputs=["Out"])(_squeeze)


@register_op("unsqueeze2", inputs=["X"], outputs=["Out"])
def _unsqueeze(ctx, ins, attrs):
    x = ins["X"][0]
    for a in sorted(attrs["axes"]):
        x = jnp.expand_dims(x, a)
    return {"Out": [x]}


register_op("unsqueeze", inputs=["X"], outputs=["Out"])(_unsqueeze)


@register_op("concat", inputs=["X"], outputs=["Out"])
def _concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("split", inputs=["X"], outputs=["Out"])
def _split(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    num = attrs.get("num", 0)
    sections = attrs.get("sections", [])
    if sections:
        idx = []
        acc = 0
        for s in sections[:-1]:
            acc += s
            idx.append(acc)
        outs = jnp.split(x, idx, axis=axis)
    else:
        outs = jnp.split(x, num, axis=axis)
    return {"Out": list(outs)}


@register_op("stack", inputs=["X"], outputs=["Y"])
def _stack(ctx, ins, attrs):
    return {"Y": [jnp.stack(ins["X"], axis=attrs.get("axis", 0))]}


@register_op("unstack", inputs=["X"], outputs=["Y"])
def _unstack(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", 0)
    n = x.shape[axis]
    outs = [jnp.squeeze(a, axis) for a in jnp.split(x, n, axis=axis)]
    return {"Y": outs}


@register_op("slice", inputs=["Input"], outputs=["Out"])
def _slice(ctx, ins, attrs):
    x = ins["Input"][0]
    axes = attrs["axes"]
    starts = attrs["starts"]
    ends = attrs["ends"]
    idx = [slice(None)] * x.ndim
    for a, s, e in zip(axes, starts, ends):
        idx[a] = slice(s, e)
    out = x[tuple(idx)]
    for a in sorted(attrs.get("decrease_axis", []), reverse=True):
        out = jnp.squeeze(out, a)
    return {"Out": [out]}


@register_op("strided_slice", inputs=["Input"], outputs=["Out"])
def _strided_slice(ctx, ins, attrs):
    x = ins["Input"][0]
    idx = [slice(None)] * x.ndim
    for a, s, e, st in zip(attrs["axes"], attrs["starts"], attrs["ends"], attrs["strides"]):
        idx[a] = slice(s, e, st)
    return {"Out": [x[tuple(idx)]]}


@register_op("cast", inputs=["X"], outputs=["Out"])
def _cast(ctx, ins, attrs):
    return {"Out": [ins["X"][0].astype(to_jnp(attrs["out_dtype"]))]}


@register_op("assign", inputs=["X"], outputs=["Out"])
def _assign(ctx, ins, attrs):
    return {"Out": [ins["X"][0]]}


@register_op("fill_constant", inputs=[], outputs=["Out"])
def _fill_constant(ctx, ins, attrs):
    shape = tuple(attrs.get("shape", []))
    return {"Out": [jnp.full(shape, attrs["value"], dtype=to_jnp(attrs.get("dtype", "float32")))]}


@register_op("assign_value", inputs=[], outputs=["Out"], grad=None)
def _assign_value(ctx, ins, attrs):
    import numpy as np

    arr = np.array(attrs["values"], dtype=to_jnp(attrs.get("dtype", "float32"))).reshape(
        attrs["shape"]
    )
    return {"Out": [jnp.asarray(arr)]}


@register_op("fill_constant_batch_size_like", inputs=["Input"], outputs=["Out"], grad=None)
def _fill_cbsl(ctx, ins, attrs):
    x = ins["Input"][0]
    shape = list(attrs["shape"])
    in_idx = attrs.get("input_dim_idx", 0)
    out_idx = attrs.get("output_dim_idx", 0)
    shape[out_idx] = x.shape[in_idx]
    return {"Out": [jnp.full(tuple(shape), attrs["value"], dtype=to_jnp(attrs.get("dtype", "float32")))]}


@register_op("fill_zeros_like", inputs=["X"], outputs=["Out"])
def _fill_zeros_like(ctx, ins, attrs):
    return {"Out": [jnp.zeros_like(ins["X"][0])]}


@register_op("fill_any_like", inputs=["X"], outputs=["Out"])
def _fill_any_like(ctx, ins, attrs):
    dtype = attrs.get("dtype")
    out = jnp.full_like(ins["X"][0], attrs["value"], dtype=to_jnp(dtype) if dtype else None)
    return {"Out": [out]}


@register_op("gather", inputs=["X", "Index"], outputs=["Out"], no_grad_slots=("Index",))
def _gather(ctx, ins, attrs):
    return {"Out": [jnp.take(ins["X"][0], ins["Index"][0], axis=attrs.get("axis", 0))]}


@register_op("gather_nd", inputs=["X", "Index"], outputs=["Out"], no_grad_slots=("Index",))
def _gather_nd(ctx, ins, attrs):
    x, index = ins["X"][0], ins["Index"][0]
    return {"Out": [x[tuple(index[..., i] for i in range(index.shape[-1]))]]}


@register_op("scatter", inputs=["X", "Ids", "Updates"], outputs=["Out"], no_grad_slots=("Ids",))
def _scatter(ctx, ins, attrs):
    x, ids, upd = ins["X"][0], ins["Ids"][0], ins["Updates"][0]
    if attrs.get("overwrite", True):
        return {"Out": [x.at[ids].set(upd)]}
    return {"Out": [x.at[ids].add(upd)]}


@register_op("index_select", inputs=["X", "Index"], outputs=["Out"], no_grad_slots=("Index",))
def _index_select(ctx, ins, attrs):
    return {"Out": [jnp.take(ins["X"][0], ins["Index"][0], axis=attrs.get("dim", 0))]}


@register_op("one_hot", inputs=["X"], outputs=["Out"], grad=None)
def _one_hot(ctx, ins, attrs):
    x = ins["X"][0]
    depth = attrs["depth"]
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = jnp.squeeze(x, -1)
    return {"Out": [jax.nn.one_hot(x, depth, dtype=jnp.float32)]}


register_op("one_hot_v2", inputs=["X"], outputs=["Out"], grad=None)(_one_hot)


@register_op("expand", inputs=["X"], outputs=["Out"])
def _expand(ctx, ins, attrs):
    x = ins["X"][0]
    times = attrs["expand_times"]
    return {"Out": [jnp.tile(x, times)]}


@register_op("expand_as", inputs=["X", "Y"], outputs=["Out"], no_grad_slots=("Y",))
def _expand_as(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.broadcast_to(x, y.shape)]}


@register_op("tile", inputs=["X"], outputs=["Out"])
def _tile(ctx, ins, attrs):
    return {"Out": [jnp.tile(ins["X"][0], attrs["repeat_times"])]}


@register_op("pad", inputs=["X"], outputs=["Out"])
def _pad(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs["paddings"]
    pairs = [(p[2 * i], p[2 * i + 1]) for i in range(x.ndim)]
    return {"Out": [jnp.pad(x, pairs, constant_values=attrs.get("pad_value", 0.0))]}


@register_op("arange", inputs=[], outputs=["Out"], grad=None)
def _arange(ctx, ins, attrs):
    return {
        "Out": [
            jnp.arange(
                attrs["start"], attrs["end"], attrs.get("step", 1),
                dtype=to_jnp(attrs.get("dtype", "int64")),
            )
        ]
    }


@register_op("linspace", inputs=[], outputs=["Out"], grad=None)
def _linspace(ctx, ins, attrs):
    return {
        "Out": [
            jnp.linspace(
                attrs["start"], attrs["stop"], attrs["num"],
                dtype=to_jnp(attrs.get("dtype", "float32")),
            )
        ]
    }


# -- comparison / logical (cf. operators/controlflow/compare_op.cc) ----------

def _register_compare(name, fn):
    @register_op(name, inputs=["X", "Y"], outputs=["Out"], grad=None)
    def _lower(ctx, ins, attrs, fn=fn):
        return {"Out": [fn(ins["X"][0], ins["Y"][0])]}


_register_compare("equal", jnp.equal)
_register_compare("not_equal", jnp.not_equal)
_register_compare("less_than", jnp.less)
_register_compare("less_equal", jnp.less_equal)
_register_compare("greater_than", jnp.greater)
_register_compare("greater_equal", jnp.greater_equal)
_register_compare("logical_and", jnp.logical_and)
_register_compare("logical_or", jnp.logical_or)
_register_compare("logical_xor", jnp.logical_xor)


@register_op("logical_not", inputs=["X"], outputs=["Out"], grad=None)
def _logical_not(ctx, ins, attrs):
    return {"Out": [jnp.logical_not(ins["X"][0])]}


@register_op("isfinite", inputs=["X"], outputs=["Out"], grad=None)
def _isfinite(ctx, ins, attrs):
    return {"Out": [jnp.all(jnp.isfinite(ins["X"][0]))]}


@register_op("isnan", inputs=["X"], outputs=["Out"], grad=None)
def _isnan(ctx, ins, attrs):
    return {"Out": [jnp.isnan(ins["X"][0])]}


@register_op("where", inputs=["Condition", "X", "Y"], outputs=["Out"], no_grad_slots=("Condition",))
def _where(ctx, ins, attrs):
    return {"Out": [jnp.where(ins["Condition"][0], ins["X"][0], ins["Y"][0])]}


@register_op("shape", inputs=["Input"], outputs=["Out"], grad=None)
def _shape(ctx, ins, attrs):
    return {"Out": [jnp.array(ins["Input"][0].shape, dtype=jnp.int32)]}


@register_op("triu", inputs=["X"], outputs=["Out"])
def _triu(ctx, ins, attrs):
    return {"Out": [jnp.triu(ins["X"][0], k=attrs.get("diagonal", 0))]}


@register_op("tril", inputs=["X"], outputs=["Out"])
def _tril(ctx, ins, attrs):
    return {"Out": [jnp.tril(ins["X"][0], k=attrs.get("diagonal", 0))]}


@register_op("cumsum", inputs=["X"], outputs=["Out"])
def _cumsum(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    out = jnp.cumsum(x, axis=axis)
    if attrs.get("reverse", False):
        out = jnp.flip(jnp.cumsum(jnp.flip(x, axis), axis=axis), axis)
    if attrs.get("exclusive", False):
        out = out - x
    return {"Out": [out]}


@register_op("increment", inputs=["X"], outputs=["Out"], grad=None)
def _increment(ctx, ins, attrs):
    x = ins["X"][0]
    # preserve x's dtype: int counters must not be promoted to float
    return {"Out": [x + jnp.asarray(attrs.get("step", 1.0), x.dtype)]}


@register_op("print", inputs=["In"], outputs=["Out"])
def _print(ctx, ins, attrs):
    """Periodic fetch printer (cf. reference operators/print_op.cc /
    layers.Print): passes X through and prints message + summarized values
    from inside the compiled program via jax.debug.print (the TPU-safe
    analogue of the reference's host-side tensor printer)."""
    import jax

    x = ins["In"][0]
    message = str(attrs.get("message", ""))
    summarize = int(attrs.get("summarize", 20))
    show_shape = bool(attrs.get("print_tensor_shape", True))
    shape = tuple(x.shape)
    flat = x.reshape(-1)
    head = flat[: summarize if summarize > 0 else flat.shape[0]]

    from ..core.block_eval import _warn_no_callbacks, host_callbacks_supported

    if not host_callbacks_supported():
        _warn_no_callbacks("layers.Print")
        return {"Out": [x]}

    # host callback, NOT jax.debug.print: the user message is arbitrary
    # text (its braces must not reach a format-string parser)
    def _emit(v):
        import numpy as _np

        if show_shape:
            print("%s shape=%s values=%s" % (message, shape, _np.asarray(v)),
                  flush=True)
        else:
            print("%s %s" % (message, _np.asarray(v)), flush=True)

    jax.debug.callback(_emit, head)
    return {"Out": [x]}


# -- tensor array (LoDTensorArray capability, static-shape redesign) ---------
# Capability parity: reference LoDTensorArray + controlflow
# `write_to_array`/`read_from_array` ops (`operators/controlflow/
# lod_array_ops` family, `lod_tensor_array.h`).  TPU-first: the array is a
# PREALLOCATED [capacity, ...] dense tensor (XLA has no growable storage);
# writes are dynamic_update_slice, reads dynamic_slice — both work with a
# runtime index inside while_loop bodies.


@register_op("tensor_array_write", inputs=["Array", "I", "X"],
             outputs=["Out"], no_grad_slots=("I",))
def _tensor_array_write(ctx, ins, attrs):
    arr, i, x = ins["Array"][0], ins["I"][0], ins["X"][0]
    import jax

    idx = i.reshape(()).astype(jnp.int32)
    out = jax.lax.dynamic_update_slice(
        arr, x[None].astype(arr.dtype),
        (idx,) + (jnp.int32(0),) * (arr.ndim - 1),
    )
    # a write past capacity-1 is CLAMPED (dynamic_update_slice semantics)
    # where the reference grows the array; under FLAGS_check_nan_inf
    # poison the overflowing write so the divergence is detectable instead
    # of silently corrupting the last slot
    from ..flags import get_flags

    if (get_flags(["FLAGS_check_nan_inf"])["FLAGS_check_nan_inf"]
            and jnp.issubdtype(out.dtype, jnp.floating)):
        out = jnp.where(idx < arr.shape[0], out,
                        jnp.full_like(out, jnp.nan))
    return {"Out": [out]}


@register_op("tensor_array_read", inputs=["Array", "I"], outputs=["Out"],
             no_grad_slots=("I",))
def _tensor_array_read(ctx, ins, attrs):
    arr, i = ins["Array"][0], ins["I"][0]
    import jax

    idx = i.reshape(()).astype(jnp.int32)
    out = jax.lax.dynamic_slice(
        arr, (idx,) + (jnp.int32(0),) * (arr.ndim - 1),
        (1,) + arr.shape[1:],
    )
    return {"Out": [out[0]]}


# ---------------------------------------------------------------------------
# tensor/loss breadth tail (reference crop_tensor_op.cc, unbind_op.cc,
# size_op.cc, gather_tree_op.cc, partial_sum/concat, center_loss_op.cc,
# teacher_student_sigmoid_loss_op.cc, fsp_op.cc,
# squared_l2_distance_op.cc)
# ---------------------------------------------------------------------------


@register_op("crop_tensor", inputs=["X"], outputs=["Out"])
def _crop_tensor(ctx, ins, attrs):
    x = ins["X"][0]
    offsets = attrs.get("offsets") or [0] * x.ndim
    shape = attrs["shape"]
    shape = [x.shape[i] - offsets[i] if s in (-1, 0) else s
             for i, s in enumerate(shape)]
    import jax

    return {"Out": [jax.lax.dynamic_slice(x, tuple(offsets), tuple(shape))]}


@register_op("unbind", inputs=["X"], outputs=["Out"], grad=None)
def _unbind(ctx, ins, attrs):
    x = ins["X"][0]
    axis = int(attrs.get("axis", 0))
    n = x.shape[axis]
    return {"Out": [jnp.squeeze(s, axis)
                    for s in jnp.split(x, n, axis=axis)]}


@register_op("size", inputs=["Input"], outputs=["Out"], grad=None)
def _size(ctx, ins, attrs):
    import numpy as _np

    return {"Out": [jnp.asarray(int(_np.prod(ins["Input"][0].shape)),
                                jnp.int64)]}


@register_op("gather_tree", inputs=["Ids", "Parents"], outputs=["Out"],
             grad=None)
def _gather_tree(ctx, ins, attrs):
    """cf. gather_tree_op.cc (beam search backtrace): walk parents from
    the last step to recover full beams."""
    import jax

    ids, parents = ins["Ids"][0], ins["Parents"][0]  # [T, B, W]
    T = ids.shape[0]
    beams = jnp.arange(ids.shape[2])[None, :].repeat(ids.shape[1], 0)

    def step(beam, t):
        out = jnp.take_along_axis(ids[t], beam, axis=1)
        prev = jnp.take_along_axis(parents[t], beam, axis=1)
        return prev, out

    _, outs = jax.lax.scan(step, beams, jnp.arange(T - 1, -1, -1))
    return {"Out": [outs[::-1]]}


@register_op("masked_fill", inputs=["X", "Mask"], outputs=["Out"],
             no_grad_slots=("Mask",))
def _masked_fill(ctx, ins, attrs):
    x, m = ins["X"][0], ins["Mask"][0]
    return {"Out": [jnp.where(m.astype(bool), jnp.asarray(
        attrs.get("value", 0.0), x.dtype), x)]}


def _partial_cols(ins, attrs):
    """Column windows for partial_sum/partial_concat.  length < 0 means
    'to the end'; a NEGATIVE start whose window reaches the axis end
    also slices to the end (python end=0 would mean position 0)."""
    start = int(attrs.get("start_index", 0))
    length = int(attrs.get("length", -1))
    parts = []
    for x in ins["X"]:
        if length < 0 or (start < 0 and start + length >= 0):
            end = x.shape[1]
        else:
            end = start + length
        parts.append(x[:, start:end])
    return parts


@register_op("partial_sum", inputs=["X"], outputs=["Out"])
def _partial_sum(ctx, ins, attrs):
    return {"Out": [sum(_partial_cols(ins, attrs))]}


@register_op("partial_concat", inputs=["X"], outputs=["Out"])
def _partial_concat(ctx, ins, attrs):
    return {"Out": [jnp.concatenate(_partial_cols(ins, attrs), axis=1)]}


@register_op("center_loss",
             inputs=["X", "Label", "Centers", "CenterUpdateRate"],
             outputs=["Loss", "SampleCenterDiff", "CentersOut"],
             no_grad_slots=("Label", "Centers", "CenterUpdateRate"),
             stateful_out_slots=("CentersOut",))
def _center_loss(ctx, ins, attrs):
    """cf. center_loss_op.cc: pull features toward running class centers;
    centers update by the mean diff of their batch members."""
    x = ins["X"][0]                     # [N, D]
    label = ins["Label"][0].reshape(-1)
    centers = ins["Centers"][0]         # [C, D]
    alpha = ins["CenterUpdateRate"][0].reshape(-1)[0]
    diff = x - centers[label]
    loss = 0.5 * jnp.sum(diff * diff, axis=1, keepdims=True)
    if attrs.get("need_update", True):
        cnt = jnp.zeros((centers.shape[0],), jnp.float32).at[label].add(1.0)
        upd = jnp.zeros_like(centers).at[label].add(diff)
        centers = centers + alpha * upd / (cnt[:, None] + 1.0)
    return {"Loss": [loss], "SampleCenterDiff": [diff],
            "CentersOut": [centers]}


@register_op("dice_loss", inputs=["X", "Label"], outputs=["Out"],
             no_grad_slots=("Label",))
def _dice_loss(ctx, ins, attrs):
    """cf. layers/loss dice_loss: 1 - 2|X∩L| / (|X|+|L|) per batch row."""
    x = ins["X"][0]
    label = ins["Label"][0].astype(x.dtype)
    eps = float(attrs.get("epsilon", 1e-5))
    red = tuple(range(1, x.ndim))
    inter = jnp.sum(x * label, axis=red)
    union = jnp.sum(x, axis=red) + jnp.sum(label, axis=red)
    return {"Out": [1.0 - (2 * inter + eps) / (union + eps)]}


@register_op("teacher_student_sigmoid_loss", inputs=["X", "Label"],
             outputs=["Y"], no_grad_slots=("Label",))
def _ts_sigmoid_loss(ctx, ins, attrs):
    """cf. teacher_student_sigmoid_loss_op.cc (CTR distillation)."""
    x = ins["X"][0].reshape(-1)
    label = ins["Label"][0].reshape(-1)
    soft_max_up = float(attrs.get("soft_max_up_bound", 15.0))
    soft_max_lo = float(attrs.get("soft_max_lower_bound", -15.0))
    xc = jnp.clip(x, soft_max_lo, soft_max_up)
    # teacher part (label in (0,1)): sigmoid CE against the soft label;
    # student part (label 0/1): plain logistic loss
    ce = jnp.maximum(xc, 0) - xc * label + jnp.log1p(jnp.exp(-jnp.abs(xc)))
    return {"Y": [ce[:, None]]}


@register_op("npair_loss", inputs=["Anchor", "Positive", "Labels"],
             outputs=["Out"], no_grad_slots=("Labels",))
def _npair_loss(ctx, ins, attrs):
    """cf. layers npair_loss: cross-entropy over anchor-positive
    similarities + L2 reg."""
    import jax

    a = ins["Anchor"][0]
    p = ins["Positive"][0]
    labels = ins["Labels"][0].reshape(-1)
    l2 = float(attrs.get("l2_reg", 0.002))
    sim = a @ p.T                       # [N, N]
    t = (labels[:, None] == labels[None, :]).astype(a.dtype)
    t = t / jnp.sum(t, axis=1, keepdims=True)
    xe = -jnp.sum(t * jax.nn.log_softmax(sim, axis=1), axis=1)
    reg = l2 * (jnp.sum(a * a) + jnp.sum(p * p)) / a.shape[0]
    return {"Out": [jnp.mean(xe) + reg]}


@register_op("fsp", inputs=["X", "Y"], outputs=["Out"])
def _fsp(ctx, ins, attrs):
    """cf. fsp_op.cc (distillation flow matrix): per-sample normalized
    Gram matrix between two feature maps."""
    x, y = ins["X"][0], ins["Y"][0]     # [N, C1, H, W], [N, C2, H, W]
    n, c1, h, w = x.shape
    c2 = y.shape[1]
    xf = x.reshape(n, c1, h * w)
    yf = y.reshape(n, c2, h * w)
    return {"Out": [jnp.einsum("nch,ndh->ncd", xf, yf) / (h * w)]}


@register_op("squared_l2_distance", inputs=["X", "Y"],
             outputs=["Out", "sub_result"])
def _squared_l2_distance(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    sub = x - y
    red = tuple(range(1, sub.ndim))
    return {"Out": [jnp.sum(sub * sub, axis=red, keepdims=False)[:, None]],
            "sub_result": [sub]}


@register_op("take", inputs=["X", "Index"], outputs=["Out"],
             no_grad_slots=("Index",))
def _take(ctx, ins, attrs):
    """cf. take (2.x): flat-index gather with clip/wrap modes."""
    x, idx = ins["X"][0].reshape(-1), ins["Index"][0]
    mode = attrs.get("mode", "raise")
    n = x.shape[0]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    else:  # raise / clip both clamp under jit (no host asserts)
        idx = jnp.clip(idx, -n, n - 1)
    return {"Out": [x[idx.astype(jnp.int32)]]}


@register_op("index_add", inputs=["X", "Index", "AddValue"],
             outputs=["Out"], no_grad_slots=("Index",))
def _index_add(ctx, ins, attrs):
    axis = int(attrs.get("axis", 0))
    x, idx, v = ins["X"][0], ins["Index"][0], ins["AddValue"][0]
    x = jnp.moveaxis(x, axis, 0)
    v = jnp.moveaxis(v, axis, 0)
    out = x.at[idx.astype(jnp.int32)].add(v)
    return {"Out": [jnp.moveaxis(out, 0, axis)]}


@register_op("index_put", inputs=["X", "Index", "Value"],
             outputs=["Out"], no_grad_slots=("Index",))
def _index_put(ctx, ins, attrs):
    x, v = ins["X"][0], ins["Value"][0]
    idx = tuple(i.astype(jnp.int32) for i in ins["Index"])
    if attrs.get("accumulate", False):
        return {"Out": [x.at[idx].add(v)]}
    return {"Out": [x.at[idx].set(v)]}


@register_op("fill_diagonal", inputs=["X"], outputs=["Out"], grad=None)
def _fill_diagonal(ctx, ins, attrs):
    x = ins["X"][0]
    v = attrs.get("value", 0.0)
    n = min(x.shape[-2], x.shape[-1])
    i = jnp.arange(n)
    return {"Out": [x.at[..., i, i].set(jnp.asarray(v, x.dtype))]}


@register_op("diagonal", inputs=["Input"], outputs=["Out"])
def _diagonal(ctx, ins, attrs):
    return {"Out": [jnp.diagonal(
        ins["Input"][0], offset=int(attrs.get("offset", 0)),
        axis1=int(attrs.get("axis1", 0)),
        axis2=int(attrs.get("axis2", 1)))]}


@register_op("rot90", inputs=["X"], outputs=["Out"])
def _rot90(ctx, ins, attrs):
    axes = attrs.get("axes", [0, 1])
    return {"Out": [jnp.rot90(ins["X"][0], k=int(attrs.get("k", 1)),
                              axes=tuple(axes))]}


@register_op("pad_constant_like", inputs=["X", "Y"], outputs=["Out"],
             no_grad_slots=("X",))
def _pad_constant_like(ctx, ins, attrs):
    """cf. pad_constant_like_op.cc: pad Y up to X's shape."""
    x, y = ins["X"][0], ins["Y"][0]
    cfg = tuple((0, int(a) - int(b)) for a, b in zip(x.shape, y.shape))
    return {"Out": [jnp.pad(y, cfg, constant_values=float(
        attrs.get("pad_value", 0.0)))]}


@register_op("shuffle_batch", inputs=["X"], outputs=["Out", "ShuffleIdx"],
             needs_rng=True, grad=None)
def _shuffle_batch(ctx, ins, attrs):
    """cf. shuffle_batch_op.cc: random permutation of dim-0 rows."""
    import jax

    x = ins["X"][0]
    perm = jax.random.permutation(ctx.rng(), x.shape[0])
    return {"Out": [x[perm]], "ShuffleIdx": [perm.astype(jnp.int64)]}


@register_op("sampling_id", inputs=["X"], outputs=["Out"],
             needs_rng=True, grad=None)
def _sampling_id(ctx, ins, attrs):
    """cf. sampling_id_op.cc: sample one category per row of a
    probability matrix."""
    import jax

    p = ins["X"][0]
    ids = jax.random.categorical(ctx.rng(), jnp.log(p + 1e-20), axis=-1)
    return {"Out": [ids.astype(jnp.int64)]}


@register_op("uniform_random_batch_size_like", inputs=["Input"],
             outputs=["Out"], needs_rng=True, grad=None)
def _uniform_random_bsl(ctx, ins, attrs):
    import jax

    from ..core.dtypes import to_jnp

    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[int(attrs.get("output_dim_idx", 0))] = x.shape[
        int(attrs.get("input_dim_idx", 0))]
    from .random_ops import step_seeded_key

    return {"Out": [jax.random.uniform(
        step_seeded_key(ctx, attrs), tuple(shape),
        dtype=to_jnp(attrs.get("dtype", "float32")),
        minval=float(attrs.get("min", -1.0)),
        maxval=float(attrs.get("max", 1.0)))]}


@register_op("batch_fc", inputs=["Input", "W", "Bias"], outputs=["Out"])
def _batch_fc(ctx, ins, attrs):
    """cf. batch_fc_op.cc: per-slot fc — [S, B, I] x [S, I, O] + [S, 1, O]."""
    x, w = ins["Input"][0], ins["W"][0]
    out = jnp.einsum("sbi,sio->sbo", x, w)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register_op("expand_v2", inputs=["X"], outputs=["Out"])
def _expand_v2(ctx, ins, attrs):
    """cf. expand_v2_op.cc: broadcast to `shape`; -1 keeps the input dim
    (input aligned to the right of shape)."""
    x = ins["X"][0]
    shape = [int(s) for s in attrs["shape"]]
    in_shape = (1,) * (len(shape) - x.ndim) + x.shape
    target = tuple(
        int(i) if s == -1 else s for s, i in zip(shape, in_shape))
    return {"Out": [jnp.broadcast_to(x, target)]}
