"""Op-tail batch 2: NN / detection / RNN ops (round-4 audit list).

deformable_conv is a gather+bilinear-sample composition (the reference's
hand CUDA im2col-with-offsets, deformable_conv_op.cu, becomes XLA gathers
that fuse); pooling-with-index ops stack strided window slices and argmax
over the window axis (static shapes, no select-and-scatter).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import get_op_def, register_op
from .nn_ops import _pair


# ---------------------------------------------------------------------------
# convolution family
# ---------------------------------------------------------------------------


@register_op("conv3d_transpose", inputs=["Input", "Filter"],
             outputs=["Output"])
def _conv3d_transpose(ctx, ins, attrs):
    """cf. conv_transpose_op.cc (3-D): NCDHW, filter [Cin, Cout/g, kd,
    kh, kw]; fractionally-strided conv like the 2-D op."""
    x, w = ins["Input"][0], ins["Filter"][0]
    if x.dtype != w.dtype and jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(w.dtype)
    strides = attrs.get("strides", [1, 1, 1])
    pads = attrs.get("paddings", [0, 0, 0])
    dils = attrs.get("dilations", [1, 1, 1])
    strides = tuple(int(s) for s in strides)
    pads = tuple(int(p) for p in pads)
    dils = tuple(int(d) for d in dils)
    groups = int(attrs.get("groups", 1))
    ks = tuple(int(s) for s in w.shape[2:])
    cin = int(w.shape[0])
    wg = w.reshape((groups, cin // groups) + tuple(w.shape[1:]))
    wg = jnp.flip(jnp.swapaxes(wg, 1, 2), axis=(3, 4, 5))
    w_t = wg.reshape((groups * int(w.shape[1]), cin // groups) + ks)
    padding = [(dils[i] * (ks[i] - 1) - pads[i],
                dils[i] * (ks[i] - 1) - pads[i]) for i in range(3)]
    out = jax.lax.conv_general_dilated(
        x, w_t, window_strides=(1, 1, 1), padding=padding,
        lhs_dilation=strides, rhs_dilation=dils,
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    return {"Output": [out]}


def _bilinear_sample_nchw(img, y, x):
    """img [C, H, W]; y/x arbitrary same-shaped float coords -> [C, ...].
    Out-of-range samples are 0 (deformable_conv border semantics)."""
    H, W = img.shape[1], img.shape[2]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    wy1, wx1 = y - y0, x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def tap(yy, xx, wgt):
        inb = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
        yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        v = img[:, yc, xc]                       # [C, ...]
        return v * (wgt * inb.astype(img.dtype))[None]

    return (tap(y0, x0, wy0 * wx0) + tap(y0, x0 + 1, wy0 * wx1)
            + tap(y0 + 1, x0, wy1 * wx0) + tap(y0 + 1, x0 + 1, wy1 * wx1))


def _deformable_conv_impl(ctx, ins, attrs, with_mask):
    x, offset, w = ins["Input"][0], ins["Offset"][0], ins["Filter"][0]
    mask = ins["Mask"][0] if (with_mask and ins.get("Mask")) else None
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dils = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    dg = int(attrs.get("deformable_groups", 1))
    B, C, H, W = x.shape
    Cout, Cg, kh, kw = w.shape
    Ho = (H + 2 * pads[0] - (dils[0] * (kh - 1) + 1)) // strides[0] + 1
    Wo = (W + 2 * pads[1] - (dils[1] * (kw - 1) + 1)) // strides[1] + 1
    off = offset.reshape(B, dg, kh * kw, 2, Ho, Wo)
    if mask is not None:
        msk = mask.reshape(B, dg, kh * kw, Ho, Wo)

    oy = jnp.arange(Ho) * strides[0] - pads[0]
    ox = jnp.arange(Wo) * strides[1] - pads[1]

    def one_image(img, off_b, msk_b):
        # img [C,H,W]; off_b [dg, k*k, 2, Ho, Wo]
        cols = []
        for t in range(kh * kw):
            ky, kx = t // kw, t % kw
            ys = oy[:, None] + ky * dils[0] + off_b[:, t, 0]   # [dg,Ho,Wo]
            xs = ox[None, :] + kx * dils[1] + off_b[:, t, 1]
            per_dg = []
            cpg = C // dg
            for d in range(dg):
                v = _bilinear_sample_nchw(
                    img[d * cpg:(d + 1) * cpg], ys[d], xs[d])
                if msk_b is not None:
                    v = v * msk_b[d, t][None]
                per_dg.append(v)
            cols.append(jnp.concatenate(per_dg, axis=0))  # [C,Ho,Wo]
        return jnp.stack(cols, axis=1)           # [C, k*k, Ho, Wo]

    if mask is not None:
        patches = jax.vmap(one_image)(x, off, msk)
    else:
        patches = jax.vmap(
            lambda img, off_b: one_image(img, off_b, None))(x, off)
    # grouped contraction: w [Cout, C/g, kh*kw]
    wf = w.reshape(Cout, Cg, kh * kw)
    cpg_o = Cout // groups
    cpg_i = C // groups
    outs = []
    for g in range(groups):
        pg = patches[:, g * cpg_i:(g + 1) * cpg_i]
        wg = wf[g * cpg_o:(g + 1) * cpg_o]
        outs.append(jnp.einsum("bckhw,ock->bohw", pg, wg))
    return {"Output": [jnp.concatenate(outs, axis=1)]}


@register_op("deformable_conv", inputs=["Input", "Offset", "Mask", "Filter"],
             outputs=["Output"])
def _deformable_conv(ctx, ins, attrs):
    """cf. deformable_conv_op.cc (v2: modulated, with Mask)."""
    return _deformable_conv_impl(ctx, ins, attrs, with_mask=True)


@register_op("deformable_conv_v1", inputs=["Input", "Offset", "Filter"],
             outputs=["Output"])
def _deformable_conv_v1(ctx, ins, attrs):
    """cf. deformable_conv_v1_op.cc (no modulation mask)."""
    return _deformable_conv_impl(ctx, ins, attrs, with_mask=False)


# ---------------------------------------------------------------------------
# pooling with indices / unpool / crop / space_to_depth
# ---------------------------------------------------------------------------


def _window_slices(x, ksize, strides, pads, spatial_start):
    """Stack k-window strided slices -> [.., prod(k), Ho..]; also return
    the GLOBAL flat index each slice position corresponds to."""
    nd = len(ksize)
    pad_cfg = [(0, 0)] * spatial_start + [(pads[i], pads[i] + ksize[i])
                                          for i in range(nd)]
    neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) \
        else jnp.iinfo(x.dtype).min
    xp = jnp.pad(x, pad_cfg, constant_values=neg)
    in_sp = x.shape[spatial_start:]
    out_sp = [(in_sp[i] + 2 * pads[i] - ksize[i]) // strides[i] + 1
              for i in range(nd)]
    slices, gidx = [], []
    import itertools

    for taps in itertools.product(*[range(k) for k in ksize]):
        sl = [slice(None)] * spatial_start
        for i in range(nd):
            sl.append(slice(taps[i], taps[i] + out_sp[i] * strides[i],
                            strides[i]))
        slices.append(xp[tuple(sl)])
        # global index of this tap at each output position
        coords = []
        for i in range(nd):
            c = jnp.arange(out_sp[i]) * strides[i] + taps[i] - pads[i]
            coords.append(c)
        flat = jnp.zeros(tuple(out_sp), jnp.int32)
        mul = 1
        for i in range(nd - 1, -1, -1):
            shape = [1] * nd
            shape[i] = out_sp[i]
            flat = flat + coords[i].reshape(shape).astype(jnp.int32) * mul
            mul *= in_sp[i]
        gidx.append(flat)
    return jnp.stack(slices, axis=spatial_start), jnp.stack(gidx, 0), out_sp


@register_op("max_pool2d_with_index", inputs=["X"], outputs=["Out", "Mask"],
             no_grad_slots=())
def _max_pool2d_with_index(ctx, ins, attrs):
    """cf. pool_with_index_op.cc: max pool emitting the flat in-plane
    index of each max (consumed by unpool / the exact backward)."""
    x = ins["X"][0]
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", ksize))
    pads = _pair(attrs.get("paddings", [0, 0]))
    if attrs.get("global_pooling", False):
        ksize = (x.shape[2], x.shape[3])
        strides = ksize
        pads = (0, 0)
    stacked, gidx, out_sp = _window_slices(x, ksize, strides, pads, 2)
    am = jnp.argmax(stacked, axis=2)             # [B, C, Ho, Wo]
    out = jnp.max(stacked, axis=2)
    mask = jnp.take_along_axis(
        gidx[None, None], am[:, :, None], axis=2)[:, :, 0]
    return {"Out": [out.astype(x.dtype)], "Mask": [mask.astype(jnp.int32)]}


@register_op("max_pool3d_with_index", inputs=["X"], outputs=["Out", "Mask"])
def _max_pool3d_with_index(ctx, ins, attrs):
    """cf. pool_with_index_op.cc (3-D NCDHW)."""
    x = ins["X"][0]
    k = attrs.get("ksize", [2, 2, 2])
    ksize = tuple(int(v) for v in k)
    strides = tuple(int(v) for v in attrs.get("strides", ksize))
    pads = tuple(int(v) for v in attrs.get("paddings", [0, 0, 0]))
    if attrs.get("global_pooling", False):
        ksize = tuple(x.shape[2:])
        strides = ksize
        pads = (0, 0, 0)
    stacked, gidx, out_sp = _window_slices(x, ksize, strides, pads, 2)
    am = jnp.argmax(stacked, axis=2)
    out = jnp.max(stacked, axis=2)
    mask = jnp.take_along_axis(
        gidx[None, None], am[:, :, None], axis=2)[:, :, 0]
    return {"Out": [out.astype(x.dtype)], "Mask": [mask.astype(jnp.int32)]}


@register_op("unpool", inputs=["X", "Indices"], outputs=["Out"],
             no_grad_slots=("Indices",))
def _unpool(ctx, ins, attrs):
    """cf. unpool_op.cc: scatter pooled values back to their recorded max
    positions (indices are flat in-plane, matching
    max_pool2d_with_index).  Scatter mode is .set (overwrite), not .add:
    with overlapping windows (stride < ksize) two pooled cells can record
    the same source index; the reference writes the value once, and since
    duplicated indices carry the identical source value, overwrite is
    exact where summing would double it."""
    x, idx = ins["X"][0], ins["Indices"][0]
    B, C, Hi, Wi = x.shape
    Ho, Wo = (int(s) for s in attrs["unpooled_shape"])

    def plane(v, i):
        return jnp.zeros((Ho * Wo,), v.dtype).at[i.reshape(-1)].set(
            v.reshape(-1)).reshape(Ho, Wo)

    out = jax.vmap(jax.vmap(plane))(x, idx.astype(jnp.int32))
    return {"Out": [out]}


@register_op("crop", inputs=["X", "Y", "Offsets"], outputs=["Out"],
             no_grad_slots=("Y", "Offsets"))
def _crop(ctx, ins, attrs):
    """cf. crop_op.cc: static slice at `offsets` with `shape` (attr or the
    shape of Y)."""
    import numpy as np

    x = ins["X"][0]
    if ins.get("Y"):
        shape = ins["Y"][0].shape
    else:
        shape = tuple(int(s) for s in attrs["shape"])
    if ins.get("Offsets"):
        off = jax.core.concrete_or_error(
            None, ins["Offsets"][0],
            "crop Offsets must be graph-time constants under XLA")
        off = tuple(int(v) for v in np.asarray(off))
    else:
        off = tuple(int(v) for v in attrs.get("offsets", [0] * x.ndim))
    sl = tuple(slice(off[i], off[i] + shape[i]) for i in range(x.ndim))
    return {"Out": [x[sl]]}


@register_op("space_to_depth", inputs=["X"], outputs=["Out"])
def _space_to_depth(ctx, ins, attrs):
    """cf. space_to_depth_op.cc: NCHW blocksize rearrange."""
    x = ins["X"][0]
    bs = int(attrs.get("blocksize", 2))
    B, C, H, W = x.shape
    x = x.reshape(B, C, H // bs, bs, W // bs, bs)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": [x.reshape(B, C * bs * bs, H // bs, W // bs)]}


# ---------------------------------------------------------------------------
# sampled / hierarchical losses, RNN variant
# ---------------------------------------------------------------------------


@register_op("nce", inputs=["Input", "Label", "Weight", "Bias",
                            "SampleWeight"],
             outputs=["Cost", "SampleLogits", "SampleLabels"],
             needs_rng=True, no_grad_slots=("Label", "SampleWeight"))
def _nce(ctx, ins, attrs):
    """cf. nce_op.cc: noise-contrastive estimation with a uniform negative
    sampler (sampler attr 0; custom_dist falls back to uniform,
    documented)."""
    x, label, w = ins["Input"][0], ins["Label"][0], ins["Weight"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    num_neg = int(attrs.get("num_neg_samples", 10))
    total = int(attrs.get("num_total_classes", w.shape[0]))
    B = x.shape[0]
    nt = label.shape[1] if label.ndim > 1 else 1
    lab = label.reshape(B, nt).astype(jnp.int32)
    negs = jax.random.randint(ctx.rng(), (B, num_neg), 0, total)
    samples = jnp.concatenate([lab, negs], axis=1)       # [B, nt+S]
    logits = jnp.einsum("bd,bsd->bs", x, w[samples])
    if bias is not None:
        logits = logits + bias[samples]
    labels_out = jnp.concatenate(
        [jnp.ones((B, nt), jnp.int32), jnp.zeros((B, num_neg), jnp.int32)],
        axis=1)
    # NCE posterior (cf. nce_op.h): the classifier scores
    # logit' = logit - log(k * q) with uniform noise q = 1/total;
    # -log sigmoid(logit') for positives, -log(1 - sigmoid) for negatives
    q = 1.0 / total
    logits_adj = logits - jnp.log(num_neg * q)
    lse = jnp.logaddexp(0.0, logits_adj)         # log(1 + e^l')
    logp_model = logits_adj - lse                # log sigmoid
    logp_noise = -lse                            # log(1 - sigmoid)
    cost = -(jnp.sum(logp_model[:, :nt], axis=1)
             + jnp.sum(logp_noise[:, nt:], axis=1))
    if ins.get("SampleWeight"):
        cost = cost * ins["SampleWeight"][0].reshape(-1)
    return {"Cost": [cost[:, None]],
            "SampleLogits": [logits], "SampleLabels": [samples]}


@register_op("hierarchical_sigmoid",
             inputs=["X", "Label", "W", "Bias", "PathTable", "PathCode"],
             outputs=["Out", "PreOut"],
             no_grad_slots=("Label", "PathTable", "PathCode"))
def _hierarchical_sigmoid(ctx, ins, attrs):
    """cf. hierarchical_sigmoid_op.cc: default complete binary tree over
    num_classes (heap indexing, matching MatrixBitCodeFunctor), or a
    custom tree via PathTable/PathCode."""
    x, label, w = ins["X"][0], ins["Label"][0], ins["W"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    B = x.shape[0]
    lab = label.reshape(-1).astype(jnp.int32)
    if ins.get("PathTable"):
        table = ins["PathTable"][0].astype(jnp.int32)    # [B, L]
        code = ins["PathCode"][0].astype(jnp.float32)    # [B, L]
        valid = (table >= 0).astype(jnp.float32)
        idx = jnp.maximum(table, 0)
    else:
        num_classes = int(attrs["num_classes"])
        L = max(1, int(jnp.ceil(jnp.log2(num_classes))))
        # heap code of (label + num_classes): bits below the leading one
        node = lab + num_classes
        bits = []
        parents = []
        for d in range(L):
            bits.append(node % 2)
            node = node // 2
            parents.append(node)
        # path from just-below-root down: reference walks calc_index =
        # parent - 1 per level while parent > 1
        idx_l, code_l, valid_l = [], [], []
        for d in range(L - 1, -1, -1):
            p = parents[d]
            valid_l.append((p >= 1).astype(jnp.float32))
            idx_l.append(jnp.maximum(p - 1, 0))
            code_l.append(bits[d].astype(jnp.float32))
        idx = jnp.stack(idx_l, axis=1)
        code = jnp.stack(code_l, axis=1)
        valid = jnp.stack(valid_l, axis=1)
    pre = jnp.einsum("bd,bld->bl", x, w[idx])
    if bias is not None:
        pre = pre + bias.reshape(-1)[idx]
    # per-node sigmoid CE toward the path code bit
    ce = jnp.logaddexp(0.0, pre) - code * pre
    out = jnp.sum(ce * valid, axis=1, keepdims=True)
    return {"Out": [out], "PreOut": [pre]}


@register_op("lstmp",
             inputs=["Input", "Weight", "ProjWeight", "Bias", "H0", "C0",
                     "SeqLens"],
             outputs=["Projection", "Cell", "LastH", "LastC"],
             no_grad_slots=("SeqLens",))
def _lstmp(ctx, ins, attrs):
    """cf. lstmp_op.cc: LSTM with a recurrent projection layer — the
    hidden state fed back (and emitted) is h_proj = act(h @ ProjWeight),
    ProjWeight [D, P], recurrent Weight [P, 4D]."""
    from .rnn_ops import _act, _scan_rnn

    x = ins["Input"][0]
    W = ins["Weight"][0]                          # [P, 4D]
    Wp = ins["ProjWeight"][0]                     # [D, P]
    D = Wp.shape[0]
    P = Wp.shape[1]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    use_peep = bool(attrs.get("use_peepholes", False))
    peep = None
    if use_peep:
        b = bias.reshape(-1)
        peep = (b[4 * D:5 * D], b[5 * D:6 * D], b[6 * D:])
    acts = (_act(attrs.get("gate_activation", "sigmoid")),
            _act(attrs.get("cell_activation", "tanh")),
            _act(attrs.get("candidate_activation", "tanh")))
    proj_act = _act(attrs.get("proj_activation", "identity"))
    B = x.shape[0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, P), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, D), x.dtype)
    lens = ins["SeqLens"][0] if ins.get("SeqLens") else None

    act_gate, act_cell, act_cand = acts

    def step(carry, xt):
        hp, c = carry
        # _lstm_cell infers the cell width from the carry, which here is
        # the PROJECTED state [B, P] — inline the cell with explicit D
        g = xt + hp @ W
        if bias is not None:
            g = g + bias.reshape(-1)[: 4 * D]
        gc, gi, gf, go = (g[..., :D], g[..., D:2 * D],
                          g[..., 2 * D:3 * D], g[..., 3 * D:])
        if peep is not None:
            w_ic, w_fc, w_oc = peep
            gi = gi + c * w_ic
            gf = gf + c * w_fc
        c_new = act_cand(gc) * act_gate(gi) + c * act_gate(gf)
        if peep is not None:
            go = go + c_new * peep[2]
        h_new = act_gate(go) * act_cell(c_new)
        hp_new = proj_act(h_new @ Wp)
        return (hp_new, c_new), (hp_new, c_new)

    (last_h, last_c), (hs, cs) = _scan_rnn(
        step, x, lens, (h0, c0), attrs.get("is_reverse", False))
    return {"Projection": [hs], "Cell": [cs],
            "LastH": [last_h], "LastC": [last_c]}


# ---------------------------------------------------------------------------
# detection tail
# ---------------------------------------------------------------------------


@register_op("prroi_pool", inputs=["X", "ROIs", "BatchRoINums"],
             outputs=["Out"], no_grad_slots=("ROIs", "BatchRoINums"))
def _prroi_pool(ctx, ins, attrs):
    """cf. prroi_pool_op.cc (Precise RoI Pooling): bin value = integral
    of the bilinearly-interpolated feature over the bin / bin area.
    Numerics note: the integral here is a dense 8x8-sample midpoint
    approximation per bin (documented; the oracle test uses the same
    quadrature).  ROIs are [R, 4] with a batch id per row in
    BatchRoINums-free mode (single image) or [R, 5] (batch_id, x1, y1,
    x2, y2)."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    S = 8  # quadrature points per bin side
    if rois.shape[1] == 5:
        bids = rois[:, 0].astype(jnp.int32)
        boxes = rois[:, 1:]
    else:
        boxes = rois
        if ins.get("BatchRoINums"):
            counts = ins["BatchRoINums"][0].reshape(-1).astype(jnp.int32)
            ends = jnp.cumsum(counts)             # [B]
            r = jnp.arange(rois.shape[0])
            bids = jnp.sum(
                (r[:, None] >= ends[None, :]).astype(jnp.int32), axis=1)
        else:
            bids = jnp.zeros((rois.shape[0],), jnp.int32)

    def one(bid, box):
        img = x[bid]
        x1, y1, x2, y2 = box * scale
        bw = jnp.maximum(x2 - x1, 1e-6) / pw
        bh = jnp.maximum(y2 - y1, 1e-6) / ph
        ys = y1 + (jnp.arange(ph)[:, None] +
                   (jnp.arange(S)[None, :] + 0.5) / S) * bh
        xs = x1 + (jnp.arange(pw)[:, None] +
                   (jnp.arange(S)[None, :] + 0.5) / S) * bw
        yy = ys.reshape(-1)[:, None]              # [ph*S, 1]
        xx = xs.reshape(-1)[None, :]              # [1, pw*S]
        v = _bilinear_sample_nchw(
            img, jnp.broadcast_to(yy, (ph * S, pw * S)),
            jnp.broadcast_to(xx, (ph * S, pw * S)))  # [C, ph*S, pw*S]
        v = v.reshape(v.shape[0], ph, S, pw, S).mean(axis=(2, 4))
        return v

    return {"Out": [jax.vmap(one)(bids, boxes)]}


@register_op("yolov3_loss",
             inputs=["X", "GTBox", "GTLabel", "GTScore"],
             outputs=["Loss", "ObjectnessMask", "GTMatchMask"],
             no_grad_slots=("GTBox", "GTLabel", "GTScore"))
def _yolov3_loss(ctx, ins, attrs):
    """cf. yolov3_loss_op.cc: per-anchor xywh (sq/CE), objectness and
    class losses on the matched cells; anchors whose best IoU with any gt
    exceeds ignore_thresh are excluded from the negative objectness
    term."""
    x = ins["X"][0]                     # [B, A*(5+C), H, W]
    gtbox = ins["GTBox"][0]             # [B, G, 4] (cx, cy, w, h), 0..1
    gtlabel = ins["GTLabel"][0]         # [B, G]
    anchors = [int(a) for a in attrs["anchors"]]
    mask_idx = [int(a) for a in attrs.get("anchor_mask",
                                          range(len(anchors) // 2))]
    C = int(attrs["class_num"])
    ignore = float(attrs.get("ignore_thresh", 0.7))
    down = int(attrs.get("downsample_ratio", 32))
    B, _, H, W = x.shape
    A = len(mask_idx)
    inp = H * down
    x = x.reshape(B, A, 5 + C, H, W)
    raw_xy = x[:, :, 0:2]
    pred_xy = jax.nn.sigmoid(raw_xy)
    pred_wh = x[:, :, 2:4]
    pred_obj = x[:, :, 4]
    pred_cls = x[:, :, 5:]
    gtscore = (ins["GTScore"][0] if ins.get("GTScore")
               else jnp.ones(gtlabel.shape, jnp.float32))

    all_anchors = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    sel_anchors = all_anchors[jnp.asarray(mask_idx)]

    # gt -> responsible anchor (best IoU of centered boxes over ALL
    # anchors, reference behavior) and cell
    gw = gtbox[..., 2] * inp
    gh = gtbox[..., 3] * inp
    inter = (jnp.minimum(gw[..., None], all_anchors[:, 0])
             * jnp.minimum(gh[..., None], all_anchors[:, 1]))
    union = gw[..., None] * gh[..., None] \
        + all_anchors[:, 0] * all_anchors[:, 1] - inter
    best = jnp.argmax(inter / jnp.maximum(union, 1e-9), axis=-1)  # [B,G]
    gi = jnp.clip((gtbox[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gtbox[..., 1] * H).astype(jnp.int32), 0, H - 1)
    has_gt = (gtbox[..., 2] > 0) & (gtbox[..., 3] > 0)

    # scatter gt targets into the grid
    def per_image(rxy, pxy, pwh, pobj, pcls, box, lab, score, bst, ci,
                  cj, hg):
        # local anchor index (or -1 when the best anchor isn't in mask)
        local = -jnp.ones_like(bst)
        for li, mi in enumerate(mask_idx):
            local = jnp.where(bst == mi, li, local)
        on = hg & (local >= 0)
        tx = box[:, 0] * W - ci
        ty = box[:, 1] * H - cj
        tw = jnp.log(jnp.maximum(
            box[:, 2] * inp / jnp.maximum(sel_anchors[
                jnp.maximum(local, 0), 0], 1e-9), 1e-9))
        th = jnp.log(jnp.maximum(
            box[:, 3] * inp / jnp.maximum(sel_anchors[
                jnp.maximum(local, 0), 1], 1e-9), 1e-9))
        tscale = (2.0 - box[:, 2] * box[:, 3]) * score

        obj_mask = jnp.zeros((A, H, W))
        match = -jnp.ones((box.shape[0],), jnp.int32)
        loss = 0.0
        la = jnp.maximum(local, 0)
        onf = on.astype(jnp.float32)
        # coordinate + class losses gathered at (la, cj, ci); the BCE
        # runs on the RAW logits (logit(clip(sigmoid(.))) would zero the
        # gradient once the sigmoid saturates in fp32)
        rxg = rxy[la, 0, cj, ci]
        ryg = rxy[la, 1, cj, ci]
        pwg = pwh[la, 0, cj, ci]
        phg = pwh[la, 1, cj, ci]
        bce = lambda p, t: (jnp.logaddexp(0.0, p) - t * p)
        # reference uses sigmoid-CE on x/y and L1 on w/h
        loss = loss + jnp.sum(
            onf * tscale * (bce(rxg, tx) + bce(ryg, ty)))
        loss = loss + jnp.sum(onf * tscale * (jnp.abs(pwg - tw)
                                              + jnp.abs(phg - th)))
        cls_logit = pcls[la, :, cj, ci]           # [G, C]
        onehot = jax.nn.one_hot(lab, C)
        loss = loss + jnp.sum(
            onf[:, None] * score[:, None]
            * (jnp.logaddexp(0.0, cls_logit) - onehot * cls_logit))
        obj_mask = obj_mask.at[la, cj, ci].max(onf)
        match = jnp.where(on, la, match)

        # negative objectness: anchors with best-gt IoU > ignore excluded
        cx = (jnp.arange(W)[None, None, :] + pxy[:, 0]) / W
        cy = (jnp.arange(H)[None, :, None] + pxy[:, 1]) / H
        pw_ = jnp.exp(pwh[:, 0]) * sel_anchors[:, 0, None, None] / inp
        ph_ = jnp.exp(pwh[:, 1]) * sel_anchors[:, 1, None, None] / inp
        px1, px2 = cx - pw_ / 2, cx + pw_ / 2
        py1, py2 = cy - ph_ / 2, cy + ph_ / 2
        gx1 = box[:, 0] - box[:, 2] / 2
        gx2 = box[:, 0] + box[:, 2] / 2
        gy1 = box[:, 1] - box[:, 3] / 2
        gy2 = box[:, 1] + box[:, 3] / 2
        ix = jnp.maximum(
            jnp.minimum(px2[..., None], gx2) - jnp.maximum(
                px1[..., None], gx1), 0)
        iy = jnp.maximum(
            jnp.minimum(py2[..., None], gy2) - jnp.maximum(
                py1[..., None], gy1), 0)
        inter2 = ix * iy
        area_p = (px2 - px1) * (py2 - py1)
        area_g = (gx2 - gx1) * (gy2 - gy1)
        iou = inter2 / jnp.maximum(
            area_p[..., None] + area_g - inter2, 1e-9)
        best_iou = jnp.max(jnp.where(hg, iou, 0.0), axis=-1)
        noobj = (best_iou <= ignore).astype(jnp.float32) * (1 - obj_mask)
        loss = loss + jnp.sum(
            obj_mask * (jnp.logaddexp(0.0, pobj) - pobj))
        loss = loss + jnp.sum(noobj * jnp.logaddexp(0.0, pobj))
        return loss, obj_mask + noobj * 0.0, match

    loss, omask, match = jax.vmap(per_image)(
        raw_xy, pred_xy, pred_wh, pred_obj, pred_cls, gtbox,
        gtlabel.astype(jnp.int32), gtscore, best, gi, gj, has_gt)
    return {"Loss": [loss], "ObjectnessMask": [omask],
            "GTMatchMask": [match]}


@register_op("multiclass_nms2", inputs=["BBoxes", "Scores"],
             outputs=["Out", "Index"], grad=None)
def _multiclass_nms2(ctx, ins, attrs):
    """cf. multiclass_nms_op.cc (v2 adds the kept-box Index output; same
    static [N, keep_top_k, 6] redesign as multiclass_nms).  Index matches
    the reference's [N,C,M]-score path addressing: image_idx * M + box_idx
    into the flattened batch of input boxes (-1 in empty slots), so code
    that gathers per-box features with Index reads the right rows."""
    from .detection_ops import multiclass_nms_core

    bboxes = ins["BBoxes"][0]
    out, src = multiclass_nms_core(bboxes, ins["Scores"][0], attrs)
    m = bboxes.shape[1]
    offs = (jnp.arange(out.shape[0], dtype=jnp.int32) * m)[:, None]
    idx = jnp.where(src >= 0, src + offs, -1)
    return {"Out": [out], "Index": [idx.astype(jnp.int32)[..., None]]}


@register_op("ctc_align", inputs=["Input"], outputs=["Output"], grad=None)
def _ctc_align(ctx, ins, attrs):
    """cf. ctc_align_op.cc: merge repeats then drop blanks; STATIC
    redesign pads the tail with `padding_value` (default 0)."""
    x = ins["Input"][0]
    blank = int(attrs.get("blank", 0))
    padv = int(attrs.get("padding_value", 0))
    T = x.shape[-1]

    def one(seq):
        prev = jnp.concatenate([jnp.asarray([-1], seq.dtype), seq[:-1]])
        keep = (seq != prev) & (seq != blank)
        order = jnp.argsort(~keep, stable=True)   # kept first, stable
        vals = jnp.where(keep, seq, padv)[order]
        return jnp.where(jnp.arange(T) < jnp.sum(keep), vals, padv)

    out = jax.vmap(one)(x.reshape(-1, T)).reshape(x.shape)
    return {"Output": [out]}


@register_op("positive_negative_pair",
             inputs=["Score", "Label", "QueryID"],
             outputs=["PositivePair", "NegativePair", "NeutralPair"],
             grad=None)
def _positive_negative_pair(ctx, ins, attrs):
    """cf. positive_negative_pair_op.cc: within each query, count ordered
    pairs where score order agrees (pos) / disagrees (neg) / ties
    (neutral) with label order."""
    s = ins["Score"][0].reshape(-1)
    lab = ins["Label"][0].reshape(-1)
    q = ins["QueryID"][0].reshape(-1)
    same_q = q[:, None] == q[None, :]
    lab_gt = lab[:, None] > lab[None, :]
    s_diff = s[:, None] - s[None, :]
    pos = jnp.sum(same_q & lab_gt & (s_diff > 0))
    neg = jnp.sum(same_q & lab_gt & (s_diff < 0))
    neu = jnp.sum(same_q & lab_gt & (s_diff == 0))
    f = lambda v: v.astype(jnp.float32).reshape(1, 1)
    return {"PositivePair": [f(pos)], "NegativePair": [f(neg)],
            "NeutralPair": [f(neu)]}


@register_op("mine_hard_examples",
             inputs=["ClsLoss", "MatchIndices"],
             outputs=["NegIndices", "UpdatedMatchIndices"], grad=None)
def _mine_hard_examples(ctx, ins, attrs):
    """cf. mine_hard_examples_op.cc (max_negative mining): per image,
    select the highest-loss unmatched priors as negatives, at most
    neg_pos_ratio * num_matched.  STATIC redesign: NegIndices is
    [N, P] padded with -1."""
    loss = ins["ClsLoss"][0]                      # [N, P]
    match = ins["MatchIndices"][0]                # [N, P], -1 = unmatched
    ratio = float(attrs.get("neg_pos_ratio", 3.0))
    N, P = loss.shape

    def one(l, m):
        unmatched = m < 0
        n_pos = jnp.sum(m >= 0)
        n_neg = jnp.minimum(
            (ratio * n_pos).astype(jnp.int32), jnp.sum(unmatched))
        order = jnp.argsort(-jnp.where(unmatched, l, -jnp.inf))
        keep = jnp.arange(P) < n_neg
        negs = jnp.where(keep, order, -1)
        # negatives stay -1 in updated match indices (already are)
        return negs.astype(jnp.int32), m

    negs, upd = jax.vmap(one)(loss, match)
    return {"NegIndices": [negs], "UpdatedMatchIndices": [upd]}


@register_op("similarity_focus", inputs=["X"], outputs=["Out"], grad=None)
def _similarity_focus(ctx, ins, attrs):
    """cf. similarity_focus_op.cc: for each selected channel (axis=1,
    indexes attr), mark the (h, w) argmax per remaining dim pair with 1
    producing a binary focus mask of X's shape."""
    x = ins["X"][0]                               # [B, C, H, W]
    indexes = [int(i) for i in attrs.get("indexes", [0])]
    B, C, H, W = x.shape
    mask = jnp.zeros_like(x)
    for ci in indexes:
        plane = x[:, ci]                          # [B, H, W]
        # per row: max column; per column: max row (reference's
        # row/column coverage procedure approximated by union of
        # per-row and per-column argmax cells)
        col_of_row = jnp.argmax(plane, axis=2)    # [B, H]
        row_of_col = jnp.argmax(plane, axis=1)    # [B, W]
        m = jnp.zeros((B, H, W))
        m = m.at[jnp.arange(B)[:, None], jnp.arange(H)[None, :],
                 col_of_row].set(1.0)
        m = m.at[jnp.arange(B)[:, None], row_of_col,
                 jnp.arange(W)[None, :]].set(1.0)
        mask = mask.at[:, ci].set(m.astype(x.dtype))
    # broadcast the union mask over unselected channels (reference
    # shares the focus across the channel dim)
    union = jnp.max(mask, axis=1, keepdims=True)
    return {"Out": [jnp.broadcast_to(union, x.shape).astype(x.dtype)]}


@register_op("broadcast", inputs=["X"], outputs=["Out"])
def _broadcast(ctx, ins, attrs):
    """cf. collective broadcast_op.cc: alias of c_broadcast semantics —
    under SPMD every shard already holds the root's value after the
    param-init broadcast, so this is the identity in-graph."""
    return {"Out": [ins["X"][0]]}


@register_op(
    "fused_batch_norm_act",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    no_grad_slots=("Mean", "Variance"),
    stateful_out_slots=("MeanOut", "VarianceOut"),
)
def _fused_batch_norm_act(ctx, ins, attrs):
    """cf. fused/fused_bn_activation_op.cc: batch_norm + activation in one
    op (the fusion itself is XLA's job; this keeps the graph-level API)."""
    res = get_op_def("batch_norm").lower(ctx, ins, attrs)
    act = attrs.get("act_type", "relu")
    res["Y"] = [get_op_def(act).lower(ctx, {"X": res["Y"]}, {})["Out"][0]]
    return res


@register_op(
    "inplace_abn",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    no_grad_slots=("Mean", "Variance"),
    stateful_out_slots=("MeanOut", "VarianceOut"),
)
def _inplace_abn(ctx, ins, attrs):
    """cf. inplace_abn_op.cc: activated batch norm — in-place-ness is an
    allocator concern XLA owns; semantics = batch_norm + activation
    (identity / leaky_relu / elu per the reference attr)."""
    res = get_op_def("batch_norm").lower(ctx, ins, attrs)
    act = attrs.get("activation", "identity")
    y = res["Y"][0]
    if act == "leaky_relu":
        alpha = float(attrs.get("alpha", 0.01))
        y = jnp.where(y >= 0, y, alpha * y)
    elif act == "elu":
        alpha = float(attrs.get("alpha", 1.0))
        y = jnp.where(y >= 0, y, alpha * (jnp.exp(y) - 1.0))
    elif act not in ("identity", "", None):
        y = get_op_def(act).lower(ctx, {"X": [y]}, {})["Out"][0]
    res["Y"] = [y]
    return res


@register_op("tensor_array_to_tensor", inputs=["X"], outputs=["Out",
                                                              "OutIndex"],
             grad=None)
def _tensor_array_to_tensor(ctx, ins, attrs):
    """cf. tensor_array_to_tensor_op.cc: concat/stack the array's written
    slots along `axis`."""
    arr = ins["X"]
    axis = int(attrs.get("axis", 0))
    if bool(attrs.get("use_stack", False)):
        out = jnp.stack(arr, axis=axis)
    else:
        out = jnp.concatenate(arr, axis=axis)
    sizes = jnp.asarray([a.shape[axis] for a in arr], jnp.int32)
    return {"Out": [out], "OutIndex": [sizes]}
