"""Op-tail batch 1: math / tensor / misc ops closing the registry gap vs
the reference operator library (round-4 audit list).

Each op cites its reference file; semantics are pinned by the numpy
oracles in tests/test_tail_ops.py.  Ops whose reference output shape is
data-dependent (unique, where_index, ctc_align) are redesigned to a
STATIC padded shape — documented per op — because XLA requires static
shapes; this mirrors the repo-wide LoD->padding design decision.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


# ---------------------------------------------------------------------------
# elementwise / small tensor ops
# ---------------------------------------------------------------------------


@register_op("tril_triu", inputs=["X"], outputs=["Out"])
def _tril_triu(ctx, ins, attrs):
    """cf. tril_triu_op.cc: lower/upper triangle with `diagonal` offset."""
    x = ins["X"][0]
    diag = int(attrs.get("diagonal", 0))
    if bool(attrs.get("lower", True)):
        return {"Out": [jnp.tril(x, k=diag)]}
    return {"Out": [jnp.triu(x, k=diag)]}


@register_op("multiplex", inputs=["X", "Ids"], outputs=["Out"],
             no_grad_slots=("Ids",))
def _multiplex(ctx, ins, attrs):
    """cf. multiplex_op.cc: out[i] = X[Ids[i]][i] (row-wise candidate
    select across the input list)."""
    xs = jnp.stack(ins["X"], axis=0)            # [K, B, ...]
    ids = ins["Ids"][0].reshape(-1).astype(jnp.int32)  # [B]
    rows = jnp.arange(xs.shape[1])
    return {"Out": [xs[ids, rows]]}


@register_op("minus", inputs=["X", "Y"], outputs=["Out"])
def _minus(ctx, ins, attrs):
    """cf. minus_op.cc: Out = X - Y."""
    return {"Out": [ins["X"][0] - ins["Y"][0]]}


@register_op("reverse", inputs=["X"], outputs=["Out"])
def _reverse(ctx, ins, attrs):
    """cf. reverse_op.cc: flip along the `axis` list."""
    axes = attrs.get("axis", [0])
    axes = [axes] if isinstance(axes, int) else list(axes)
    return {"Out": [jnp.flip(ins["X"][0], axis=tuple(int(a) for a in axes))]}


@register_op("eye", inputs=[], outputs=["Out"])
def _eye(ctx, ins, attrs):
    """cf. eye_op.cc."""
    from ..core.dtypes import to_jnp

    n = int(attrs["num_rows"])
    m = int(attrs.get("num_columns", -1))
    m = n if m < 0 else m
    return {"Out": [jnp.eye(n, m, dtype=to_jnp(attrs.get("dtype",
                                                         "float32")))]}


@register_op("diag", inputs=["Diagonal"], outputs=["Out"])
def _diag(ctx, ins, attrs):
    """cf. diag_op.cc: 1-D diagonal -> square matrix."""
    return {"Out": [jnp.diag(ins["Diagonal"][0].reshape(-1))]}


@register_op("fill", inputs=[], outputs=["Out"])
def _fill(ctx, ins, attrs):
    """cf. fill_op.cc: materialize attr `value` data with attr `shape`."""
    import numpy as np

    from ..core.dtypes import to_jnp

    shape = tuple(int(s) for s in attrs["shape"])
    vals = np.asarray(attrs["value"], dtype=np.float64).reshape(shape)
    return {"Out": [jnp.asarray(vals, dtype=to_jnp(attrs.get("dtype",
                                                             "float32")))]}


@register_op("fill_zeros_like2", inputs=["X"], outputs=["Out"])
def _fill_zeros_like2(ctx, ins, attrs):
    """cf. fill_zeros_like_op.cc (v2 carries an explicit dtype attr)."""
    from ..core.dtypes import to_jnp

    dt = attrs.get("dtype")
    x = ins["X"][0]
    return {"Out": [jnp.zeros(x.shape, to_jnp(dt) if dt else x.dtype)]}


@register_op("range", inputs=["Start", "End", "Step"], outputs=["Out"],
             grad=None)
def _range(ctx, ins, attrs):
    """cf. range_op.cc.  XLA needs a static length, so Start/End/Step must
    be graph-time constants (fill_constant feeds or attr fallback)."""
    import numpy as np

    def _concrete(slot, attr):
        if ins.get(slot):
            v = ins[slot][0]
            try:
                return float(np.asarray(jax.core.concrete_or_error(
                    None, v, "range op needs concrete Start/End/Step "
                    "(data-dependent lengths cannot be staged to XLA)")))
            except TypeError:
                return float(np.asarray(v).reshape(()))
        return float(attrs[attr])

    start = _concrete("Start", "start")
    end = _concrete("End", "end")
    step = _concrete("Step", "step")
    out = jnp.arange(start, end, step)
    if ins.get("Start"):
        out = out.astype(ins["Start"][0].dtype)
    return {"Out": [out]}


@register_op("unique", inputs=["X"], outputs=["Out", "Index"], grad=None)
def _unique(ctx, ins, attrs):
    """cf. unique_op.cc.  STATIC redesign: Out is padded to len(X) (the
    reference emits a variable-length tensor); trailing slots repeat the
    first unique value.  Index (the orig->unique map) is exact."""
    x = ins["X"][0].reshape(-1)
    out, inv = jnp.unique(x, return_inverse=True, size=x.shape[0],
                          fill_value=x[0])
    return {"Out": [out], "Index": [inv.astype(jnp.int32)]}


@register_op("unique_with_counts", inputs=["X"],
             outputs=["Out", "Index", "Count"], grad=None)
def _unique_with_counts(ctx, ins, attrs):
    """cf. unique_with_counts_op.cc (same static-padding redesign)."""
    x = ins["X"][0].reshape(-1)
    out, inv, cnt = jnp.unique(x, return_inverse=True, return_counts=True,
                               size=x.shape[0], fill_value=x[0])
    return {"Out": [out], "Index": [inv.astype(jnp.int32)],
            "Count": [cnt.astype(jnp.int32)]}


@register_op("where_index", inputs=["Condition"], outputs=["Out"],
             grad=None)
def _where_index(ctx, ins, attrs):
    """cf. where_index_op.cc (np.nonzero).  STATIC redesign: padded to
    numel rows with -1 (the true count = rows with index >= 0)."""
    c = ins["Condition"][0]
    out = jnp.argwhere(c, size=c.size, fill_value=-1)
    return {"Out": [out.astype(jnp.int64)]}


@register_op("is_empty", inputs=["X"], outputs=["Out"], grad=None)
def _is_empty(ctx, ins, attrs):
    """cf. is_empty_op.cc."""
    return {"Out": [jnp.asarray(ins["X"][0].size == 0)]}


@register_op("gaussian_random_batch_size_like", inputs=["Input"],
             outputs=["Out"], needs_rng=True, grad=None)
def _gaussian_random_bsl(ctx, ins, attrs):
    """cf. gaussian_random_batch_size_like_op.cc (batch_size_like.h:49)."""
    from ..core.dtypes import to_jnp

    x = ins["Input"][0]
    shape = list(attrs["shape"])
    shape[int(attrs.get("output_dim_idx", 0))] = x.shape[
        int(attrs.get("input_dim_idx", 0))]
    out = float(attrs.get("mean", 0.0)) + float(attrs.get("std", 1.0)) \
        * jax.random.normal(ctx.rng(), tuple(shape),
                            dtype=to_jnp(attrs.get("dtype", "float32")))
    return {"Out": [out]}


@register_op("bilinear_tensor_product", inputs=["X", "Y", "Weight", "Bias"],
             outputs=["Out"])
def _bilinear_tensor_product(ctx, ins, attrs):
    """cf. bilinear_tensor_product_op.cc: out[b,o] = x[b] W[o] y[b]^T."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    out = jnp.einsum("bm,omn,bn->bo", x, w, y)
    if ins.get("Bias"):
        out = out + ins["Bias"][0]
    return {"Out": [out]}


@register_op("cross_entropy2", inputs=["X", "Label"],
             outputs=["Y", "MatchX", "XShape"], no_grad_slots=("Label",))
def _cross_entropy2(ctx, ins, attrs):
    """cf. cross_entropy2_op.cc: hard-label CE over an already-normalized
    probability input; MatchX saves the matched prob for the backward."""
    x, label = ins["X"][0], ins["Label"][0]
    lab = label.reshape(label.shape[:-1]).astype(jnp.int32)
    match = jnp.take_along_axis(x, lab[..., None], axis=-1)
    y = -jnp.log(jnp.maximum(match, 1e-20))
    return {"Y": [y], "MatchX": [match],
            "XShape": [jnp.zeros((len(x.shape) + 1,), jnp.int64)]}


@register_op("conv_shift", inputs=["X", "Y"], outputs=["Out"])
def _conv_shift(ctx, ins, attrs):
    """cf. conv_shift_op.cc: circular correlation — out[b,i] =
    sum_j x[b, (i + j - N//2) mod M] * y[b, j]."""
    x, y = ins["X"][0], ins["Y"][0]
    M, N = x.shape[1], y.shape[1]
    idx = (jnp.arange(M)[:, None] + jnp.arange(N)[None, :] - N // 2) % M
    return {"Out": [jnp.einsum("bmn,bn->bm", x[:, idx], y)]}


@register_op("bpr_loss", inputs=["X", "Label"], outputs=["Out"],
             no_grad_slots=("Label",))
def _bpr_loss(ctx, ins, attrs):
    """cf. bpr_loss_op.cc (Bayesian Personalized Ranking): per row,
    -mean_j log(sigmoid(x[label] - x[j != label]))."""
    x, label = ins["X"][0], ins["Label"][0]
    B, C = x.shape
    lab = label.reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, lab[:, None], axis=1)
    diff = pos - x
    lognd = jnp.logaddexp(0.0, -diff)           # -log(sigmoid(diff))
    mask = jnp.arange(C)[None, :] != lab[:, None]
    out = jnp.sum(jnp.where(mask, lognd, 0.0), axis=1, keepdims=True) \
        / jnp.maximum(C - 1, 1)
    return {"Out": [out]}


@register_op("cvm", inputs=["X", "CVM"], outputs=["Y"],
             no_grad_slots=("CVM",))
def _cvm(ctx, ins, attrs):
    """cf. cvm_op.cc: the first two feature columns are (show, click);
    use_cvm=True keeps them log-transformed, False drops them."""
    x = ins["X"][0]
    if bool(attrs.get("use_cvm", True)):
        show = jnp.log(x[:, 0:1] + 1.0)
        ctr = jnp.log(x[:, 1:2] + 1.0) - jnp.log(x[:, 0:1] + 1.0)
        return {"Y": [jnp.concatenate([show, ctr, x[:, 2:]], axis=1)]}
    return {"Y": [x[:, 2:]]}


@register_op("hash", inputs=["X"], outputs=["Out"], grad=None)
def _hash(ctx, ins, attrs):
    """cf. hash_op.cc: num_hash rows of (xxhash(x_row, seed=i) % mod_by).
    The hash family here is a splitmix-style integer mix — a documented
    redesign (the exact xxhash bits are not a semantic contract; tests
    pin THIS mix)."""
    x = ins["X"][0].astype(jnp.uint32)
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs.get("mod_by", 1))

    def mix(v, seed):
        v = (v + jnp.uint32(seed)) * jnp.uint32(0x9E3779B1)
        v = v ^ (v >> 15)
        v = v * jnp.uint32(0x85EBCA77)
        v = v ^ (v >> 13)
        return v

    rows = []
    for i in range(num_hash):
        h = jnp.zeros(x.shape[:-1], jnp.uint32)
        for j in range(x.shape[-1]):
            h = mix(h ^ x[..., j], i * 0x2545F491 + j + 1)
        rows.append((h % jnp.uint32(mod_by)).astype(jnp.int64))
    out = jnp.stack(rows, axis=-1)[..., None]     # [.., num_hash, 1]
    return {"Out": [out.reshape(x.shape[:-1] + (num_hash, 1))]}


@register_op("seed", inputs=[], outputs=["Out"], needs_rng=True, grad=None)
def _seed(ctx, ins, attrs):
    """cf. seed_op.cc: emit the configured (or a generated) seed."""
    s = int(attrs.get("seed", 0))
    if s != 0:
        return {"Out": [jnp.asarray([s], jnp.int32)]}
    r = jax.random.randint(ctx.rng(), (1,), 1, 2 ** 31 - 1)
    return {"Out": [r.astype(jnp.int32)]}


@register_op("get_tensor_from_selected_rows", inputs=["X"], outputs=["Out"])
def _get_tensor_from_selected_rows(ctx, ins, attrs):
    """cf. get_tensor_from_selected_rows_op.cc: in this design sparse
    rows are already dense (ids, rows) pairs folded by the optimizer
    path, so this is the identity on the dense value."""
    return {"Out": [ins["X"][0]]}


@register_op("merge_selected_rows", inputs=["X", "RowIds"], outputs=["Out"],
             no_grad_slots=("RowIds",))
def _merge_selected_rows(ctx, ins, attrs):
    """cf. merge_selected_rows_op.cc: sum rows with duplicate ids.  Takes
    the (values, row_ids) pair of this design's sparse-rows convention
    and returns values with duplicates accumulated onto the FIRST
    occurrence (later duplicates zeroed)."""
    vals, ids = ins["X"][0], ins["RowIds"][0].reshape(-1)
    # accumulate every row onto the first row holding the same id
    same = ids[None, :] == ids[:, None]
    first_idx = jnp.argmax(same, axis=1)         # first occurrence per row
    out = jnp.zeros_like(vals).at[first_idx].add(vals)
    return {"Out": [out]}


@register_op("lod_array_length", inputs=["X"], outputs=["Out"], grad=None)
def _lod_array_length(ctx, ins, attrs):
    """cf. lod_array_length_op.cc over this design's fixed-capacity
    tensor array (count of written slots)."""
    arr = ins["X"]
    return {"Out": [jnp.asarray([len(arr)], jnp.int64)]}


@register_op("max_sequence_len", inputs=["RankTable"], outputs=["Out"],
             grad=None)
def _max_sequence_len(ctx, ins, attrs):
    """cf. max_sequence_len_op.cc: with padded batches the max length is
    the time dimension of the packed tensor."""
    return {"Out": [jnp.asarray([ins["RankTable"][0].shape[1]],
                                jnp.int64)]}


@register_op("fake_init", inputs=[], outputs=["Out"], grad=None)
def _fake_init(ctx, ins, attrs):
    """cf. fake_init_op.cc: placeholder init (PS-mode vars) — zeros."""
    from ..core.dtypes import to_jnp

    return {"Out": [jnp.zeros(tuple(int(s) for s in attrs["shape"]),
                              to_jnp(attrs.get("dtype", "float32")))]}


@register_op("delete_var", inputs=["X"], outputs=[], grad=None)
def _delete_var(ctx, ins, attrs):
    """cf. delete_var_op.cc: buffer frees are XLA's job — no-op."""
    return {}


# ---------------------------------------------------------------------------
# optimizer-support ops
# ---------------------------------------------------------------------------


@register_op(
    "average_accumulates",
    inputs=["param", "in_sum_1", "in_sum_2", "in_sum_3",
            "in_num_accumulates", "in_old_num_accumulates",
            "in_num_updates"],
    outputs=["out_sum_1", "out_sum_2", "out_sum_3", "out_num_accumulates",
             "out_old_num_accumulates", "out_num_updates"],
    grad=None,
)
def _average_accumulates(ctx, ins, attrs):
    """cf. average_accumulates_op.h AccumulateAverage: sum_1 accumulates
    params; every 16384 updates it folds into sum_2; when the window
    closes (num_accumulates >= min_window and >= num_updates *
    average_window capped at max_window) everything folds into sum_3 and
    the accumulators reset."""
    p = ins["param"][0]
    s1, s2, s3 = ins["in_sum_1"][0], ins["in_sum_2"][0], ins["in_sum_3"][0]
    na = ins["in_num_accumulates"][0].reshape(())
    ona = ins["in_old_num_accumulates"][0].reshape(())
    nu = ins["in_num_updates"][0].reshape(())
    avg_win = float(attrs.get("average_window", 0))
    max_avg = int(attrs.get("max_average_window", 2 ** 31 - 1))
    min_avg = int(attrs.get("min_average_window", 10000))
    K_MAX = 16384

    nu = nu + 1
    na = na + 1
    s1 = s1 + p
    fold12 = (nu % K_MAX) == 0
    s2 = jnp.where(fold12, s2 + s1, s2)
    s1 = jnp.where(fold12, jnp.zeros_like(s1), s1)
    window = jnp.minimum(
        jnp.float32(max_avg), nu.astype(jnp.float32) * avg_win)
    close = (na >= min_avg) & (na.astype(jnp.float32) >= window)
    s3 = jnp.where(close, s1 + s2, s3)
    s1 = jnp.where(close, jnp.zeros_like(s1), s1)
    s2 = jnp.where(close, jnp.zeros_like(s2), s2)
    ona = jnp.where(close, na, ona)
    na = jnp.where(close, jnp.zeros_like(na), na)
    shape1 = ins["in_num_accumulates"][0].shape
    return {
        "out_sum_1": [s1], "out_sum_2": [s2], "out_sum_3": [s3],
        "out_num_accumulates": [na.reshape(shape1)],
        "out_old_num_accumulates": [ona.reshape(shape1)],
        "out_num_updates": [nu.reshape(shape1)],
    }


@register_op(
    "proximal_adagrad",
    inputs=["Param", "Moment", "Grad", "LearningRate"],
    outputs=["ParamOut", "MomentOut"], grad=None,
)
def _proximal_adagrad(ctx, ins, attrs):
    """cf. proximal_adagrad_op.cc: adagrad step then the proximal L1/L2
    shrinkage prox_param / (1 + lr_adj * l2) with soft-threshold l1."""
    p, m, g = ins["Param"][0], ins["Moment"][0], ins["Grad"][0]
    lr = ins["LearningRate"][0].reshape(())
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    m = m + g * g
    lr_adj = lr * jax.lax.rsqrt(m)
    prox = p - lr_adj * g
    out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr_adj * l1, 0.0) \
        / (1.0 + lr_adj * l2)
    return {"ParamOut": [out], "MomentOut": [m]}


@register_op(
    "proximal_gd",
    inputs=["Param", "Grad", "LearningRate"],
    outputs=["ParamOut"], grad=None,
)
def _proximal_gd(ctx, ins, attrs):
    """cf. proximal_gd_op.cc."""
    p, g = ins["Param"][0], ins["Grad"][0]
    lr = ins["LearningRate"][0].reshape(())
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    prox = p - lr * g
    out = jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0) \
        / (1.0 + lr * l2)
    return {"ParamOut": [out]}


@register_op("assert", inputs=["Cond", "Data"], outputs=["Out"], grad=None)
def _assert_op(ctx, ins, attrs):
    """cf. operators/assert_op.cc: host-checked assertion — when Cond is
    false, print the message + summarized Data and raise.  Degrades to a
    warning when the platform has no host callbacks (axon tunnel)."""
    import jax

    cond = ins["Cond"][0]
    data = ins["Data"] if ins.get("Data") else []
    message = str(attrs.get("message", ""))
    summarize = int(attrs.get("summarize", 10))

    from ..core.block_eval import _warn_no_callbacks, host_callbacks_supported

    if not host_callbacks_supported():
        _warn_no_callbacks("layers.Assert")
        return {"Out": [cond]}

    def _check(c, *vals):
        import numpy as _np

        if not _np.asarray(c).all():
            parts = [message] if message else []
            for v in vals:
                parts.append(str(_np.asarray(v).reshape(-1)[:summarize]))
            raise RuntimeError(
                "Assert failed: %s" % (" ".join(parts) or "<no message>"))

    heads = [d.reshape(-1)[:summarize] for d in data]
    jax.debug.callback(_check, cond, *heads)
    return {"Out": [cond]}
