"""Linear-algebra ops.

Capability parity: reference `paddle/fluid/operators/` kron_op.cc,
cholesky_op.cc, matrix_power_op.cc, inverse_op.cc, triangular_solve (in
newer tree), cross_op.cc, trace_op.cc, diag_op.cc/diag_embed_op.cc,
dist_op.cc, histogram_op.cc, bincount_op.cc, index_sample_op.cc and the
einsum/multi_dot python APIs.  One jnp/lax lowering per op — XLA supplies
the factorization/solve kernels the reference hand-wrote against
cuSOLVER/Eigen.
"""

import jax.numpy as jnp

from ..core.registry import register_op


@register_op("kron", inputs=["X", "Y"], outputs=["Out"])
def _kron(ctx, ins, attrs):
    return {"Out": [jnp.kron(ins["X"][0], ins["Y"][0])]}


@register_op("einsum", inputs=["Operands"], outputs=["Out"])
def _einsum(ctx, ins, attrs):
    return {"Out": [jnp.einsum(attrs["equation"], *ins["Operands"])]}


@register_op("cholesky", inputs=["X"], outputs=["Out"])
def _cholesky(ctx, ins, attrs):
    x = ins["X"][0]
    u = bool(attrs.get("upper", False))
    L = jnp.linalg.cholesky(x)
    return {"Out": [jnp.swapaxes(L, -1, -2) if u else L]}


@register_op("inverse", inputs=["Input"], outputs=["Output"])
def _inverse(ctx, ins, attrs):
    return {"Output": [jnp.linalg.inv(ins["Input"][0])]}


@register_op("matrix_power", inputs=["X"], outputs=["Out"])
def _matrix_power(ctx, ins, attrs):
    return {"Out": [jnp.linalg.matrix_power(ins["X"][0], int(attrs["n"]))]}


@register_op("triangular_solve", inputs=["X", "Y"], outputs=["Out"])
def _triangular_solve(ctx, ins, attrs):
    import jax

    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jax.scipy.linalg.solve_triangular(
        x, y,
        lower=not attrs.get("upper", True),
        trans=1 if attrs.get("transpose", False) else 0,
        unit_diagonal=attrs.get("unitriangular", False),
    )]}


@register_op("cross", inputs=["X", "Y"], outputs=["Out"])
def _cross(ctx, ins, attrs):
    axis = attrs.get("dim")
    if axis is None:  # first axis of size 3 (reference default)
        x = ins["X"][0]
        axis = next(i for i, s in enumerate(x.shape) if s == 3)
    return {"Out": [jnp.cross(ins["X"][0], ins["Y"][0], axis=int(axis))]}


@register_op("trace", inputs=["Input"], outputs=["Out"])
def _trace(ctx, ins, attrs):
    return {"Out": [jnp.trace(
        ins["Input"][0], offset=int(attrs.get("offset", 0)),
        axis1=int(attrs.get("axis1", 0)), axis2=int(attrs.get("axis2", 1)),
    )]}


@register_op("diag_v2", inputs=["X"], outputs=["Out"])
def _diag_v2(ctx, ins, attrs):
    x = ins["X"][0]
    k = int(attrs.get("offset", 0))
    if x.ndim == 1:
        out = jnp.diag(x, k=k)
        pad = attrs.get("padding_value", 0.0)
        if pad:
            mask = jnp.diag(jnp.ones_like(x), k=k)
            out = out + (1 - mask) * pad
        return {"Out": [out]}
    return {"Out": [jnp.diagonal(x, offset=k)]}


@register_op("diag_embed", inputs=["Input"], outputs=["Out"])
def _diag_embed(ctx, ins, attrs):
    x = ins["Input"][0]
    k = int(attrs.get("offset", 0))
    d1 = int(attrs.get("dim1", -2))
    d2 = int(attrs.get("dim2", -1))
    n = x.shape[-1] + abs(k)
    idx = jnp.arange(x.shape[-1])
    rows = idx + (abs(k) if k < 0 else 0)
    cols = idx + (k if k > 0 else 0)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    out = out.at[..., rows, cols].set(x)
    if (d1, d2) != (-2, -1):
        out = jnp.moveaxis(out, (-2, -1), (d1, d2))
    return {"Out": [out]}


@register_op("multi_dot", inputs=["X"], outputs=["Out"])
def _multi_dot(ctx, ins, attrs):
    return {"Out": [jnp.linalg.multi_dot(list(ins["X"]))]}


@register_op("dist", inputs=["X", "Y"], outputs=["Out"])
def _dist(ctx, ins, attrs):
    p = float(attrs.get("p", 2.0))
    d = (ins["X"][0] - ins["Y"][0]).reshape(-1)
    if p == float("inf"):
        return {"Out": [jnp.max(jnp.abs(d))]}
    if p == 0:
        return {"Out": [jnp.sum((d != 0).astype(d.dtype))]}
    return {"Out": [jnp.power(jnp.sum(jnp.power(jnp.abs(d), p)), 1.0 / p)]}


@register_op("histogram", inputs=["X"], outputs=["Out"], grad=None)
def _histogram(ctx, ins, attrs):
    x = ins["X"][0].reshape(-1)
    bins = int(attrs.get("bins", 100))
    lo = float(attrs.get("min", 0.0))
    hi = float(attrs.get("max", 0.0))
    if lo == 0.0 and hi == 0.0:
        lo, hi = jnp.min(x), jnp.max(x)
    h, _ = jnp.histogram(x, bins=bins, range=(lo, hi))
    return {"Out": [h.astype(jnp.int64)]}


@register_op("bincount", inputs=["X", "Weights"], outputs=["Out"],
             grad=None)
def _bincount(ctx, ins, attrs):
    x = ins["X"][0].reshape(-1)
    w = ins["Weights"][0].reshape(-1) if ins.get("Weights") else None
    # static shapes: minlength must cover the value range (attr, like the
    # reference's output resize after a device max-scan)
    length = int(attrs["minlength"])
    return {"Out": [jnp.bincount(x, weights=w, length=length)]}


@register_op("index_sample", inputs=["X", "Index"], outputs=["Out"],
             no_grad_slots=("Index",))
def _index_sample(ctx, ins, attrs):
    x, idx = ins["X"][0], ins["Index"][0]
    return {"Out": [jnp.take_along_axis(x, idx.astype(jnp.int32), axis=1)]}


# ---------------------------------------------------------------------------
# decompositions / solvers (reference qr_op.cc, svd_op.cc, eigh_op.cc,
# determinant_op.cc, solve/lstsq in the 2.x tree — XLA supplies the
# factorization kernels the reference bound to cuSOLVER)
# ---------------------------------------------------------------------------


@register_op("qr", inputs=["X"], outputs=["Q", "R"], grad=None)
def _qr(ctx, ins, attrs):
    mode = attrs.get("mode", "reduced")
    out = jnp.linalg.qr(ins["X"][0], mode=mode)
    if mode == "r":  # single-array return; Q slot gets an empty sentinel
        return {"Q": [jnp.zeros((0, 0), out.dtype)], "R": [out]}
    return {"Q": [out[0]], "R": [out[1]]}


@register_op("svd", inputs=["X"], outputs=["U", "S", "VH"], grad=None)
def _svd(ctx, ins, attrs):
    u, s, vh = jnp.linalg.svd(
        ins["X"][0], full_matrices=bool(attrs.get("full_matrices", False)))
    return {"U": [u], "S": [s], "VH": [vh]}


@register_op("eigh", inputs=["X"], outputs=["Eigenvalues", "Eigenvectors"],
             grad=None)
def _eigh(ctx, ins, attrs):
    uplo = attrs.get("UPLO", "L")
    w, v = jnp.linalg.eigh(ins["X"][0], symmetrize_input=True,
                           UPLO=uplo)
    return {"Eigenvalues": [w], "Eigenvectors": [v]}


@register_op("eigvalsh", inputs=["X"], outputs=["Eigenvalues"], grad=None)
def _eigvalsh(ctx, ins, attrs):
    return {"Eigenvalues": [jnp.linalg.eigvalsh(ins["X"][0])]}


@register_op("determinant", inputs=["Input"], outputs=["Out"])
def _determinant(ctx, ins, attrs):
    return {"Out": [jnp.linalg.det(ins["Input"][0])]}


@register_op("slogdeterminant", inputs=["Input"], outputs=["Sign", "Out"],
             grad=None)
def _slogdet(ctx, ins, attrs):
    sign, logdet = jnp.linalg.slogdet(ins["Input"][0])
    return {"Sign": [sign], "Out": [logdet]}


@register_op("pinv", inputs=["X"], outputs=["Out"], grad=None)
def _pinv(ctx, ins, attrs):
    return {"Out": [jnp.linalg.pinv(
        ins["X"][0], rtol=float(attrs.get("rcond", 1e-15)))]}


@register_op("solve", inputs=["X", "Y"], outputs=["Out"])
def _solve(ctx, ins, attrs):
    return {"Out": [jnp.linalg.solve(ins["X"][0], ins["Y"][0])]}


@register_op("lstsq", inputs=["X", "Y"], outputs=["Solution", "Residuals"],
             grad=None)
def _lstsq(ctx, ins, attrs):
    sol, res, _rank, _sv = jnp.linalg.lstsq(ins["X"][0], ins["Y"][0])
    return {"Solution": [sol], "Residuals": [res]}


@register_op("lu", inputs=["X"], outputs=["Out", "Pivots"], grad=None)
def _lu(ctx, ins, attrs):
    import jax.scipy.linalg as jsl

    lu, piv = jsl.lu_factor(ins["X"][0])
    return {"Out": [lu], "Pivots": [piv.astype(jnp.int32)]}


@register_op("matrix_rank", inputs=["X"], outputs=["Out"], grad=None)
def _matrix_rank(ctx, ins, attrs):
    # reference semantics: 'tol' is an ABSOLUTE singular-value threshold
    tol = attrs.get("tol", None)
    return {"Out": [jnp.linalg.matrix_rank(
        ins["X"][0], tol=tol).astype(jnp.int64)]}


@register_op("cholesky_solve", inputs=["X", "Y"], outputs=["Out"])
def _cholesky_solve(ctx, ins, attrs):
    import jax.scipy.linalg as jsl

    upper = bool(attrs.get("upper", False))
    # solve A x = b given the cholesky factor of A
    return {"Out": [jsl.cho_solve((ins["Y"][0], not upper), ins["X"][0])]}


@register_op("mv", inputs=["X", "Vec"], outputs=["Out"])
def _mv(ctx, ins, attrs):
    return {"Out": [ins["X"][0] @ ins["Vec"][0]]}
