"""NN ops: conv, pool, norms, dropout, embedding, losses.

Capability parity: reference `paddle/fluid/operators/` conv_op.cc (cudnn +
im2col paths), pool_op.cc, batch_norm_op.cc, layer_norm_op.cc, dropout_op.cc,
lookup_table_op.cc, softmax_with_cross_entropy_op.cc.  TPU-first: convs lower
to lax.conv_general_dilated (XLA picks the MXU tiling — the reference's
cudnn-algorithm search is subsumed by the compiler), norms are fused by XLA,
dropout uses counter-based stateless PRNG.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v), int(v))


@register_op("conv2d", inputs=["Input", "Filter"], outputs=["Output"])
def _conv2d(ctx, ins, attrs):
    """Conv (cf. conv_op.cc).  groups>1 -> feature_group_count.

    data_format NCHW (reference default) or NHWC — on TPU the NHWC form
    keeps channels on the lane (minor) dimension, which is what XLA's MXU
    tiling wants; the filter stays OIHW (paddle layout) either way."""
    x, w = ins["Input"][0], ins["Filter"][0]
    # AMP white-list behavior: a float input meets a lower-precision
    # filter (bf16 params under amp) at the filter's dtype
    if x.dtype != w.dtype and jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(w.dtype)
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = attrs.get("paddings", [0, 0])
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    fmt = attrs.get("data_format", attrs.get("data_layout", "NCHW"))
    if len(pads) == 2:
        padding = [(pads[0], pads[0]), (pads[1], pads[1])]
    else:  # [top, bottom, left, right]
        padding = [(pads[0], pads[1]), (pads[2], pads[3])]
    if isinstance(attrs.get("padding_algorithm"), str):
        alg = attrs["padding_algorithm"]
        if alg == "SAME":
            padding = "SAME"
        elif alg == "VALID":
            padding = "VALID"
    out = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=strides,
        padding=padding,
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=(fmt, "OIHW", fmt),
    )
    return {"Output": [out]}


@register_op("depthwise_conv2d", inputs=["Input", "Filter"], outputs=["Output"])
def _depthwise_conv2d(ctx, ins, attrs):
    attrs = dict(attrs)
    attrs["groups"] = int(ins["Input"][0].shape[1])
    from ..core.registry import get_op_def

    return get_op_def("conv2d").lower(ctx, ins, attrs)


@register_op(
    "conv2d_transpose", inputs=["Input", "Filter"], outputs=["Output"]
)
def _conv2d_transpose(ctx, ins, attrs):
    """cf. conv_transpose_op.cc.  Filter layout IOHW (paddle convention:
    [Cin, Cout/groups, kh, kw]).  Implemented as the standard fractionally-
    strided conv: lhs_dilation=stride, spatially-flipped kernel with I/O
    swapped, padding d*(k-1)-p — giving Paddle's output size
    (H-1)*stride - 2*pad + dilation*(kh-1) + 1.
    """
    x, w = ins["Input"][0], ins["Filter"][0]
    if x.dtype != w.dtype and jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(w.dtype)  # AMP: input follows the filter's precision
    strides = _pair(attrs.get("strides", [1, 1]))
    pads = _pair(attrs.get("paddings", [0, 0]))
    dilations = _pair(attrs.get("dilations", [1, 1]))
    groups = int(attrs.get("groups", 1))
    kh, kw = int(w.shape[2]), int(w.shape[3])
    if groups == 1:
        # IOHW -> OIHW with spatial flip
        w_t = jnp.flip(jnp.swapaxes(w, 0, 1), axis=(2, 3))
    else:
        # paddle filter [Cin, Cout/g, kh, kw]: per group swap I/O + flip,
        # concat along O so feature_group_count=g sees [Cout, Cin/g, k, k]
        cin = int(w.shape[0])
        wg = w.reshape(groups, cin // groups, w.shape[1], kh, kw)
        wg = jnp.flip(jnp.swapaxes(wg, 1, 2), axis=(3, 4))
        w_t = wg.reshape(groups * int(w.shape[1]), cin // groups, kh, kw)
    padding = [
        (dilations[0] * (kh - 1) - pads[0], dilations[0] * (kh - 1) - pads[0]),
        (dilations[1] * (kw - 1) - pads[1], dilations[1] * (kw - 1) - pads[1]),
    ]
    out = jax.lax.conv_general_dilated(
        x,
        w_t,
        window_strides=(1, 1),
        padding=padding,
        lhs_dilation=strides,
        rhs_dilation=dilations,
        feature_group_count=groups,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )
    return {"Output": [out]}


@register_op("pool2d", inputs=["X"], outputs=["Out"])
def _pool2d(ctx, ins, attrs):
    """max/avg pooling via reduce_window (cf. pool_op.cc); NCHW or NHWC."""
    x = ins["X"][0]
    ptype = attrs.get("pooling_type", "max")
    ksize = _pair(attrs.get("ksize", [2, 2]))
    strides = _pair(attrs.get("strides", ksize))
    pads = _pair(attrs.get("paddings", [0, 0]))
    fmt = attrs.get("data_format", attrs.get("data_layout", "NCHW"))
    h_ax, w_ax = (2, 3) if fmt == "NCHW" else (1, 2)
    if attrs.get("global_pooling", False):
        ksize = (x.shape[h_ax], x.shape[w_ax])
        strides = ksize
        pads = (0, 0)
    if attrs.get("adaptive", False):
        oh, ow = ksize
        ih, iw = x.shape[h_ax], x.shape[w_ax]
        if ih % oh or iw % ow:
            return _adaptive_pool_general(x, ptype, (oh, ow), h_ax)
        ksize = (ih // oh, iw // ow)
        strides = ksize
        pads = (0, 0)
    if fmt == "NCHW":
        window = (1, 1) + ksize
        strides4 = (1, 1) + strides
        padding = ((0, 0), (0, 0), (pads[0], pads[0]), (pads[1], pads[1]))
    else:
        window = (1,) + ksize + (1,)
        strides4 = (1,) + strides + (1,)
        padding = ((0, 0), (pads[0], pads[0]), (pads[1], pads[1]), (0, 0))
    if ptype == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides4, padding)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides4, padding)
        if attrs.get("exclusive", True) and pads != (0, 0):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, window, strides4, padding
            )
            out = summed / counts
        else:
            out = summed / (ksize[0] * ksize[1])
    return {"Out": [out.astype(x.dtype)]}


def _adaptive_pool_general(x, ptype, osize, h_ax):
    """Adaptive pool with non-divisible bins (cf. pool_op.cc AdaptStartIndex/
    AdaptEndIndex): bin i covers [floor(i*I/O), ceil((i+1)*I/O))."""
    oh, ow = osize
    ih, iw = x.shape[h_ax], x.shape[h_ax + 1]

    def bins(i_size, o_size):
        return [(i * i_size // o_size, -(-(i + 1) * i_size // o_size))
                for i in range(o_size)]

    red = jnp.max if ptype == "max" else jnp.mean
    rows = []
    for r0, r1 in bins(ih, oh):
        cols = []
        for c0, c1 in bins(iw, ow):
            sl = [slice(None)] * x.ndim
            sl[h_ax] = slice(r0, r1)
            sl[h_ax + 1] = slice(c0, c1)
            cols.append(red(x[tuple(sl)], axis=(h_ax, h_ax + 1)))
        rows.append(jnp.stack(cols, axis=h_ax))
    out = jnp.stack(rows, axis=h_ax)
    return {"Out": [out.astype(x.dtype)]}


import functools as _functools


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _bn_train_fused(x, scale, bias, c_axis, eps):
    y, m, rstd = _bn_train_fwd_impl(x, scale, bias, c_axis, eps)
    return y, m, rstd


def _bn_train_fwd_impl(x, scale, bias, c_axis, eps):
    """One-pass batch-norm training forward.

    TPU note: mean and E[x^2] are sibling reduces over the same input, so
    XLA fuses them into ONE read of x (jnp.var would serialize a second
    pass); the normalize is then a single fused multiply-add in x's dtype.
    The hand-written VJP below keeps the backward to two passes (one
    fused reduce pair over (dy, dy*x), one elementwise dx pass) instead
    of the larger graph JAX AD would emit.  cf. batch_norm_op.cc,
    batch_norm_op.cu (cuDNN fused path).

    Numerical robustness: plain E[x^2]-E[x]^2 cancels catastrophically
    when |mean| >> std, so the pass reduces (x-s) and (x-s)^2 where s is
    one sample per channel (x[0,...,0,:]) — a free shift within ~std of
    the true mean, bounding the relative cancellation error by
    ~eps*(1 + (m-s)^2/var) ~ 1e-6 instead of eps*m^2/var."""
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = tuple(x.shape[c_axis] if i == c_axis else 1
                   for i in range(x.ndim))
    idx = tuple(slice(None) if i == c_axis else 0 for i in range(x.ndim))
    shift = jax.lax.stop_gradient(x[idx].astype(jnp.float32))
    xs = x.astype(jnp.float32) - shift.reshape(bshape)
    d = jnp.mean(xs, axis=axes)          # sibling reduces: one pass
    d2 = jnp.mean(jnp.square(xs), axis=axes)
    m = shift + d
    v = jnp.maximum(d2 - d * d, 0.0)
    rstd = jax.lax.rsqrt(v + eps)
    s32 = scale.astype(jnp.float32)
    k = (s32 * rstd).astype(x.dtype)
    c = (bias.astype(jnp.float32) - m * s32 * rstd).astype(x.dtype)
    y = x * k.reshape(bshape) + c.reshape(bshape)
    return y, m, rstd


def _bn_train_f(x, scale, bias, c_axis, eps):
    y, m, rstd = _bn_train_fwd_impl(x, scale, bias, c_axis, eps)
    return (y, m, rstd), (x, scale, m, rstd)


def _bn_train_b(c_axis, eps, saved, cts):
    dy = cts[0]  # running-stat EMA outputs are stop_gradient'd by callers
    x, scale, m, rstd = saved
    axes = tuple(i for i in range(x.ndim) if i != c_axis)
    bshape = tuple(x.shape[c_axis] if i == c_axis else 1
                   for i in range(x.ndim))
    n = x.size // x.shape[c_axis]
    s_dy = jnp.sum(dy, axis=axes, dtype=jnp.float32)
    s_dyx = jnp.sum((dy * x).astype(jnp.float32), axis=axes)
    dgamma = (s_dyx - m * s_dy) * rstd
    dbeta = s_dy
    s32 = scale.astype(jnp.float32)
    k = (s32 * rstd).astype(x.dtype)
    g1 = (s_dy / n).astype(x.dtype)
    g2 = (dgamma * rstd / n).astype(x.dtype)
    mb = m.astype(x.dtype)
    dx = (k.reshape(bshape) * (dy - g1.reshape(bshape))
          - (k * g2).reshape(bshape) * (x - mb.reshape(bshape)))
    return dx, dgamma.astype(scale.dtype), dbeta.astype(scale.dtype)


_bn_train_fused.defvjp(_bn_train_f, _bn_train_b)


@register_op(
    "batch_norm",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    no_grad_slots=("Mean", "Variance"),
    stateful_out_slots=("MeanOut", "VarianceOut"),
)
def _batch_norm(ctx, ins, attrs):
    """cf. batch_norm_op.cc.  Training: batch stats + EMA update of running
    stats (MeanOut/VarianceOut alias the Mean/Variance persistables).
    The training path runs the fused one-pass implementation above."""
    x = ins["X"][0]
    scale, bias = ins["Scale"][0], ins["Bias"][0]
    mean, var = ins["Mean"][0], ins["Variance"][0]
    momentum = attrs.get("momentum", 0.9)
    eps = float(attrs.get("epsilon", 1e-5))
    is_test = attrs.get("is_test", False) or ctx.is_test
    layout = attrs.get("data_layout", attrs.get("data_format", "NCHW"))
    c_axis = 1 if layout == "NCHW" else x.ndim - 1
    bshape = tuple(x.shape[c_axis] if i == c_axis else 1 for i in range(x.ndim))

    if is_test:
        inv_std = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
        xh = (x.astype(jnp.float32) - mean.reshape(bshape)) \
            * inv_std.reshape(bshape)
        y = xh * scale.reshape(bshape) + bias.reshape(bshape)
        return {
            "Y": [y.astype(x.dtype)],
            "MeanOut": [mean],
            "VarianceOut": [var],
            "SavedMean": [mean.astype(jnp.float32)],
            "SavedVariance": [inv_std.astype(jnp.float32)],
        }

    y, use_mean, inv_std = _bn_train_fused(x, scale, bias, c_axis, eps)
    sm = jax.lax.stop_gradient(use_mean)
    sv = jax.lax.stop_gradient(
        jnp.maximum(1.0 / jnp.square(inv_std) - eps, 0.0))
    mean_out = mean * momentum + sm * (1 - momentum)
    var_out = var * momentum + sv * (1 - momentum)
    return {
        "Y": [y],
        "MeanOut": [mean_out.astype(mean.dtype)],
        "VarianceOut": [var_out.astype(var.dtype)],
        # Saved* are non-differentiable auxiliaries (the fused VJP only
        # propagates Y's cotangent, matching batch_norm_grad_op)
        "SavedMean": [sm.astype(jnp.float32)],
        "SavedVariance": [jax.lax.stop_gradient(inv_std).astype(jnp.float32)],
    }


@_functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _ln_fused(x, scale, bias, bna, eps):
    y, m, rstd = _ln_fwd_impl(x, scale, bias, bna, eps)
    return y, m, rstd


def _ln_fwd_impl(x, scale, bias, bna, eps):
    """Row-wise layer norm with a hand-written VJP.

    The VJP keeps the backward to (a) one fused pass producing the three
    row-reductions (sum dy*g, sum dy*g*xhat over the normalized dims)
    plus dx, and (b) one column-reduce pair for dgamma/dbeta — without
    it XLA fuses the dx math into neighbouring matmul epilogues into
    mega-fusions that run ~8x under roofline (measured on the BERT
    trunk).  cf. layer_norm_op.cc / layer_norm_grad."""
    axes = tuple(range(bna, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    y = (xf - mean) * rstd
    bshape = (1,) * bna + x.shape[bna:]
    if scale is not None:
        y = y * scale.reshape(bshape).astype(jnp.float32)
    if bias is not None:
        y = y + bias.reshape(bshape).astype(jnp.float32)
    return y.astype(x.dtype), mean, rstd


def _ln_f(x, scale, bias, bna, eps):
    y, m, rstd = _ln_fwd_impl(x, scale, bias, bna, eps)
    return (y, m, rstd), (x, scale, bias, m, rstd)


def _ln_b(bna, eps, saved, cts):
    dy = cts[0].astype(jnp.float32)
    x, scale, bias, m, rstd = saved
    axes = tuple(range(bna, x.ndim))
    n = _prod(x.shape[bna:])
    bshape = (1,) * bna + x.shape[bna:]
    xhat = (x.astype(jnp.float32) - m) * rstd
    g = dy if scale is None else dy * scale.reshape(bshape).astype(jnp.float32)
    mg = jnp.mean(g, axis=axes, keepdims=True)
    mgx = jnp.mean(g * xhat, axis=axes, keepdims=True)
    dx = rstd * (g - mg - xhat * mgx)
    # exact contributions of the mean/rstd outputs' cotangents (zero in
    # the usual stop_gradient'd training path — XLA folds the zeros):
    # d m/d x = 1/n; d rstd/d x = -rstd^3 (x-m)/n
    dm, dr = cts[1].astype(jnp.float32), cts[2].astype(jnp.float32)
    dx = dx + dm / n - dr * (rstd ** 3) * (x.astype(jnp.float32) - m) / n
    dx = dx.astype(x.dtype)
    red = tuple(range(bna))
    dscale = (jnp.sum(dy * xhat, axis=red).reshape(scale.shape)
              .astype(scale.dtype) if scale is not None else None)
    dbias = (jnp.sum(dy, axis=red).reshape(bias.shape).astype(bias.dtype)
             if bias is not None else None)
    return dx, dscale, dbias


_ln_fused.defvjp(_ln_f, _ln_b)


@register_op(
    "layer_norm",
    inputs=["X", "Scale", "Bias"],
    outputs=["Y", "Mean", "Variance"],
)
def _layer_norm(ctx, ins, attrs):
    """cf. layer_norm_op.cc: normalize over dims >= begin_norm_axis."""
    x = ins["X"][0]
    eps = float(attrs.get("epsilon", 1e-5))
    bna = attrs.get("begin_norm_axis", 1)
    scale = ins["Scale"][0] if ins.get("Scale") else None
    bias = ins["Bias"][0] if ins.get("Bias") else None
    y, mean, rstd = _ln_fused(x, scale, bias, bna, eps)
    flat = (int(_prod(x.shape[:bna])),)
    var = jax.lax.stop_gradient(
        jnp.maximum(1.0 / jnp.square(rstd) - eps, 0.0))
    return {
        "Y": [y],
        "Mean": [jax.lax.stop_gradient(mean).reshape(flat)],
        "Variance": [var.reshape(flat)],
    }


def _prod(xs):
    r = 1
    for v in xs:
        r *= int(v)
    return r


@register_op(
    "dropout",
    inputs=["X"],
    outputs=["Out", "Mask"],
    grad="dropout_grad_maker",
    needs_rng=True,
)
def _dropout(ctx, ins, attrs):
    """cf. dropout_op.cc.  Stateless threefry key per op instance."""
    x = ins["X"][0]
    p = attrs.get("dropout_prob", 0.5)
    is_test = attrs.get("is_test", False) or ctx.is_test
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if is_test:
        out = x * (1.0 - p) if impl == "downgrade_in_infer" else x
        return {"Out": [out.astype(x.dtype)], "Mask": [jnp.ones_like(x, dtype=jnp.uint8)]}
    keep = jax.random.bernoulli(ctx.rng(), 1.0 - p, x.shape)
    if impl == "upscale_in_train":
        out = jnp.where(keep, x / (1.0 - p), 0.0)
    else:
        out = jnp.where(keep, x, 0.0)
    return {"Out": [out.astype(x.dtype)], "Mask": [keep.astype(jnp.uint8)]}


@register_op("dropout_grad", inputs=["Mask", "Out@GRAD"], outputs=["X@GRAD"], grad=None)
def _dropout_grad(ctx, ins, attrs):
    g = ins["Out@GRAD"][0]
    mask = ins["Mask"][0].astype(g.dtype)
    p = attrs.get("dropout_prob", 0.5)
    impl = attrs.get("dropout_implementation", "downgrade_in_infer")
    if impl == "upscale_in_train":
        gx = g * mask / (1.0 - p)
    else:
        gx = g * mask
    return {"X@GRAD": [gx]}


@register_op(
    "flash_attention",
    inputs=["Q", "K", "V", "Bias", "QSeg", "KSeg"],
    outputs=["Out"],
    no_grad_slots=("QSeg", "KSeg"),
)
def _flash_attention(ctx, ins, attrs):
    """Fused scaled-dot-product attention.

    Capability parity: reference fused attention
    (`operators/fused/multihead_matmul_op.cu`,
    `ir/multihead_matmul_fuse_pass.cc`) — there it is a graph-fusion pass +
    hand CUDA; here it is a single op whose TPU lowering is a pallas
    flash-attention kernel (ops/pallas/attention.py) and whose oracle path
    is the naive jnp composition XLA fuses on CPU.

    Q/K/V: [batch, heads, seq, head_dim] (attrs layout="BHSD", default)
    or [batch, seq, heads, head_dim] ("BSHD", the TPU-fast layout — no
    head transposes materialize); optional Bias broadcastable to
    [batch, heads, q_seq, k_seq] (additive, pre-softmax).  Optional
    QSeg/KSeg: [batch, seq] int segment ids for packed batches (in-graph
    LoD parity) — attention is confined to equal ids.  attrs: scale
    (default 1/sqrt(head_dim)), causal, layout.
    """
    q, k, v = ins["Q"][0], ins["K"][0], ins["V"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    qseg = ins["QSeg"][0] if ins.get("QSeg") else None
    kseg = ins["KSeg"][0] if ins.get("KSeg") else None
    if kseg is not None and qseg is None:
        raise ValueError(
            "flash_attention: KSeg without QSeg is meaningless (equality "
            "masking needs both sides); feed QSeg too"
        )
    segment_ids = None
    if qseg is not None:
        segment_ids = (qseg, kseg if kseg is not None else qseg)
    scale = attrs.get("scale") or float(q.shape[-1]) ** -0.5
    causal = attrs.get("causal", False)
    layout = attrs.get("layout", "BHSD")

    from ...ops.attention import scaled_dot_product_attention

    out = scaled_dot_product_attention(q, k, v, bias=bias,
                                       segment_ids=segment_ids,
                                       scale=scale, causal=causal,
                                       layout=layout)
    return {"Out": [out]}


@register_op(
    "switch_moe",
    inputs=["X", "GateW", "W1", "B1", "W2", "B2"],
    outputs=["Out", "AuxLoss"],
)
def _switch_moe(ctx, ins, attrs):
    """Switch-style top-1 mixture-of-experts FFN (expert parallelism).

    Capability parity: the reference has no MoE (SURVEY §2.3 — EP absent);
    this is a new TPU-native capability.  Einsum dispatch/combine with a
    capacity limit (GShard pattern) keeps everything dense and MXU-shaped;
    the expert dim of W1/W2 shards on the `ep` mesh axis under GSPMD, which
    inserts the all-to-alls the dispatch implies.

    X: [tokens, d]; GateW: [d, E]; W1: [E, d, h]; B1: [E, h];
    W2: [E, h, d]; B2: [E, d].  attrs: capacity_factor (default 1.25),
    top_k (1 = Switch, 2 = GShard top-2 with renormalized gates),
    z_loss_weight (router z-loss, ST-MoE: mean(logsumexp(logits)^2),
    folded into AuxLoss).
    AuxLoss: load-balancing loss (fraction*prob * E over the RANK-0
    routing choice, the Switch/GShard convention) + z_loss_weight *
    z_loss.
    """
    x = ins["X"][0]
    gw = ins["GateW"][0]
    w1, b1 = ins["W1"][0], ins["B1"][0]
    w2, b2 = ins["W2"][0], ins["B2"][0]
    t, d = x.shape
    e = gw.shape[1]
    top_k = int(attrs.get("top_k", 1))
    cap = int(attrs.get("capacity_factor", 1.25) * top_k * t / e + 1)

    xf = x.astype(jnp.float32)
    logits = xf @ gw.astype(jnp.float32)  # [t, E]
    probs = jax.nn.softmax(logits, axis=-1)

    # top-k routing choices (GShard: rank-0 tokens claim capacity first)
    masked = probs
    chosen, gates = [], []
    for _ in range(top_k):
        exp_r = jnp.argmax(masked, axis=-1)          # [t]
        chosen.append(exp_r)
        gates.append(jnp.take_along_axis(
            probs, exp_r[:, None], axis=1)[:, 0])
        masked = masked * (1.0 - jax.nn.one_hot(exp_r, e))
    if top_k > 1:                                    # renormalize gates
        denom = sum(gates) + 1e-9
        gates = [g / denom for g in gates]

    # capacity positions over ALL choices: rank-0 assignments occupy
    # buffers before rank-1 (concatenate along the token axis)
    onehots = [jax.nn.one_hot(c, e, dtype=jnp.int32) for c in chosen]
    stacked = jnp.concatenate(onehots, axis=0)       # [k*t, E]
    pos_all = jnp.cumsum(stacked, axis=0) * stacked - 1

    out = jnp.zeros((t, d), jnp.float32)
    xin = jnp.zeros((e, cap, d), jnp.float32)
    disps = []
    for r in range(top_k):
        pos_r = jnp.sum(pos_all[r * t:(r + 1) * t] * onehots[r], axis=-1)
        keep = pos_r < cap
        disp = (
            onehots[r].astype(jnp.float32)[:, :, None]
            * jax.nn.one_hot(jnp.where(keep, pos_r, cap), cap + 1,
                             dtype=jnp.float32)[:, None, :cap]
        )
        disps.append(disp)
        xin = xin + jnp.einsum("tec,td->ecd", disp, xf)
    h = jnp.einsum("ecd,edh->ech", xin, w1.astype(jnp.float32))
    h = jax.nn.gelu(h + b1.astype(jnp.float32)[:, None, :])
    y = jnp.einsum("ech,ehd->ecd", h, w2.astype(jnp.float32))
    y = y + b2.astype(jnp.float32)[:, None, :]
    for r in range(top_k):
        out = out + jnp.einsum("tec,ecd->td", disps[r], y)             * gates[r][:, None]

    # Switch/GShard load-balancing aux loss over the rank-0 choice
    frac = jnp.mean(onehots[0].astype(jnp.float32), axis=0)  # [E]
    prob_mean = jnp.mean(probs, axis=0)  # [E]
    aux = jnp.sum(frac * prob_mean) * e
    zw = float(attrs.get("z_loss_weight", 0.0))
    if zw:
        z = jnp.mean(jax.scipy.special.logsumexp(logits, axis=-1) ** 2)
        aux = aux + zw * z
    return {"Out": [out.astype(x.dtype)], "AuxLoss": [aux]}


@register_op(
    "group_norm",
    inputs=["X", "Scale", "Bias"],
    outputs=["Y", "Mean", "Variance"],
)
def _group_norm(ctx, ins, attrs):
    """cf. group_norm_op.cc: normalize per (N, group) over grouped channels
    and spatial dims; NCHW layout."""
    x = ins["X"][0]
    g = attrs["groups"]
    eps = attrs.get("epsilon", 1e-5)
    n, c = x.shape[0], x.shape[1]
    xf = x.astype(jnp.float32).reshape((n, g, c // g) + x.shape[2:])
    axes = tuple(range(2, xf.ndim))
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    y = ((xf - mean) * jax.lax.rsqrt(var + eps)).reshape(x.shape)
    bshape = (1, c) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(bshape).astype(jnp.float32)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(bshape).astype(jnp.float32)
    return {
        "Y": [y.astype(x.dtype)],
        "Mean": [mean.reshape(n, g)],
        "Variance": [var.reshape(n, g)],
    }


@register_op(
    "lookup_table",
    inputs=["W", "Ids"],
    outputs=["Out"],
    no_grad_slots=("Ids",),
    grad="lookup_table_grad_maker",
)
def _lookup_table(ctx, ins, attrs):
    """Embedding gather (cf. lookup_table_op.cc).  padding_idx rows zeroed."""
    w, ids = ins["W"][0], ins["Ids"][0]
    padding_idx = attrs.get("padding_idx", -1)
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    out = jnp.take(w, ids, axis=0)
    if padding_idx is not None and padding_idx >= 0:
        out = jnp.where((ids == padding_idx)[..., None], 0.0, out)
    return {"Out": [out]}


register_op("lookup_table_v2", inputs=["W", "Ids"], outputs=["Out"],
            no_grad_slots=("Ids",), grad="lookup_table_grad_maker")(
    _lookup_table
)


@register_op(
    "lookup_table_sparse_grad",
    inputs=["Ids", "OutGrad"],
    outputs=["Rows", "Values"],
    grad=None,
)
def _lookup_table_sparse_grad(ctx, ins, attrs):
    """SelectedRows-style embedding gradient (cf. `selected_rows.h:1`,
    lookup_table_op.cc grad SelectedRows branch): the gradient of the big
    table is (Rows, Values) — the looked-up ids and the per-id output
    grads — NEVER a dense [V, D] scatter.  padding_idx rows contribute 0."""
    ids = ins["Ids"][0]
    g = ins["OutGrad"][0]
    if ids.ndim >= 2 and ids.shape[-1] == 1:
        ids = ids.squeeze(-1)
    rows = ids.reshape(-1).astype(jnp.int32)
    d = g.shape[-1]
    vals = g.reshape(-1, d)
    padding_idx = attrs.get("padding_idx", -1)
    if padding_idx is not None and padding_idx >= 0:
        vals = jnp.where((rows == padding_idx)[:, None], 0.0, vals)
    return {"Rows": [rows], "Values": [vals]}


@register_op(
    "softmax_with_cross_entropy",
    inputs=["Logits", "Label"],
    outputs=["Softmax", "Loss"],
    no_grad_slots=("Label",),
)
def _softmax_with_cross_entropy(ctx, ins, attrs):
    """cf. softmax_with_cross_entropy_op.cc — numerically-stable fused path;
    XLA fuses log_softmax+gather into one kernel, grad via auto-VJP is the
    canonical (softmax - onehot) form after simplification."""
    logits, label = ins["Logits"][0], ins["Label"][0]
    axis = attrs.get("axis", -1)
    soft_label = attrs.get("soft_label", False)
    ignore_index = attrs.get("ignore_index", -100)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
    softmax = jnp.exp(logp)
    if soft_label:
        loss = -jnp.sum(label.astype(jnp.float32) * logp, axis=axis, keepdims=True)
    else:
        lab = label
        squeezed = False
        if lab.ndim == logits.ndim and lab.shape[axis] == 1:
            lab = jnp.squeeze(lab, axis=axis)
            squeezed = True
        loss = -jnp.take_along_axis(
            logp, jnp.expand_dims(lab, axis), axis=axis
        )
        valid = (lab != ignore_index)
        loss = jnp.where(jnp.expand_dims(valid, axis), loss, 0.0)
    return {"Softmax": [softmax.astype(logits.dtype)], "Loss": [loss.astype(logits.dtype)]}


@register_op(
    "cross_entropy", inputs=["X", "Label"], outputs=["Y"], no_grad_slots=("Label",)
)
def _cross_entropy(ctx, ins, attrs):
    """cf. cross_entropy_op.cc: input is a probability distribution."""
    x, label = ins["X"][0], ins["Label"][0]
    soft_label = attrs.get("soft_label", False)
    eps = 1e-8
    logp = jnp.log(jnp.clip(x, eps, 1.0))
    if soft_label:
        y = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lab = label
        if lab.ndim == x.ndim and lab.shape[-1] == 1:
            lab = jnp.squeeze(lab, -1)
        y = -jnp.take_along_axis(logp, jnp.expand_dims(lab, -1), axis=-1)
    return {"Y": [y]}


@register_op("mse_loss", inputs=["X", "Y"], outputs=["Out"])
def _mse(ctx, ins, attrs):
    d = ins["X"][0] - ins["Y"][0]
    return {"Out": [jnp.mean(jnp.square(d))]}


@register_op("square_error_cost", inputs=["X", "Y"], outputs=["Out"])
def _square_error_cost(ctx, ins, attrs):
    d = ins["X"][0] - ins["Y"][0]
    return {"Out": [jnp.square(d)]}


@register_op(
    "huber_loss", inputs=["X", "Y"], outputs=["Out", "Residual"]
)
def _huber(ctx, ins, attrs):
    delta = attrs.get("delta", 1.0)
    r = ins["Y"][0] - ins["X"][0]
    absr = jnp.abs(r)
    out = jnp.where(absr <= delta, 0.5 * r * r, delta * (absr - 0.5 * delta))
    return {"Out": [out], "Residual": [r]}


@register_op(
    "sigmoid_cross_entropy_with_logits",
    inputs=["X", "Label"],
    outputs=["Out"],
    no_grad_slots=("Label",),
)
def _sce_logits(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]
    out = jnp.maximum(x, 0) - x * label + jnp.log1p(jnp.exp(-jnp.abs(x)))
    return {"Out": [out]}


@register_op("prelu", inputs=["X", "Alpha"], outputs=["Out"])
def _prelu(ctx, ins, attrs):
    x, alpha = ins["X"][0], ins["Alpha"][0]
    mode = attrs.get("mode", "all")
    if mode == "channel":
        alpha = alpha.reshape((1, -1) + (1,) * (x.ndim - 2))
    return {"Out": [jnp.where(x >= 0, x, alpha * x)]}
