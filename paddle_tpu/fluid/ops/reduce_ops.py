"""Reduction ops (cf. paddle/fluid/operators/reduce_ops/, mean_op.cc,
arg_min_max ops, top_k_op.cc, argsort_op.cc)."""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _axes(attrs, ndim):
    if attrs.get("reduce_all", False):
        return None
    dim = attrs.get("dim", [0])
    if isinstance(dim, int):
        dim = [dim]
    return tuple(d if d >= 0 else d + ndim for d in dim)


def _register_reduce(name, fn):
    @register_op("reduce_" + name, inputs=["X"], outputs=["Out"])
    def _lower(ctx, ins, attrs, fn=fn):
        x = ins["X"][0]
        out = fn(x, axis=_axes(attrs, x.ndim), keepdims=attrs.get("keep_dim", False))
        return {"Out": [out]}


_register_reduce("sum", jnp.sum)
_register_reduce("mean", jnp.mean)
_register_reduce("max", jnp.max)
_register_reduce("min", jnp.min)
_register_reduce("prod", jnp.prod)


@register_op("reduce_any", inputs=["X"], outputs=["Out"], grad=None)
def _reduce_any(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.any(x, axis=_axes(attrs, x.ndim), keepdims=attrs.get("keep_dim", False))]}


@register_op("reduce_all", inputs=["X"], outputs=["Out"], grad=None)
def _reduce_all(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.all(x, axis=_axes(attrs, x.ndim), keepdims=attrs.get("keep_dim", False))]}


@register_op("mean", inputs=["X"], outputs=["Out"])
def _mean(ctx, ins, attrs):
    return {"Out": [jnp.mean(ins["X"][0])]}


@register_op("arg_max", inputs=["X"], outputs=["Out"], grad=None)
def _arg_max(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    out = jnp.argmax(x, axis=axis)
    if attrs.get("keepdims", False):
        out = jnp.expand_dims(out, axis)
    return {"Out": [out.astype(jnp.int64)]}


@register_op("arg_min", inputs=["X"], outputs=["Out"], grad=None)
def _arg_min(ctx, ins, attrs):
    x = ins["X"][0]
    out = jnp.argmin(x, axis=attrs.get("axis", -1))
    return {"Out": [out.astype(jnp.int64)]}


@register_op("top_k", inputs=["X"], outputs=["Out", "Indices"], grad=None)
def _top_k(ctx, ins, attrs):
    vals, idx = jax.lax.top_k(ins["X"][0], attrs.get("k", 1))
    return {"Out": [vals], "Indices": [idx.astype(jnp.int64)]}


register_op("top_k_v2", inputs=["X"], outputs=["Out", "Indices"], grad=None)(_top_k)


@register_op("argsort", inputs=["X"], outputs=["Out", "Indices"], grad=None)
def _argsort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    desc = attrs.get("descending", False)
    idx = jnp.argsort(-x if desc else x, axis=axis)
    out = jnp.take_along_axis(x, idx, axis=axis)
    return {"Out": [out], "Indices": [idx.astype(jnp.int64)]}


@register_op("max", inputs=["X"], outputs=["Out"])
def _max(ctx, ins, attrs):
    x = ins["X"][0]
    return {"Out": [jnp.max(x, axis=_axes(attrs, x.ndim), keepdims=attrs.get("keep_dim", False))]}


@register_op("norm", inputs=["X"], outputs=["Out", "Norm"])
def _norm(ctx, ins, attrs):
    """L2-normalize along axis (cf. norm_op.cc)."""
    x = ins["X"][0]
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    n = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": [x / n], "Norm": [n]}


@register_op("p_norm", inputs=["X"], outputs=["Out"])
def _p_norm(ctx, ins, attrs):
    x = ins["X"][0]
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis", -1)
    keepdim = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdim) ** (1.0 / p)
    return {"Out": [out]}


@register_op("squared_l2_norm", inputs=["X"], outputs=["Out"])
def _squared_l2_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.square(ins["X"][0])).reshape((1,))]}
