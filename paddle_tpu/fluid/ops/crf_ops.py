"""Sequence-labeling ops: linear-chain CRF, Viterbi decode, chunk eval,
edit distance, CTC loss.

Capability parity: reference `paddle/fluid/operators/linear_chain_crf_op.cc`,
`crf_decoding_op.cc`, `chunk_eval_op.cc`, `edit_distance_op.cc`,
`warpctc_op.cc`.  TPU-first redesign: the reference walks LoD offset tables
sequence-by-sequence on the CPU; here every op runs on padded-dense
``[B, T, ...]`` batches with an explicit ``Length [B]`` input, the dynamic
programs (forward algorithm, Viterbi, Levenshtein, CTC alpha) are
``lax.scan`` recurrences in log space — fixed shapes, fully batched, and
(for CRF/CTC) differentiable by the auto-VJP path instead of hand-written
grad kernels.

Transition layout follows the reference exactly (`linear_chain_crf_op.cc`
comment block): ``Transition`` is ``[N+2, N]`` where row 0 holds start
weights a, row 1 end weights b, and rows 2.. the pairwise matrix
w[i, j] = score of moving from tag i to tag j.
"""

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.special import logsumexp

from ..core.registry import register_op


def _split_transition(transition):
    return transition[0], transition[1], transition[2:]


def _crf_forward(emission, transition, lens):
    """Returns (alpha [B,T,N], logZ [B]) of the masked forward algorithm."""
    B, T, N = emission.shape
    start, end, trans = _split_transition(transition)
    alpha0 = emission[:, 0] + start[None, :]

    def step(alpha, xs):
        emit_t, valid = xs  # [B,N], [B]
        nxt = logsumexp(alpha[:, :, None] + trans[None], axis=1) + emit_t
        alpha = jnp.where(valid[:, None], nxt, alpha)
        return alpha, alpha

    if T > 1:
        valid = (jnp.arange(1, T)[:, None] < lens[None, :])  # [T-1, B]
        alphaT, alphas = lax.scan(
            step, alpha0, (emission[:, 1:].transpose(1, 0, 2), valid)
        )
        alpha = jnp.concatenate(
            [alpha0[:, None], alphas.transpose(1, 0, 2)], axis=1
        )
    else:
        alphaT, alpha = alpha0, alpha0[:, None]
    logZ = logsumexp(alphaT + end[None, :], axis=1)
    return alpha, logZ


def _gold_score(emission, transition, label, lens):
    B, T, N = emission.shape
    start, end, trans = _split_transition(transition)
    pos = jnp.arange(T)
    label = jnp.clip(label, 0, N - 1)
    maskv = pos[None, :] < lens[:, None]
    emit_sc = jnp.take_along_axis(emission, label[..., None], axis=2)[..., 0]
    score = jnp.sum(jnp.where(maskv, emit_sc, 0.0), axis=1)
    if T > 1:
        tr = trans[label[:, :-1], label[:, 1:]]  # [B, T-1]
        maskt = pos[None, 1:] < lens[:, None]
        score = score + jnp.sum(jnp.where(maskt, tr, 0.0), axis=1)
    last = jnp.take_along_axis(
        label, jnp.maximum(lens - 1, 0)[:, None], axis=1
    )[:, 0]
    return score + start[label[:, 0]] + end[last]


@register_op("linear_chain_crf",
             inputs=["Emission", "Transition", "Label", "Length"],
             outputs=["LogLikelihood", "Alpha"],
             no_grad_slots=("Label", "Length"))
def _linear_chain_crf(ctx, ins, attrs):
    """cf. linear_chain_crf_op.cc: per-sequence CRF cost.

    LogLikelihood is the NEGATIVE log conditional likelihood
    -log P(label | emission) as in the reference (its output is minimized
    directly by the book SRL model), shape [B, 1].
    """
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    label = ins["Label"][0]
    if label.ndim == 3:
        label = label[..., 0]
    lens = ins["Length"][0]
    alpha, logZ = _crf_forward(emission, transition, lens)
    score = _gold_score(emission, transition, label, lens)
    nll = (logZ - score)[:, None]
    return {"LogLikelihood": [nll], "Alpha": [alpha]}


@register_op("crf_decoding",
             inputs=["Emission", "Transition", "Label", "Length"],
             outputs=["ViterbiPath"], grad=None)
def _crf_decoding(ctx, ins, attrs):
    """cf. crf_decoding_op.cc: masked Viterbi decode.

    Without Label: ViterbiPath [B, T] int64 holds the best tag sequence
    (padding positions are 0).  With Label: reference semantics — the
    output is 1 where the decoded tag equals the label, else 0.
    """
    emission = ins["Emission"][0]
    transition = ins["Transition"][0]
    lens = ins["Length"][0]
    B, T, N = emission.shape
    start, end, trans = _split_transition(transition)

    delta0 = emission[:, 0] + start[None, :]
    if T > 1:
        def step(delta, xs):
            emit_t, valid = xs
            scores = delta[:, :, None] + trans[None]       # [B, N, N]
            best_prev = jnp.argmax(scores, axis=1)          # [B, N]
            nxt = jnp.max(scores, axis=1) + emit_t
            delta = jnp.where(valid[:, None], nxt, delta)
            # padding steps keep identity backpointers so backtracking
            # through them is a no-op
            bp = jnp.where(valid[:, None], best_prev,
                           jnp.arange(N)[None, :])
            return delta, bp

        valid = (jnp.arange(1, T)[:, None] < lens[None, :])
        deltaT, bps = lax.scan(
            step, delta0, (emission[:, 1:].transpose(1, 0, 2), valid)
        )
        last_tag = jnp.argmax(deltaT + end[None, :], axis=1)  # [B]

        def back(tag, bp):
            prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
            return prev, prev

        _, tags = lax.scan(back, last_tag, bps, reverse=True)  # [T-1, B]
        path = jnp.concatenate([tags, last_tag[None, :]], axis=0).T
    else:
        path = jnp.argmax(delta0 + end[None, :], axis=1)[:, None]
    maskv = jnp.arange(T)[None, :] < lens[:, None]
    path = jnp.where(maskv, path, 0).astype(jnp.int64)
    if ins.get("Label"):
        label = ins["Label"][0]
        if label.ndim == 3:
            label = label[..., 0]
        path = jnp.where(maskv, (path == label).astype(jnp.int64), 0)
    return {"ViterbiPath": [path]}


def _chunk_bounds(tags, lens, scheme, num_chunk_types):
    """Per-position (is_start, is_end, chunk_type, in_chunk) under
    IOB / IOE / IOBES / plain tag schemes (conlleval-style boundary rules,
    cf. chunk_eval_op.cc Segment semantics)."""
    B, T = tags.shape
    n_tag = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    other = num_chunk_types * n_tag  # any tag >= this is "outside"
    inside = tags < other
    ctype = jnp.where(inside, tags // n_tag, -1)
    ttype = jnp.where(inside, tags % n_tag, -1)

    prev_ct = jnp.concatenate([jnp.full((B, 1), -2), ctype[:, :-1]], axis=1)
    next_ct = jnp.concatenate([ctype[:, 1:], jnp.full((B, 1), -2)], axis=1)
    prev_tt = jnp.concatenate([jnp.full((B, 1), -2), ttype[:, :-1]], axis=1)
    next_tt = jnp.concatenate([ttype[:, 1:], jnp.full((B, 1), -2)], axis=1)

    pos = jnp.arange(T)[None, :]
    valid = pos < lens[:, None]
    is_first = pos == 0
    is_last = pos == (lens[:, None] - 1)
    prev_out = is_first | (prev_ct < 0) | (prev_ct != ctype)
    next_out = is_last | (next_ct < 0) | (next_ct != ctype)

    if scheme == "plain":
        is_start = inside
        is_end = inside
    elif scheme == "IOB":  # B=0, I=1
        is_start = inside & ((ttype == 0) | prev_out)
        is_end = inside & (next_out | (next_tt == 0))
    elif scheme == "IOE":  # I=0, E=1
        is_start = inside & (prev_out | (prev_tt == 1))
        is_end = inside & ((ttype == 1) | next_out)
    else:  # IOBES: B=0, I=1, E=2, S=3
        # an I after E/S (orphan continuation) starts a fresh chunk; an I
        # before B/S ends the open one (conlleval behavior)
        is_start = inside & ((ttype == 0) | (ttype == 3) | prev_out
                             | (prev_tt == 2) | (prev_tt == 3))
        is_end = inside & ((ttype == 2) | (ttype == 3) | next_out
                           | (next_tt == 0) | (next_tt == 3))
    return is_start & valid, is_end & valid, ctype


@register_op("chunk_eval",
             inputs=["Inference", "Label", "Length"],
             outputs=["Precision", "Recall", "F1-Score",
                      "NumInferChunks", "NumLabelChunks",
                      "NumCorrectChunks"],
             grad=None)
def _chunk_eval(ctx, ins, attrs):
    """cf. chunk_eval_op.cc: chunk-level precision/recall/F1 for sequence
    labeling (NER/SRL).  A predicted chunk is correct iff a gold chunk has
    the SAME (begin, end, type) triple — computed here by one masked scan
    instead of the reference's per-sequence segment walk."""
    inf = ins["Inference"][0]
    lab = ins["Label"][0]
    if inf.ndim == 3:
        inf = inf[..., 0]
    if lab.ndim == 3:
        lab = lab[..., 0]
    lens = ins["Length"][0]
    scheme = attrs.get("chunk_scheme", "IOB")
    num_chunk_types = int(attrs["num_chunk_types"])
    excluded = attrs.get("excluded_chunk_types", []) or []

    si, ei, ti = _chunk_bounds(inf, lens, scheme, num_chunk_types)
    sl, el, tl = _chunk_bounds(lab, lens, scheme, num_chunk_types)
    if excluded:
        exc = jnp.asarray(list(excluded))
        keep_i = ~jnp.isin(ti, exc)
        keep_l = ~jnp.isin(tl, exc)
        si, ei = si & keep_i, ei & keep_i
        sl, el = sl & keep_l, el & keep_l

    n_inf = jnp.sum(si)
    n_lab = jnp.sum(sl)

    # single pass: a match opens when both sequences start a chunk of the
    # same type at t and survives until both close it at the same t
    def step(open_, xs):
        s_i, s_l, e_i, e_l, ty_eq = xs
        open_ = jnp.where(s_i & s_l & ty_eq, True, open_ & ~(s_i | s_l))
        corr = open_ & e_i & e_l
        open_ = open_ & ~(e_i | e_l)
        return open_, corr

    xs = (si.T, sl.T, ei.T, el.T, (ti == tl).T)
    _, corr = lax.scan(step, jnp.zeros(inf.shape[0], bool), xs)
    n_corr = jnp.sum(corr)

    f = jnp.float32
    prec = jnp.where(n_inf > 0, n_corr / jnp.maximum(n_inf, 1), 0.0).astype(f)
    rec = jnp.where(n_lab > 0, n_corr / jnp.maximum(n_lab, 1), 0.0).astype(f)
    f1 = jnp.where(prec + rec > 0, 2 * prec * rec /
                   jnp.maximum(prec + rec, 1e-12), 0.0).astype(f)
    i64 = jnp.int64
    return {
        "Precision": [prec[None]], "Recall": [rec[None]],
        "F1-Score": [f1[None]],
        "NumInferChunks": [n_inf.astype(i64)[None]],
        "NumLabelChunks": [n_lab.astype(i64)[None]],
        "NumCorrectChunks": [n_corr.astype(i64)[None]],
    }


@register_op("edit_distance",
             inputs=["Hyps", "HypsLength", "Refs", "RefsLength"],
             outputs=["Out", "SequenceNum"], grad=None)
def _edit_distance(ctx, ins, attrs):
    """cf. edit_distance_op.cc: batched Levenshtein distance.

    The row recurrence's in-row dependency (insertions) is resolved with a
    cumulative min — new_row[j] = j-offset + cummin(tmp[k] - k) — so each
    DP row is one vectorized step of a lax.scan over hypothesis tokens.
    """
    hyps, hlen = ins["Hyps"][0], ins["HypsLength"][0]
    refs, rlen = ins["Refs"][0], ins["RefsLength"][0]
    B, T1 = hyps.shape
    T2 = refs.shape[1]
    f = jnp.float32
    jcol = jnp.arange(T2 + 1, dtype=f)
    row0 = jnp.broadcast_to(jcol, (B, T2 + 1))

    def step(prev_row, h_t):
        sub = (refs != h_t[:, None]).astype(f)                  # [B, T2]
        tmp = jnp.minimum(prev_row[:, :-1] + sub, prev_row[:, 1:] + 1.0)
        tmp = jnp.concatenate([prev_row[:, :1] + 1.0, tmp], axis=1)
        new_row = jcol[None, :] + lax.cummin(tmp - jcol[None, :], axis=1)
        return new_row, new_row

    _, rows = lax.scan(step, row0, hyps.T)                      # [T1, B, T2+1]
    table = jnp.concatenate([row0[None], rows], axis=0)         # [T1+1, B, T2+1]
    d = table[hlen, jnp.arange(B), rlen]                        # [B]
    if attrs.get("normalized", True):
        d = d / jnp.maximum(rlen.astype(f), 1.0)
    return {"Out": [d[:, None]],
            "SequenceNum": [jnp.asarray([B], jnp.int64)]}


@register_op("warpctc",
             inputs=["Logits", "LogitsLength", "Label", "LabelLength"],
             outputs=["Loss"],
             no_grad_slots=("LogitsLength", "Label", "LabelLength"))
def _warpctc(ctx, ins, attrs):
    """cf. warpctc_op.cc: CTC loss.  The external warp-ctc library's
    alpha recursion becomes a log-space lax.scan over time on the padded
    extended label sequence (blank-interleaved, 2L+1); the gradient falls
    out of autodiff instead of warpctc's hand-computed betas.

    Logits are raw (unnormalized) activations [B, T, C]; softmax is applied
    internally like the reference.  Loss is per-sequence [B, 1].
    """
    logits, llen = ins["Logits"][0], ins["LogitsLength"][0]
    label, label_len = ins["Label"][0], ins["LabelLength"][0]
    blank = int(attrs.get("blank", 0))
    B, T, C = logits.shape
    L = label.shape[1]
    S = 2 * L + 1
    logp = jax.nn.log_softmax(logits, axis=-1)

    # extended sequence: [blank, l1, blank, l2, ..., blank]
    ext = jnp.full((B, S), blank, dtype=label.dtype)
    ext = ext.at[:, 1::2].set(jnp.clip(label, 0, C - 1))
    neg_inf = jnp.asarray(-1e30, logp.dtype)

    s_idx = jnp.arange(S)
    # skip (s-2 -> s) allowed where ext[s] is a real label differing from
    # ext[s-2]
    ext_m2 = jnp.concatenate(
        [jnp.full((B, 2), -1, dtype=ext.dtype), ext[:, :-2]], axis=1)
    can_skip = (s_idx[None, :] % 2 == 1) & (ext != ext_m2)

    def gather_logp(t_logp):
        return jnp.take_along_axis(t_logp, ext, axis=1)  # [B, S]

    alpha0 = jnp.full((B, S), neg_inf)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, blank])
    if L > 0:
        alpha0 = alpha0.at[:, 1].set(
            jnp.take_along_axis(logp[:, 0], ext[:, 1:2], axis=1)[:, 0])

    def shift(a, k):
        return jnp.concatenate(
            [jnp.full((B, k), neg_inf), a[:, :-k]], axis=1)

    def step(alpha, xs):
        t_logp, valid = xs
        stay = alpha
        diag = shift(alpha, 1)
        skip = jnp.where(can_skip, shift(alpha, 2), neg_inf)
        merged = logsumexp(
            jnp.stack([stay, diag, skip], axis=0), axis=0)
        nxt = merged + gather_logp(t_logp)
        alpha = jnp.where(valid[:, None], nxt, alpha)
        return alpha, None

    if T > 1:
        valid = (jnp.arange(1, T)[:, None] < llen[None, :])
        alphaT, _ = lax.scan(
            step, alpha0, (logp[:, 1:].transpose(1, 0, 2), valid))
    else:
        alphaT = alpha0
    endA = jnp.take_along_axis(alphaT, (2 * label_len)[:, None], axis=1)[:, 0]
    endB = jnp.take_along_axis(
        alphaT, jnp.maximum(2 * label_len - 1, 0)[:, None], axis=1)[:, 0]
    # empty transcript: only the all-blank path exists; endB would double-
    # count endA
    endB = jnp.where(label_len > 0, endB, neg_inf)
    ll = logsumexp(jnp.stack([endA, endB], axis=0), axis=0)
    loss = -ll[:, None]
    if attrs.get("norm_by_times", False):
        loss = loss / jnp.maximum(llen[:, None].astype(loss.dtype), 1.0)
    return {"Loss": [loss]}
