"""Optimizer update ops (cf. paddle/fluid/operators/optimizers/: sgd_op.cc,
momentum_op.cc, adam_op.cc, lamb_op.cc, adagrad_op.cc, rmsprop_op.cc, ...).

Reference semantics: each op reads Param/Grad/accumulators and writes
ParamOut/...Out IN PLACE (output var name == input var name).  Here the
in-place convention is preserved at the IR level; functionally the lowering
returns new arrays and the executor's sequential env makes later ops see the
update, with XLA donating buffers so updates really are in-place on device.

All update math runs in the accumulator dtype (fp32 master weights for AMP
come from the amp layer keeping Param fp32).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op(
    "sgd",
    inputs=["Param", "Grad", "LearningRate"],
    outputs=["ParamOut"],
    grad=None,
)
def _sgd(ctx, ins, attrs):
    p, g, lr = ins["Param"][0], ins["Grad"][0], ins["LearningRate"][0]
    return {"ParamOut": [(p - lr * g.astype(p.dtype)).astype(p.dtype)]}


@register_op(
    "momentum",
    inputs=["Param", "Grad", "Velocity", "LearningRate"],
    outputs=["ParamOut", "VelocityOut"],
    grad=None,
)
def _momentum(ctx, ins, attrs):
    p, g, v, lr = (
        ins["Param"][0],
        ins["Grad"][0],
        ins["Velocity"][0],
        ins["LearningRate"][0],
    )
    mu = attrs.get("mu", 0.9)
    use_nesterov = attrs.get("use_nesterov", False)
    g = g.astype(p.dtype)
    v_out = mu * v + g
    if use_nesterov:
        p_out = p - (g + mu * v_out) * lr
    else:
        p_out = p - lr * v_out
    return {"ParamOut": [p_out], "VelocityOut": [v_out]}


@register_op(
    "adam",
    inputs=[
        "Param",
        "Grad",
        "LearningRate",
        "Moment1",
        "Moment2",
        "Beta1Pow",
        "Beta2Pow",
    ],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    grad=None,
)
def _adam(ctx, ins, attrs):
    p = ins["Param"][0]
    g = ins["Grad"][0].astype(jnp.float32)
    lr = ins["LearningRate"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * g * g
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_out = p.astype(jnp.float32) - lr_t * m1o / (jnp.sqrt(m2o) + eps)
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "Moment1Out": [m1o],
        "Moment2Out": [m2o],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register_op(
    "adamw",
    inputs=[
        "Param",
        "Grad",
        "LearningRate",
        "Moment1",
        "Moment2",
        "Beta1Pow",
        "Beta2Pow",
    ],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    grad=None,
)
def _adamw(ctx, ins, attrs):
    """Decoupled weight decay Adam (2.0-era op, included for BERT recipes)."""
    p = ins["Param"][0]
    lr = ins["LearningRate"][0]
    wd = attrs.get("coeff", 0.01)
    out = _adam(ctx, ins, attrs)
    p_out = out["ParamOut"][0] - lr * wd * p.astype(jnp.float32)
    out["ParamOut"] = [p_out.astype(p.dtype)]
    return out


@register_op(
    "lamb",
    inputs=[
        "Param",
        "Grad",
        "LearningRate",
        "Moment1",
        "Moment2",
        "Beta1Pow",
        "Beta2Pow",
    ],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut", "Beta2PowOut"],
    grad=None,
)
def _lamb(ctx, ins, attrs):
    """cf. lamb_op.cc: layer-adaptive trust ratio on top of Adam."""
    p = ins["Param"][0]
    g = ins["Grad"][0].astype(jnp.float32)
    lr = ins["LearningRate"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-6)
    wd = attrs.get("weight_decay", 0.01)
    pf = p.astype(jnp.float32)
    m1o = b1 * m1 + (1 - b1) * g
    m2o = b2 * m2 + (1 - b2) * g * g
    m1h = m1o / (1 - b1p)
    m2h = m2o / (1 - b2p)
    r = m1h / (jnp.sqrt(m2h) + eps) + wd * pf
    p_norm = jnp.sqrt(jnp.sum(jnp.square(pf)))
    r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
    trust = jnp.where(
        (p_norm > 0) & (r_norm > 0), p_norm / r_norm, jnp.ones_like(p_norm)
    )
    p_out = pf - lr * trust * r
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "Moment1Out": [m1o],
        "Moment2Out": [m2o],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }


@register_op(
    "adagrad",
    inputs=["Param", "Grad", "Moment", "LearningRate"],
    outputs=["ParamOut", "MomentOut"],
    grad=None,
)
def _adagrad(ctx, ins, attrs):
    p, g, m, lr = (
        ins["Param"][0],
        ins["Grad"][0].astype(jnp.float32),
        ins["Moment"][0],
        ins["LearningRate"][0],
    )
    eps = attrs.get("epsilon", 1e-6)
    m_out = m + g * g
    p_out = p.astype(jnp.float32) - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [m_out]}


@register_op(
    "adadelta",
    inputs=["Param", "Grad", "AvgSquaredGrad", "AvgSquaredUpdate"],
    outputs=["ParamOut", "AvgSquaredGradOut", "AvgSquaredUpdateOut"],
    grad=None,
)
def _adadelta(ctx, ins, attrs):
    p = ins["Param"][0]
    g = ins["Grad"][0].astype(jnp.float32)
    g2 = ins["AvgSquaredGrad"][0]
    u2 = ins["AvgSquaredUpdate"][0]
    rho = attrs.get("rho", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    g2o = rho * g2 + (1 - rho) * g * g
    upd = -jnp.sqrt((u2 + eps) / (g2o + eps)) * g
    u2o = rho * u2 + (1 - rho) * upd * upd
    return {
        "ParamOut": [(p.astype(jnp.float32) + upd).astype(p.dtype)],
        "AvgSquaredGradOut": [g2o],
        "AvgSquaredUpdateOut": [u2o],
    }


@register_op(
    "rmsprop",
    inputs=["Param", "Grad", "LearningRate", "Moment", "MeanSquare", "MeanGrad"],
    outputs=["ParamOut", "MomentOut", "MeanSquareOut", "MeanGradOut"],
    grad=None,
)
def _rmsprop(ctx, ins, attrs):
    p = ins["Param"][0]
    g = ins["Grad"][0].astype(jnp.float32)
    lr = ins["LearningRate"][0]
    mom = ins["Moment"][0]
    ms = ins["MeanSquare"][0]
    mg = ins["MeanGrad"][0]
    rho = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    momentum = attrs.get("momentum", 0.0)
    centered = attrs.get("centered", False)
    ms_out = rho * ms + (1 - rho) * g * g
    if centered:
        mg_out = rho * mg + (1 - rho) * g
        denom = ms_out - mg_out * mg_out + eps
    else:
        mg_out = mg
        denom = ms_out + eps
    mom_out = momentum * mom + lr * g / jnp.sqrt(denom)
    p_out = p.astype(jnp.float32) - mom_out
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "MomentOut": [mom_out],
        "MeanSquareOut": [ms_out],
        "MeanGradOut": [mg_out],
    }


@register_op(
    "adamax",
    inputs=["Param", "Grad", "LearningRate", "Moment", "InfNorm", "Beta1Pow"],
    outputs=["ParamOut", "MomentOut", "InfNormOut"],
    grad=None,
)
def _adamax(ctx, ins, attrs):
    p = ins["Param"][0]
    g = ins["Grad"][0].astype(jnp.float32)
    lr = ins["LearningRate"][0]
    m, inf, b1p = ins["Moment"][0], ins["InfNorm"][0], ins["Beta1Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    m_out = b1 * m + (1 - b1) * g
    inf_out = jnp.maximum(b2 * inf, jnp.abs(g))
    p_out = p.astype(jnp.float32) - (lr / (1 - b1p)) * (m_out / (inf_out + eps))
    return {
        "ParamOut": [p_out.astype(p.dtype)],
        "MomentOut": [m_out],
        "InfNormOut": [inf_out],
    }


@register_op(
    "decayed_adagrad",
    inputs=["Param", "Grad", "Moment", "LearningRate"],
    outputs=["ParamOut", "MomentOut"],
    grad=None,
)
def _decayed_adagrad(ctx, ins, attrs):
    p, g, m, lr = (
        ins["Param"][0],
        ins["Grad"][0].astype(jnp.float32),
        ins["Moment"][0],
        ins["LearningRate"][0],
    )
    decay = attrs.get("decay", 0.95)
    eps = attrs.get("epsilon", 1e-6)
    m_out = decay * m + (1 - decay) * g * g
    p_out = p.astype(jnp.float32) - lr * g / (jnp.sqrt(m_out) + eps)
    return {"ParamOut": [p_out.astype(p.dtype)], "MomentOut": [m_out]}


@register_op(
    "ftrl",
    inputs=["Param", "SquaredAccumulator", "LinearAccumulator", "Grad", "LearningRate"],
    outputs=["ParamOut", "SquaredAccumOut", "LinearAccumOut"],
    grad=None,
)
def _ftrl(ctx, ins, attrs):
    p = ins["Param"][0].astype(jnp.float32)
    sq = ins["SquaredAccumulator"][0]
    lin = ins["LinearAccumulator"][0]
    g = ins["Grad"][0].astype(jnp.float32)
    lr = ins["LearningRate"][0]
    l1 = attrs.get("l1", 0.0)
    l2 = attrs.get("l2", 0.0)
    lr_power = attrs.get("lr_power", -0.5)
    new_sq = sq + g * g
    sigma = (new_sq**-lr_power - sq**-lr_power) / lr
    lin_out = lin + g - sigma * p
    y = new_sq**-lr_power / lr + 2 * l2
    p_out = jnp.where(
        jnp.abs(lin_out) > l1,
        (jnp.sign(lin_out) * l1 - lin_out) / y,
        jnp.zeros_like(p),
    )
    return {
        "ParamOut": [p_out.astype(ins["Param"][0].dtype)],
        "SquaredAccumOut": [new_sq],
        "LinearAccumOut": [lin_out],
    }


@register_op(
    "lars_momentum",
    inputs=["Param", "Grad", "Velocity", "LearningRate"],
    outputs=["ParamOut", "VelocityOut"],
    grad=None,
)
def _lars_momentum(ctx, ins, attrs):
    """cf. lars_momentum_op.cc: local LR = lars_coeff * ||p|| / (||g|| + wd*||p||)."""
    p = ins["Param"][0].astype(jnp.float32)
    g = ins["Grad"][0].astype(jnp.float32)
    v = ins["Velocity"][0]
    lr = ins["LearningRate"][0]
    mu = attrs.get("mu", 0.9)
    coeff = attrs.get("lars_coeff", 0.001)
    wd = attrs.get("lars_weight_decay", 0.0005)
    eps = attrs.get("epsilon", 0.0)
    p_norm = jnp.sqrt(jnp.sum(jnp.square(p)))
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        coeff * p_norm / (g_norm + wd * p_norm + eps),
        jnp.ones_like(p_norm),
    )
    v_out = mu * v + lr * local_lr * (g + wd * p)
    p_out = p - v_out
    return {
        "ParamOut": [p_out.astype(ins["Param"][0].dtype)],
        "VelocityOut": [v_out],
    }


@register_op(
    "dpsgd",
    inputs=["Param", "Grad", "LearningRate"],
    outputs=["ParamOut"],
    grad=None,
    needs_rng=True,
)
def _dpsgd(ctx, ins, attrs):
    """Differentially-private SGD (cf. dpsgd_op.cc): clip + gaussian noise."""
    import jax

    p, g, lr = ins["Param"][0], ins["Grad"][0].astype(jnp.float32), ins["LearningRate"][0]
    clip = attrs.get("clip", 10.0)
    batch_size = attrs.get("batch_size", 16.0)
    sigma = attrs.get("sigma", 1.0)
    g_norm = jnp.sqrt(jnp.sum(jnp.square(g)))
    scale = jnp.minimum(jnp.ones_like(g_norm), clip / jnp.maximum(g_norm, 1e-10))
    noise = sigma * clip * jax.random.normal(ctx.rng(), g.shape, dtype=jnp.float32)
    g_priv = (g * scale + noise) / batch_size
    return {"ParamOut": [(p.astype(jnp.float32) - lr * g_priv).astype(p.dtype)]}


OPTIMIZER_OP_TYPES = frozenset(
    {
        "sgd",
        "momentum",
        "adam",
        "adamw",
        "lamb",
        "adagrad",
        "adadelta",
        "rmsprop",
        "adamax",
        "decayed_adagrad",
        "ftrl",
        "lars_momentum",
        "dpsgd",
    }
)


# -- SelectedRows-style sparse updates ---------------------------------------
# Capability parity: reference `framework/selected_rows.h:1` +
# `operators/optimizers/sgd_op.cc` (SelectedRows branch) and `adam_op.cc`
# lazy_mode.  TPU-first: the sparse gradient is an explicit (Rows [N],
# Values [N, D]) pair with static N = number of looked-up ids; the update
# is an XLA scatter touching O(N*D) elements of the donated parameter
# buffer instead of an O(V*D) dense elementwise update.


@register_op(
    "sgd_sparse",
    inputs=["Param", "Rows", "Values", "LearningRate"],
    outputs=["ParamOut"],
    grad=None,
)
def _sgd_sparse(ctx, ins, attrs):
    p = ins["Param"][0]
    rows = ins["Rows"][0].astype(jnp.int32)
    vals = ins["Values"][0].astype(p.dtype)
    lr = ins["LearningRate"][0]
    # duplicate rows accumulate, matching SelectedRows MergeAdd + update
    return {"ParamOut": [p.at[rows].add(-(lr * vals).astype(p.dtype))]}


@register_op(
    "adam_sparse",
    inputs=[
        "Param", "Rows", "Values", "LearningRate",
        "Moment1", "Moment2", "Beta1Pow", "Beta2Pow",
    ],
    outputs=["ParamOut", "Moment1Out", "Moment2Out", "Beta1PowOut",
             "Beta2PowOut"],
    grad=None,
)
def _adam_sparse(ctx, ins, attrs):
    """Lazy-mode sparse Adam (cf. adam_op.cc `lazy_mode`): rows absent from
    the gradient keep their moments UNdecayed and their params untouched —
    a semantic difference from dense adam, matching the reference."""
    p = ins["Param"][0]
    rows = ins["Rows"][0].astype(jnp.int32)
    vals = ins["Values"][0].astype(jnp.float32)
    lr = ins["LearningRate"][0]
    m1, m2 = ins["Moment1"][0], ins["Moment2"][0]
    b1p, b2p = ins["Beta1Pow"][0], ins["Beta2Pow"][0]
    b1 = attrs.get("beta1", 0.9)
    b2 = attrs.get("beta2", 0.999)
    eps = attrs.get("epsilon", 1e-8)
    # merge duplicate rows (SelectedRows MergeAdd) WITHOUT densifying:
    # sort occurrences by row, per-group totals via boundary cumsum
    # differences, broadcast the total back to every occurrence — then
    # duplicate scatter writes below all carry identical values, so .set
    # is deterministic.  Everything stays O(N*D + N log N).
    order = jnp.argsort(rows)
    r_s = jnp.take(rows, order)
    v_s = jnp.take(vals, order, axis=0)
    if r_s.shape[0] == 0:
        merged = vals  # empty sparse grad: nothing to merge
    else:
        # compact group index per occurrence (0,0,1,2,2,...), then exact
        # per-group totals via segment_sum — no global running sum, so no
        # cancellation for long Rows vectors
        boundary = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             (r_s[1:] != r_s[:-1]).astype(jnp.int32)])
        gid = jnp.cumsum(boundary)
        totals = jax.ops.segment_sum(v_s, gid, num_segments=r_s.shape[0])
        total_s = jnp.take(totals, gid, axis=0)
        merged = jnp.zeros_like(vals).at[order].set(total_s)  # occ. order

    m1_r = jnp.take(m1, rows, axis=0)
    m2_r = jnp.take(m2, rows, axis=0)
    p_r = jnp.take(p, rows, axis=0).astype(jnp.float32)
    m1_new = b1 * m1_r + (1 - b1) * merged
    m2_new = b2 * m2_r + (1 - b2) * merged * merged
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p_r - lr_t * m1_new / (jnp.sqrt(m2_new) + eps)
    return {
        "ParamOut": [p.at[rows].set(p_new.astype(p.dtype))],
        "Moment1Out": [m1.at[rows].set(m1_new)],
        "Moment2Out": [m2.at[rows].set(m2_new)],
        "Beta1PowOut": [b1p * b1],
        "Beta2PowOut": [b2p * b2],
    }
