"""Sequence ops over padded-dense batches (the LoD capability, TPU-first).

Capability parity: reference `paddle/fluid/operators/sequence_ops/` (48
files operating on LoDTensor offset tables, cf. `framework/lod_tensor.h:52`).
TPU-first redesign: variable-length batches are a padded dense tensor
``[B, T, ...]`` plus an explicit ``SeqLens [B]`` int array — XLA needs
static shapes, and masks/gathers over a padded layout vectorize onto the
VPU where the reference walks per-sequence offset tables on CPU.  Every op
takes the lengths as a real input slot so the mask math stays inside the
jitted program.

Conventions:
- positions >= SeqLens[b] are padding; ops write zeros (or the declared
  pad value) there so downstream matmuls stay clean.
- ops that change lengths return the new lengths as an output slot
  (``OutLens``) instead of mutating LoD metadata.
"""

import functools

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _pos(T):
    return jnp.arange(T)


def _valid_mask(lens, T):
    """[B, T] bool, True where position < length."""
    return _pos(T)[None, :] < lens[:, None]


def _bcast(mask, x):
    """Broadcast [B, T] mask to x's rank ([B, T, ...])."""
    return mask.reshape(mask.shape + (1,) * (x.ndim - 2))


@register_op("sequence_mask", inputs=["X"], outputs=["Y"], grad=None)
def _sequence_mask(ctx, ins, attrs):
    """cf. sequence_ops/sequence_mask_op.cc: lengths -> 0/1 mask."""
    lens = ins["X"][0]
    maxlen = int(attrs.get("maxlen", -1))
    if maxlen < 0:
        raise ValueError(
            "sequence_mask needs a static maxlen attr on TPU (dynamic "
            "max(lengths) would be a dynamic shape)")
    out = (_pos(maxlen)[None, :] < lens[..., None])
    return {"Y": [out.astype(attrs.get("out_dtype", "int64"))]}


@register_op("sequence_pool", inputs=["X", "SeqLens"], outputs=["Out"],
             no_grad_slots=("SeqLens",))
def _sequence_pool(ctx, ins, attrs):
    """cf. sequence_ops/sequence_pool_op.cc: per-sequence reduce over time.

    pooltype: AVERAGE | SUM | SQRT | MAX | LAST | FIRST.  Empty sequences
    produce pad_value (reference behavior).
    """
    x, lens = ins["X"][0], ins["SeqLens"][0]
    ptype = attrs.get("pooltype", "AVERAGE").upper()
    pad_value = attrs.get("pad_value", 0.0)
    T = x.shape[1]
    mask = _bcast(_valid_mask(lens, T), x)
    n = jnp.maximum(lens, 1).reshape((-1,) + (1,) * (x.ndim - 2))
    if ptype == "SUM":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1)
    elif ptype == "AVERAGE":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1) / n
    elif ptype == "SQRT":
        out = jnp.sum(jnp.where(mask, x, 0), axis=1) / jnp.sqrt(
            n.astype(x.dtype))
    elif ptype == "MAX":
        neg = jnp.finfo(x.dtype).min if jnp.issubdtype(
            x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        out = jnp.max(jnp.where(mask, x, neg), axis=1)
    elif ptype == "LAST":
        idx = jnp.maximum(lens - 1, 0)
        out = jnp.take_along_axis(
            x, idx.reshape((-1, 1) + (1,) * (x.ndim - 2)), axis=1
        ).squeeze(1)
    elif ptype == "FIRST":
        out = x[:, 0]
    else:
        raise ValueError("unknown pooltype %r" % ptype)
    empty = (lens == 0).reshape((-1,) + (1,) * (out.ndim - 1))
    out = jnp.where(empty, jnp.asarray(pad_value, out.dtype), out)
    return {"Out": [out]}


@register_op("sequence_softmax", inputs=["X", "SeqLens"], outputs=["Out"],
             no_grad_slots=("SeqLens",))
def _sequence_softmax(ctx, ins, attrs):
    """cf. sequence_ops/sequence_softmax_op.cc: softmax over the valid
    prefix of axis 1; padding positions get 0."""
    x, lens = ins["X"][0], ins["SeqLens"][0]
    T = x.shape[1]
    mask = _valid_mask(lens, T)
    if x.ndim > 2:
        mask = _bcast(mask, x)
    neg = jnp.asarray(jnp.finfo(jnp.float32).min, x.dtype)
    z = jnp.where(mask, x, neg)
    out = jax.nn.softmax(z.astype(jnp.float32), axis=1).astype(x.dtype)
    return {"Out": [jnp.where(mask, out, 0)]}


@register_op("sequence_reverse", inputs=["X", "SeqLens"], outputs=["Y"],
             no_grad_slots=("SeqLens",))
def _sequence_reverse(ctx, ins, attrs):
    """cf. sequence_ops/sequence_reverse_op.h: reverse each valid prefix,
    padding stays in place."""
    x, lens = ins["X"][0], ins["SeqLens"][0]
    T = x.shape[1]
    pos = _pos(T)[None, :]
    idx = jnp.where(pos < lens[:, None], lens[:, None] - 1 - pos, pos)
    return {"Y": [jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)]}


@register_op("sequence_expand_as", inputs=["X", "Y", "SeqLens"],
             outputs=["Out"], no_grad_slots=("Y", "SeqLens"))
def _sequence_expand_as(ctx, ins, attrs):
    """cf. sequence_ops/sequence_expand_as_op.cc: broadcast each row of X
    over the valid time steps of reference Y; padding is zero."""
    x, y, lens = ins["X"][0], ins["Y"][0], ins["SeqLens"][0]
    T = y.shape[1]
    out = jnp.broadcast_to(
        x[:, None], (x.shape[0], T) + x.shape[1:]).astype(x.dtype)
    return {"Out": [jnp.where(_bcast(_valid_mask(lens, T), out), out, 0)]}


@register_op("sequence_expand", inputs=["X", "RefLens"], outputs=["Out"],
             no_grad_slots=("RefLens",))
def _sequence_expand(ctx, ins, attrs):
    """cf. sequence_ops/sequence_expand_op.cc: repeat row b RefLens[b]
    times.  Dense layout: Out[b, r] = X[b] for r < RefLens[b], else 0,
    with static bound attrs['max_ref_len'] (the reference's ragged output
    rows become a padded repeat axis)."""
    x, ref = ins["X"][0], ins["RefLens"][0]
    R = int(attrs.get("max_ref_len", -1))
    if R < 0:
        raise ValueError("sequence_expand needs static max_ref_len attr")
    out = jnp.broadcast_to(x[:, None], (x.shape[0], R) + x.shape[1:])
    mask = _pos(R)[None, :] < ref[:, None]
    return {"Out": [jnp.where(
        mask.reshape(mask.shape + (1,) * (x.ndim - 1)), out, 0)]}


def _compact(x, keep):
    """Stable-compact valid positions of axis 1 to the front.

    keep: [B, T] bool.  Returns (compacted x, new lens).  Uses a stable
    argsort on the inverted mask — a vectorizable TPU idiom for the
    reference's per-sequence memmove loops.
    """
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(
        x, order.reshape(order.shape + (1,) * (x.ndim - 2)), axis=1)
    newlens = jnp.sum(keep, axis=1).astype(jnp.int32)
    newmask = _valid_mask(newlens, x.shape[1])
    out = jnp.where(_bcast(newmask, out), out, 0)
    return out, newlens


@register_op("sequence_concat", inputs=["X", "SeqLens"],
             outputs=["Out", "OutLens"],
             no_grad_slots=("SeqLens",), stateful_out_slots=("OutLens",))
def _sequence_concat(ctx, ins, attrs):
    """cf. sequence_ops/sequence_concat_op.cc: concat the valid prefixes
    of N padded inputs along time, then re-pad."""
    xs, lens = ins["X"], ins["SeqLens"]
    cat = jnp.concatenate(xs, axis=1)
    offs = []
    for x, l in zip(xs, lens):
        offs.append(_valid_mask(l, x.shape[1]))
    keep = jnp.concatenate(offs, axis=1)
    out, outlens = _compact(cat, keep)
    return {"Out": [out], "OutLens": [outlens]}


@register_op("sequence_pad", inputs=["X", "SeqLens"],
             outputs=["Out", "Length"],
             no_grad_slots=("SeqLens",), stateful_out_slots=("Length",))
def _sequence_pad(ctx, ins, attrs):
    """cf. sequence_ops/sequence_pad_op.cc: normalize to padded_length,
    filling padding with pad_value."""
    x, lens = ins["X"][0], ins["SeqLens"][0]
    P = int(attrs.get("padded_length", -1))
    if P < 0:
        P = x.shape[1]
    pad_value = attrs.get("pad_value", 0.0)
    if P > x.shape[1]:
        pad = [(0, 0)] * x.ndim
        pad[1] = (0, P - x.shape[1])
        x = jnp.pad(x, pad)
    elif P < x.shape[1]:
        x = x[:, :P]
    lens = jnp.minimum(lens, P)
    mask = _bcast(_valid_mask(lens, P), x)
    return {"Out": [jnp.where(mask, x, jnp.asarray(pad_value, x.dtype))],
            "Length": [lens.astype(jnp.int64)]}


@register_op("sequence_unpad", inputs=["X", "Length"], outputs=["Out"],
             no_grad_slots=("Length",))
def _sequence_unpad(ctx, ins, attrs):
    """cf. sequence_ops/sequence_unpad_op.cc.  The reference flattens to a
    ragged LoD tensor; the dense equivalent zeroes padding and keeps the
    (x, lens) pair as the sequence representation."""
    x, lens = ins["X"][0], ins["Length"][0]
    mask = _bcast(_valid_mask(lens, x.shape[1]), x)
    return {"Out": [jnp.where(mask, x, 0)]}


@register_op("sequence_slice", inputs=["X", "Offset", "Length"],
             outputs=["Out"], no_grad_slots=("Offset", "Length"))
def _sequence_slice(ctx, ins, attrs):
    """cf. sequence_ops/sequence_slice_op.h: per-row slice
    [offset_b, offset_b + length_b) of the time axis, left-aligned."""
    x = ins["X"][0]
    off = ins["Offset"][0].reshape(-1)
    ln = ins["Length"][0].reshape(-1)
    T = x.shape[1]
    pos = _pos(T)[None, :]
    src = jnp.clip(pos + off[:, None], 0, T - 1)
    out = jnp.take_along_axis(
        x, src.reshape(src.shape + (1,) * (x.ndim - 2)), axis=1)
    mask = pos < ln[:, None]
    return {"Out": [jnp.where(_bcast(mask, out), out, 0)]}


@register_op("sequence_erase", inputs=["X", "SeqLens"],
             outputs=["Out", "OutLens"], grad=None)
def _sequence_erase(ctx, ins, attrs):
    """cf. sequence_ops/sequence_erase_op.cc: drop listed token ids and
    compact each sequence."""
    x, lens = ins["X"][0], ins["SeqLens"][0]
    tokens = attrs.get("tokens", [])
    keep = _valid_mask(lens, x.shape[1])
    for t in tokens:
        keep = keep & (x != t)
    out, outlens = _compact(x, keep)
    return {"Out": [out], "OutLens": [outlens]}


@register_op("sequence_enumerate", inputs=["X", "SeqLens"], outputs=["Out"],
             grad=None)
def _sequence_enumerate(ctx, ins, attrs):
    """cf. sequence_ops/sequence_enumerate_op.cc: sliding windows of ids;
    positions past the end filled with pad_value."""
    x, lens = ins["X"][0], ins["SeqLens"][0]
    win = int(attrs["win_size"])
    pad = attrs.get("pad_value", 0)
    T = x.shape[1]
    cols = []
    for w in range(win):
        shifted = jnp.concatenate(
            [x[:, w:], jnp.full((x.shape[0], w), pad, x.dtype)], axis=1)
        inside = (_pos(T)[None, :] + w) < lens[:, None]
        cols.append(jnp.where(inside, shifted, pad))
    out = jnp.stack(cols, axis=-1)
    valid = _valid_mask(lens, T)
    return {"Out": [jnp.where(valid[..., None], out, pad)]}


@register_op("sequence_reshape", inputs=["X", "SeqLens"],
             outputs=["Out", "OutLens"], no_grad_slots=("SeqLens",),
             stateful_out_slots=("OutLens",))
def _sequence_reshape(ctx, ins, attrs):
    """cf. sequence_ops/sequence_reshape_op.cc: re-chunk each row's valid
    region (len*D elements, contiguous in the padded row-major layout)
    into new_dim-wide steps."""
    x, lens = ins["X"][0], ins["SeqLens"][0]
    new_dim = int(attrs["new_dim"])
    B, T, D = x.shape[0], x.shape[1], x.shape[-1]
    total = T * D
    if total % new_dim:
        raise ValueError("T*D=%d not divisible by new_dim=%d" % (total, new_dim))
    out = x.reshape(B, total // new_dim, new_dim)
    # per-row ceil: a row whose len*D is not divisible by new_dim keeps a
    # zero-padded final step instead of silently dropping valid elements
    # (the reference op raises on non-divisible rows; raising on traced
    # lengths is impossible under jit)
    newlens = -((lens * D) // -new_dim)
    mask = _bcast(_valid_mask(newlens, out.shape[1]), out)
    return {"Out": [jnp.where(mask, out, 0)],
            "OutLens": [newlens.astype(jnp.int32)]}


@register_op("sequence_scatter", inputs=["X", "Ids", "Updates", "UpdLens"],
             outputs=["Out"], no_grad_slots=("Ids", "UpdLens"))
def _sequence_scatter(ctx, ins, attrs):
    """cf. sequence_ops/sequence_scatter_op.cc: per-row scatter-add of
    updates into the time axis at the given indices."""
    x, ids, upd, ulens = (ins["X"][0], ins["Ids"][0], ins["Updates"][0],
                          ins["UpdLens"][0])
    U = ids.shape[1]
    mask = _pos(U)[None, :] < ulens[:, None]
    upd = jnp.where(_bcast(mask, upd), upd, 0)
    ids = jnp.where(mask, ids, 0)  # masked updates are zero, index 0 is safe
    one_hot = jax.nn.one_hot(ids, x.shape[1], dtype=x.dtype)  # [B, U, T]
    add = jnp.einsum("but,bu...->bt...", one_hot, upd)
    return {"Out": [x + add]}


@register_op("sequence_conv", inputs=["X", "SeqLens", "Filter"],
             outputs=["Out"], no_grad_slots=("SeqLens",))
def _sequence_conv(ctx, ins, attrs):
    """cf. sequence_ops/sequence_conv_op.cc + math/context_project.h: stack
    a context window around each step (zero beyond the valid region) and
    project.  Filter: [context_length * D, M]."""
    x, lens, filt = ins["X"][0], ins["SeqLens"][0], ins["Filter"][0]
    ctx_len = int(attrs.get("context_length", 3))
    ctx_start = int(attrs.get("context_start", -(ctx_len - 1) // 2))
    B, T, D = x.shape
    valid = _valid_mask(lens, T)
    xz = jnp.where(valid[..., None], x, 0)
    cols = []
    for w in range(ctx_len):
        shift = ctx_start + w
        rolled = jnp.roll(xz, -shift, axis=1)
        pos = _pos(T)[None, :] + shift
        inside = (pos >= 0) & (pos < lens[:, None])
        cols.append(jnp.where(inside[..., None], rolled, 0))
    stacked = jnp.concatenate(cols, axis=-1)  # [B, T, ctx_len*D]
    out = jnp.einsum("btc,cm->btm", stacked, filt)
    return {"Out": [jnp.where(valid[..., None], out, 0)]}


@register_op("segment_pool", inputs=["X", "SegIds"], outputs=["Out"],
             no_grad_slots=("SegIds",))
def _segment_pool(ctx, ins, attrs):
    """Pool features per packed segment (in-graph LoD parity for pooling,
    cf. reference sequence_pool over LoDTensor offsets,
    `operators/sequence_ops/sequence_pool_op.cc`).

    X: [B, T, D]; SegIds: [B, T] int, id s in [0, num_segments) selects a
    segment, anything outside (e.g. padding marked -1 or >= N) is dropped.
    attrs: num_segments (static), pooltype in SUM/AVERAGE/MAX/SQRT.
    Out: [B, num_segments, D].

    SUM/AVERAGE/SQRT lower to a one-hot matmul so the reduction runs on
    the MXU; MAX uses a masked segment reduction.
    """
    x, seg = ins["X"][0], ins["SegIds"][0]
    n = int(attrs["num_segments"])
    pooltype = attrs.get("pooltype", "SUM").upper()
    seg = seg.astype(jnp.int32)
    valid = (seg >= 0) & (seg < n)
    safe = jnp.where(valid, seg, 0)
    one_hot = jax.nn.one_hot(safe, n, dtype=x.dtype) * valid[..., None]
    if pooltype == "MAX":
        big = jnp.asarray(
            jnp.finfo(x.dtype).min
            if jnp.issubdtype(x.dtype, jnp.floating)
            else jnp.iinfo(x.dtype).min,
            x.dtype,
        )
        # [B, T, n, 1] mask against [B, T, 1, D] -> segment max over T
        m = (one_hot > 0)[..., None]
        vals = jnp.where(m, x[:, :, None, :], big)
        out = jnp.max(vals, axis=1)
        counts = jnp.einsum("btn->bn", one_hot)
        return {"Out": [jnp.where(counts[..., None] > 0, out, 0)]}
    out = jnp.einsum("btn,btd->bnd", one_hot, x)
    if pooltype in ("AVERAGE", "MEAN", "SQRT"):
        counts = jnp.einsum("btn->bn", one_hot)
        denom = jnp.maximum(counts, 1.0)
        if pooltype == "SQRT":
            denom = jnp.sqrt(denom)
        out = out / denom[..., None]
    return {"Out": [out]}


@register_op("sequence_topk_avg_pooling",
             inputs=["X", "RowLens", "ColLens"], outputs=["Out"],
             no_grad_slots=("RowLens", "ColLens"))
def _sequence_topk_avg_pooling(ctx, ins, attrs):
    """cf. sequence_topk_avg_pooling_op.cc (match-matrix pooling): for
    each row position and channel, average the top-k column values —
    out[..., c*K + i] = sum(top_{topks[i]}) / topks[i] (the reference
    divides by the FULL k, sequence_topk_avg_pooling_op.h:147).

    PADDED redesign of the LoD layout: X [B, C, R, Co] with optional
    RowLens/ColLens [B] masking the ragged tails; Out [B, R, C*K]."""
    x = ins["X"][0]
    b, c, r, co = x.shape
    topks = [int(k) for k in attrs["topks"]]
    col_lens = (ins["ColLens"][0].reshape(-1)
                if ins.get("ColLens") else jnp.full((b,), co))
    row_lens = (ins["RowLens"][0].reshape(-1)
                if ins.get("RowLens") else jnp.full((b,), r))

    col_mask = _valid_mask(col_lens, co)                     # [B, Co]
    xm = jnp.where(col_mask[:, None, None, :], x.astype(jnp.float32),
                   -jnp.inf)
    kmax = min(max(topks), co)
    vals, _ = jax.lax.top_k(xm, kmax)                        # [B,C,R,kmax]
    # zero the PAD positions by position (col_lens), not by finiteness —
    # a legitimate -inf/NaN in a valid column must propagate
    pos_ok = _valid_mask(col_lens, kmax)                     # [B, kmax]
    vals = jnp.where(pos_ok[:, None, None, :], vals, 0.0)
    csum = jnp.cumsum(vals, axis=-1)
    cols = []
    for k in topks:
        idx = min(k, co) - 1
        cols.append(csum[..., idx] / k)                      # [B, C, R]
    out = jnp.stack(cols, axis=-1)                           # [B,C,R,K]
    out = out.transpose(0, 2, 1, 3).reshape(b, r, c * len(topks))
    out = out * _valid_mask(row_lens, r)[:, :, None]
    return {"Out": [out.astype(x.dtype)]}


@register_op("match_matrix_tensor", inputs=["X", "Y", "W"],
             outputs=["Out", "Tmp"])
def _match_matrix_tensor(ctx, ins, attrs):
    """cf. match_matrix_tensor_op.cc: per-channel bilinear match matrix
    out[b, t, i, j] = x[b, i] @ W[:, t, :] @ y[b, j] for text-matching
    pairs (feeds sequence_topk_avg_pooling).  PADDED redesign: X
    [B, Lx, D], Y [B, Ly, D], W [D, dim_t, D] -> Out [B, dim_t, Lx, Ly]
    (ragged tails are the caller's mask, as with the pooling op)."""
    x, y, w = ins["X"][0], ins["Y"][0], ins["W"][0]
    tmp = jnp.einsum("bid,dte->bite", x, w)       # [B, Lx, T, D]
    out = jnp.einsum("bite,bje->btij", tmp, y)    # [B, T, Lx, Ly]
    return {"Out": [out], "Tmp": [tmp]}
