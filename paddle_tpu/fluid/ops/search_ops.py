"""Search-ranking / tree-model niche op family (the round-5 op tail).

Capability parity (one op per reference file): `lod_reset_op.cc`,
`filter_by_instag_op.cc`, `sample_logits_op.cc`, `rank_attention_op.cc`
(+ rank_attention.cu.h kernels), `tree_conv_op.cc` (+ tree2col.cc),
`var_conv_2d_op.cc`, `pyramid_hash_op.cc`.

TPU-first redesigns, shared theme: every LoD-offset input becomes dense
`[B, ...]` + explicit length vectors, every data-dependent output shape
becomes a fixed-shape output + validity mask, and the sequential CPU
kernels become batched gathers/matmuls the MXU can chew on.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op("lod_reset", inputs=["X", "Y"], outputs=["Out", "OutLens"],
             no_grad_slots=("Y",))
def _lod_reset(ctx, ins, attrs):
    """cf. lod_reset_op.cc: the data passes through untouched; only the
    segmentation changes.  STATIC redesign: the LoD lives outside the
    tensor as a SeqLens vector here (fluid/packing.py), so the op emits
    the NEW lengths as an explicit OutLens output — computed from Y
    (offset vector, reference Example 2) or the `target_lod` attr —
    instead of mutating tensor metadata."""
    x = ins["X"][0]
    if ins.get("Y"):
        off = ins["Y"][0].reshape(-1).astype(jnp.int32)
    else:
        tl = attrs.get("target_lod")
        if not tl:
            raise ValueError(
                "lod_reset needs Y (offsets) or a target_lod attr")
        off = jnp.asarray(list(tl), jnp.int32)
    return {"Out": [x], "OutLens": [off[1:] - off[:-1]]}


@register_op("filter_by_instag",
             inputs=["Ins", "SeqLens", "InsTag", "FilterTag"],
             outputs=["Out", "LossWeight", "IndexMap"],
             no_grad_slots=("SeqLens", "InsTag", "FilterTag"))
def _filter_by_instag(ctx, ins, attrs):
    """cf. filter_by_instag_op.cc: keep only instances whose tag set
    intersects the filter tags; the rest contribute zero loss.

    STATIC redesign: the reference compacts kept rows into a shorter
    LoD tensor (shape depends on data).  Here Ins rows [N, D] stay in
    place, grouped into B sequences by SeqLens [B]; InsTag [B, T] padded
    with -1; FilterTag [F].  Out [N, D] zeroes (out_val_if_empty) the
    rows of dropped sequences, LossWeight [B] is the reference's 1/0 loss
    weight, and IndexMap [B] holds the kept-flag (the compaction map is
    meaningless without compaction).  Downstream losses multiply by
    LossWeight — the same training signal as the reference's compacted
    batch."""
    x = ins["Ins"][0]
    lens = ins["SeqLens"][0].reshape(-1).astype(jnp.int32)
    tags = ins["InsTag"][0]
    ftags = ins["FilterTag"][0].reshape(-1)
    fill = float(attrs.get("out_val_if_empty", 0))
    hit = jnp.any(tags[:, :, None] == ftags[None, None, :], axis=(1, 2)) \
        if tags.ndim == 2 else \
        jnp.any(tags[:, None] == ftags[None, :], axis=1)
    keep = hit.astype(x.dtype)                         # [B]
    # expand per-sequence keep to per-row via the cumulative boundaries
    bounds = jnp.cumsum(lens)
    row_seq = jnp.searchsorted(bounds, jnp.arange(x.shape[0]), side="right")
    row_keep = keep[jnp.clip(row_seq, 0, lens.shape[0] - 1)]
    out = jnp.where(row_keep[:, None] > 0, x, jnp.asarray(fill, x.dtype))
    return {"Out": [out], "LossWeight": [keep.reshape(-1, 1)],
            "IndexMap": [hit.astype(jnp.int32)]}


@register_op("sample_logits",
             inputs=["Logits", "Labels", "CustomizedSamples",
                     "CustomizedProbabilities"],
             outputs=["Samples", "Probabilities", "SampledLogits",
                      "SampledLabels"],
             no_grad_slots=("Labels", "CustomizedSamples",
                            "CustomizedProbabilities"),
             needs_rng=True)
def _sample_logits(ctx, ins, attrs):
    """cf. sample_logits_op.cc: sampled-softmax helper.  Samples row =
    [true labels | S negatives]; SampledLogits = gathered logits -
    log q(sample) (the sampled-softmax correction), accidental hits
    (a negative equal to a true label) knocked down by 1e20; Probability
    is the log-uniform q(k) = (log(k+2)-log(k+1))/log(K+1).

    TPU redesign: the reference's sequential unique log-uniform sampler
    becomes a Gumbel-top-S draw over the log-uniform distribution — an
    O(K) vectorized op yielding S DISTINCT classes (the `uniq` contract)
    shared across the batch, like the reference's batched sampler."""
    logits = ins["Logits"][0]
    labels = ins["Labels"][0].astype(jnp.int32)
    n, k = logits.shape
    nt = labels.shape[1]
    s = int(attrs.get("num_samples", 5))
    remove_hits = bool(attrs.get("remove_accidental_hits", True))

    log_q = jnp.log(jnp.log(jnp.arange(k, dtype=jnp.float32) + 2.0)
                    - jnp.log(jnp.arange(k, dtype=jnp.float32) + 1.0)) \
        - jnp.log(jnp.log(jnp.float32(k + 1)))

    if attrs.get("use_customized_samples", False) and \
            ins.get("CustomizedSamples"):
        samples = ins["CustomizedSamples"][0].astype(jnp.int32)
        probabilities = ins["CustomizedProbabilities"][0]
    else:
        g = -jnp.log(-jnp.log(
            jax.random.uniform(ctx.rng(), (k,), minval=1e-20, maxval=1.0)))
        _, neg = jax.lax.top_k(log_q + g, s)           # S distinct classes
        neg = jnp.broadcast_to(neg[None, :], (n, s)).astype(jnp.int32)
        samples = jnp.concatenate([labels, neg], axis=1)
        probabilities = jnp.exp(log_q)[samples]
    sampled_logits = jnp.take_along_axis(logits, samples, axis=1)
    if remove_hits:
        # a sampled negative that IS one of the row's true labels
        acc = jnp.any(
            samples[:, nt:, None] == labels[:, None, :], axis=2)
        sampled_logits = sampled_logits.at[:, nt:].add(
            jnp.where(acc, -1e20, 0.0))
    sampled_logits = sampled_logits - jnp.log(
        jnp.maximum(probabilities, 1e-30))
    sampled_labels = jnp.broadcast_to(
        jnp.arange(nt, dtype=jnp.int32)[None, :], (n, nt))
    return {"Samples": [samples], "Probabilities": [probabilities],
            "SampledLogits": [sampled_logits],
            "SampledLabels": [sampled_labels]}


@register_op("rank_attention", inputs=["X", "RankOffset", "RankParam"],
             outputs=["Out", "InputHelp", "InsRank"],
             no_grad_slots=("X", "RankOffset"))
def _rank_attention(ctx, ins, attrs):
    """cf. rank_attention_op.cc + rank_attention.cu.h: per-instance rank
    attention.  RankOffset row i = [rank_i, (rank_1, idx_1), ...,
    (rank_k, idx_k)] (1-based ranks, -1 invalid); the instance's output
    is sum_k X[idx_k] @ RankParam[(rank_i-1)*max_rank + rank_k - 1],
    i.e. a parameter block chosen by the (instance rank, peer rank)
    pair.  The CUDA expand kernels become one batched gather + einsum
    (MXU-friendly); only RankParam receives gradient, like the
    reference's grad op."""
    x = ins["X"][0]                                    # [N, D]
    ro = ins["RankOffset"][0].astype(jnp.int32)        # [N, 1+2*M]
    param = ins["RankParam"][0]                        # [M*M*D, P]
    max_rank = (ro.shape[1] - 1) // 2
    attr_rank = int(attrs.get("MaxRank", max_rank))
    if attr_rank != max_rank:
        raise ValueError(
            "rank_attention: MaxRank attr (%d) must equal the peer-slot "
            "count implied by RankOffset width (%d = (%d-1)/2); the "
            "parameter grid is MaxRank x MaxRank blocks"
            % (attr_rank, max_rank, ro.shape[1]))
    n, d = x.shape
    p = param.shape[1]
    param3 = param.reshape(max_rank * max_rank, d, p)

    lower = ro[:, 0] - 1                               # [N]
    faster = ro[:, 1::2] - 1                           # [N, M]
    index = ro[:, 2::2]                                # [N, M]
    valid = (lower[:, None] >= 0) & (faster >= 0)      # [N, M]

    gathered = x[jnp.clip(index, 0, n - 1)]            # [N, M, D]
    input_help = jnp.where(valid[:, :, None], gathered, 0.0)
    block = jnp.clip(lower[:, None] * max_rank + faster,
                     0, max_rank * max_rank - 1)       # [N, M]
    pblocks = jnp.where(valid[:, :, None, None],
                        param3[block], 0.0)            # [N, M, D, P]
    out = jnp.einsum("nmd,nmdp->np", input_help, pblocks)
    ins_rank = jnp.where(ro[:, 0] > 0, ro[:, 0], -1).astype(
        x.dtype).reshape(-1, 1)
    return {"Out": [out],
            "InputHelp": [input_help.reshape(n, max_rank * d)],
            "InsRank": [ins_rank]}


@register_op("tree_conv", inputs=["NodesVector", "EdgeSet", "Filter"],
             outputs=["Out"], no_grad_slots=("EdgeSet",))
def _tree_conv(ctx, ins, attrs):
    """cf. tree_conv_op.cc + math/tree2col.cc: tree-based convolution
    (TBCNN).  Node u's patch holds u plus its descendants down to depth
    max_depth-1; each patch node contributes x ·(eta_l W_l + eta_r W_r +
    eta_t W_t) with the continuous position weights from the TBCNN paper
    (eta_t = (D-d)/D; eta_l/(eta_r) split by the child's 1-based position
    among its siblings).

    TPU redesign: the per-node patch recursion (tree2col) becomes
    adjacency-matrix powers — descendants at depth d are Adj^d rows — so
    the whole batch is d matmuls + einsums instead of a data-dependent
    tree walk."""
    nodes = ins["NodesVector"][0]                      # [B, N, F]
    edges = ins["EdgeSet"][0].astype(jnp.int32)        # [B, E, 2] 1-based
    w = ins["Filter"][0]                               # [F, 3, O, C]
    max_depth = int(attrs.get("max_depth", 2))
    b, n, f = nodes.shape
    e = edges.shape[1]

    def one(x, es):
        parent, child = es[:, 0], es[:, 1]
        ok = (parent > 0) & (child > 0)
        pi = jnp.where(ok, parent - 1, n)              # n = scrap row
        ci = jnp.where(ok, child - 1, n)
        adj = jnp.zeros((n + 1, n + 1), x.dtype).at[pi, ci].set(
            jnp.where(ok, 1.0, 0.0))[:n, :n]
        # l_c: sibling count; idx_c: 1-based order among same-parent edges
        l_children = jnp.zeros((n + 1,), jnp.int32).at[pi].add(
            jnp.where(ok, 1, 0))
        same_parent_before = jnp.sum(
            (pi[None, :e] == pi[:, None])
            & (jnp.arange(e)[None, :] < jnp.arange(e)[:, None]), axis=1)
        idx_c = jnp.zeros((n + 1,), jnp.int32).at[ci].set(
            same_parent_before.astype(jnp.int32) + 1)[:n]
        l_c = l_children[pi]                           # per-edge
        l_of = jnp.zeros((n + 1,), jnp.int32).at[ci].set(l_c)[:n]

        alpha = jnp.where(l_of == 1, 0.5,
                          (idx_c - 1.0) / jnp.maximum(l_of - 1.0, 1.0))

        # depth 0: every node itself, eta = (0, 0, 1)
        out = jnp.einsum("nf,foc->noc", x, w[:, 2])
        reach = jnp.eye(n, dtype=x.dtype)
        for d in range(1, max_depth):
            reach = reach @ adj                        # descendants @ d
            eta_t = float(max_depth - d) / max_depth
            eta_l = (1.0 - eta_t) * alpha
            # note: (1 - eta_l) with eta_l ALREADY scaled — the reference
            # formula (tree2col.cc eta_r), not (1 - alpha)
            eta_r = (1.0 - eta_t) * (1.0 - eta_l)
            mixed = (jnp.einsum("n,nf,foc->noc", eta_l, x, w[:, 0])
                     + jnp.einsum("n,nf,foc->noc", eta_r, x, w[:, 1])
                     + eta_t * jnp.einsum("nf,foc->noc", x, w[:, 2]))
            out = out + jnp.einsum("un,noc->uoc", reach, mixed)
        return out

    return {"Out": [jax.vmap(one)(nodes, edges)]}


@register_op("var_conv_2d",
             inputs=["X", "RowLens", "ColLens", "W"],
             outputs=["Out"], no_grad_slots=("RowLens", "ColLens"))
def _var_conv_2d(ctx, ins, attrs):
    """cf. var_conv_2d_op.cc: 2-D conv where every sample has its own
    spatial extent (text-matching grids).  Reference: flat LoD buffer +
    per-sample im2col with centered zero padding, output extent
    ceil(h/s) x ceil(w/s).

    STATIC redesign: X arrives dense [B, C, Hmax, Wmax] with RowLens/
    ColLens [B]; input is masked to each sample's extent (zeros outside,
    exactly the reference's padding reads), ONE lax conv with centered
    padding (kh//2 low / kh-1-kh//2 high — the reference's half-kernel
    offsets, NOT XLA SAME which pads high) covers the whole batch on the
    MXU, and outputs beyond a sample's ceil-extent are zeroed."""
    x = ins["X"][0]
    rows = ins["RowLens"][0].reshape(-1).astype(jnp.int32)
    cols = ins["ColLens"][0].reshape(-1).astype(jnp.int32)
    w = ins["W"][0]                                    # [O, C*kh*kw]
    kh = int(attrs.get("KernelH", attrs.get("kernel_h", 3)))
    kw = int(attrs.get("KernelW", attrs.get("kernel_w", 3)))
    sh = int(attrs.get("StrideH", attrs.get("stride_h", 1)))
    sw = int(attrs.get("StrideW", attrs.get("stride_w", 1)))
    b, c, hm, wm = x.shape
    o = w.shape[0]
    wf = w.reshape(o, c, kh, kw)

    hmask = (jnp.arange(hm)[None, :] < rows[:, None]).astype(x.dtype)
    wmask = (jnp.arange(wm)[None, :] < cols[:, None]).astype(x.dtype)
    xin = x * hmask[:, None, :, None] * wmask[:, None, None, :]

    out = jax.lax.conv_general_dilated(
        xin, wf, window_strides=(sh, sw),
        padding=((kh // 2, kh - 1 - kh // 2), (kw // 2, kw - 1 - kw // 2)),
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    ho, wo = out.shape[2], out.shape[3]
    out_rows = jnp.where(rows > 0, (rows - 1) // sh + 1, 0)
    out_cols = jnp.where(cols > 0, (cols - 1) // sw + 1, 0)
    om = ((jnp.arange(ho)[None, :] < out_rows[:, None]).astype(x.dtype))
    on = ((jnp.arange(wo)[None, :] < out_cols[:, None]).astype(x.dtype))
    return {"Out": [out * om[:, None, :, None] * on[:, None, None, :]]}


def _mix_hash(h, v):
    """Deterministic 32-bit mix (xorshift-multiply), jit-friendly."""
    h = (h ^ v) * jnp.uint32(0x9E3779B1)
    h = h ^ (h >> 15)
    return h * jnp.uint32(0x85EBCA77)


@register_op("pyramid_hash", inputs=["X", "SeqLens", "W"],
             outputs=["Out"], no_grad_slots=("X", "SeqLens"),
             needs_rng=True)
def _pyramid_hash(ctx, ins, attrs):
    """cf. pyramid_hash_op.cc (contrib search_pyramid_hash): hash every
    n-gram (n = 2..pyramid_layer) of a token sequence into a flat
    embedding buffer W [space_len, 1] — num_emb/rand_len hash probes per
    gram, each gathering rand_len contiguous floats — and sum the grams
    starting at each position.

    TPU redesign: the reference's per-gram XXH32 + sparse-row loop
    becomes a vectorized xorshift-mix hash (different hash function,
    same capability: the table is random-init and learned, so only
    distribution quality matters, not the exact hash) and one batched
    gather; out-of-range grams (crossing the sequence end, per SeqLens)
    contribute zero.  drop_out_percent applies in-graph when
    is_training (reference white/black-list filtering is a PS-serving
    feature, subsumed per SURVEY §2.3)."""
    toks = ins["X"][0].astype(jnp.uint32)              # [B, T]
    lens = ins["SeqLens"][0].reshape(-1).astype(jnp.int32)
    w = ins["W"][0].reshape(-1)                        # [space_len]
    num_emb = int(attrs.get("num_emb", 64))
    rand_len = int(attrs.get("rand_len", 16))
    layers = int(attrs.get("pyramid_layer", 2))
    drop = float(attrs.get("drop_out_percent", 0.0))
    training = bool(attrs.get("is_training", False))
    space = w.shape[0]
    bsz, t = toks.shape
    chunks = num_emb // rand_len

    out = jnp.zeros((bsz, t, num_emb), w.dtype)
    pos = jnp.arange(t)
    for n in range(2, layers + 1):
        h = jnp.full(toks.shape, jnp.uint32(2166136261))
        for j in range(n):
            h = _mix_hash(h, jnp.roll(toks, -j, axis=1))
        ok = (pos[None, :] + n) <= lens[:, None]       # gram fits
        gram = jnp.zeros((bsz, t, num_emb), w.dtype)
        for cix in range(chunks):
            hc = _mix_hash(h, jnp.uint32(cix + 1))
            start = (hc % jnp.uint32(max(space - rand_len, 1))).astype(
                jnp.int32)
            idx = start[:, :, None] + jnp.arange(rand_len)[None, None, :]
            gram = gram.at[:, :, cix * rand_len:(cix + 1) * rand_len].set(
                w[idx])
        out = out + jnp.where(ok[:, :, None], gram, 0.0)
    if training and drop > 0:
        keepp = 1.0 - drop
        mask = jax.random.bernoulli(ctx.rng(), keepp, out.shape)
        out = jnp.where(mask, out / keepp, 0.0)
    return {"Out": [out]}
