"""Op library: importing this package registers every operator's JAX lowering.

This is the TPU-native equivalent of the reference's static-registrar op
library (`paddle/fluid/operators/`, 588 REGISTER_OPERATOR sites): one pure
JAX lowering per op instead of per-(place,dtype,layout) kernels, with XLA as
the kernel backend and fuser.
"""

from . import (  # noqa: F401
    crf_ops,
    detection_ops,
    extra_ops,
    linalg_ops,
    math_ops,
    metric_ops,
    nn_ops,
    optimizer_ops,
    py_func_op,
    quant_ops,
    random_ops,
    reduce_ops,
    rnn_ops,
    search_ops,
    sequence_ops,
    tail_nn_ops,
    tail_ops,
    tensor_ops,
)
from .optimizer_ops import OPTIMIZER_OP_TYPES  # noqa: F401
