"""Dense math ops: elementwise (w/ axis broadcast), activations, matmul.

Capability parity: reference `paddle/fluid/operators/elementwise/`,
`activation_op.cc`, `matmul_op.cc`, `mul_op.cc`.  Each op here is ONE pure
JAX lowering — XLA supplies the CPU/TPU kernels and the fusion that the
reference implemented by hand (elementwise CUDA kernels, fused activations).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _paddle_bcast(x, y, axis):
    """Reference broadcast rule (elementwise_op.h): align Y to X at `axis`."""
    if x.ndim == y.ndim:
        return x, y
    if y.ndim > x.ndim:  # numpy-style fallback
        return x, y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return x, y.reshape(new_shape)


def _register_elementwise(name, fn):
    @register_op(
        "elementwise_" + name, inputs=["X", "Y"], outputs=["Out"]
    )
    def _lower(ctx, ins, attrs, fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _paddle_bcast(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}


_register_elementwise("add", jnp.add)
_register_elementwise("sub", jnp.subtract)
_register_elementwise("mul", jnp.multiply)
_register_elementwise("div", jnp.divide)
_register_elementwise("pow", jnp.power)
_register_elementwise("max", jnp.maximum)
_register_elementwise("min", jnp.minimum)
_register_elementwise("mod", jnp.mod)
_register_elementwise("floordiv", jnp.floor_divide)


# -- activations (cf. activation_op.cc) --------------------------------------

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "exp": jnp.exp,
    "log": jnp.log,
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "abs": jnp.abs,
    "square": jnp.square,
    "reciprocal": lambda x: 1.0 / x,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "silu": jax.nn.silu,
    "erf": jax.lax.erf,
    "sign": jnp.sign,
    "logsigmoid": jax.nn.log_sigmoid,
}


def _register_activation(name, fn):
    @register_op(name, inputs=["X"], outputs=["Out"])
    def _lower(ctx, ins, attrs, fn=fn):
        return {"Out": [fn(ins["X"][0])]}


for _name, _fn in _ACTIVATIONS.items():
    _register_activation(_name, _fn)


@register_op("leaky_relu", inputs=["X"], outputs=["Out"])
def _leaky_relu(ctx, ins, attrs):
    alpha = attrs.get("alpha", 0.02)
    x = ins["X"][0]
    return {"Out": [jnp.where(x >= 0, x, alpha * x)]}


@register_op("elu", inputs=["X"], outputs=["Out"])
def _elu(ctx, ins, attrs):
    return {"Out": [jax.nn.elu(ins["X"][0], alpha=attrs.get("alpha", 1.0))]}


@register_op("gelu", inputs=["X"], outputs=["Out"])
def _gelu(ctx, ins, attrs):
    approx = attrs.get("approximate", False)
    return {"Out": [jax.nn.gelu(ins["X"][0], approximate=approx)]}


@register_op("hard_sigmoid", inputs=["X"], outputs=["Out"])
def _hard_sigmoid(ctx, ins, attrs):
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return {"Out": [jnp.clip(ins["X"][0] * slope + offset, 0.0, 1.0)]}


@register_op("swish", inputs=["X"], outputs=["Out"])
def _swish(ctx, ins, attrs):
    beta = attrs.get("beta", 1.0)
    x = ins["X"][0]
    return {"Out": [x * jax.nn.sigmoid(beta * x)]}


@register_op("relu6", inputs=["X"], outputs=["Out"])
def _relu6(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], 0.0, attrs.get("threshold", 6.0))]}


@register_op("pow", inputs=["X"], outputs=["Out"])
def _pow(ctx, ins, attrs):
    return {"Out": [jnp.power(ins["X"][0], attrs.get("factor", 1.0))]}


@register_op("scale", inputs=["X"], outputs=["Out"])
def _scale(ctx, ins, attrs):
    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        out = x * s + b
    else:
        out = (x + b) * s
    return {"Out": [out.astype(x.dtype)]}


@register_op("clip", inputs=["X"], outputs=["Out"])
def _clip(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], attrs["min"], attrs["max"])]}


@register_op("softmax", inputs=["X"], outputs=["Out"])
def _softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


@register_op("log_softmax", inputs=["X"], outputs=["Out"])
def _log_softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


# -- matmul family -----------------------------------------------------------


@register_op("matmul", inputs=["X", "Y"], outputs=["Out"])
def _matmul(ctx, ins, attrs):
    """cf. matmul_op.cc: optional transposes + alpha, batched by leading dims.

    TPU note: this is the MXU path; executor-level precision policy decides
    bf16 accumulation (see amp).  We keep the contraction in one jnp.matmul
    so XLA tiles it onto the systolic array.
    """
    x, y = ins["X"][0], ins["Y"][0]
    tx = attrs.get("transpose_X", attrs.get("transpose_x", False))
    ty = attrs.get("transpose_Y", attrs.get("transpose_y", False))
    alpha = attrs.get("alpha", 1.0)
    if tx:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ty:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("mul", inputs=["X", "Y"], outputs=["Out"])
def _mul(ctx, ins, attrs):
    """cf. mul_op.cc: flatten X to 2D at x_num_col_dims, Y at y_num_col_dims."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    x2 = x.reshape((-1, _prod(x.shape[xn:])))
    y2 = y.reshape((int(_prod(y.shape[:yn])), -1))
    out2 = jnp.matmul(x2, y2)
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {"Out": [out2.reshape(out_shape)]}


def _prod(xs):
    r = 1
    for v in xs:
        r *= int(v)
    return r


@register_op("dot", inputs=["X", "Y"], outputs=["Out"])
def _dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=True)]}


@register_op("bmm", inputs=["X", "Y"], outputs=["Out"])
def _bmm(ctx, ins, attrs):
    return {"Out": [jnp.matmul(ins["X"][0], ins["Y"][0])]}


@register_op("addmm", inputs=["Input", "X", "Y"], outputs=["Out"])
def _addmm(ctx, ins, attrs):
    alpha = attrs.get("Alpha", 1.0)
    beta = attrs.get("Beta", 1.0)
    return {
        "Out": [beta * ins["Input"][0] + alpha * jnp.matmul(ins["X"][0], ins["Y"][0])]
    }


@register_op("sum", inputs=["X"], outputs=["Out"])
def _sum(ctx, ins, attrs):
    """Multi-input elementwise add (grad accumulation; cf. sum_op.cc)."""
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}
