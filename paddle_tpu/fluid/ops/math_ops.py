"""Dense math ops: elementwise (w/ axis broadcast), activations, matmul.

Capability parity: reference `paddle/fluid/operators/elementwise/`,
`activation_op.cc`, `matmul_op.cc`, `mul_op.cc`.  Each op here is ONE pure
JAX lowering — XLA supplies the CPU/TPU kernels and the fusion that the
reference implemented by hand (elementwise CUDA kernels, fused activations).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


def _paddle_bcast(x, y, axis):
    """Reference broadcast rule (elementwise_op.h): align Y to X at `axis`."""
    if x.ndim == y.ndim:
        return x, y
    if y.ndim > x.ndim:  # numpy-style fallback
        return x, y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return x, y.reshape(new_shape)


def _register_elementwise(name, fn):
    @register_op(
        "elementwise_" + name, inputs=["X", "Y"], outputs=["Out"]
    )
    def _lower(ctx, ins, attrs, fn=fn):
        x, y = ins["X"][0], ins["Y"][0]
        x, y = _paddle_bcast(x, y, attrs.get("axis", -1))
        return {"Out": [fn(x, y)]}


_register_elementwise("add", jnp.add)
_register_elementwise("sub", jnp.subtract)
_register_elementwise("mul", jnp.multiply)
_register_elementwise("div", jnp.divide)
_register_elementwise("pow", jnp.power)
_register_elementwise("max", jnp.maximum)
_register_elementwise("min", jnp.minimum)
_register_elementwise("mod", jnp.mod)
_register_elementwise("floordiv", jnp.floor_divide)


# -- activations (cf. activation_op.cc) --------------------------------------

_ACTIVATIONS = {
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "exp": jnp.exp,
    "log": jnp.log,
    "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt,
    "abs": jnp.abs,
    "square": jnp.square,
    "reciprocal": lambda x: 1.0 / x,
    "floor": jnp.floor,
    "ceil": jnp.ceil,
    "round": jnp.round,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "softplus": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
    "silu": jax.nn.silu,
    "erf": jax.lax.erf,
    "sign": jnp.sign,
    "logsigmoid": jax.nn.log_sigmoid,
}


def _register_activation(name, fn):
    @register_op(name, inputs=["X"], outputs=["Out"])
    def _lower(ctx, ins, attrs, fn=fn):
        return {"Out": [fn(ins["X"][0])]}


for _name, _fn in _ACTIVATIONS.items():
    _register_activation(_name, _fn)


@register_op("leaky_relu", inputs=["X"], outputs=["Out"])
def _leaky_relu(ctx, ins, attrs):
    alpha = attrs.get("alpha", 0.02)
    x = ins["X"][0]
    return {"Out": [jnp.where(x >= 0, x, alpha * x)]}


@register_op("elu", inputs=["X"], outputs=["Out"])
def _elu(ctx, ins, attrs):
    return {"Out": [jax.nn.elu(ins["X"][0], alpha=attrs.get("alpha", 1.0))]}


@register_op("gelu", inputs=["X"], outputs=["Out"])
def _gelu(ctx, ins, attrs):
    approx = attrs.get("approximate", False)
    return {"Out": [jax.nn.gelu(ins["X"][0], approximate=approx)]}


@register_op("hard_sigmoid", inputs=["X"], outputs=["Out"])
def _hard_sigmoid(ctx, ins, attrs):
    slope = attrs.get("slope", 0.2)
    offset = attrs.get("offset", 0.5)
    return {"Out": [jnp.clip(ins["X"][0] * slope + offset, 0.0, 1.0)]}


@register_op("swish", inputs=["X"], outputs=["Out"])
def _swish(ctx, ins, attrs):
    beta = attrs.get("beta", 1.0)
    x = ins["X"][0]
    return {"Out": [x * jax.nn.sigmoid(beta * x)]}


@register_op("relu6", inputs=["X"], outputs=["Out"])
def _relu6(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], 0.0, attrs.get("threshold", 6.0))]}


@register_op("pow", inputs=["X"], outputs=["Out"])
def _pow(ctx, ins, attrs):
    return {"Out": [jnp.power(ins["X"][0], attrs.get("factor", 1.0))]}


@register_op("scale", inputs=["X"], outputs=["Out"])
def _scale(ctx, ins, attrs):
    x = ins["X"][0]
    s = attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if attrs.get("bias_after_scale", True):
        out = x * s + b
    else:
        out = (x + b) * s
    return {"Out": [out.astype(x.dtype)]}


@register_op("clip", inputs=["X"], outputs=["Out"])
def _clip(ctx, ins, attrs):
    return {"Out": [jnp.clip(ins["X"][0], attrs["min"], attrs["max"])]}


@register_op("softmax", inputs=["X"], outputs=["Out"])
def _softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


@register_op("log_softmax", inputs=["X"], outputs=["Out"])
def _log_softmax(ctx, ins, attrs):
    return {"Out": [jax.nn.log_softmax(ins["X"][0], axis=attrs.get("axis", -1))]}


# -- matmul family -----------------------------------------------------------


@register_op("matmul", inputs=["X", "Y"], outputs=["Out"])
def _matmul(ctx, ins, attrs):
    """cf. matmul_op.cc: optional transposes + alpha, batched by leading dims.

    TPU note: this is the MXU path; executor-level precision policy decides
    bf16 accumulation (see amp).  We keep the contraction in one jnp.matmul
    so XLA tiles it onto the systolic array.
    """
    x, y = ins["X"][0], ins["Y"][0]
    tx = attrs.get("transpose_X", attrs.get("transpose_x", False))
    ty = attrs.get("transpose_Y", attrs.get("transpose_y", False))
    alpha = attrs.get("alpha", 1.0)
    if tx:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if ty:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    if alpha != 1.0:
        out = out * alpha
    return {"Out": [out]}


@register_op("mul", inputs=["X", "Y"], outputs=["Out"])
def _mul(ctx, ins, attrs):
    """cf. mul_op.cc: flatten X to 2D at x_num_col_dims, Y at y_num_col_dims."""
    x, y = ins["X"][0], ins["Y"][0]
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    x2 = x.reshape((-1, _prod(x.shape[xn:])))
    y2 = y.reshape((int(_prod(y.shape[:yn])), -1))
    out2 = jnp.matmul(x2, y2)
    out_shape = x.shape[:xn] + y.shape[yn:]
    return {"Out": [out2.reshape(out_shape)]}


def _prod(xs):
    r = 1
    for v in xs:
        r *= int(v)
    return r


@register_op("dot", inputs=["X", "Y"], outputs=["Out"])
def _dot(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    return {"Out": [jnp.sum(x * y, axis=-1, keepdims=True)]}


@register_op("matmul_bias_act", inputs=["X", "Y", "Bias"], outputs=["Out"])
def _matmul_bias_act(ctx, ins, attrs):
    """Fused-epilogue GEMM: matmul + bias add + activation in one op.

    The target of `fluid.ir.MatmulBiasActFusePass` (which rewrites the
    matmul/mul -> elementwise_add -> act chains the `unfused-epilogue`
    lint flags) and of `nn.functional.fused_linear`.  On TPU, plain
    untransposed 128-tileable shapes lower to the pallas fused-epilogue
    kernel (`ops.pallas.matmul.matmul_bias_act`, custom-VJP fused
    backward); everything else lowers to the jnp composition XLA fuses
    itself — numerically the same contraction either way (f32
    accumulation).

    attrs: ``act_type`` in {none, relu, tanh, gelu} (+``approximate``
    for the tanh gelu), and ONE of the two source-op attr conventions —
    mul-style ``x_num_col_dims``/``y_num_col_dims`` flattening, or
    matmul-style ``transpose_X``/``transpose_Y``/``alpha``."""
    import jax as _jax

    x, w = ins["X"][0], ins["Y"][0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    act = attrs.get("act_type", "none")
    if act not in ("none", "relu", "tanh", "gelu"):
        # validated on EVERY path: the batched/naive branches below
        # would otherwise silently return un-activated output for an
        # activation the pallas path raises on
        raise ValueError(
            "matmul_bias_act act_type must be one of "
            "('none', 'relu', 'tanh', 'gelu'), got %r" % act)
    approx = attrs.get("approximate", False)
    xn = attrs.get("x_num_col_dims")
    if xn is not None:                      # mul-style flatten
        yn = attrs.get("y_num_col_dims", 1)
        out_shape = x.shape[:xn] + w.shape[yn:]
        x2 = x.reshape((-1, _prod(x.shape[xn:])))
        w2 = w.reshape((int(_prod(w.shape[:yn])), -1))
        alpha, tx, ty = 1.0, False, False
    else:                                   # matmul-style
        tx = attrs.get("transpose_X", attrs.get("transpose_x", False))
        ty = attrs.get("transpose_Y", attrs.get("transpose_y", False))
        alpha = attrs.get("alpha", 1.0)
        x2 = jnp.swapaxes(x, -1, -2) if (tx and x.ndim > 1) else x
        w2 = jnp.swapaxes(w, -1, -2) if (ty and w.ndim > 1) else w
        out_shape = None                    # jnp.matmul shape as-is

    from ...ops.pallas.matmul import matmul_bias_act, naive_matmul_bias_act

    use_pallas = (
        _jax.default_backend() == "tpu"
        and x2.ndim == 2 and w2.ndim == 2
        and not tx and not ty and alpha == 1.0
        and x2.shape[0] % 128 == 0 and x2.shape[1] % 128 == 0
        and w2.shape[1] % 128 == 0
    )
    if use_pallas:
        out = matmul_bias_act(x2, w2, bias, activation=act,
                              approximate=approx)
    else:
        if x2.ndim == 2 and w2.ndim == 2 and alpha == 1.0:
            out = naive_matmul_bias_act(x2, w2, bias, activation=act,
                                        approximate=approx)
        else:
            out = jnp.matmul(x2, w2)
            if alpha != 1.0:
                out = out * alpha
            if bias is not None:
                out = out + bias
            if act == "gelu":
                out = _jax.nn.gelu(out, approximate=approx)
            elif act == "relu":
                out = _jax.nn.relu(out)
            elif act == "tanh":
                out = jnp.tanh(out)
    if out_shape is not None:
        out = out.reshape(out_shape)
    return {"Out": [out]}


@register_op("bmm", inputs=["X", "Y"], outputs=["Out"])
def _bmm(ctx, ins, attrs):
    return {"Out": [jnp.matmul(ins["X"][0], ins["Y"][0])]}


@register_op("addmm", inputs=["Input", "X", "Y"], outputs=["Out"])
def _addmm(ctx, ins, attrs):
    alpha = attrs.get("Alpha", 1.0)
    beta = attrs.get("Beta", 1.0)
    return {
        "Out": [beta * ins["Input"][0] + alpha * jnp.matmul(ins["X"][0], ins["Y"][0])]
    }


@register_op("sum", inputs=["X"], outputs=["Out"])
def _sum(ctx, ins, attrs):
    """Multi-input elementwise add (grad accumulation; cf. sum_op.cc)."""
    xs = ins["X"]
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# 2.x math tail (reference elementwise_fmax/fmin, remainder, heaviside,
# logit, nansum/nanmean, amax/amin, median/quantile, std/var ops)
# ---------------------------------------------------------------------------


@register_op("elementwise_fmax", inputs=["X", "Y"], outputs=["Out"])
def _fmax(ctx, ins, attrs):
    return {"Out": [jnp.fmax(ins["X"][0], ins["Y"][0])]}


@register_op("elementwise_fmin", inputs=["X", "Y"], outputs=["Out"])
def _fmin(ctx, ins, attrs):
    return {"Out": [jnp.fmin(ins["X"][0], ins["Y"][0])]}


@register_op("remainder", inputs=["X", "Y"], outputs=["Out"], grad=None)
def _remainder(ctx, ins, attrs):
    return {"Out": [jnp.remainder(ins["X"][0], ins["Y"][0])]}


@register_op("heaviside", inputs=["X", "Y"], outputs=["Out"], grad=None)
def _heaviside(ctx, ins, attrs):
    return {"Out": [jnp.heaviside(ins["X"][0], ins["Y"][0])]}


@register_op("logit", inputs=["X"], outputs=["Out"])
def _logit(ctx, ins, attrs):
    eps = float(attrs.get("eps", 0.0))
    x = ins["X"][0]
    if eps > 0:
        x = jnp.clip(x, eps, 1.0 - eps)
    return {"Out": [jnp.log(x) - jnp.log1p(-x)]}


@register_op("logaddexp", inputs=["X", "Y"], outputs=["Out"])
def _logaddexp(ctx, ins, attrs):
    return {"Out": [jnp.logaddexp(ins["X"][0], ins["Y"][0])]}


def _axis_of(attrs):
    a = attrs.get("axis", attrs.get("dim", None))
    if a in (None, [], ()):
        return None
    return tuple(a) if isinstance(a, (list, tuple)) else int(a)


@register_op("nansum", inputs=["X"], outputs=["Out"], grad=None)
def _nansum(ctx, ins, attrs):
    return {"Out": [jnp.nansum(ins["X"][0], axis=_axis_of(attrs),
                               keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("nanmean", inputs=["X"], outputs=["Out"], grad=None)
def _nanmean(ctx, ins, attrs):
    return {"Out": [jnp.nanmean(ins["X"][0], axis=_axis_of(attrs),
                                keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("reduce_amax", inputs=["X"], outputs=["Out"], grad=None)
def _amax(ctx, ins, attrs):
    return {"Out": [jnp.amax(ins["X"][0], axis=_axis_of(attrs),
                             keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("reduce_amin", inputs=["X"], outputs=["Out"], grad=None)
def _amin(ctx, ins, attrs):
    return {"Out": [jnp.amin(ins["X"][0], axis=_axis_of(attrs),
                             keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("median", inputs=["X"], outputs=["Out"], grad=None)
def _median(ctx, ins, attrs):
    return {"Out": [jnp.median(ins["X"][0], axis=_axis_of(attrs),
                               keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("quantile", inputs=["X"], outputs=["Out"], grad=None)
def _quantile(ctx, ins, attrs):
    q = attrs["q"]
    return {"Out": [jnp.quantile(
        ins["X"][0], jnp.asarray(q), axis=_axis_of(attrs),
        keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("reduce_std", inputs=["X"], outputs=["Out"])
def _std(ctx, ins, attrs):
    return {"Out": [jnp.std(
        ins["X"][0], axis=_axis_of(attrs),
        ddof=1 if attrs.get("unbiased", True) else 0,
        keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("reduce_var", inputs=["X"], outputs=["Out"])
def _var(ctx, ins, attrs):
    return {"Out": [jnp.var(
        ins["X"][0], axis=_axis_of(attrs),
        ddof=1 if attrs.get("unbiased", True) else 0,
        keepdims=bool(attrs.get("keep_dim", False)))]}


@register_op("brelu", inputs=["X"], outputs=["Out"])
def _brelu(ctx, ins, attrs):
    lo = float(attrs.get("t_min", 0.0))
    hi = float(attrs.get("t_max", 24.0))
    return {"Out": [jnp.clip(ins["X"][0], lo, hi)]}


@register_op("soft_relu", inputs=["X"], outputs=["Out"])
def _soft_relu(ctx, ins, attrs):
    t = float(attrs.get("threshold", 40.0))
    x = jnp.clip(ins["X"][0], -t, t)
    return {"Out": [jnp.log1p(jnp.exp(x))]}


@register_op("logcumsumexp", inputs=["X"], outputs=["Out"])
def _logcumsumexp(ctx, ins, attrs):
    axis = int(attrs.get("axis", -1))
    x = ins["X"][0]
    m = jnp.max(x, axis=axis, keepdims=True)
    return {"Out": [jnp.log(jnp.cumsum(jnp.exp(x - m), axis=axis)) + m]}


@register_op("gcd", inputs=["X", "Y"], outputs=["Out"], grad=None)
def _gcd(ctx, ins, attrs):
    return {"Out": [jnp.gcd(ins["X"][0], ins["Y"][0])]}


@register_op("lcm", inputs=["X", "Y"], outputs=["Out"], grad=None)
def _lcm(ctx, ins, attrs):
    return {"Out": [jnp.lcm(ins["X"][0], ins["Y"][0])]}


@register_op("addcmul", inputs=["Input", "Tensor1", "Tensor2"],
             outputs=["Out"])
def _addcmul(ctx, ins, attrs):
    v = float(attrs.get("value", 1.0))
    return {"Out": [ins["Input"][0]
                    + v * ins["Tensor1"][0] * ins["Tensor2"][0]]}


@register_op("lerp", inputs=["X", "Y", "Weight"], outputs=["Out"])
def _lerp(ctx, ins, attrs):
    x, y, w = ins["X"][0], ins["Y"][0], ins["Weight"][0]
    return {"Out": [x + w * (y - x)]}


@register_op("i0", inputs=["X"], outputs=["Out"])
def _i0(ctx, ins, attrs):
    from jax.scipy.special import i0

    return {"Out": [i0(ins["X"][0])]}


@register_op("i1", inputs=["X"], outputs=["Out"])
def _i1(ctx, ins, attrs):
    from jax.scipy.special import i1

    return {"Out": [i1(ins["X"][0])]}


@register_op("isinf", inputs=["X"], outputs=["Out"], grad=None)
def _isinf(ctx, ins, attrs):
    return {"Out": [jnp.isinf(ins["X"][0])]}


@register_op("l1_norm", inputs=["X"], outputs=["Out"])
def _l1_norm(ctx, ins, attrs):
    return {"Out": [jnp.sum(jnp.abs(ins["X"][0]))]}


@register_op("frobenius_norm", inputs=["X"], outputs=["Out"])
def _frobenius_norm(ctx, ins, attrs):
    axis = attrs.get("axis")
    return {"Out": [jnp.sqrt(jnp.sum(
        ins["X"][0] ** 2,
        axis=tuple(axis) if axis else None,
        keepdims=bool(attrs.get("keep_dim", False))))]}


@register_op("modified_huber_loss", inputs=["X", "Y"],
             outputs=["Out", "IntermediateVal"], no_grad_slots=("Y",))
def _modified_huber_loss(ctx, ins, attrs):
    """cf. modified_huber_loss_op.cc: binary classification loss on
    margin z = (2y-1)*x: max(0,1-z)^2 for z >= -1, else -4z."""
    x = ins["X"][0].reshape(-1)
    y = ins["Y"][0].reshape(-1).astype(x.dtype)
    z = (2.0 * y - 1.0) * x
    loss = jnp.where(z >= -1.0, jnp.maximum(0.0, 1.0 - z) ** 2, -4.0 * z)
    return {"Out": [loss[:, None]], "IntermediateVal": [z[:, None]]}


@register_op("clip_by_norm", inputs=["X"], outputs=["Out"])
def _clip_by_norm(ctx, ins, attrs):
    x = ins["X"][0]
    mx = float(attrs["max_norm"])
    norm = jnp.sqrt(jnp.sum(x * x))
    return {"Out": [jnp.where(norm > mx, x * (mx / jnp.maximum(norm, 1e-12)),
                              x)]}
