"""Random/initializer ops (cf. operators/gaussian_random_op.cc,
uniform_random_op.cc, truncated_gaussian_random_op.cc, randperm_op.cc).

TPU-first: stateless threefry PRNG.  If an op carries a nonzero `seed` attr it
derives its own key (reproducible op, reference semantics); otherwise keys come
from the executor-threaded program key via ctx.rng().
"""

import jax
import jax.numpy as jnp

from ..core.dtypes import to_jnp
from ..core.registry import register_op


def _key(ctx, attrs):
    seed = attrs.get("seed", 0)
    if seed:
        return jax.random.PRNGKey(seed)
    return ctx.rng()


def step_seeded_key(ctx, attrs):
    """Seed folded into the STEP-varying key: a nonzero seed makes the
    stream reproducible across runs while still drawing fresh values
    every step (shuffle_batch's contract — the draw must change per
    step; plain PRNGKey(seed) would freeze it)."""
    seed = int(attrs.get("seed", 0))
    key = ctx.rng()
    return jax.random.fold_in(key, seed) if seed else key


@register_op("gaussian_random", inputs=[], outputs=["Out"], grad=None, needs_rng=True)
def _gaussian_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jnp(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.normal(
        _key(ctx, attrs), shape, dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op("uniform_random", inputs=[], outputs=["Out"], grad=None, needs_rng=True)
def _uniform_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jnp(attrs.get("dtype", "float32"))
    out = jax.random.uniform(
        _key(ctx, attrs),
        shape,
        minval=attrs.get("min", -1.0),
        maxval=attrs.get("max", 1.0),
        dtype=jnp.float32,
    )
    return {"Out": [out.astype(dtype)]}


@register_op(
    "truncated_gaussian_random", inputs=[], outputs=["Out"], grad=None, needs_rng=True
)
def _truncated_gaussian_random(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    dtype = to_jnp(attrs.get("dtype", "float32"))
    out = attrs.get("mean", 0.0) + attrs.get("std", 1.0) * jax.random.truncated_normal(
        _key(ctx, attrs), -2.0, 2.0, shape, dtype=jnp.float32
    )
    return {"Out": [out.astype(dtype)]}


@register_op("randint", inputs=[], outputs=["Out"], grad=None, needs_rng=True)
def _randint(ctx, ins, attrs):
    shape = tuple(attrs["shape"])
    out = jax.random.randint(
        _key(ctx, attrs), shape, attrs.get("low", 0), attrs["high"]
    )
    return {"Out": [out.astype(to_jnp(attrs.get("dtype", "int64")))]}


@register_op("randperm", inputs=[], outputs=["Out"], grad=None, needs_rng=True)
def _randperm(ctx, ins, attrs):
    n = attrs["n"]
    out = jax.random.permutation(_key(ctx, attrs), n)
    return {"Out": [out.astype(to_jnp(attrs.get("dtype", "int64")))]}


@register_op("bernoulli", inputs=["X"], outputs=["Out"], grad=None, needs_rng=True)
def _bernoulli(ctx, ins, attrs):
    x = ins["X"][0]
    out = jax.random.bernoulli(_key(ctx, attrs), x)
    return {"Out": [out.astype(x.dtype)]}
