"""Breadth op families beyond the round-1 core.

Capability parity by family (reference `paddle/fluid/operators/`):
- activations: activation_op.cc (the full registry, not just the common 12)
- manipulation: roll_op.cc, flip_op.cc, meshgrid_op.cc, expand_v2_op.cc,
  repeat_interleave (newer tree), take/put_along_axis, scatter_nd_op.cc,
  unfold_op.cc, argsort_op.cc (sort), searchsorted, kthvalue, shard_index_op.cc
- losses: kldiv_loss_op.cc, log_loss_op.cc, label_smooth_op.cc,
  margin_rank_loss_op.cc, hinge_loss_op.cc, cos_sim_op.cc, nll_loss_op.cc,
  rank_loss_op.cc, bce_loss_op.cc, smooth_l1_loss_op.cc
- norms: instance_norm_op.cc, sync_batch_norm_op.cu (psum of batch stats
  over the data-parallel axis — here a mesh-axis pmean inside shard_map),
  spectral_norm_op.cc, data_norm_op.cc
- vision: grid_sampler_op.cc, affine_grid_op.cc, interpolate_op.cc
  (bilinear/nearest), pixel_shuffle_op.cc, conv3d (conv_op.cc), pool3d
  (pool_op.cc)

Every lowering is pure jnp/lax; XLA fuses and tiles them (the reference
hand-wrote one CUDA kernel per op per dtype).
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

# ---------------------------------------------------------------------------
# activation extras (cf. activation_op.cc full registry)
# ---------------------------------------------------------------------------


def _register_unary(name, fn):
    @register_op(name, inputs=["X"], outputs=["Out"])
    def _lower(ctx, ins, attrs, fn=fn):
        return {"Out": [fn(ins["X"][0], attrs)]}


_register_unary("sinh", lambda x, a: jnp.sinh(x))
_register_unary("cosh", lambda x, a: jnp.cosh(x))
_register_unary("tan", lambda x, a: jnp.tan(x))
_register_unary("asin", lambda x, a: jnp.arcsin(x))
_register_unary("acos", lambda x, a: jnp.arccos(x))
_register_unary("atan", lambda x, a: jnp.arctan(x))
_register_unary("asinh", lambda x, a: jnp.arcsinh(x))
_register_unary("acosh", lambda x, a: jnp.arccosh(x))
_register_unary("atanh", lambda x, a: jnp.arctanh(x))
_register_unary("expm1", lambda x, a: jnp.expm1(x))
_register_unary("log1p", lambda x, a: jnp.log1p(x))
_register_unary("log2", lambda x, a: jnp.log2(x))
_register_unary("log10", lambda x, a: jnp.log10(x))
_register_unary("lgamma", lambda x, a: jax.lax.lgamma(x))
_register_unary("digamma", lambda x, a: jax.lax.digamma(x))
_register_unary("erfinv", lambda x, a: jax.lax.erf_inv(x))
_register_unary("trunc", lambda x, a: jnp.trunc(x))
_register_unary("frac", lambda x, a: x - jnp.trunc(x))
_register_unary(
    "hard_swish",
    # reference: x * min(max(0, x + offset), threshold) / scale
    lambda x, a: x * jnp.clip(
        x + a.get("offset", 3.0), 0.0, a.get("threshold", 6.0)
    ) / a.get("scale", 6.0),
)
_register_unary(
    "hard_shrink",
    lambda x, a: jnp.where(
        jnp.abs(x) > a.get("threshold", 0.5), x, 0.0
    ),
)
_register_unary(
    "softshrink",
    lambda x, a: jnp.sign(x) * jnp.maximum(
        jnp.abs(x) - a.get("lambda", 0.5), 0.0
    ),
)
_register_unary("tanh_shrink", lambda x, a: x - jnp.tanh(x))
_register_unary(
    "thresholded_relu",
    lambda x, a: jnp.where(x > a.get("threshold", 1.0), x, 0.0),
)
_register_unary(
    "stanh",
    lambda x, a: a.get("scale_b", 1.7159)
    * jnp.tanh(a.get("scale_a", 0.67) * x),
)
_register_unary("mish", lambda x, a: x * jnp.tanh(jax.nn.softplus(x)))
_register_unary("celu", lambda x, a: jax.nn.celu(x, a.get("alpha", 1.0)))
_register_unary("selu", lambda x, a: jax.nn.selu(x))
_register_unary("erfc", lambda x, a: jax.lax.erfc(x))


@register_op("atan2", inputs=["X1", "X2"], outputs=["Out"])
def _atan2(ctx, ins, attrs):
    return {"Out": [jnp.arctan2(ins["X1"][0], ins["X2"][0])]}


@register_op("logsumexp", inputs=["X"], outputs=["Out"])
def _logsumexp(ctx, ins, attrs):
    axis = attrs.get("axis")
    keepdim = attrs.get("keepdim", False)
    axis = tuple(axis) if isinstance(axis, (list, tuple)) else axis
    return {"Out": [jax.scipy.special.logsumexp(
        ins["X"][0], axis=axis, keepdims=keepdim
    )]}


@register_op("cumprod", inputs=["X"], outputs=["Out"])
def _cumprod(ctx, ins, attrs):
    return {"Out": [jnp.cumprod(ins["X"][0], axis=int(attrs.get("dim", -1)))]}


# ---------------------------------------------------------------------------
# manipulation
# ---------------------------------------------------------------------------


@register_op("roll", inputs=["X"], outputs=["Out"])
def _roll(ctx, ins, attrs):
    shifts = attrs["shifts"]
    axis = attrs.get("axis")
    if axis is None or axis == []:
        if len(shifts) != 1:
            raise ValueError(
                "roll: %d shifts but no axis — pass one axis per shift"
                % len(shifts)
            )
        return {"Out": [jnp.roll(ins["X"][0].reshape(-1),
                                 shifts[0]).reshape(ins["X"][0].shape)]}
    return {"Out": [jnp.roll(ins["X"][0], tuple(shifts), tuple(axis))]}


@register_op("flip", inputs=["X"], outputs=["Out"])
def _flip(ctx, ins, attrs):
    return {"Out": [jnp.flip(ins["X"][0], axis=tuple(attrs["axis"]))]}


@register_op("meshgrid", inputs=["X"], outputs=["Out"])
def _meshgrid(ctx, ins, attrs):
    return {"Out": list(jnp.meshgrid(*ins["X"], indexing="ij"))}


@register_op("broadcast_to", inputs=["X"], outputs=["Out"])
def _broadcast_to(ctx, ins, attrs):
    return {"Out": [jnp.broadcast_to(ins["X"][0], tuple(attrs["shape"]))]}


@register_op("repeat_interleave", inputs=["X"], outputs=["Out"])
def _repeat_interleave(ctx, ins, attrs):
    return {"Out": [jnp.repeat(
        ins["X"][0], int(attrs["repeats"]), axis=attrs.get("dim")
    )]}


@register_op("take_along_axis", inputs=["Input", "Index"], outputs=["Result"],
             no_grad_slots=("Index",))
def _take_along_axis(ctx, ins, attrs):
    return {"Result": [jnp.take_along_axis(
        ins["Input"][0], ins["Index"][0].astype(jnp.int32),
        axis=int(attrs["Axis"]),
    )]}


@register_op("put_along_axis", inputs=["Input", "Index", "Value"],
             outputs=["Result"], no_grad_slots=("Index",))
def _put_along_axis(ctx, ins, attrs):
    x, idx, v = ins["Input"][0], ins["Index"][0], ins["Value"][0]
    axis = int(attrs["Axis"])
    reduce = attrs.get("Reduce", "assign")
    idx = idx.astype(jnp.int32)
    dims = [jnp.arange(s) for s in idx.shape]
    grids = jnp.meshgrid(*dims, indexing="ij")
    grids[axis] = idx
    v = jnp.broadcast_to(v, idx.shape)
    if reduce == "add":
        return {"Result": [x.at[tuple(grids)].add(v)]}
    if reduce == "multiply" or reduce == "mul":
        return {"Result": [x.at[tuple(grids)].multiply(v)]}
    return {"Result": [x.at[tuple(grids)].set(v)]}


@register_op("scatter_nd_add", inputs=["X", "Index", "Updates"],
             outputs=["Out"], no_grad_slots=("Index",))
def _scatter_nd_add(ctx, ins, attrs):
    x, idx, upd = ins["X"][0], ins["Index"][0], ins["Updates"][0]
    idx = idx.astype(jnp.int32)
    return {"Out": [x.at[tuple(jnp.moveaxis(idx, -1, 0))].add(upd)]}


@register_op("unfold", inputs=["X"], outputs=["Y"])
def _unfold(ctx, ins, attrs):
    """im2col (cf. unfold_op.cc / math/im2col.cc): [N,C,H,W] ->
    [N, C*kh*kw, L] — the MXU-friendly patch extraction."""
    x = ins["X"][0]
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs.get("strides", [1, 1])
    pads = list(attrs.get("paddings", [0, 0]))
    if len(pads) == 2:  # symmetric [ph, pw] -> [pt, pl, pb, pr]
        pads = [pads[0], pads[1], pads[0], pads[1]]
    pt, pl, pb, pr = pads
    dh, dw = attrs.get("dilations", [1, 1])
    n, c, h, w = x.shape
    x = jnp.pad(x, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
    oh = (h + pt + pb - dh * (kh - 1) - 1) // sh + 1
    ow = (w + pl + pr - dw * (kw - 1) - 1) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = x[:, :, i * dh:i * dh + oh * sh:sh,
                      j * dw:j * dw + ow * sw:sw]
            cols.append(patch)
    out = jnp.stack(cols, axis=2)  # [N, C, kh*kw, oh, ow]
    return {"Y": [out.reshape(n, c * kh * kw, oh * ow)]}


@register_op("sort", inputs=["X"], outputs=["Out", "Indices"],
             stateful_out_slots=("Indices",))
def _sort(ctx, ins, attrs):
    x = ins["X"][0]
    axis = int(attrs.get("axis", -1))
    desc = attrs.get("descending", False)
    idx = jnp.argsort(x, axis=axis, descending=desc)
    return {"Out": [jnp.take_along_axis(x, idx, axis=axis)],
            "Indices": [idx.astype(jnp.int64)]}


@register_op("searchsorted", inputs=["SortedSequence", "Values"],
             outputs=["Out"], grad=None)
def _searchsorted(ctx, ins, attrs):
    seq, vals = ins["SortedSequence"][0], ins["Values"][0]
    side = "right" if attrs.get("right", False) else "left"
    if seq.ndim == 1:
        out = jnp.searchsorted(seq, vals, side=side)
    else:
        out = jax.vmap(
            lambda s, v: jnp.searchsorted(s, v, side=side)
        )(seq.reshape(-1, seq.shape[-1]), vals.reshape(-1, vals.shape[-1]))
        out = out.reshape(vals.shape)
    dt = jnp.int32 if attrs.get("out_int32", False) else jnp.int64
    return {"Out": [out.astype(dt)]}


@register_op("kthvalue", inputs=["X"], outputs=["Out", "Indices"],
             stateful_out_slots=("Indices",))
def _kthvalue(ctx, ins, attrs):
    x = ins["X"][0]
    k = int(attrs["k"])
    axis = int(attrs.get("axis", -1))
    keepdim = attrs.get("keepdim", False)
    idx = jnp.argsort(x, axis=axis)
    kth_idx = jnp.take(idx, k - 1, axis=axis)
    out = jnp.take_along_axis(
        x, jnp.expand_dims(kth_idx, axis), axis=axis
    )
    if not keepdim:
        out = jnp.squeeze(out, axis)
    return {"Out": [out], "Indices": [kth_idx.astype(jnp.int64)]}


@register_op("shard_index", inputs=["X"], outputs=["Out"], grad=None)
def _shard_index(ctx, ins, attrs):
    """cf. shard_index_op.cc: map global ids to shard-local ids."""
    x = ins["X"][0]
    index_num = int(attrs["index_num"])
    nshards = int(attrs["nshards"])
    shard_id = int(attrs["shard_id"])
    ignore = int(attrs.get("ignore_value", -1))
    per = (index_num + nshards - 1) // nshards
    mine = (x // per) == shard_id
    return {"Out": [jnp.where(mine, x % per, ignore)]}


# ---------------------------------------------------------------------------
# losses
# ---------------------------------------------------------------------------


@register_op("kldiv_loss", inputs=["X", "Target"], outputs=["Loss"])
def _kldiv_loss(ctx, ins, attrs):
    x, t = ins["X"][0], ins["Target"][0]  # x is log-prob (reference semantics)
    loss = t * (jnp.log(jnp.maximum(t, 1e-10)) - x)
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return {"Loss": [jnp.mean(loss)]}
    if red == "sum":
        return {"Loss": [jnp.sum(loss)]}
    if red == "batchmean":
        return {"Loss": [jnp.sum(loss) / x.shape[0]]}
    return {"Loss": [loss]}


@register_op("log_loss", inputs=["Predicted", "Labels"], outputs=["Loss"])
def _log_loss(ctx, ins, attrs):
    p, l = ins["Predicted"][0], ins["Labels"][0]
    e = float(attrs.get("epsilon", 1e-4))
    return {"Loss": [-l * jnp.log(p + e) - (1 - l) * jnp.log(1 - p + e)]}


@register_op("label_smooth", inputs=["X", "PriorDist"], outputs=["Out"])
def _label_smooth(ctx, ins, attrs):
    x = ins["X"][0]
    eps = float(attrs.get("epsilon", 0.1))
    if ins.get("PriorDist"):
        prior = ins["PriorDist"][0]
        return {"Out": [(1 - eps) * x + eps * prior]}
    return {"Out": [(1 - eps) * x + eps / x.shape[-1]]}


@register_op("margin_rank_loss", inputs=["X1", "X2", "Label"],
             outputs=["Out"], no_grad_slots=("Label",))
def _margin_rank_loss(ctx, ins, attrs):
    m = float(attrs.get("margin", 0.0))
    x1, x2, l = ins["X1"][0], ins["X2"][0], ins["Label"][0]
    return {"Out": [jnp.maximum(0.0, -l * (x1 - x2) + m)]}


@register_op("hinge_loss", inputs=["Logits", "Labels"], outputs=["Loss"],
             no_grad_slots=("Labels",))
def _hinge_loss(ctx, ins, attrs):
    x, y = ins["Logits"][0], ins["Labels"][0]
    return {"Loss": [jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * x)]}


@register_op("cos_sim", inputs=["X", "Y"], outputs=["Out"])
def _cos_sim(ctx, ins, attrs):
    x, y = ins["X"][0], ins["Y"][0]
    xn = jnp.sqrt(jnp.sum(x * x, -1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, -1, keepdims=True))
    return {"Out": [jnp.sum(x * y, -1, keepdims=True) / (xn * yn + 1e-12)]}


@register_op("nll_loss", inputs=["X", "Label", "Weight"], outputs=["Out"],
             no_grad_slots=("Label",))
def _nll_loss(ctx, ins, attrs):
    x, label = ins["X"][0], ins["Label"][0]  # x: [N, C] log-probs
    w = ins["Weight"][0] if ins.get("Weight") else jnp.ones(x.shape[1], x.dtype)
    label = label.reshape(-1).astype(jnp.int32)
    picked = -jnp.take_along_axis(x, label[:, None], axis=1)[:, 0]
    wl = w[label]
    red = attrs.get("reduction", "mean")
    if red == "mean":
        return {"Out": [jnp.sum(picked * wl) / jnp.sum(wl)]}
    if red == "sum":
        return {"Out": [jnp.sum(picked * wl)]}
    return {"Out": [picked * wl]}


@register_op("rank_loss", inputs=["Label", "Left", "Right"], outputs=["Out"],
             no_grad_slots=("Label",))
def _rank_loss(ctx, ins, attrs):
    l, x1, x2 = ins["Label"][0], ins["Left"][0], ins["Right"][0]
    d = x1 - x2
    return {"Out": [jax.nn.softplus(d) - l * d]}


@register_op("bce_loss", inputs=["X", "Label"], outputs=["Out"])
def _bce_loss(ctx, ins, attrs):
    x, l = ins["X"][0], ins["Label"][0]
    x = jnp.clip(x, 1e-12, 1.0 - 1e-7)
    return {"Out": [-(l * jnp.log(x) + (1 - l) * jnp.log(1 - x))]}


@register_op("smooth_l1_loss", inputs=["X", "Y"], outputs=["Out", "Diff"],
             stateful_out_slots=("Diff",))
def _smooth_l1_loss(ctx, ins, attrs):
    d = ins["X"][0] - ins["Y"][0]
    sigma = float(attrs.get("sigma", 1.0))
    s2 = sigma * sigma
    a = jnp.abs(d)
    loss = jnp.where(a < 1.0 / s2, 0.5 * s2 * d * d, a - 0.5 / s2)
    return {"Out": [loss], "Diff": [d]}


# ---------------------------------------------------------------------------
# norm variants
# ---------------------------------------------------------------------------


@register_op("instance_norm", inputs=["X", "Scale", "Bias"],
             outputs=["Y", "SavedMean", "SavedVariance"],
             stateful_out_slots=("SavedMean", "SavedVariance"))
def _instance_norm(ctx, ins, attrs):
    x = ins["X"][0]  # [N, C, ...]
    eps = float(attrs.get("epsilon", 1e-5))
    red = tuple(range(2, x.ndim))
    mean = jnp.mean(x, axis=red, keepdims=True)
    var = jnp.var(x, axis=red, keepdims=True)
    y = (x - mean) * jax.lax.rsqrt(var + eps)
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    if ins.get("Scale"):
        y = y * ins["Scale"][0].reshape(shape)
    if ins.get("Bias"):
        y = y + ins["Bias"][0].reshape(shape)
    return {"Y": [y], "SavedMean": [jnp.squeeze(mean)],
            "SavedVariance": [jnp.squeeze(var)]}


@register_op(
    "sync_batch_norm",
    inputs=["X", "Scale", "Bias", "Mean", "Variance"],
    outputs=["Y", "MeanOut", "VarianceOut", "SavedMean", "SavedVariance"],
    stateful_out_slots=("MeanOut", "VarianceOut", "SavedMean",
                        "SavedVariance"),
)
def _sync_batch_norm(ctx, ins, attrs):
    """cf. sync_batch_norm_op.cu: batch statistics are averaged across the
    data-parallel ranks (there: ncclAllReduce of sum/sum-of-squares; here:
    lax.pmean over the `dp` mesh axis when the program runs inside
    shard_map — outside any mapped axis it degenerates to plain BN,
    matching one-rank reference behavior)."""
    from ...distributed.collective import _axis_bound

    x = ins["X"][0]
    eps = float(attrs.get("epsilon", 1e-5))
    mom = float(attrs.get("momentum", 0.9))
    is_test = attrs.get("is_test", False) or ctx.is_test
    axes = (0,) + tuple(range(2, x.ndim))
    shape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    r_mean, r_var = ins["Mean"][0], ins["Variance"][0]
    if is_test:
        mean, var = r_mean, r_var
        new_mean, new_var = r_mean, r_var
    else:
        mean = jnp.mean(x, axis=axes)
        sq = jnp.mean(x * x, axis=axes)
        if _axis_bound("dp"):
            mean = jax.lax.pmean(mean, "dp")
            sq = jax.lax.pmean(sq, "dp")
        var = sq - mean * mean
        new_mean = mom * r_mean + (1 - mom) * mean
        new_var = mom * r_var + (1 - mom) * var
    y = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + eps)
    y = y * ins["Scale"][0].reshape(shape) + ins["Bias"][0].reshape(shape)
    return {
        "Y": [y], "MeanOut": [new_mean], "VarianceOut": [new_var],
        "SavedMean": [mean], "SavedVariance": [var],
    }


@register_op("spectral_norm", inputs=["Weight", "U", "V"], outputs=["Out"],
             no_grad_slots=("U", "V"))
def _spectral_norm(ctx, ins, attrs):
    """cf. spectral_norm_op.cc: power-iteration estimate of sigma_max, then
    W / sigma.  U/V are persistent estimate vectors (updated outside)."""
    w = ins["Weight"][0]
    u, v = ins["U"][0], ins["V"][0]
    dim = int(attrs.get("dim", 0))
    power_iters = int(attrs.get("power_iters", 1))
    eps = float(attrs.get("eps", 1e-12))
    w_mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    for _ in range(power_iters):
        v = w_mat.T @ u
        v = v / (jnp.linalg.norm(v) + eps)
        u = w_mat @ v
        u = u / (jnp.linalg.norm(u) + eps)
    sigma = u @ w_mat @ v
    return {"Out": [w / sigma]}


@register_op(
    "data_norm", inputs=["X", "BatchSize", "BatchSum", "BatchSquareSum"],
    outputs=["Y", "Means", "Scales"],
    stateful_out_slots=("Means", "Scales"),
)
def _data_norm(ctx, ins, attrs):
    """cf. data_norm_op.cc: normalization by accumulated batch statistics
    (CTR models); the running counters update outside the op."""
    x = ins["X"][0]
    n = ins["BatchSize"][0]
    s = ins["BatchSum"][0]
    ss = ins["BatchSquareSum"][0]
    means = s / n
    scales = jnp.sqrt(n / ss)
    return {"Y": [(x - means) * scales], "Means": [means],
            "Scales": [scales]}


# ---------------------------------------------------------------------------
# vision
# ---------------------------------------------------------------------------


@register_op("affine_grid", inputs=["Theta"], outputs=["Output"])
def _affine_grid(ctx, ins, attrs):
    """cf. affine_grid_op.cc: [N,2,3] theta -> [N,H,W,2] sampling grid."""
    theta = ins["Theta"][0]
    n, h, w = theta.shape[0], attrs["output_shape"][2], attrs["output_shape"][3]
    align = attrs.get("align_corners", True)
    if align:
        ys = jnp.linspace(-1.0, 1.0, h)
        xs = jnp.linspace(-1.0, 1.0, w)
    else:
        ys = (jnp.arange(h) + 0.5) * 2.0 / h - 1.0
        xs = (jnp.arange(w) + 0.5) * 2.0 / w - 1.0
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H,W,3]
    out = jnp.einsum("hwk,nck->nhwc", base, theta)  # [N,H,W,2]
    return {"Output": [out]}


@register_op("grid_sampler", inputs=["X", "Grid"], outputs=["Output"])
def _grid_sampler(ctx, ins, attrs):
    """cf. grid_sampler_op.cc: bilinear sample of [N,C,H,W] at [N,Ho,Wo,2]
    normalized coordinates (zero padding outside)."""
    x, grid = ins["X"][0], ins["Grid"][0]
    n, c, h, w = x.shape
    align = attrs.get("align_corners", True)
    gx, gy = grid[..., 0], grid[..., 1]
    if align:
        fx = (gx + 1.0) * (w - 1) / 2.0
        fy = (gy + 1.0) * (h - 1) / 2.0
    else:
        fx = ((gx + 1.0) * w - 1.0) / 2.0
        fy = ((gy + 1.0) * h - 1.0) / 2.0
    x0 = jnp.floor(fx)
    y0 = jnp.floor(fy)
    wx = fx - x0
    wy = fy - y0

    def gather(yi, xi):
        inside = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        yc = jnp.clip(yi, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xi, 0, w - 1).astype(jnp.int32)
        # [N,Ho,Wo] index into [N,C,H,W] -> [N,C,Ho,Wo]
        v = jax.vmap(lambda img, yy, xx: img[:, yy, xx])(x, yc, xc)
        return v * inside[:, None, :, :]

    v00 = gather(y0, x0)
    v01 = gather(y0, x0 + 1)
    v10 = gather(y0 + 1, x0)
    v11 = gather(y0 + 1, x0 + 1)
    wx = wx[:, None]
    wy = wy[:, None]
    out = (
        v00 * (1 - wx) * (1 - wy) + v01 * wx * (1 - wy)
        + v10 * (1 - wx) * wy + v11 * wx * wy
    )
    return {"Output": [out]}


def _interp(x, out_h, out_w, method, align_corners):
    n, c, h, w = x.shape
    if align_corners and method == "linear" and out_h > 1 and out_w > 1:
        # jax.image.resize implements half-pixel centers; align_corners
        # resamples on the corner-aligned lattice instead
        ys = jnp.linspace(0, h - 1, out_h)
        xs = jnp.linspace(0, w - 1, out_w)
        y0 = jnp.floor(ys).astype(jnp.int32)
        x0 = jnp.floor(xs).astype(jnp.int32)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = (ys - y0)[None, None, :, None]
        wx = (xs - x0)[None, None, None, :]
        g = lambda yi, xi: x[:, :, yi][:, :, :, xi]
        return (
            g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1) * (1 - wy) * wx
            + g(y1, x0) * wy * (1 - wx) + g(y1, x1) * wy * wx
        )
    return jax.image.resize(x, (n, c, out_h, out_w), method=method)


@register_op("bilinear_interp", inputs=["X"], outputs=["Out"])
def _bilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]
    oh = int(attrs.get("out_h", 0)) or int(x.shape[2] * attrs["scale"])
    ow = int(attrs.get("out_w", 0)) or int(x.shape[3] * attrs["scale"])
    return {"Out": [_interp(x, oh, ow, "linear",
                            attrs.get("align_corners", True))]}


@register_op("nearest_interp", inputs=["X"], outputs=["Out"])
def _nearest_interp(ctx, ins, attrs):
    x = ins["X"][0]
    oh = int(attrs.get("out_h", 0)) or int(x.shape[2] * attrs["scale"])
    ow = int(attrs.get("out_w", 0)) or int(x.shape[3] * attrs["scale"])
    if attrs.get("align_corners", True) and oh > 1 and ow > 1:
        h, w = x.shape[2], x.shape[3]
        yi = jnp.round(jnp.linspace(0, h - 1, oh)).astype(jnp.int32)
        xi = jnp.round(jnp.linspace(0, w - 1, ow)).astype(jnp.int32)
        return {"Out": [x[:, :, yi][:, :, :, xi]]}
    return {"Out": [_interp(x, oh, ow, "nearest", False)]}


@register_op("pixel_shuffle", inputs=["X"], outputs=["Out"])
def _pixel_shuffle(ctx, ins, attrs):
    x = ins["X"][0]
    r = int(attrs["upscale_factor"])
    n, c, h, w = x.shape
    x = x.reshape(n, c // (r * r), r, r, h, w)
    x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
    return {"Out": [x.reshape(n, c // (r * r), h * r, w * r)]}


@register_op("conv3d", inputs=["Input", "Filter"], outputs=["Output"])
def _conv3d(ctx, ins, attrs):
    x, f = ins["Input"][0], ins["Filter"][0]  # NCDHW, OI dhw
    if x.dtype != f.dtype and jnp.issubdtype(x.dtype, jnp.floating):
        x = x.astype(f.dtype)  # AMP: input follows the filter's precision
    s = attrs.get("strides", [1, 1, 1])
    p = attrs.get("paddings", [0, 0, 0])
    d = attrs.get("dilations", [1, 1, 1])
    g = int(attrs.get("groups", 1))
    out = jax.lax.conv_general_dilated(
        x, f, window_strides=tuple(s),
        padding=[(p[0], p[0]), (p[1], p[1]), (p[2], p[2])],
        rhs_dilation=tuple(d), feature_group_count=g,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
    )
    return {"Output": [out]}


@register_op("pool3d", inputs=["X"], outputs=["Out"])
def _pool3d(ctx, ins, attrs):
    x = ins["X"][0]
    ksize = attrs["ksize"]
    stride = attrs.get("strides", ksize)
    pad = attrs.get("paddings", [0, 0, 0])
    ptype = attrs.get("pooling_type", "max")
    if attrs.get("global_pooling", False):
        red = (2, 3, 4)
        out = (jnp.max if ptype == "max" else jnp.mean)(x, axis=red,
                                                        keepdims=True)
        return {"Out": [out]}
    dims = (1, 1) + tuple(ksize)
    strides = (1, 1) + tuple(stride)
    pads = ((0, 0), (0, 0)) + tuple((pp, pp) for pp in pad)
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, dims, strides, pads)
    else:
        out = jax.lax.reduce_window(
            x, 0.0, jax.lax.add, dims, strides, pads
        ) / float(ksize[0] * ksize[1] * ksize[2])
    return {"Out": [out]}


# ---------------------------------------------------------------------------
# interpolate tail (reference interpolate_op.cc trilinear/bicubic/linear
# modes), pad2d/pad3d, channel utilities
# ---------------------------------------------------------------------------


@register_op("linear_interp", inputs=["X"], outputs=["Out"])
def _linear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCW
    ow = int(attrs.get("out_w", 0)) or int(x.shape[2] * attrs["scale"])
    n, c, w = x.shape
    if attrs.get("align_corners", True) and ow > 1:
        xs = jnp.linspace(0, w - 1, ow)
        x0 = jnp.floor(xs).astype(jnp.int32)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wx = (xs - x0)[None, None, :]
        return {"Out": [x[:, :, x0] * (1 - wx) + x[:, :, x1] * wx]}
    return {"Out": [jax.image.resize(x, (n, c, ow), method="linear")]}


@register_op("trilinear_interp", inputs=["X"], outputs=["Out"])
def _trilinear_interp(ctx, ins, attrs):
    x = ins["X"][0]  # NCDHW
    n, c, d, h, w = x.shape
    od = int(attrs.get("out_d", 0)) or int(d * attrs["scale"])
    oh = int(attrs.get("out_h", 0)) or int(h * attrs["scale"])
    ow = int(attrs.get("out_w", 0)) or int(w * attrs["scale"])
    if attrs.get("align_corners", True) and min(od, oh, ow) > 1:
        # corner-aligned separable linear resample per axis
        def axis_ids(sz, out):
            s = jnp.linspace(0, sz - 1, out)
            i0 = jnp.floor(s).astype(jnp.int32)
            return i0, jnp.minimum(i0 + 1, sz - 1), s - i0

        d0, d1, wd = axis_ids(d, od)
        h0, h1, wh = axis_ids(h, oh)
        w0, w1, ww = axis_ids(w, ow)
        wd = wd[:, None, None]
        wh = wh[None, :, None]
        ww = ww[None, None, :]

        def g(di, hi, wi):
            return x[:, :, di][:, :, :, hi][:, :, :, :, wi]

        out = (
            g(d0, h0, w0) * (1 - wd) * (1 - wh) * (1 - ww)
            + g(d0, h0, w1) * (1 - wd) * (1 - wh) * ww
            + g(d0, h1, w0) * (1 - wd) * wh * (1 - ww)
            + g(d0, h1, w1) * (1 - wd) * wh * ww
            + g(d1, h0, w0) * wd * (1 - wh) * (1 - ww)
            + g(d1, h0, w1) * wd * (1 - wh) * ww
            + g(d1, h1, w0) * wd * wh * (1 - ww)
            + g(d1, h1, w1) * wd * wh * ww
        )
        return {"Out": [out]}
    return {"Out": [jax.image.resize(
        x, (n, c, od, oh, ow), method="trilinear")]}


@register_op("bicubic_interp", inputs=["X"], outputs=["Out"])
def _bicubic_interp(ctx, ins, attrs):
    x = ins["X"][0]
    n, c, h, w = x.shape
    oh = int(attrs.get("out_h", 0)) or int(h * attrs["scale"])
    ow = int(attrs.get("out_w", 0)) or int(w * attrs["scale"])
    # half-pixel bicubic (jax.image cubic = Keys kernel, the reference's
    # align_corners=False default path)
    return {"Out": [jax.image.resize(x, (n, c, oh, ow), method="cubic")]}


@register_op("pad2d", inputs=["X"], outputs=["Out"])
def _pad2d(ctx, ins, attrs):
    x = ins["X"][0]  # NCHW
    p = attrs["paddings"]  # [top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    cfg = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]))
    if mode == "constant":
        return {"Out": [jnp.pad(x, cfg, constant_values=float(
            attrs.get("pad_value", 0.0)))]}
    jmode = {"reflect": "reflect", "edge": "edge"}[mode]
    return {"Out": [jnp.pad(x, cfg, mode=jmode)]}


@register_op("pad3d", inputs=["X"], outputs=["Out"])
def _pad3d(ctx, ins, attrs):
    x = ins["X"][0]  # NCDHW
    p = attrs["paddings"]  # [front, back, top, bottom, left, right]
    mode = attrs.get("mode", "constant")
    cfg = ((0, 0), (0, 0), (p[0], p[1]), (p[2], p[3]), (p[4], p[5]))
    if mode == "constant":
        return {"Out": [jnp.pad(x, cfg, constant_values=float(
            attrs.get("value", 0.0)))]}
    jmode = {"reflect": "reflect", "replicate": "edge",
             "circular": "wrap"}[mode]
    return {"Out": [jnp.pad(x, cfg, mode=jmode)]}


@register_op("pixel_unshuffle", inputs=["X"], outputs=["Out"])
def _pixel_unshuffle(ctx, ins, attrs):
    x = ins["X"][0]
    r = int(attrs["downscale_factor"])
    n, c, h, w = x.shape
    x = x.reshape(n, c, h // r, r, w // r, r)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return {"Out": [x.reshape(n, c * r * r, h // r, w // r)]}


@register_op("shuffle_channel", inputs=["X"], outputs=["Out"])
def _shuffle_channel(ctx, ins, attrs):
    x = ins["X"][0]
    g = int(attrs["group"])
    n, c, h, w = x.shape
    x = x.reshape(n, g, c // g, h, w)
    return {"Out": [jnp.transpose(x, (0, 2, 1, 3, 4)).reshape(n, c, h, w)]}


@register_op("temporal_shift", inputs=["X"], outputs=["Out"])
def _temporal_shift(ctx, ins, attrs):
    """cf. temporal_shift_op.cc: shift 1/fold of channels one step back,
    1/fold one step forward along the segment (time) dim."""
    x = ins["X"][0]  # [N*T, C, H, W]
    t = int(attrs["seg_num"])
    frac = float(attrs.get("shift_ratio", 0.25))
    nt, c, h, w = x.shape
    n = nt // t
    x = x.reshape(n, t, c, h, w)
    c1 = int(c * frac)
    c2 = int(c * 2 * frac)
    back = jnp.concatenate(
        [x[:, 1:, :c1], jnp.zeros((n, 1, c1, h, w), x.dtype)], axis=1)
    fwd = jnp.concatenate(
        [jnp.zeros((n, 1, c2 - c1, h, w), x.dtype), x[:, :-1, c1:c2]],
        axis=1)
    out = jnp.concatenate([back, fwd, x[:, :, c2:]], axis=2)
    return {"Out": [out.reshape(nt, c, h, w)]}


@register_op("lrn", inputs=["X"], outputs=["Out"])
def _lrn(ctx, ins, attrs):
    """cf. lrn_op.cc: local response normalization across channels."""
    x = ins["X"][0]
    n_size = int(attrs.get("n", 5))
    k = float(attrs.get("k", 2.0))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    sq = x * x
    half = n_size // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    den = sum(
        pad[:, i:i + x.shape[1]] for i in range(n_size)
    )
    return {"Out": [x / (k + alpha * den) ** beta]}


@register_op("maxout", inputs=["X"], outputs=["Out"])
def _maxout(ctx, ins, attrs):
    x = ins["X"][0]
    g = int(attrs["groups"])
    n, c, h, w = x.shape
    return {"Out": [x.reshape(n, c // g, g, h, w).max(axis=2)]}


@register_op("row_conv", inputs=["X", "Filter", "SeqLens"],
             outputs=["Out"], no_grad_slots=("SeqLens",))
def _row_conv(ctx, ins, attrs):
    """cf. row_conv_op.cc (lookahead conv for deep speech): out[t] =
    sum_{i<future} x[t+i] * filter[i], masked past each sequence end."""
    x, f = ins["X"][0], ins["Filter"][0]  # [B, T, D], [K, D]
    lens = ins["SeqLens"][0]
    K = f.shape[0]
    B, T, D = x.shape
    mask = (jnp.arange(T)[None, :] < lens[:, None])[..., None]
    xm = jnp.where(mask, x, 0)
    pad = jnp.pad(xm, ((0, 0), (0, K - 1), (0, 0)))
    out = sum(pad[:, i:i + T] * f[i][None, None, :] for i in range(K))
    return {"Out": [jnp.where(mask, out, 0)]}


@register_op("im2sequence", inputs=["X"], outputs=["Out"])
def _im2sequence(ctx, ins, attrs):
    """cf. im2sequence_op.cc (OCR): image patches -> sequence rows,
    [N, C, H, W] -> [N * oh * ow, C * kh * kw]."""
    x = ins["X"][0]
    kh, kw = attrs["kernels"]
    sh, sw = attrs.get("strides", [1, 1])
    p = attrs.get("paddings", [0, 0, 0, 0])  # up, left, down, right
    x = jnp.pad(x, ((0, 0), (0, 0), (p[0], p[2]), (p[1], p[3])))
    n, c, h, w = x.shape
    oh = (h - kh) // sh + 1
    ow = (w - kw) // sw + 1
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), "VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))  # [N, C*kh*kw, oh, ow]
    out = patches.transpose(0, 2, 3, 1).reshape(n * oh * ow, c * kh * kw)
    return {"Out": [out]}


@register_op("spp", inputs=["X"], outputs=["Out"])
def _spp(ctx, ins, attrs):
    """cf. spp_op.cc: spatial pyramid pooling — concat pooled levels
    1x1, 2x2, ..., 2^(L-1) bins."""
    x = ins["X"][0]
    levels = int(attrs.get("pyramid_height", 3))
    ptype = attrs.get("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2 ** l
        ys = (jnp.arange(h) * bins) // h        # bin id per row
        xs = (jnp.arange(w) * bins) // w
        for by in range(bins):
            for bx in range(bins):
                m = (ys == by)[:, None] & (xs == bx)[None, :]
                if ptype == "max":
                    neg = jnp.finfo(x.dtype).min
                    v = jnp.max(jnp.where(m[None, None], x, neg),
                                axis=(2, 3))
                else:
                    cnt = jnp.maximum(jnp.sum(m), 1)
                    v = jnp.sum(jnp.where(m[None, None], x, 0),
                                axis=(2, 3)) / cnt
                outs.append(v)
    return {"Out": [jnp.concatenate(outs, axis=1)]}


@register_op("fold", inputs=["X"], outputs=["Y"])
def _fold(ctx, ins, attrs):
    """cf. fold_op.cc: col2im — inverse of unfold, overlaps summed."""
    x = ins["X"][0]                             # [N, C*kh*kw, L]
    oh, ow = attrs["output_sizes"]
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs.get("strides", [1, 1])
    ph, pw_ = attrs.get("paddings", [0, 0])[:2] if attrs.get(
        "paddings") else (0, 0)
    n, ckk, L = x.shape
    c = ckk // (kh * kw)
    nh = (oh + 2 * ph - kh) // sh + 1
    nw = (ow + 2 * pw_ - kw) // sw + 1
    x = x.reshape(n, c, kh, kw, nh, nw)
    out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw_), x.dtype)
    for i in range(kh):
        for j in range(kw):
            ys = i + sh * jnp.arange(nh)
            xs = j + sw * jnp.arange(nw)
            out = out.at[:, :, ys[:, None], xs[None, :]].add(x[:, :, i, j])
    return {"Y": [out[:, :, ph:ph + oh, pw_:pw_ + ow]]}


@register_op("random_crop", inputs=["X"], outputs=["Out"],
             needs_rng=True, grad=None)
def _random_crop(ctx, ins, attrs):
    """cf. random_crop_op.cc: random spatial crop to `shape` (trailing
    dims)."""
    import jax

    x = ins["X"][0]
    shape = [int(s) for s in attrs["shape"]]
    lead = x.ndim - len(shape)
    key = ctx.rng()
    starts = []
    for i, s in enumerate(shape):
        hi = x.shape[lead + i] - s
        k, key = jax.random.split(key)
        starts.append(jax.random.randint(k, (), 0, hi + 1))
    idx = (jnp.int32(0),) * lead + tuple(
        s.astype(jnp.int32) for s in starts)
    sizes = x.shape[:lead] + tuple(shape)
    return {"Out": [jax.lax.dynamic_slice(x, idx, sizes)]}
