"""Detection op family (SSD/YOLO-style building blocks).

Capability parity: reference `paddle/fluid/operators/detection/` —
prior_box_op.cc, box_coder_op.cc, yolo_box_op.cc (in yolov3 tree),
iou_similarity_op.cc, box_clip_op.cc, anchor_generator_op.cc,
multiclass_nms_op.cc, roi_align_op.cc, bipartite_match_op.cc.

TPU-first notes:
- everything is static-shaped; `multiclass_nms` returns FIXED-size
  [N, keep_top_k, 6] with -1 labels marking empty slots instead of the
  reference's LoD-compacted output (the consumer masks on label >= 0) —
  dynamic result counts cannot exist under XLA,
- NMS suppression is the O(K^2) mask-matrix formulation over the top-K
  candidates (K static), which vectorizes onto the VPU instead of the
  reference's sequential greedy loop,
- roi_align's bilinear sampling is a gather + weight blend, batched with
  vmap over rois.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op


def _box_area(box):
    return jnp.maximum(box[..., 2] - box[..., 0], 0) * jnp.maximum(
        box[..., 3] - box[..., 1], 0
    )


def _pairwise_iou(a, b):
    """a: [N,4], b: [M,4] (xyxy) -> [N,M]."""
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    union = _box_area(a)[:, None] + _box_area(b)[None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


@register_op("iou_similarity", inputs=["X", "Y"], outputs=["Out"],
             grad=None)
def _iou_similarity(ctx, ins, attrs):
    """cf. iou_similarity_op.cc: pairwise IoU of two box lists."""
    return {"Out": [_pairwise_iou(ins["X"][0], ins["Y"][0])]}


@register_op("box_clip", inputs=["Input", "ImInfo"], outputs=["Output"],
             grad=None)
def _box_clip(ctx, ins, attrs):
    """cf. box_clip_op.cc: clip [N,B,4] boxes into per-image bounds
    ImInfo [N,3] = (h, w, scale)."""
    boxes, im = ins["Input"][0], ins["ImInfo"][0]
    h = (im[:, 0] / im[:, 2] - 1.0)[:, None]
    w = (im[:, 1] / im[:, 2] - 1.0)[:, None]
    x1 = jnp.clip(boxes[..., 0], 0.0, w)
    y1 = jnp.clip(boxes[..., 1], 0.0, h)
    x2 = jnp.clip(boxes[..., 2], 0.0, w)
    y2 = jnp.clip(boxes[..., 3], 0.0, h)
    return {"Output": [jnp.stack([x1, y1, x2, y2], axis=-1)]}


@register_op("prior_box", inputs=["Input", "Image"],
             outputs=["Boxes", "Variances"], grad=None)
def _prior_box(ctx, ins, attrs):
    """cf. prior_box_op.cc (SSD): one prior per (cell, size/ratio combo),
    centered on the feature-map grid."""
    feat, img = ins["Input"][0], ins["Image"][0]
    min_sizes = [float(m) for m in attrs["min_sizes"]]
    max_sizes = [float(m) for m in attrs.get("max_sizes", [])]
    ratios = [float(r) for r in attrs.get("aspect_ratios", [1.0])]
    flip = attrs.get("flip", False)
    clip = attrs.get("clip", False)
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    offset = float(attrs.get("offset", 0.5))

    fh, fw = feat.shape[2], feat.shape[3]
    ih, iw = img.shape[2], img.shape[3]
    step_h = float(attrs.get("step_h", 0.0)) or ih / fh
    step_w = float(attrs.get("step_w", 0.0)) or iw / fw

    ars = [1.0]
    for r in ratios:
        if all(abs(r - a) > 1e-6 for a in ars):
            ars.append(r)
            if flip:
                ars.append(1.0 / r)

    whs = []
    for ms in min_sizes:
        for ar in ars:
            whs.append((ms * (ar ** 0.5), ms / (ar ** 0.5)))
        if max_sizes:
            mx = max_sizes[min_sizes.index(ms)]
            whs.append(((ms * mx) ** 0.5, (ms * mx) ** 0.5))
    n_prior = len(whs)

    cx = (jnp.arange(fw) + offset) * step_w
    cy = (jnp.arange(fh) + offset) * step_h
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")  # [fh, fw]
    wh = jnp.asarray(whs, jnp.float32)  # [P, 2]
    boxes = jnp.stack([
        (gx[..., None] - wh[None, None, :, 0] / 2) / iw,
        (gy[..., None] - wh[None, None, :, 1] / 2) / ih,
        (gx[..., None] + wh[None, None, :, 0] / 2) / iw,
        (gy[..., None] + wh[None, None, :, 1] / 2) / ih,
    ], axis=-1)  # [fh, fw, P, 4]
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), (fh, fw, n_prior, 4)
    )
    return {"Boxes": [boxes], "Variances": [var]}


@register_op("box_coder", inputs=["PriorBox", "PriorBoxVar", "TargetBox"],
             outputs=["OutputBox"], no_grad_slots=("PriorBox", "PriorBoxVar"))
def _box_coder(ctx, ins, attrs):
    """cf. box_coder_op.cc: encode_center_size / decode_center_size."""
    prior = ins["PriorBox"][0]  # [M, 4] xyxy
    pvar = ins["PriorBoxVar"][0] if ins.get("PriorBoxVar") else None
    target = ins["TargetBox"][0]
    code_type = attrs.get("code_type", "encode_center_size")
    norm = attrs.get("box_normalized", True)
    one = 0.0 if norm else 1.0

    pw = prior[:, 2] - prior[:, 0] + one
    ph = prior[:, 3] - prior[:, 1] + one
    pcx = prior[:, 0] + pw * 0.5
    pcy = prior[:, 1] + ph * 0.5
    if pvar is None:
        pvar = jnp.ones((prior.shape[0], 4), jnp.float32)

    if code_type.startswith("encode"):
        tw = target[:, 2] - target[:, 0] + one
        th = target[:, 3] - target[:, 1] + one
        tcx = target[:, 0] + tw * 0.5
        tcy = target[:, 1] + th * 0.5
        # [N, M]
        ox = (tcx[:, None] - pcx[None, :]) / pw[None, :] / pvar[None, :, 0]
        oy = (tcy[:, None] - pcy[None, :]) / ph[None, :] / pvar[None, :, 1]
        ow = jnp.log(tw[:, None] / pw[None, :]) / pvar[None, :, 2]
        oh = jnp.log(th[:, None] / ph[None, :]) / pvar[None, :, 3]
        return {"OutputBox": [jnp.stack([ox, oy, ow, oh], axis=-1)]}

    # decode: target [N, M, 4] deltas (or [M, 4] broadcast)
    if target.ndim == 2:
        target = target[None]
    dcx = pvar[None, :, 0] * target[..., 0] * pw[None, :] + pcx[None, :]
    dcy = pvar[None, :, 1] * target[..., 1] * ph[None, :] + pcy[None, :]
    dw = jnp.exp(pvar[None, :, 2] * target[..., 2]) * pw[None, :]
    dh = jnp.exp(pvar[None, :, 3] * target[..., 3]) * ph[None, :]
    out = jnp.stack([
        dcx - dw * 0.5, dcy - dh * 0.5,
        dcx + dw * 0.5 - one, dcy + dh * 0.5 - one,
    ], axis=-1)
    return {"OutputBox": [out]}


@register_op("anchor_generator", inputs=["Input"],
             outputs=["Anchors", "Variances"], grad=None)
def _anchor_generator(ctx, ins, attrs):
    """cf. anchor_generator_op.cc (Faster-RCNN RPN anchors)."""
    feat = ins["Input"][0]
    sizes = [float(s) for s in attrs["anchor_sizes"]]
    ratios = [float(r) for r in attrs["aspect_ratios"]]
    variances = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])
    stride = attrs.get("stride", [16.0, 16.0])
    offset = float(attrs.get("offset", 0.5))
    fh, fw = feat.shape[2], feat.shape[3]

    whs = []
    for r in ratios:
        for s in sizes:
            area = s * s
            w = (area / r) ** 0.5
            whs.append((w, w * r))
    wh = jnp.asarray(whs, jnp.float32)  # [A, 2]
    cx = (jnp.arange(fw) + offset) * stride[0]
    cy = (jnp.arange(fh) + offset) * stride[1]
    gy, gx = jnp.meshgrid(cy, cx, indexing="ij")
    anchors = jnp.stack([
        gx[..., None] - wh[None, None, :, 0] / 2,
        gy[..., None] - wh[None, None, :, 1] / 2,
        gx[..., None] + wh[None, None, :, 0] / 2,
        gy[..., None] + wh[None, None, :, 1] / 2,
    ], axis=-1)  # [fh, fw, A, 4]
    var = jnp.broadcast_to(
        jnp.asarray(variances, jnp.float32), anchors.shape
    )
    return {"Anchors": [anchors], "Variances": [var]}


@register_op("yolo_box", inputs=["X", "ImgSize"],
             outputs=["Boxes", "Scores"], no_grad_slots=("ImgSize",))
def _yolo_box(ctx, ins, attrs):
    """cf. yolo_box_op.cc: decode YOLOv3 head [N, A*(5+C), H, W] into
    boxes [N, A*H*W, 4] + per-class scores [N, A*H*W, C]."""
    x, img_size = ins["X"][0], ins["ImgSize"][0]
    anchors = attrs["anchors"]  # flat [w0,h0,w1,h1,...]
    class_num = int(attrs["class_num"])
    conf_thresh = float(attrs.get("conf_thresh", 0.01))
    downsample = int(attrs.get("downsample_ratio", 32))
    na = len(anchors) // 2
    n, _, h, w = x.shape
    x = x.reshape(n, na, 5 + class_num, h, w)

    gx = jnp.arange(w, dtype=jnp.float32)
    gy = jnp.arange(h, dtype=jnp.float32)
    grid_y, grid_x = jnp.meshgrid(gy, gx, indexing="ij")
    aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(na, 1, 1)
    ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(na, 1, 1)

    bx = (jax.nn.sigmoid(x[:, :, 0]) + grid_x) / w  # [n, na, h, w]
    by = (jax.nn.sigmoid(x[:, :, 1]) + grid_y) / h
    input_w = float(w * downsample)
    input_h = float(h * downsample)
    bw = jnp.exp(x[:, :, 2]) * aw / input_w
    bh = jnp.exp(x[:, :, 3]) * ah / input_h
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:]) * conf[:, :, None]
    probs = jnp.where(probs >= conf_thresh, probs, 0.0)

    img_h = img_size[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
    img_w = img_size[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
    boxes = jnp.stack([
        (bx - bw / 2) * img_w, (by - bh / 2) * img_h,
        (bx + bw / 2) * img_w, (by + bh / 2) * img_h,
    ], axis=-1)  # [n, na, h, w, 4]
    return {
        "Boxes": [boxes.reshape(n, na * h * w, 4)],
        "Scores": [jnp.moveaxis(probs, 2, -1).reshape(n, na * h * w,
                                                      class_num)],
    }


def multiclass_nms_core(bboxes, scores, attrs):
    """Shared NMS core for multiclass_nms / multiclass_nms2.  STATIC-shape
    redesign: returns (out [N, keep_top_k, 6] = (label, score, x1, y1, x2,
    y2) with label = -1 in empty slots, src [N, keep_top_k] = source box
    index into M, -1 in empty slots).  The reference emits a LoD-compacted
    variable-length list, impossible under XLA.  Suppression is the O(K^2)
    IoU mask matrix over the per-class top-K, not a sequential greedy
    loop."""
    score_threshold = float(attrs.get("score_threshold", 0.0))
    nms_threshold = float(attrs.get("nms_threshold", 0.3))
    nms_top_k = int(attrs.get("nms_top_k", 64))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    background = int(attrs.get("background_label", 0))
    n, m, _ = bboxes.shape
    c = scores.shape[1]
    k = min(nms_top_k, m)

    def one_image(boxes, sc):
        def one_class(cls_scores):
            vals, idx = jax.lax.top_k(cls_scores, k)
            cand = jnp.take(boxes, idx, axis=0)  # [k, 4]
            iou = _pairwise_iou(cand, cand)
            # suppressed if a HIGHER-scoring candidate overlaps too much
            higher = jnp.triu(jnp.ones((k, k), jnp.bool_), 1).T
            sup = jnp.any((iou > nms_threshold) & higher, axis=1)
            keep = (~sup) & (vals > score_threshold)
            return jnp.where(keep, vals, -1.0), cand, idx

        cls_vals, cls_boxes, cls_src = jax.vmap(one_class)(sc)
        if 0 <= background < c:
            # the reference skips the background class entirely
            # (multiclass_nms_op.cc NMSFast: c == background_label)
            cls_vals = cls_vals.at[background].set(-1.0)
        labels = jnp.broadcast_to(
            jnp.arange(c, dtype=jnp.float32)[:, None], (c, k)
        )
        flat_scores = cls_vals.reshape(-1)
        flat_boxes = cls_boxes.reshape(-1, 4)
        flat_labels = labels.reshape(-1)
        flat_src = cls_src.reshape(-1)
        kk = min(keep_top_k, flat_scores.shape[0])
        top_vals, top_idx = jax.lax.top_k(flat_scores, kk)
        valid = top_vals > 0
        out = jnp.concatenate([
            jnp.where(valid[:, None], flat_labels[top_idx][:, None], -1.0),
            top_vals[:, None],
            flat_boxes[top_idx],
        ], axis=1)  # [kk, 6]
        src = jnp.where(valid, flat_src[top_idx], -1).astype(jnp.int32)
        if kk < keep_top_k:
            pad = jnp.full((keep_top_k - kk, 6), -1.0, out.dtype)
            out = jnp.concatenate([out, pad], axis=0)
            src = jnp.concatenate(
                [src, jnp.full((keep_top_k - kk,), -1, jnp.int32)])
        return out, src

    return jax.vmap(one_image)(bboxes, scores)


@register_op("multiclass_nms", inputs=["BBoxes", "Scores"], outputs=["Out"],
             grad=None)
def _multiclass_nms(ctx, ins, attrs):
    """cf. multiclass_nms_op.cc — see multiclass_nms_core."""
    out, _ = multiclass_nms_core(ins["BBoxes"][0], ins["Scores"][0], attrs)
    return {"Out": [out]}


@register_op("roi_align", inputs=["X", "ROIs"], outputs=["Out"],
             no_grad_slots=("ROIs",))
def _roi_align(ctx, ins, attrs):
    """cf. roi_align_op.cc: average of bilinear samples per output cell.
    ROIs: [R, 5] = (batch_idx, x1, y1, x2, y2) in input coordinates."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    spatial_scale = float(attrs.get("spatial_scale", 1.0))
    sampling = int(attrs.get("sampling_ratio", 2))
    sampling = sampling if sampling > 0 else 2
    n, ch, h, w = x.shape

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid [ph*sampling, pw*sampling]
        sy = y1 + (jnp.arange(ph * sampling) + 0.5) * bin_h / sampling
        sx = x1 + (jnp.arange(pw * sampling) + 0.5) * bin_w / sampling
        gy, gx = jnp.meshgrid(sy, sx, indexing="ij")

        y0 = jnp.clip(jnp.floor(gy), 0, h - 1).astype(jnp.int32)
        x0 = jnp.clip(jnp.floor(gx), 0, w - 1).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, h - 1)
        x1i = jnp.minimum(x0 + 1, w - 1)
        wy = gy - y0
        wx = gx - x0
        img = x[b]  # [C, H, W]
        g = lambda yy, xx: img[:, yy, xx]  # [C, S, S]
        val = (
            g(y0, x0) * (1 - wy) * (1 - wx) + g(y0, x1i) * (1 - wy) * wx
            + g(y1i, x0) * wy * (1 - wx) + g(y1i, x1i) * wy * wx
        )  # [C, ph*s, pw*s]
        val = val.reshape(ch, ph, sampling, pw, sampling)
        return val.mean(axis=(2, 4))  # [C, ph, pw]

    return {"Out": [jax.vmap(one_roi)(rois.astype(jnp.float32))]}


@register_op("bipartite_match", inputs=["DistMat"],
             outputs=["ColToRowMatchIndices", "ColToRowMatchDist"],
             grad=None)
def _bipartite_match(ctx, ins, attrs):
    """cf. bipartite_match_op.cc: greedy bipartite matching of a distance
    (similarity) matrix [N, M] rows=gt, cols=priors.  Sequential greedy in
    a lax.fori_loop over rows (N is small: number of ground-truth boxes)."""
    dist = ins["DistMat"][0]
    match_type = attrs.get("match_type", "bipartite")
    overlap_threshold = float(attrs.get("dist_threshold", 0.5))
    n, m = dist.shape

    def body(_, state):
        matched_cols, matched_rows, d = state
        # best remaining (row, col)
        best = jnp.argmax(d)
        r, cidx = best // m, best % m
        ok = d[r, cidx] > 0
        matched_cols = matched_cols.at[cidx].set(
            jnp.where(ok, r, matched_cols[cidx])
        )
        matched_rows = matched_rows.at[r].set(
            jnp.where(ok, cidx, matched_rows[r])
        )
        # zero out the matched row + col
        d = jnp.where(ok, d.at[r, :].set(-1.0).at[:, cidx].set(-1.0), d)
        return matched_cols, matched_rows, d

    init = (jnp.full((m,), -1, jnp.int32), jnp.full((n,), -1, jnp.int32),
            dist)
    cols, rows, _ = jax.lax.fori_loop(0, n, body, init)
    if match_type == "per_prediction":
        # additionally match every unmatched col to its best row above
        # the threshold (SSD matching step 2)
        best_rows = jnp.argmax(dist, axis=0).astype(jnp.int32)
        best_vals = jnp.max(dist, axis=0)
        cols = jnp.where(
            (cols < 0) & (best_vals > overlap_threshold), best_rows, cols
        )
    col_dist = jnp.where(
        cols >= 0,
        jnp.take_along_axis(
            dist, jnp.maximum(cols, 0)[None, :], axis=0
        )[0],
        0.0,
    )
    return {
        "ColToRowMatchIndices": [cols[None, :]],
        "ColToRowMatchDist": [col_dist[None, :]],
    }


# ---------------------------------------------------------------------------
# RPN / FPN tail (reference generate_proposals_op.cc,
# distribute_fpn_proposals_op.cc, collect_fpn_proposals_op.cc,
# density_prior_box_op.cc, sigmoid_focal_loss_op.cc,
# polygon_box_transform_op.cc, box_decoder_and_assign_op.cc,
# target_assign_op.cc).  Static-shape conventions as above: fixed top-N
# buffers with score/validity sentinels instead of LoD-compacted outputs.
# ---------------------------------------------------------------------------


def _decode_bbox(anchors, deltas, variances=None):
    """anchor-relative (dx,dy,dw,dh) -> corner boxes."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + aw * 0.5
    ay = anchors[:, 1] + ah * 0.5
    v = variances if variances is not None else jnp.ones_like(deltas)
    dx, dy, dw, dh = (deltas * v).T
    cx = dx * aw + ax
    cy = dy * ah + ay
    w = jnp.exp(jnp.minimum(dw, 10.0)) * aw
    h = jnp.exp(jnp.minimum(dh, 10.0)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2,
                      cx + w / 2 - 1, cy + h / 2 - 1], axis=1)


@register_op("generate_proposals",
             inputs=["Scores", "BboxDeltas", "ImInfo", "Anchors",
                     "Variances"],
             outputs=["RpnRois", "RpnRoiProbs"], grad=None)
def _generate_proposals(ctx, ins, attrs):
    """cf. generate_proposals_op.cc: top-pre_nms scores -> decode -> clip
    -> filter small -> NMS -> top post_nms.  Output is a FIXED
    [N, post_nms_topN, 4] roi buffer + [N, post_nms_topN] scores (zero
    score marks an empty slot)."""
    scores = ins["Scores"][0]       # [N, A, H, W]
    deltas = ins["BboxDeltas"][0]   # [N, A*4, H, W]
    im_info = ins["ImInfo"][0]      # [N, 3] (h, w, scale)
    anchors = ins["Anchors"][0].reshape(-1, 4)
    variances = ins["Variances"][0].reshape(-1, 4) \
        if ins.get("Variances") else None
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thr = float(attrs.get("nms_thresh", 0.7))
    min_size = float(attrs.get("min_size", 0.0))
    N, A, H, W = scores.shape
    K = A * H * W
    pre_n = min(pre_n, K)
    post_n = min(post_n, pre_n)

    sc = scores.reshape(N, K)
    dl = deltas.reshape(N, A, 4, H, W).transpose(0, 1, 3, 4, 2).reshape(
        N, K, 4)

    def per_image(s, d, info):
        boxes = _decode_bbox(anchors, d, variances)
        boxes = jnp.stack([
            jnp.clip(boxes[:, 0], 0, info[1] - 1),
            jnp.clip(boxes[:, 1], 0, info[0] - 1),
            jnp.clip(boxes[:, 2], 0, info[1] - 1),
            jnp.clip(boxes[:, 3], 0, info[0] - 1)], axis=1)
        ws = boxes[:, 2] - boxes[:, 0] + 1
        hs = boxes[:, 3] - boxes[:, 1] + 1
        keep = (ws >= min_size * info[2]) & (hs >= min_size * info[2])
        s = jnp.where(keep, s, -jnp.inf)
        top_s, top_i = jax.lax.top_k(s, pre_n)
        top_b = boxes[top_i]
        # O(K^2) mask NMS over the pre_n candidates (score-descending)
        iou = _pairwise_iou(top_b, top_b)
        supp = jnp.zeros(pre_n, bool)

        def body(i, supp):
            kill = (iou[i] > nms_thr) & (jnp.arange(pre_n) > i) & ~supp[i]
            return supp | kill

        supp = jax.lax.fori_loop(0, pre_n, body, supp)
        final_s = jnp.where(supp | (top_s == -jnp.inf), -jnp.inf, top_s)
        out_s, oi = jax.lax.top_k(final_s, post_n)
        out_b = top_b[oi]
        valid = out_s > -jnp.inf
        return (jnp.where(valid[:, None], out_b, 0),
                jnp.where(valid, out_s, 0))

    rois, probs = jax.vmap(per_image)(sc, dl, im_info)
    return {"RpnRois": [rois], "RpnRoiProbs": [probs]}


@register_op("distribute_fpn_proposals",
             inputs=["FpnRois", "RoisNum"],
             outputs=["MultiFpnRois", "RestoreIndex", "LevelIds"],
             grad=None)
def _distribute_fpn_proposals(ctx, ins, attrs):
    """cf. distribute_fpn_proposals_op.cc.  Static redesign: instead of L
    ragged per-level outputs, emit [R] level ids + the level-sorted roi
    buffer [R, 4] + RestoreIndex mapping sorted order back to input order
    (the consumer slices per level with the ids)."""
    rois = ins["FpnRois"][0]        # [R, 4]
    min_l = int(attrs["min_level"])
    max_l = int(attrs["max_level"])
    s0 = float(attrs.get("refer_scale", 224))
    l0 = int(attrs.get("refer_level", 4))
    w = jnp.clip(rois[:, 2] - rois[:, 0], 0)
    h = jnp.clip(rois[:, 3] - rois[:, 1], 0)
    scale = jnp.sqrt(w * h)
    lvl = jnp.floor(l0 + jnp.log2(scale / s0 + 1e-8)).astype(jnp.int32)
    lvl = jnp.clip(lvl, min_l, max_l)
    order = jnp.argsort(lvl, stable=True)
    restore = jnp.argsort(order, stable=True)
    return {"MultiFpnRois": [rois[order]],
            "RestoreIndex": [restore.astype(jnp.int64)[:, None]],
            "LevelIds": [lvl[order].astype(jnp.int64)]}


@register_op("collect_fpn_proposals",
             inputs=["MultiLevelRois", "MultiLevelScores"],
             outputs=["FpnRois"], grad=None)
def _collect_fpn_proposals(ctx, ins, attrs):
    """cf. collect_fpn_proposals_op.cc: concat per-level rois, keep the
    post_nms_topN best by score (fixed-size output)."""
    rois = jnp.concatenate(ins["MultiLevelRois"], axis=0)
    scores = jnp.concatenate(
        [s.reshape(-1) for s in ins["MultiLevelScores"]], axis=0)
    n = min(int(attrs.get("post_nms_topN", 1000)), scores.shape[0])
    top_s, idx = jax.lax.top_k(scores, n)
    return {"FpnRois": [rois[idx]]}


@register_op("density_prior_box", inputs=["Input", "Image"],
             outputs=["Boxes", "Variances"], grad=None)
def _density_prior_box(ctx, ins, attrs):
    """cf. density_prior_box_op.cc (SSD-style dense anchor lattice)."""
    feat = ins["Input"][0]
    img = ins["Image"][0]
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    fixed_sizes = attrs["fixed_sizes"]
    fixed_ratios = attrs["fixed_ratios"]
    densities = attrs["densities"]
    step_w = float(attrs.get("step_w", 0.0)) or img_w / W
    step_h = float(attrs.get("step_h", 0.0)) or img_h / H
    offset = float(attrs.get("offset", 0.5))
    var = attrs.get("variances", [0.1, 0.1, 0.2, 0.2])

    boxes = []
    for size, density in zip(fixed_sizes, densities):
        for ratio in fixed_ratios:
            bw = size * np.sqrt(ratio)
            bh = size / np.sqrt(ratio)
            step = size / density
            for di in range(density):
                for dj in range(density):
                    cx_off = (dj + 0.5) * step - size / 2
                    cy_off = (di + 0.5) * step - size / 2
                    boxes.append((cx_off, cy_off, bw, bh))
    xs = (jnp.arange(W) + offset) * step_w
    ys = (jnp.arange(H) + offset) * step_h
    cx, cy = jnp.meshgrid(xs, ys)          # [H, W]
    out = []
    for cx_off, cy_off, bw, bh in boxes:
        bx = cx + cx_off
        by = cy + cy_off
        out.append(jnp.stack([
            (bx - bw / 2) / img_w, (by - bh / 2) / img_h,
            (bx + bw / 2) / img_w, (by + bh / 2) / img_h], axis=-1))
    prior = jnp.stack(out, axis=2)          # [H, W, P, 4]
    prior = jnp.clip(prior, 0.0, 1.0)
    variances = jnp.broadcast_to(jnp.asarray(var, jnp.float32),
                                 prior.shape)
    return {"Boxes": [prior], "Variances": [variances]}


@register_op("sigmoid_focal_loss", inputs=["X", "Label", "FgNum"],
             outputs=["Out"], no_grad_slots=("Label", "FgNum"))
def _sigmoid_focal_loss(ctx, ins, attrs):
    """cf. sigmoid_focal_loss_op.cc (RetinaNet): FL = -alpha_t (1-p_t)^g
    log(p_t) per (sample, class), labels 1..C (0 = background)."""
    x = ins["X"][0]                 # [N, C]
    label = ins["Label"][0].reshape(-1)
    fg = ins["FgNum"][0].reshape(-1)[0].astype(jnp.float32)
    gamma = float(attrs.get("gamma", 2.0))
    alpha = float(attrs.get("alpha", 0.25))
    C = x.shape[1]
    # target[n, c] = 1 iff label[n] == c+1
    t = (label[:, None] == (jnp.arange(C) + 1)[None, :]).astype(x.dtype)
    p = jax.nn.sigmoid(x)
    ce = -(t * jax.nn.log_sigmoid(x) + (1 - t) * jax.nn.log_sigmoid(-x))
    p_t = t * p + (1 - t) * (1 - p)
    a_t = t * alpha + (1 - t) * (1 - alpha)
    loss = a_t * (1 - p_t) ** gamma * ce / jnp.maximum(fg, 1.0)
    return {"Out": [loss]}


@register_op("polygon_box_transform", inputs=["Input"], outputs=["Output"],
             grad=None)
def _polygon_box_transform(ctx, ins, attrs):
    """cf. polygon_box_transform_op.cc (EAST text detection): offset
    channels -> absolute vertex coordinates at 4x resolution."""
    x = ins["Input"][0]             # [N, 2K, H, W]
    n, c, h, w = x.shape
    xs = jnp.arange(w, dtype=x.dtype) * 4.0
    ys = jnp.arange(h, dtype=x.dtype) * 4.0
    grid_x = jnp.broadcast_to(xs[None, :], (h, w))
    grid_y = jnp.broadcast_to(ys[:, None], (h, w))
    base = jnp.stack([grid_x, grid_y], axis=0)      # [2, H, W]
    base = jnp.tile(base, (c // 2, 1, 1))           # [2K, H, W]
    return {"Output": [base[None] - x]}


@register_op("box_decoder_and_assign",
             inputs=["PriorBox", "PriorBoxVar", "TargetBox", "BoxScore"],
             outputs=["DecodeBox", "OutputAssignBox"], grad=None)
def _box_decoder_and_assign(ctx, ins, attrs):
    """cf. box_decoder_and_assign_op.cc: decode per-class deltas, assign
    each roi its argmax-class box."""
    prior = ins["PriorBox"][0]      # [R, 4]
    pvar = ins["PriorBoxVar"][0]    # [R, 4]
    target = ins["TargetBox"][0]    # [R, C*4]
    score = ins["BoxScore"][0]      # [R, C]
    R, C4 = target.shape
    C = C4 // 4
    per_class = target.reshape(R, C, 4)
    decoded = jax.vmap(
        lambda t: _decode_bbox(prior, t, pvar),
        in_axes=1, out_axes=1)(per_class)       # [R, C, 4]
    best = jnp.argmax(score, axis=1)
    assign = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, 2), axis=1)[:, 0]
    return {"DecodeBox": [decoded.reshape(R, C4)],
            "OutputAssignBox": [assign]}


@register_op("target_assign",
             inputs=["X", "MatchIndices", "NegIndices"],
             outputs=["Out", "OutWeight"], grad=None)
def _target_assign(ctx, ins, attrs):
    """cf. target_assign_op.cc: scatter per-gt rows onto matched priors;
    unmatched rows get `mismatch_value` with weight 0 (negatives weight
    1 via NegIndices mask)."""
    x = ins["X"][0]                 # [N, G, K]
    match = ins["MatchIndices"][0]  # [N, P] (-1 = unmatched)
    mismatch = float(attrs.get("mismatch_value", 0.0))
    safe = jnp.maximum(match, 0)
    out = jax.vmap(lambda xb, mb: xb[mb])(x, safe)      # [N, P, K]
    matched = (match >= 0)[..., None]
    out = jnp.where(matched, out, mismatch)
    weight = matched.astype(jnp.float32)
    if ins.get("NegIndices"):
        neg = ins["NegIndices"][0]  # [N, P] 0/1 mask of negatives
        weight = jnp.maximum(weight, neg[..., None].astype(jnp.float32))
    return {"Out": [out], "OutWeight": [weight]}


@register_op("roi_pool", inputs=["X", "ROIs"], outputs=["Out"],
             no_grad_slots=("ROIs",))
def _roi_pool(ctx, ins, attrs):
    """cf. roi_pool_op.cc: max pooling over each roi's bin grid
    (quantized boundaries, unlike roi_align's bilinear samples)."""
    x, rois = ins["X"][0], ins["ROIs"][0]     # [N,C,H,W], [R,4] (batch 0)
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    feat = x[0]                                # single-image contract

    def one_roi(roi):
        x1 = jnp.round(roi[0] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[2] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def one_bin(i, j):
            by0 = y1 + (i * rh) // ph
            by1 = y1 + ((i + 1) * rh + ph - 1) // ph
            bx0 = x1 + (j * rw) // pw
            bx1 = x1 + ((j + 1) * rw + pw - 1) // pw
            m = ((ys >= by0) & (ys < jnp.maximum(by1, by0 + 1)))[:, None] \
                & ((xs >= bx0) & (xs < jnp.maximum(bx1, bx0 + 1)))[None, :]
            neg = jnp.finfo(feat.dtype).min
            return jnp.max(jnp.where(m[None], feat, neg), axis=(1, 2))

        rows = jnp.stack([
            jnp.stack([one_bin(i, j) for j in range(pw)], axis=1)
            for i in range(ph)
        ], axis=1)                              # [C, ph, pw]
        return rows

    return {"Out": [jax.vmap(one_roi)(rois)]}


@register_op("psroi_pool", inputs=["X", "ROIs"], outputs=["Out"],
             no_grad_slots=("ROIs",))
def _psroi_pool(ctx, ins, attrs):
    """cf. psroi_pool_op.cc (R-FCN): position-sensitive average pooling —
    bin (i, j) reads channel group (i*pw + j)."""
    x, rois = ins["X"][0], ins["ROIs"][0]
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    oc = int(attrs["output_channels"])
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    feat = x[0].reshape(ph * pw, oc, H, W) if C == ph * pw * oc else None
    if feat is None:
        raise ValueError("psroi_pool needs C == pooled_h*pooled_w*out_ch")
    ys = jnp.arange(H)
    xs = jnp.arange(W)

    def one_roi(roi):
        x1 = roi[0] * scale
        y1 = roi[1] * scale
        x2 = roi[2] * scale
        y2 = roi[3] * scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)

        def one_bin(i, j):
            by0 = y1 + rh * i / ph
            by1 = y1 + rh * (i + 1) / ph
            bx0 = x1 + rw * j / pw
            bx1 = x1 + rw * (j + 1) / pw
            m = ((ys >= jnp.floor(by0)) & (ys < jnp.ceil(by1)))[:, None] \
                & ((xs >= jnp.floor(bx0)) & (xs < jnp.ceil(bx1)))[None, :]
            g = feat[i * pw + j]                # [oc, H, W]
            cnt = jnp.maximum(jnp.sum(m), 1)
            return jnp.sum(jnp.where(m[None], g, 0), axis=(1, 2)) / cnt

        return jnp.stack([
            jnp.stack([one_bin(i, j) for j in range(pw)], axis=1)
            for i in range(ph)
        ], axis=1)                              # [oc, ph, pw]

    return {"Out": [jax.vmap(one_roi)(rois)]}


@register_op("affine_channel", inputs=["X", "Scale", "Bias"],
             outputs=["Out"])
def _affine_channel(ctx, ins, attrs):
    """cf. affine_channel_op.cc: per-channel x*scale + bias (frozen-BN)."""
    x = ins["X"][0]
    s = ins["Scale"][0].reshape(1, -1, 1, 1)
    b = ins["Bias"][0].reshape(1, -1, 1, 1)
    return {"Out": [x * s + b]}


@register_op("matrix_nms", inputs=["BBoxes", "Scores"], outputs=["Out"],
             grad=None)
def _matrix_nms(ctx, ins, attrs):
    """cf. matrix_nms_op.cc (SOLOv2): parallel soft-NMS — each candidate's
    score decays by its max IoU with any higher-scored same-class
    candidate (gaussian or linear kernel), no sequential suppression.
    Static output [N, keep_top_k, 6] with label -1 padding."""
    bboxes = ins["BBoxes"][0]                   # [N, M, 4]
    scores = ins["Scores"][0]                   # [N, C, M]
    thr = float(attrs.get("score_threshold", 0.05))
    post = float(attrs.get("post_threshold", 0.0))
    nms_top_k = int(attrs.get("nms_top_k", 100))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    use_gauss = bool(attrs.get("use_gaussian", False))
    sigma = float(attrs.get("gaussian_sigma", 2.0))
    bg = int(attrs.get("background_label", 0))
    N, C, M = scores.shape
    K = min(nms_top_k, M)

    def per_image(boxes, sc):
        def per_class(c_scores, cid):
            top_s, top_i = jax.lax.top_k(c_scores, K)
            top_b = boxes[top_i]
            iou = _pairwise_iou(top_b, top_b)
            # decay[i] = prod over j<i of kernel(iou_ji); matrix form uses
            # the max IoU among higher-scored candidates
            upper = jnp.triu(iou, k=1)          # j suppresses i>j
            max_iou = jnp.max(upper, axis=0)
            if use_gauss:
                decay = jnp.exp(-(max_iou ** 2) / sigma)
            else:
                decay = 1.0 - max_iou
            s2 = top_s * decay
            s2 = jnp.where(top_s > thr, s2, 0.0)
            lab = jnp.full((K,), cid, jnp.float32)
            return jnp.concatenate(
                [lab[:, None], s2[:, None], top_b], axis=1)  # [K, 6]

        cls_ids = [c for c in range(C) if c != bg]
        allc = jnp.concatenate(
            [per_class(sc[c], c) for c in cls_ids], axis=0)
        order = jnp.argsort(-allc[:, 1])
        out = allc[order[:keep_top_k]]
        pad = keep_top_k - out.shape[0]
        if pad > 0:
            out = jnp.concatenate(
                [out, jnp.full((pad, 6), -1.0, out.dtype)], axis=0)
        return jnp.where(out[:, 1:2] > post,
                         out, out.at[:, 0].set(-1.0))

    return {"Out": [jax.vmap(per_image)(bboxes, scores)]}


def _bbox_deltas(anchors, gt):
    """Standard (dx, dy, dw, dh) encoding of gt vs anchors [..., 4]."""
    aw = anchors[..., 2] - anchors[..., 0] + 1e-9
    ah = anchors[..., 3] - anchors[..., 1] + 1e-9
    ax = anchors[..., 0] + aw * 0.5
    ay = anchors[..., 1] + ah * 0.5
    gw = gt[..., 2] - gt[..., 0] + 1e-9
    gh = gt[..., 3] - gt[..., 1] + 1e-9
    gx = gt[..., 0] + gw * 0.5
    gy = gt[..., 1] + gh * 0.5
    return jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                      jnp.log(gw / aw), jnp.log(gh / ah)], axis=-1)


def _assign_anchor_labels(anchors, gtbox, has_gt, pos_thr, neg_thr,
                          anchor_valid=None):
    """IoU matching core shared by the target-assign ops: returns
    (labels [A] in {1,0,-1}, matched gt index [A], max IoU [A]).
    Anchors matching no gt well enough stay -1 (ignore).  anchor_valid
    [A] masks anchors out BEFORE assignment (reference straddle filter
    order), so the per-gt best-anchor rule runs over valid anchors
    only; invalid anchors end -1."""
    iou = _pairwise_iou(anchors, gtbox)            # [A, G]
    iou = jnp.where(has_gt[None, :], iou, -1.0)
    if anchor_valid is not None:
        iou = jnp.where(anchor_valid[:, None], iou, -1.0)
    best_gt = jnp.argmax(iou, axis=1)              # [A]
    best_iou = jnp.max(iou, axis=1)
    labels = jnp.full((anchors.shape[0],), -1, jnp.int32)
    labels = jnp.where(best_iou < neg_thr, 0, labels)
    labels = jnp.where(best_iou >= pos_thr, 1, labels)
    # every gt's best anchor is positive (reference rule), ties included
    per_gt_best = jnp.max(iou, axis=0)             # [G]
    is_gt_best = jnp.any(
        (iou >= per_gt_best[None, :] - 1e-6) & (iou > 0)
        & has_gt[None, :], axis=1)
    labels = jnp.where(is_gt_best, 1, labels)
    if anchor_valid is not None:
        labels = jnp.where(anchor_valid, labels, -1)
    return labels, best_gt, best_iou


def _subsample(key, labels, want_pos, want_total, use_random):
    """Cap positives at want_pos and negatives at want_total - n_pos by
    flipping the excess to -1 (ignore).  use_random permutes with the
    PER-IMAGE key; otherwise the lowest anchor indices win."""
    a = labels.shape[0]
    if use_random:
        order = jax.random.permutation(key, a)
    else:
        order = jnp.arange(a)
    rank_of = jnp.zeros((a,), jnp.int32).at[order].set(
        jnp.arange(a, dtype=jnp.int32))
    pos = labels == 1

    def keep_first(mask, k):
        r = jnp.where(mask, rank_of, a + 1)
        kth = jnp.sort(r)[jnp.maximum(k - 1, 0)]
        return mask & (r <= jnp.where(k > 0, kth, -1))

    keep_pos = keep_first(pos, jnp.minimum(want_pos, jnp.sum(pos)))
    n_pos = jnp.sum(keep_pos)
    neg = labels == 0
    keep_neg = keep_first(neg, jnp.minimum(want_total - n_pos,
                                           jnp.sum(neg)))
    out = jnp.full_like(labels, -1)
    out = jnp.where(keep_pos, 1, out)
    out = jnp.where(keep_neg, 0, out)
    return out


@register_op("rpn_target_assign",
             inputs=["Anchor", "GtBoxes", "IsCrowd", "ImInfo"],
             outputs=["TargetLabel", "TargetBBox", "BBoxInsideWeight",
                      "ScoreIndex", "LocationIndex"],
             needs_rng=True, grad=None)
def _rpn_target_assign(ctx, ins, attrs):
    """cf. rpn_target_assign_op.cc.  STATIC redesign: instead of the
    LoD-compacted [F]/[F+B] index tensors, every output is anchor-dense
    per image — TargetLabel [N, A] in {1, 0, -1=ignore}, TargetBBox
    [N, A, 4] deltas (valid where label==1), BBoxInsideWeight [N, A, 4]
    (1 on positives).  ScoreIndex/LocationIndex become {0,1} masks
    [N, A] marking scored (label>=0) / localized (label==1) anchors."""
    anchors = ins["Anchor"][0]                     # [A, 4]
    gtbox = ins["GtBoxes"][0]                      # [N, G, 4]
    crowd = (ins["IsCrowd"][0] if ins.get("IsCrowd") else None)
    iminfo = ins["ImInfo"][0]                      # [N, 3] (h, w, scale)
    batch = int(attrs.get("rpn_batch_size_per_im", 256))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    pos_thr = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_thr = float(attrs.get("rpn_negative_overlap", 0.3))
    straddle = float(attrs.get("rpn_straddle_thresh", 0.0))
    use_random = bool(attrs.get("use_random", True))
    if crowd is None:
        crowd = jnp.zeros(gtbox.shape[:2], jnp.int32)

    def per_image(gt, crowd_row, im, key):
        has_gt = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
        has_gt = has_gt & (crowd_row.reshape(-1) == 0)
        # straddle filter (reference default 0) runs BEFORE assignment:
        # anchors crossing the image boundary are excluded up front so a
        # gt whose best anchor straddles still gets its best IN-BOUNDS
        # anchor forced positive (reference order)
        inside = None
        if straddle >= 0:
            inside = ((anchors[:, 0] >= -straddle)
                      & (anchors[:, 1] >= -straddle)
                      & (anchors[:, 2] < im[1] + straddle)
                      & (anchors[:, 3] < im[0] + straddle))
        labels, best_gt, _ = _assign_anchor_labels(
            anchors, gt, has_gt, pos_thr, neg_thr, anchor_valid=inside)
        labels = _subsample(key, labels, int(batch * fg_frac), batch,
                            use_random)
        deltas = _bbox_deltas(anchors, gt[best_gt])
        w = (labels == 1).astype(jnp.float32)[:, None]
        return (labels, deltas * w, jnp.broadcast_to(w, deltas.shape),
                (labels >= 0).astype(jnp.int32),
                (labels == 1).astype(jnp.int32))

    keys = jax.random.split(ctx.rng(), gtbox.shape[0])  # per-image keys
    lab, tb, biw, sidx, lidx = jax.vmap(per_image)(
        gtbox, crowd, iminfo, keys)
    return {"TargetLabel": [lab], "TargetBBox": [tb],
            "BBoxInsideWeight": [biw], "ScoreIndex": [sidx],
            "LocationIndex": [lidx]}


@register_op("retinanet_target_assign",
             inputs=["Anchor", "GtBoxes", "GtLabels", "IsCrowd", "ImInfo"],
             outputs=["TargetLabel", "TargetBBox", "BBoxInsideWeight",
                      "ForegroundNumber", "ScoreIndex", "LocationIndex"],
             grad=None)
def _retinanet_target_assign(ctx, ins, attrs):
    """cf. retinanet_target_assign_op.cc: like RPN assign but every
    non-ignored anchor is scored (focal loss, no subsampling) and
    TargetLabel carries the CLASS id (0 = background).  Same anchor-dense
    static redesign as rpn_target_assign."""
    anchors = ins["Anchor"][0]
    gtbox = ins["GtBoxes"][0]                      # [N, G, 4]
    gtlab = ins["GtLabels"][0]                     # [N, G] (>=1)
    rcrowd = (ins["IsCrowd"][0] if ins.get("IsCrowd")
              else jnp.zeros(gtbox.shape[:2], jnp.int32))
    pos_thr = float(attrs.get("positive_overlap", 0.5))
    neg_thr = float(attrs.get("negative_overlap", 0.4))

    def per_image(gt, gl, crowd_row):
        has_gt = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
        has_gt = has_gt & (crowd_row.reshape(-1) == 0)
        labels, best_gt, _ = _assign_anchor_labels(
            anchors, gt, has_gt, pos_thr, neg_thr)
        cls = jnp.where(labels == 1,
                        gl.reshape(-1)[best_gt].astype(jnp.int32),
                        jnp.where(labels == 0, 0, -1))
        deltas = _bbox_deltas(anchors, gt[best_gt])
        w = (labels == 1).astype(jnp.float32)[:, None]
        fg = jnp.sum(labels == 1).astype(jnp.int32).reshape(1)
        return (cls, deltas * w, jnp.broadcast_to(w, deltas.shape), fg,
                (labels >= 0).astype(jnp.int32),
                (labels == 1).astype(jnp.int32))

    cls, tb, biw, fg, sidx, lidx = jax.vmap(per_image)(
        gtbox, gtlab, rcrowd)
    return {"TargetLabel": [cls], "TargetBBox": [tb],
            "BBoxInsideWeight": [biw], "ForegroundNumber": [fg],
            "ScoreIndex": [sidx], "LocationIndex": [lidx]}


@register_op("generate_proposal_labels",
             inputs=["RpnRois", "GtClasses", "IsCrowd", "GtBoxes",
                     "ImInfo"],
             outputs=["Rois", "LabelsInt32", "BboxTargets",
                      "BboxInsideWeights", "BboxOutsideWeights"],
             needs_rng=True, grad=None)
def _generate_proposal_labels(ctx, ins, attrs):
    """cf. generate_proposal_labels_op.cc: sample second-stage RoIs with
    class + regression targets.  STATIC redesign: outputs are dense over
    the input proposals [N, R] — LabelsInt32 in {class, 0=bg, -1=unused},
    BboxTargets [N, R, 4*C] one-hot-per-class deltas, inside weights 1
    on the matched class slot of foregrounds, outside weights 1 on every
    sampled (label >= 0) roi's slot."""
    rois = ins["RpnRois"][0]                       # [N, R, 4]
    gtcls = ins["GtClasses"][0]                    # [N, G]
    gtbox = ins["GtBoxes"][0]                      # [N, G, 4]
    bs = int(attrs.get("batch_size_per_im", 512))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_thr = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    ncls = int(attrs.get("class_nums", 81))
    use_random = bool(attrs.get("use_random", True))

    crowd = (ins["IsCrowd"][0] if ins.get("IsCrowd")
             else jnp.zeros(gtbox.shape[:2], jnp.int32))

    def per_image(pr, gt, gl, crowd_row, key):
        # reference behavior: the gt boxes themselves join the candidate
        # RoIs, so every valid gt is a foreground sample from step 0
        pr = jnp.concatenate([pr, gt], axis=0)
        has_gt = (gt[:, 2] > gt[:, 0]) & (gt[:, 3] > gt[:, 1])
        has_gt = has_gt & (crowd_row.reshape(-1) == 0)
        iou = _pairwise_iou(pr, gt)
        # invalid gts contribute IoU 0 (not -1): an image with no valid
        # gt still samples its proposals as BACKGROUND (reference
        # generate_proposal_labels behavior)
        iou = jnp.where(has_gt[None, :], iou, 0.0)
        best_gt = jnp.argmax(iou, axis=1)
        best_iou = jnp.max(iou, axis=1)
        labels = jnp.full((pr.shape[0],), -1, jnp.int32)
        labels = jnp.where((best_iou < bg_hi) & (best_iou >= bg_lo),
                           0, labels)
        labels = jnp.where(best_iou >= fg_thr, 1, labels)
        labels = _subsample(key, labels, int(bs * fg_frac), bs, use_random)
        cls = jnp.where(labels == 1,
                        gl.reshape(-1)[best_gt].astype(jnp.int32),
                        jnp.where(labels == 0, 0, -1))
        deltas = _bbox_deltas(pr, gt[best_gt])
        onehot = jax.nn.one_hot(jnp.maximum(cls, 0), ncls)  # [R, C]
        fgw = (labels == 1).astype(jnp.float32)[:, None]
        tgt = (onehot[:, :, None] * deltas[:, None, :] * fgw[:, :, None]
               ).reshape(pr.shape[0], 4 * ncls)
        biw = (onehot[:, :, None] * fgw[:, :, None]
               * jnp.ones((1, 1, 4))).reshape(pr.shape[0], 4 * ncls)
        scored = (labels >= 0).astype(jnp.float32)[:, None]
        bow = (onehot[:, :, None] * scored[:, :, None]
               * jnp.ones((1, 1, 4))).reshape(pr.shape[0], 4 * ncls)
        return pr, cls, tgt, biw, bow

    keys = jax.random.split(ctx.rng(), rois.shape[0])
    r, c, t, bi, bo = jax.vmap(per_image)(rois, gtbox, gtcls, crowd, keys)
    return {"Rois": [r], "LabelsInt32": [c], "BboxTargets": [t],
            "BboxInsideWeights": [bi], "BboxOutsideWeights": [bo]}
