"""Metric ops (cf. paddle/fluid/operators/metrics/accuracy_op.cc,
auc_op.cc, precision_recall_op.cc, detection_map_op.cc)."""

import jax
import jax.numpy as jnp

from ..core.registry import register_op


@register_op(
    "accuracy",
    inputs=["Out", "Indices", "Label"],
    outputs=["Accuracy", "Correct", "Total"],
    grad=None,
)
def _accuracy(ctx, ins, attrs):
    """cf. accuracy_op.cc: fraction of rows whose top-k indices contain label."""
    indices = ins["Indices"][0]
    label = ins["Label"][0]
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[:, 0]
    hit = jnp.any(indices == label[:, None], axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.array(indices.shape[0], dtype=jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": [acc], "Correct": [correct], "Total": [total]}


@register_op("auc", inputs=["Predict", "Label", "StatPos", "StatNeg"],
             outputs=["AUC", "StatPosOut", "StatNegOut"], grad=None,
             stateful_out_slots=("StatPosOut", "StatNegOut"))
def _auc(ctx, ins, attrs):
    """cf. metrics/auc_op.cc: streaming ROC-AUC over score-histogram
    buckets.  StatPos/StatNeg [num_thresholds+1] accumulate positive /
    negative counts per bucket across batches; AUC is the trapezoid sum
    over the accumulated histogram."""
    pred = ins["Predict"][0]
    label = ins["Label"][0].reshape(-1)
    pos_score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
        else pred.reshape(-1)
    stat_pos, stat_neg = ins["StatPos"][0], ins["StatNeg"][0]
    n_th = stat_pos.shape[0] - 1
    bucket = jnp.clip((pos_score * n_th).astype(jnp.int32), 0, n_th)
    is_pos = (label > 0).astype(stat_pos.dtype)
    stat_pos = stat_pos.at[bucket].add(is_pos)
    stat_neg = stat_neg.at[bucket].add(1.0 - is_pos)
    # descending-threshold sweep: accumulate TP/FP from the top bucket
    pos_rev = jnp.cumsum(stat_pos[::-1])
    neg_rev = jnp.cumsum(stat_neg[::-1])
    tot_pos, tot_neg = pos_rev[-1], neg_rev[-1]
    # trapezoid: sum over buckets of d(FP) * (TP_prev + TP_cur) / 2
    tp_prev = jnp.concatenate([jnp.zeros(1, pos_rev.dtype), pos_rev[:-1]])
    fp_prev = jnp.concatenate([jnp.zeros(1, neg_rev.dtype), neg_rev[:-1]])
    area = jnp.sum((neg_rev - fp_prev) * (pos_rev + tp_prev) / 2.0)
    denom = tot_pos * tot_neg
    auc = jnp.where(denom > 0, area / jnp.maximum(denom, 1.0), 0.0)
    return {"AUC": [auc.astype(jnp.float32)[None]],
            "StatPosOut": [stat_pos], "StatNegOut": [stat_neg]}


@register_op("precision_recall",
             inputs=["MaxProbs", "Indices", "Labels", "Weights",
                     "StatesInfo"],
             outputs=["BatchMetrics", "AccumMetrics", "AccumStatesInfo"],
             grad=None, stateful_out_slots=("AccumStatesInfo",))
def _precision_recall(ctx, ins, attrs):
    """cf. metrics/precision_recall_op.cc: multi-class macro/micro
    precision/recall/F1.  StatesInfo [C, 4] accumulates per-class
    (TP, FP, TN, FN); BatchMetrics/AccumMetrics are
    [macro-P, macro-R, macro-F1, micro-P, micro-R, micro-F1]."""
    idx = ins["Indices"][0].reshape(-1)
    labels = ins["Labels"][0].reshape(-1)
    C = int(attrs["class_number"])
    w = (ins["Weights"][0].reshape(-1)
         if ins.get("Weights") else jnp.ones_like(idx, jnp.float32))
    states = (ins["StatesInfo"][0] if ins.get("StatesInfo")
              else jnp.zeros((C, 4), jnp.float32))

    pred_oh = jax.nn.one_hot(idx, C, dtype=jnp.float32) * w[:, None]
    lab_oh = jax.nn.one_hot(labels, C, dtype=jnp.float32) * w[:, None]
    tp = jnp.sum(pred_oh * (idx == labels).astype(jnp.float32)[:, None],
                 axis=0)
    fp = jnp.sum(pred_oh, axis=0) - tp
    fn = jnp.sum(lab_oh, axis=0) - tp
    tn = jnp.sum(w) - tp - fp - fn
    batch = jnp.stack([tp, fp, tn, fn], axis=1)

    def metrics(st):
        tp_, fp_, _tn, fn_ = st[:, 0], st[:, 1], st[:, 2], st[:, 3]
        p = jnp.where(tp_ + fp_ > 0, tp_ / jnp.maximum(tp_ + fp_, 1e-12), 0)
        r = jnp.where(tp_ + fn_ > 0, tp_ / jnp.maximum(tp_ + fn_, 1e-12), 0)
        f1 = jnp.where(p + r > 0, 2 * p * r / jnp.maximum(p + r, 1e-12), 0)
        mp, mr, mf = jnp.mean(p), jnp.mean(r), jnp.mean(f1)
        stp, sfp, sfn = jnp.sum(tp_), jnp.sum(fp_), jnp.sum(fn_)
        up = jnp.where(stp + sfp > 0, stp / jnp.maximum(stp + sfp, 1e-12), 0)
        ur = jnp.where(stp + sfn > 0, stp / jnp.maximum(stp + sfn, 1e-12), 0)
        uf = jnp.where(up + ur > 0, 2 * up * ur / jnp.maximum(up + ur, 1e-12), 0)
        return jnp.stack([mp, mr, mf, up, ur, uf]).astype(jnp.float32)

    accum = states + batch
    return {"BatchMetrics": [metrics(batch)],
            "AccumMetrics": [metrics(accum)],
            "AccumStatesInfo": [accum]}


@register_op("detection_map",
             inputs=["DetectRes", "Label"],
             outputs=["MAP"], grad=None)
def _detection_map(ctx, ins, attrs):
    """cf. metrics/detection_map_op.cc (simplified single-batch form).

    DetectRes: [N, M, 6] = (label, score, x1, y1, x2, y2), label < 0 pads.
    Label (ground truth): [N, G, 5] = (label, x1, y1, x2, y2), label < 0
    pads.  Computes mean average precision over classes at
    `overlap_threshold` IoU with the 11-point (ap_type="11point") or
    integral interpolation — the matching is the reference greedy
    best-IoU assignment, vectorized per class."""
    det = ins["DetectRes"][0]
    gt = ins["Label"][0]
    thr = float(attrs.get("overlap_threshold", 0.5))
    ap_type = attrs.get("ap_type", "integral")
    C = int(attrs["class_num"])
    N, M, _ = det.shape
    G = gt.shape[1]

    def box_iou(a, b):
        # a [M,4], b [G,4]
        lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
        rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
        wh = jnp.clip(rb - lt, 0)
        inter = wh[..., 0] * wh[..., 1]
        area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(
            a[:, 3] - a[:, 1], 0)
        area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(
            b[:, 3] - b[:, 1], 0)
        return inter / jnp.maximum(
            area_a[:, None] + area_b[None, :] - inter, 1e-10)

    # IoU is class-independent: compute [N, M, G] ONCE outside the
    # per-class vmap
    iou_all = jax.vmap(
        lambda i: box_iou(det[i, :, 2:6], gt[i, :, 1:5]))(
            jnp.arange(N))                                       # [N, M, G]

    def per_class(c):
        # flatten all images' detections of class c, sort by score desc
        dlab, dsc = det[..., 0], det[..., 1]
        sel = (dlab == c)
        scores = jnp.where(sel, dsc, -jnp.inf).reshape(-1)      # [N*M]
        order = jnp.argsort(-scores)
        img_of = jnp.repeat(jnp.arange(N), M)[order]
        slot_of = jnp.tile(jnp.arange(M), N)[order]
        valid = scores[order] > -jnp.inf
        glab = gt[..., 0]
        gt_sel = (glab == c)                                     # [N, G]
        npos = jnp.sum(gt_sel)

        def step(used, k):
            i, m, ok = img_of[k], slot_of[k], valid[k]
            ious = jnp.where(gt_sel[i] & ~used[i], iou_all[i, m], -1.0)
            j = jnp.argmax(ious)
            hit = ok & (ious[j] >= thr)
            used = used.at[i, j].set(used[i, j] | hit)
            tp = jnp.where(hit, 1.0, 0.0) * ok
            fp = jnp.where(hit, 0.0, 1.0) * ok
            return used, (tp, fp)

        used0 = jnp.zeros((N, G), bool)
        _, (tps, fps) = jax.lax.scan(step, used0, jnp.arange(N * M))
        ctp, cfp = jnp.cumsum(tps), jnp.cumsum(fps)
        rec = ctp / jnp.maximum(npos, 1)
        prec = ctp / jnp.maximum(ctp + cfp, 1e-10)
        if ap_type == "11point":
            pts = jnp.linspace(0, 1, 11)
            pmax = jax.vmap(
                lambda r: jnp.max(jnp.where(rec >= r, prec, 0.0)))(pts)
            ap = jnp.mean(pmax)
        else:  # integral
            d_rec = jnp.diff(jnp.concatenate([jnp.zeros(1), rec]))
            ap = jnp.sum(d_rec * prec)
        return jnp.where(npos > 0, ap, -1.0)

    aps = jax.vmap(per_class)(jnp.arange(C))
    have = aps >= 0
    mAP = jnp.sum(jnp.where(have, aps, 0.0)) / jnp.maximum(
        jnp.sum(have), 1)
    return {"MAP": [mAP.astype(jnp.float32)[None]]}


@register_op("mean_iou", inputs=["Predictions", "Labels"],
             outputs=["OutMeanIou", "OutWrong", "OutCorrect"], grad=None)
def _mean_iou(ctx, ins, attrs):
    """cf. metrics mean_iou_op.cc: mean intersection-over-union across
    segmentation classes present in prediction or label."""
    pred = ins["Predictions"][0].reshape(-1)
    lab = ins["Labels"][0].reshape(-1)
    C = int(attrs["num_classes"])
    inter = jnp.zeros((C,), jnp.float32).at[
        jnp.where(pred == lab, pred, C - 1)
    ].add(jnp.where(pred == lab, 1.0, 0.0))
    area_p = jnp.zeros((C,), jnp.float32).at[pred].add(1.0)
    area_l = jnp.zeros((C,), jnp.float32).at[lab].add(1.0)
    union = area_p + area_l - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
    correct = inter.astype(jnp.int64)
    wrong = (area_p - inter).astype(jnp.int64)
    return {"OutMeanIou": [miou[None].astype(jnp.float32)],
            "OutWrong": [wrong], "OutCorrect": [correct]}
