"""Metric ops (cf. paddle/fluid/operators/metrics/accuracy_op.cc, auc_op.cc)."""

import jax.numpy as jnp

from ..core.registry import register_op


@register_op(
    "accuracy",
    inputs=["Out", "Indices", "Label"],
    outputs=["Accuracy", "Correct", "Total"],
    grad=None,
)
def _accuracy(ctx, ins, attrs):
    """cf. accuracy_op.cc: fraction of rows whose top-k indices contain label."""
    indices = ins["Indices"][0]
    label = ins["Label"][0]
    if label.ndim == 2 and label.shape[-1] == 1:
        label = label[:, 0]
    hit = jnp.any(indices == label[:, None], axis=1)
    correct = jnp.sum(hit.astype(jnp.int32))
    total = jnp.array(indices.shape[0], dtype=jnp.int32)
    acc = correct.astype(jnp.float32) / total.astype(jnp.float32)
    return {"Accuracy": [acc], "Correct": [correct], "Total": [total]}
