"""Recurrent ops (LSTM/GRU) and beam search, lowered onto lax.scan.

Capability parity: reference `operators/lstm_op.cc` + `math/detail/
lstm_kernel.h` (gate order: candidate, input, forget, output),
`operators/gru_op.cc` + `math/gru_compute.cc`, `operators/lstm_unit_op.cc`,
`operators/gru_unit_op.cc`, `operators/beam_search_op.cc` +
`math/beam_search.cc`.  TPU-first redesign: the recurrence is ONE
`lax.scan` over the time axis inside the jitted program (the reference
walks LoD-batched rows on CPU / cuDNN); variable lengths are handled by
freezing the carried state at padded steps, so LastH/LastC equal the state
at each row's true last step.  Beam search is dense [B, beam] tensors with
`lax.top_k` over beam*vocab — no LoD offset juggling.
"""

import jax
import jax.numpy as jnp

from ..core.registry import register_op

_ACTS = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "identity": lambda x: x,
}


def _act(name):
    try:
        return _ACTS[name]
    except KeyError:
        raise ValueError("unsupported rnn activation %r (have %s)"
                         % (name, sorted(_ACTS))) from None


def _lstm_cell(x4, h, c, W, bias, peep, acts):
    """One LSTM step.  x4: [B, 4D] pre-projected input; gate columns in
    reference order {candidate, input, forget, output}."""
    act_gate, act_cell, act_cand = acts
    D = h.shape[-1]
    g = x4 + h @ W
    if bias is not None:
        g = g + bias[..., :4 * D]
    gc, gi, gf, go = (g[..., :D], g[..., D:2 * D],
                      g[..., 2 * D:3 * D], g[..., 3 * D:])
    if peep is not None:
        w_ic, w_fc, w_oc = peep
        gi = gi + c * w_ic
        gf = gf + c * w_fc
    c_new = act_cand(gc) * act_gate(gi) + c * act_gate(gf)
    if peep is not None:
        go = go + c_new * w_oc
    h_new = act_gate(go) * act_cell(c_new)
    return h_new, c_new


def _gru_cell(x3, h, W, bias, origin_mode, acts):
    """One GRU step.  x3: [B, 3D] pre-projected; W: [D, 3D] with columns
    {update, reset, candidate} (reference gru_compute layout)."""
    act_gate, act_cand = acts
    D = h.shape[-1]
    if bias is not None:
        x3 = x3 + bias
    xu, xr, xc = x3[..., :D], x3[..., D:2 * D], x3[..., 2 * D:]
    u = act_gate(xu + h @ W[:, :D])
    r = act_gate(xr + h @ W[:, D:2 * D])
    c = act_cand(xc + (r * h) @ W[:, 2 * D:])
    if origin_mode:  # h = u*h_prev + (1-u)*c  (GRUCell / origin paper form)
        return u * h + (1.0 - u) * c
    return (1.0 - u) * h + u * c  # dynamic_gru default form


def _scan_rnn(step_fn, x, lens, init_carry, is_reverse):
    """Run step_fn over time with length masking.

    step_fn(carry, xt) -> (new_carry, out_t); carries are masked so padded
    steps leave state unchanged and emit zeros.  With is_reverse the scan
    visits t = T-1..0: padded steps come first and keep the initial state,
    so the recurrence runs over the valid prefix in reverse order while
    outputs stay at their original positions.
    """
    B, T = x.shape[0], x.shape[1]
    xs = jnp.moveaxis(x, 1, 0)  # [T, B, ...]
    if lens is None:
        mask = jnp.ones((T, B, 1), x.dtype)
    else:
        mask = (jnp.arange(T)[:, None] < lens[None, :]).astype(x.dtype)
        mask = mask[..., None]

    def body(carry, tm):
        xt, m = tm
        new_carry, out = step_fn(carry, xt)
        new_carry = jax.tree.map(
            lambda n, o: m * n + (1.0 - m) * o, new_carry, carry)
        out = jax.tree.map(lambda o: m * o, out)
        return new_carry, out

    carry, outs = jax.lax.scan(
        body, init_carry, (xs, mask), reverse=bool(is_reverse))
    return carry, jax.tree.map(lambda o: jnp.moveaxis(o, 0, 1), outs)


@register_op("lstm",
             inputs=["Input", "Weight", "Bias", "H0", "C0", "SeqLens"],
             outputs=["Hidden", "Cell", "LastH", "LastC"],
             no_grad_slots=("SeqLens",))
def _lstm(ctx, ins, attrs):
    """cf. lstm_op.cc: Input [B,T,4D] = x@Wx+b already projected; Weight
    [D,4D] hidden-to-hidden; Bias [1,4D] or [1,7D] with peepholes
    ({b, W_ic, W_fc, W_oc}, cf. lstm_op.cc peephole layout)."""
    x = ins["Input"][0]
    W = ins["Weight"][0]
    D = W.shape[0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    use_peep = bool(attrs.get("use_peepholes", False))
    peep = None
    if use_peep:
        if bias is None or bias.shape[-1] != 7 * D:
            raise ValueError("use_peepholes needs Bias of width 7*D")
        b = bias.reshape(-1)
        peep = (b[4 * D:5 * D], b[5 * D:6 * D], b[6 * D:])
    acts = (_act(attrs.get("gate_activation", "sigmoid")),
            _act(attrs.get("cell_activation", "tanh")),
            _act(attrs.get("candidate_activation", "tanh")))
    B = x.shape[0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, D), x.dtype)
    c0 = ins["C0"][0] if ins.get("C0") else jnp.zeros((B, D), x.dtype)
    lens = ins["SeqLens"][0] if ins.get("SeqLens") else None

    def step(carry, xt):
        h, c = carry
        h_new, c_new = _lstm_cell(xt, h, c, W, bias, peep, acts)
        return (h_new, c_new), (h_new, c_new)

    (last_h, last_c), (hs, cs) = _scan_rnn(
        step, x, lens, (h0, c0), attrs.get("is_reverse", False))
    return {"Hidden": [hs], "Cell": [cs],
            "LastH": [last_h], "LastC": [last_c]}


@register_op("gru", inputs=["Input", "Weight", "Bias", "H0", "SeqLens"],
             outputs=["Hidden", "LastH"], no_grad_slots=("SeqLens",))
def _gru(ctx, ins, attrs):
    """cf. gru_op.cc: Input [B,T,3D] pre-projected; Weight [D,3D] columns
    {update, reset, candidate}."""
    x = ins["Input"][0]
    W = ins["Weight"][0]
    D = W.shape[0]
    bias = ins["Bias"][0] if ins.get("Bias") else None
    acts = (_act(attrs.get("gate_activation", "sigmoid")),
            _act(attrs.get("activation", "tanh")))
    origin = bool(attrs.get("origin_mode", False))
    B = x.shape[0]
    h0 = ins["H0"][0] if ins.get("H0") else jnp.zeros((B, D), x.dtype)
    lens = ins["SeqLens"][0] if ins.get("SeqLens") else None

    def step(h, xt):
        h_new = _gru_cell(xt, h, W, bias, origin, acts)
        return h_new, h_new

    last_h, hs = _scan_rnn(step, x, lens, h0, attrs.get("is_reverse", False))
    return {"Hidden": [hs], "LastH": [last_h]}


@register_op("lstm_unit", inputs=["X", "HPrev", "CPrev", "Weight", "Bias"],
             outputs=["H", "C"])
def _lstm_unit(ctx, ins, attrs):
    """cf. lstm_unit_op.cc: one step; X [B,4D] pre-projected input part."""
    acts = (_act(attrs.get("gate_activation", "sigmoid")),
            _act(attrs.get("cell_activation", "tanh")),
            _act(attrs.get("candidate_activation", "tanh")))
    bias = ins["Bias"][0] if ins.get("Bias") else None
    x = ins["X"][0]
    fb = float(attrs.get("forget_bias", 0.0))
    if fb:
        D = ins["Weight"][0].shape[0]
        x = x.at[..., 2 * D:3 * D].add(fb)  # forget-gate column block
    h, c = _lstm_cell(x, ins["HPrev"][0], ins["CPrev"][0],
                      ins["Weight"][0], bias, None, acts)
    return {"H": [h], "C": [c]}


@register_op("gru_unit", inputs=["X", "HPrev", "Weight", "Bias"],
             outputs=["H"])
def _gru_unit(ctx, ins, attrs):
    """cf. gru_unit_op.cc: one step; X [B,3D] pre-projected input part."""
    acts = (_act(attrs.get("gate_activation", "sigmoid")),
            _act(attrs.get("activation", "tanh")))
    bias = ins["Bias"][0] if ins.get("Bias") else None
    h = _gru_cell(ins["X"][0], ins["HPrev"][0], ins["Weight"][0], bias,
                  bool(attrs.get("origin_mode", False)), acts)
    return {"H": [h]}


_NEG = -1e9


@register_op("beam_search", inputs=["PreIds", "PreScores", "Scores"],
             outputs=["SelectedIds", "SelectedScores", "ParentIdx"],
             grad=None)
def _beam_search(ctx, ins, attrs):
    """One beam-search step (cf. beam_search_op.cc / math/beam_search.cc).

    Dense layout: PreIds/PreScores [B, beam]; Scores [B, beam, V] = log
    probs of the next token per live beam (already accumulated when
    attrs['is_accumulated'], reference default).  Finished beams (pre id
    == end_id) contribute a single end_id candidate carrying their score,
    so they survive top-k unchanged.  Initialize PreScores as
    [0, -1e9, ...] per batch row so step 0 doesn't pick beam duplicates.
    Returns [B, beam] ids/scores and the parent beam of each selection.
    """
    pre_ids, pre_scores, scores = (
        ins["PreIds"][0], ins["PreScores"][0], ins["Scores"][0])
    beam_size = int(attrs.get("beam_size", pre_ids.shape[1]))
    end_id = int(attrs.get("end_id", 0))
    V = scores.shape[-1]
    total = scores if attrs.get("is_accumulated", True) else (
        pre_scores[..., None] + scores)
    finished = (pre_ids == end_id)[..., None]
    keep_end = jax.nn.one_hot(end_id, V, dtype=jnp.bool_)
    fin_scores = jnp.where(keep_end, pre_scores[..., None], _NEG)
    total = jnp.where(finished, fin_scores, total)
    flat = total.reshape(total.shape[0], -1)
    top_scores, top_idx = jax.lax.top_k(flat, beam_size)
    parent = (top_idx // V).astype(jnp.int64)
    token = (top_idx % V).astype(jnp.int64)
    return {"SelectedIds": [token], "SelectedScores": [top_scores],
            "ParentIdx": [parent]}


@register_op("beam_search_decode", inputs=["Ids", "Parents", "FinalScores"],
             outputs=["SentenceIds", "SentenceScores"], grad=None)
def _beam_search_decode(ctx, ins, attrs):
    """Backtrack stored (ids, parents) into full hypotheses (cf.
    beam_search_decode_op.cc).  Ids/Parents [T, B, beam] from the step op;
    output SentenceIds [B, beam, T] in generation order."""
    ids, parents = ins["Ids"][0], ins["Parents"][0]
    B, beam = ids.shape[1], ids.shape[2]
    k0 = jnp.broadcast_to(jnp.arange(beam, dtype=parents.dtype), (B, beam))

    def back(k, t_slice):
        ids_t, par_t = t_slice
        tok = jnp.take_along_axis(ids_t, k, axis=1)
        return jnp.take_along_axis(par_t, k, axis=1), tok

    _, toks = jax.lax.scan(back, k0, (ids, parents), reverse=True)
    return {"SentenceIds": [jnp.moveaxis(toks, 0, 1).transpose(0, 2, 1)],
            "SentenceScores": [ins["FinalScores"][0]]}
