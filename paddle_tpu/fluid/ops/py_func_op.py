"""py_func: user Python callables as graph ops (host callbacks).

Capability parity: reference `operators/py_func_op.cc` +
`layers/nn.py py_func` — the ONE place C++ calls back into Python.
TPU-first: the forward callable runs through `jax.pure_callback` (XLA
host callback with declared output shapes/dtypes); a registered
`backward_func` becomes the op's custom VJP, itself a pure_callback.
Like the reference (which registers callables in a process-global table
keyed by an integer id, py_func_op.cc PyFuncRegistry), programs carrying
py_func ops serialize the ID only — they replay in-process but not
across processes.

Callables must be PURE (deterministic, side-effect-free): this
framework's program-rewrite autodiff re-derives the forward inside the
gradient computation, so with a backward_func the forward callback can
run twice per step (XLA deduplicates identical callbacks when it can) —
a stateful callable would hand backward_func outputs from a different
invocation than the forward pass used.

This is also the template for the CUSTOM-OP story: `register_op` (see
`core/registry.py`) is the public extension point — a user module can
register a new op type with a JAX lowering (grads via JAX AD or a
custom_vjp inside the lowering) and drive it from layers; see
tests/test_py_func_and_custom_op.py for the worked example (reference
`tests/custom_op/`)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op

# process-global callable table (reference PyFuncRegistry).  Re-registering
# the SAME (forward, backward) pair returns the existing id, so rebuilding
# a program in a loop does not grow the table; truly distinct closures do
# accumulate for the process lifetime (the reference has the same
# property) — clear_registry() is the escape hatch for long-lived servers.
_REGISTRY: dict = {}
_IDS_BY_PAIR: dict = {}
_NEXT_ID = [0]


def register_callables(forward_fn, backward_fn=None):
    """Register (forward, backward) callables; returns the integer id the
    op's attrs carry."""
    key = (id(forward_fn), id(backward_fn))
    hit = _IDS_BY_PAIR.get(key)
    if hit is not None and _REGISTRY.get(hit) == (forward_fn, backward_fn):
        return hit
    _NEXT_ID[0] += 1
    _REGISTRY[_NEXT_ID[0]] = (forward_fn, backward_fn)
    _IDS_BY_PAIR[key] = _NEXT_ID[0]
    return _NEXT_ID[0]


def clear_registry():
    """Drop every registered callable (programs holding py_func ops stop
    replaying afterwards)."""
    _REGISTRY.clear()
    _IDS_BY_PAIR.clear()


def _as_arrays(vals):
    return tuple(np.asarray(v) for v in vals)


@register_op("py_func", inputs=["X"], outputs=["Out"])
def _py_func(ctx, ins, attrs):
    fid = int(attrs["func_id"])
    if fid not in _REGISTRY:
        raise RuntimeError(
            "py_func callable id %d is not registered in this process "
            "(py_func programs replay in-process only, like the "
            "reference PyFuncRegistry)" % fid)
    fwd, bwd = _REGISTRY[fid]
    xs = tuple(ins["X"])
    out_specs = attrs["out_specs"]  # [(shape, dtype), ...]
    batch = int(xs[0].shape[0]) if xs and xs[0].ndim else 1

    def _resolve(shp):
        # -1 dims follow the first input's batch (batch_size_like rule)
        return tuple(batch if int(d) < 0 else int(d) for d in shp)

    structs = [
        jax.ShapeDtypeStruct(_resolve(shp), np.dtype(dt))
        for shp, dt in out_specs
    ]

    def host_fwd(*arrs):
        res = fwd(*_as_arrays(arrs))
        res = res if isinstance(res, (list, tuple)) else [res]
        if len(res) != len(structs):
            raise ValueError(
                "py_func forward returned %d output(s) but %d out var(s) "
                "were declared (reference py_func_op.cc errors the same "
                "way)" % (len(res), len(structs)))
        return tuple(
            np.asarray(r, dtype=s.dtype).reshape(s.shape)
            for r, s in zip(res, structs)
        )

    def call_fwd(*xs_):
        out = jax.pure_callback(host_fwd, tuple(structs), *xs_)
        return tuple(out)

    if bwd is None:
        outs = call_fwd(*(jax.lax.stop_gradient(x) for x in xs))
        return {"Out": list(outs)}

    @jax.custom_vjp
    def f(*xs_):
        return call_fwd(*xs_)

    def f_fwd(*xs_):
        outs = call_fwd(*xs_)
        return outs, (xs_, outs)

    def f_bwd(saved, douts):
        xs_, outs = saved
        x_structs = tuple(
            jax.ShapeDtypeStruct(x.shape, x.dtype) for x in xs_
        )

        def host_bwd(*arrs):
            # reference convention: backward_func(*inputs, *outputs,
            # *out_grads) -> grads for each input
            res = bwd(*_as_arrays(arrs))
            res = res if isinstance(res, (list, tuple)) else [res]
            if len(res) != len(x_structs):
                raise ValueError(
                    "py_func backward returned %d gradient(s) for %d "
                    "input(s)" % (len(res), len(x_structs)))
            return tuple(
                np.asarray(r, dtype=s.dtype).reshape(s.shape)
                for r, s in zip(res, x_structs)
            )

        gx = jax.pure_callback(
            host_bwd, x_structs, *(xs_ + outs + tuple(douts)))
        return tuple(gx)

    f.defvjp(f_fwd, f_bwd)
    return {"Out": list(f(*xs))}
