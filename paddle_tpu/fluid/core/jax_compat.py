"""Version-portable access to jax APIs that moved between releases.

`shard_map` graduated from `jax.experimental.shard_map` (kwarg:
`check_rep`) to `jax.shard_map` (kwarg: `check_vma`), and the
varying-cast / axis-size helpers changed shape along the way
(`jax.lax.pvary` / `jax.lax.pcast(..., to="varying")` /
`jax.lax.axis_size`).  Every caller in this repo goes through this
module so one jax install difference cannot fan out into
AttributeErrors across the executor, the static pipeline, and ring
attention (the long-standing "21 env failures" class).

Fallback semantics (experimental API):

  * `check=False` maps to `check_rep=False`.  With the checker off the
    old API cannot accept replicated (partially-unmapped) out_specs, so
    the wrapper auto-maps them: each such output gains a leading dim
    mapped over the missing mesh axes inside the body, and the
    caller-facing wrapper slices shard 0 back off.  For genuinely
    replicated outputs (which is what an unmapped out_spec asserts)
    this is value-identical.
  * `check=True`/None maps to `check_rep=True`: the old checker proves
    replicated out_specs itself (no rewrite needed), but demands
    matching replication types across `cond`/`switch` branches — code
    mixing per-shard values with replicated constants must `pvary` the
    constants (the compat `pvary` below types as varying on BOTH APIs).
  * `fallback_check` overrides `check` for the fallback only: a caller
    tuned for the new API's `check_vma=False` whose body trips the old
    checker-off limitations (e.g. rank-0 residuals under autodiff) can
    keep its native setting and run the old API with the checker on.

Either checker is a static analysis, never a runtime transform, so
numerics do not change.
"""

from __future__ import annotations

import jax

__all__ = ["has_shard_map", "has_native_shard_map", "shard_map", "pvary",
           "axis_size"]


def has_shard_map():
    """True when SOME shard_map implementation is importable."""
    if hasattr(jax, "shard_map"):
        return True
    try:
        from jax.experimental.shard_map import shard_map as _  # noqa: F401

        return True
    except ImportError:
        return False


def has_native_shard_map():
    """True only for the graduated `jax.shard_map` API.  Some programs
    need it outright — the experimental fallback's replication checker
    cannot type e.g. the static pipeline's autodiff partial-eval — so
    capability gates (tests/conftest.py markers, the dryrun's
    static-pipeline section) key on THIS, while code that tolerates
    the fallback keys on `has_shard_map`."""
    return hasattr(jax, "shard_map")


def _spec_axes(spec):
    """Mesh axis names referenced by a PartitionSpec."""
    used = set()
    for entry in tuple(spec):
        if entry is None:
            continue
        if isinstance(entry, tuple):
            used.update(entry)
        else:
            used.add(entry)
    return used


def shard_map(f, mesh, in_specs, out_specs, check=None,
              fallback_check=None):
    """`jax.shard_map` when this jax has it, else the experimental one.

    `check`: tri-state — None keeps the implementation default on the
    native API; False/True map to `check_vma` there.  On the fallback,
    `fallback_check` (when given) overrides `check`; see the module
    docstring for the two fallback modes."""
    if hasattr(jax, "shard_map"):
        kw = {} if check is None else {"check_vma": bool(check)}
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)

    import jax.numpy as jnp
    import jax.tree_util as jtu
    from jax.experimental.shard_map import shard_map as _shard_map
    from jax.sharding import PartitionSpec as P

    check = check if fallback_check is None else fallback_check
    if check is None or check:
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=True)

    axis_names = tuple(getattr(mesh, "axis_names", ()))
    is_p = lambda x: isinstance(x, P)
    specs_flat, treedef = jtu.tree_flatten(out_specs, is_leaf=is_p)
    missing = [tuple(a for a in axis_names if a not in _spec_axes(s))
               for s in specs_flat]
    if not any(missing):
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)

    # out_specs leave some mesh axis unmapped: map those axes over a new
    # size-1-per-shard leading dim so check_rep=False accepts them
    new_specs = treedef.unflatten([
        P(m, *s) if m else s for s, m in zip(specs_flat, missing)])

    # out_specs may be a PREFIX tree (one P() standing for a whole dict
    # of outputs), so each matched position is transformed as a subtree.
    # jtu.tree_map, not jax.tree.map: the latter postdates some of the
    # jax versions this fallback exists for
    def body(*args):
        outs_flat = treedef.flatten_up_to(f(*args))
        return treedef.unflatten([
            jtu.tree_map(lambda a: jnp.expand_dims(a, 0), o) if m else o
            for o, m in zip(outs_flat, missing)])

    mapped = _shard_map(body, mesh=mesh, in_specs=in_specs,
                        out_specs=new_specs, check_rep=False)

    def call(*args):
        outs_flat = treedef.flatten_up_to(mapped(*args))
        return treedef.unflatten([
            jtu.tree_map(lambda a: a[0], o) if m else o
            for o, m in zip(outs_flat, missing)])

    return call


def axis_size(axis_name):
    """Size of a mapped axis from inside shard_map: `jax.lax.axis_size`
    where it exists, else the classic `psum(1, axis)` identity."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def pvary(x, axis_name):
    """Mark `x` device-varying over `axis_name`.  Where no cast API
    exists, route the value through a data dependence on
    `axis_index(axis_name)` — `where(idx < 0, x, x)` is value- and
    gradient-identity but the old replication checker types it as
    varying on `axis_name`, which is all the cast is for."""
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, axis_name)
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(x, axis_name, to="varying")
    import jax.numpy as jnp
    import jax.tree_util as jtu

    flag = jax.lax.axis_index(axis_name) < 0   # False, typed varying
    return jtu.tree_map(lambda a: jnp.where(flag, a, a), x)
