"""StatRegistry: named int64 counters for runtime observability.

Capability parity: reference `platform/monitor.h:31-76` — `StatRegistry`
singleton of `StatValue` counters with `STAT_ADD`/`STAT_RESET` macros
(used there for GPU memory high-water marks).  Here the registry is a
plain host-side dict the framework increments at interesting points
(program compiles, executor runs, predictor requests); users read it via
`fluid.core.monitor.stat_values()` or reset with `reset()`.
"""

from __future__ import annotations

from ...observability import locks as _locks

_lock = _locks.named_lock("fluid.monitor.stats", level="metrics")
_stats: dict[str, int] = {}


def stat_add(name: str, value: int = 1) -> None:
    """cf. STAT_ADD(item, t) (`monitor.h:142`)."""
    with _lock:
        _stats[name] = _stats.get(name, 0) + int(value)


def stat_set(name: str, value: int) -> None:
    with _lock:
        _stats[name] = int(value)


def stat_get(name: str) -> int:
    with _lock:
        return _stats.get(name, 0)


def stat_values() -> dict[str, int]:
    """Snapshot of all counters (cf. StatRegistry::publish)."""
    with _lock:
        return dict(_stats)


def reset(name: str = None) -> None:
    with _lock:
        if name is None:
            _stats.clear()
        else:
            _stats.pop(name, None)
