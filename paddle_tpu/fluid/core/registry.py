"""Operator registry: op definitions carry a JAX lowering + grad rule.

Capability parity: reference `paddle/fluid/framework/op_registry.h:223-269`
(REGISTER_OPERATOR / REGISTER_OP_*_KERNEL macros populating OpInfoMap with
creator, proto, GradOpMaker, InferShape).  TPU-first redesign: instead of a
per-(dtype, place, layout) kernel map dispatched at interpreter time
(`operator.cc:1032` ChooseKernel), every op registers ONE pure JAX lowering.
XLA is the kernel library; shape/dtype inference is derived from the lowering
itself via `jax.eval_shape`, so there is no hand-written InferShape for most
ops.  Gradients default to an auto-VJP maker (see backward.py) replacing the
per-op C++ GradOpMaker (`grad_op_desc_maker.h`).

Lowering signature::

    def lower(ctx, ins, attrs):  # -> {out_slot: [jax.Array, ...]}
        ...

where ``ins`` is ``{in_slot: [jax.Array, ...]}`` and ``ctx`` is a
:class:`LowerContext` giving deterministic RNG keys and compile-time info.
"""

from __future__ import annotations

import jax


class LowerContext:
    """Per-trace context handed to op lowerings.

    - ``rng()`` returns a fresh deterministic PRNG key (random ops).  The
      executor threads a single key into the traced program; each call splits
      a counter-indexed subkey so programs stay reproducible under jit.
    - ``is_test`` mirrors the reference's global train/eval switch.
    """

    def __init__(self, base_key=None, is_test=False, mesh=None):
        self._base_key = base_key
        self._counter = 0
        self.is_test = is_test
        self.mesh = mesh

    def rng(self):
        if self._base_key is None:
            raise RuntimeError(
                "This op needs randomness but no PRNG key was provided "
                "to the lowering context."
            )
        self._counter += 1
        return jax.random.fold_in(self._base_key, self._counter)


class OpDef:
    """A registered operator.

    Attributes:
      type: op type string (e.g. ``"matmul"``).
      lower: the pure JAX lowering function.
      input_slots / output_slots: declared slot names, in canonical order.
        Order matters: it defines the flat argument layout used by the
        auto-VJP grad path.
      grad_maker: None => non-differentiable; "auto" => generic VJP grad op;
        or a callable(op, block, grad_map) -> list of grad Operator specs
        (see backward.py for the calling convention).
      no_grad_slots: input slots that never receive a gradient (e.g. integer
        index inputs).
      stateful_out_slots: output slots that alias/update persistable state
        (e.g. batch_norm's MeanOut) — excluded from autodiff paths.
    """

    def __init__(
        self,
        type,
        lower,
        input_slots,
        output_slots,
        grad_maker="auto",
        no_grad_slots=(),
        stateful_out_slots=(),
        needs_rng=False,
    ):
        self.type = type
        self.lower = lower
        self.input_slots = tuple(input_slots)
        self.output_slots = tuple(output_slots)
        self.grad_maker = grad_maker
        self.no_grad_slots = frozenset(no_grad_slots)
        self.stateful_out_slots = frozenset(stateful_out_slots)
        self.needs_rng = needs_rng


_OP_REGISTRY: dict[str, OpDef] = {}


def register_op(
    type,
    inputs,
    outputs,
    grad="auto",
    no_grad_slots=(),
    stateful_out_slots=(),
    needs_rng=False,
):
    """Decorator registering a lowering as op ``type``.

    Example::

        @register_op("relu", inputs=["X"], outputs=["Out"])
        def _relu(ctx, ins, attrs):
            return {"Out": [jax.nn.relu(ins["X"][0])]}
    """

    def deco(fn):
        if type in _OP_REGISTRY:
            raise ValueError("op '%s' registered twice" % type)
        _OP_REGISTRY[type] = OpDef(
            type,
            fn,
            inputs,
            outputs,
            grad_maker=grad,
            no_grad_slots=no_grad_slots,
            stateful_out_slots=stateful_out_slots,
            needs_rng=needs_rng,
        )
        return fn

    return deco


def get_op_def(type) -> OpDef:
    try:
        return _OP_REGISTRY[type]
    except KeyError:
        raise KeyError(
            "operator '%s' is not registered (registered: %s...)"
            % (type, sorted(_OP_REGISTRY)[:20])
        ) from None


def has_op(type):
    return type in _OP_REGISTRY


def registered_ops():
    return sorted(_OP_REGISTRY)
