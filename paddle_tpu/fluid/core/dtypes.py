"""Dtype registry: string names <-> jax/numpy dtypes.

Capability parity: reference `paddle/fluid/framework/framework.proto:104`
(VarType.Type enum) and `python/paddle/fluid/data_feeder.py` dtype conversion.
TPU-first: bfloat16 is a first-class citizen (reference used float16 via
`platform/float16.h`).
"""

import jax.numpy as jnp
import numpy as np

_STR2JNP = {
    "bool": jnp.bool_,
    "int8": jnp.int8,
    "uint8": jnp.uint8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
}

_CANON = {v: k for k, v in _STR2JNP.items()}


def to_jnp(dtype):
    """Convert any dtype spec (str, np.dtype, jnp dtype) to a jnp dtype."""
    if isinstance(dtype, str):
        if dtype in _STR2JNP:
            return _STR2JNP[dtype]
        return jnp.dtype(dtype).type
    return jnp.dtype(dtype).type


def to_str(dtype):
    """Canonical string name for a dtype."""
    j = to_jnp(dtype)
    if j in _CANON:
        return _CANON[j]
    return str(np.dtype(j))


def is_floating(dtype):
    return jnp.issubdtype(to_jnp(dtype), jnp.floating)


def is_integer(dtype):
    return jnp.issubdtype(to_jnp(dtype), jnp.integer)
