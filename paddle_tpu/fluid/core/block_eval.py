"""Shared op-sequence interpreter: the ONE place that runs lowerings.

Used by the executor's traced block body, the recompute_segment composite
op, and eager initializer evaluation — any change to lowering conventions
(ctx fields, slot handling, diagnostics) lands here once.
"""

from __future__ import annotations

from .registry import get_op_def


def run_ops(ops, env, ctx):
    """Run a sequence of ops over a name->value env (mutated in place).

    ops: framework.Operator objects OR serialized dicts
    (framework.Operator.to_dict form: {"type", "inputs", "outputs", "attrs"}).
    """
    for op in ops:
        if isinstance(op, dict):
            op_type, op_ins, op_outs, op_attrs = (
                op["type"], op["inputs"], op["outputs"], op["attrs"]
            )
        else:
            op_type, op_ins, op_outs, op_attrs = (
                op.type, op.inputs, op.outputs, op.attrs
            )
        opdef = get_op_def(op_type)
        try:
            ins = {
                slot: [env[n] for n in names] for slot, names in op_ins.items()
            }
        except KeyError as e:
            raise RuntimeError(
                "op '%s' reads var %s which is not materialized in this "
                "execution environment" % (op_type, e)
            ) from None
        outs = opdef.lower(ctx, ins, op_attrs)
        for slot, names in op_outs.items():
            for n, val in zip(names, outs[slot]):
                env[n] = val
    return env
