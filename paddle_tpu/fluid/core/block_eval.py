"""Shared op-sequence interpreter: the ONE place that runs lowerings.

Used by the executor's traced block body, the recompute_segment composite
op, and eager initializer evaluation — any change to lowering conventions
(ctx fields, slot handling, diagnostics) lands here once.
"""

from __future__ import annotations

from .registry import get_op_def

_HOST_CB_SUPPORTED = None


def host_callbacks_supported():
    """Some PJRT plugins (e.g. the axon TPU tunnel) implement no host
    send/recv, so jax.debug.callback fails at compile time.  Probe once;
    debugging ops degrade gracefully (with a warning) when unsupported."""
    global _HOST_CB_SUPPORTED
    if _HOST_CB_SUPPORTED is None:
        import jax

        try:
            def probe(x):
                jax.debug.callback(lambda v: None, x)
                return x

            # the probe usually fires while TRACING a program (op lowering);
            # escape to compile-time eval so it really compiles + runs now
            with jax.ensure_compile_time_eval():
                jax.jit(probe)(1.0).block_until_ready()
            _HOST_CB_SUPPORTED = True
        except Exception:
            _HOST_CB_SUPPORTED = False
    return _HOST_CB_SUPPORTED


def _warn_no_callbacks(feature):
    import warnings

    warnings.warn(
        "%s needs host callbacks, which this backend's PJRT plugin does "
        "not support — it is a no-op here; debug on JAX_PLATFORMS=cpu"
        % feature
    )


def _nan_guard(op_type, out_name, val):
    """Per-op NaN/Inf localization (reference
    `details/nan_inf_utils_detail.cc` via FLAGS_check_nan_inf): a host
    callback raises naming the exact op + output var, from inside the
    compiled program."""
    import jax
    import jax.numpy as jnp

    if not hasattr(val, "dtype") or not jnp.issubdtype(val.dtype,
                                                       jnp.floating):
        return val
    if not host_callbacks_supported():
        _warn_no_callbacks("FLAGS_check_nan_inf per-op localization")
        return val

    def cb(bad):
        if bool(bad):
            raise FloatingPointError(
                "NaN/Inf detected in output '%s' of op '%s' "
                "(FLAGS_check_nan_inf)" % (out_name, op_type)
            )

    jax.debug.callback(cb, ~jnp.all(jnp.isfinite(val)))
    return val


def run_ops(ops, env, ctx):
    """Run a sequence of ops over a name->value env (mutated in place).

    ops: framework.Operator objects OR serialized dicts
    (framework.Operator.to_dict form: {"type", "inputs", "outputs", "attrs"}).
    """
    from ..flags import get_flags

    check_nan = bool(
        get_flags(["FLAGS_check_nan_inf"]).get("FLAGS_check_nan_inf")
    )
    for op in ops:
        if isinstance(op, dict):
            op_type, op_ins, op_outs, op_attrs = (
                op["type"], op["inputs"], op["outputs"], op["attrs"]
            )
        else:
            op_type, op_ins, op_outs, op_attrs = (
                op.type, op.inputs, op.outputs, op.attrs
            )
        opdef = get_op_def(op_type)
        try:
            ins = {
                slot: [env[n] for n in names] for slot, names in op_ins.items()
            }
        except KeyError as e:
            raise RuntimeError(
                "op '%s' reads var %s which is not materialized in this "
                "execution environment" % (op_type, e)
            ) from None
        outs = opdef.lower(ctx, ins, op_attrs)
        for slot, names in op_outs.items():
            for n, val in zip(names, outs[slot]):
                if check_nan:
                    val = _nan_guard(op_type, n, val)
                env[n] = val
    return env
