"""Place: typed device identity.

Capability parity: reference `paddle/fluid/platform/place.h:26-98` defines
CPUPlace / CUDAPlace / CUDAPinnedPlace as a boost::variant and
`DeviceContextPool` (`device_context.h:513`) maps Place -> per-device context.

TPU-first design: a Place wraps a `jax.Device` (or is a symbolic request like
TPUPlace(0) resolved lazily).  There is no per-place stream/handle bundle —
XLA owns streams — so the "device context" collapses to the jax device plus
the executor's compiled-executable cache.
"""

import functools


class Place:
    """Base class for device identities."""

    _kind = "undefined"
    _jax_platform = None

    def __init__(self, device_id=0):
        self.device_id = int(device_id)

    # -- resolution ---------------------------------------------------------
    def get_device(self):
        """Resolve to a concrete jax.Device (best effort, may fall back)."""
        import jax

        devs = _devices_by_platform(self._jax_platform)
        if not devs:
            devs = jax.devices()  # fall back to the default backend
        return devs[self.device_id % len(devs)]

    # -- identity -----------------------------------------------------------
    def __eq__(self, other):
        return (
            isinstance(other, Place)
            and self._kind == other._kind
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self._kind, self.device_id))

    def __repr__(self):
        return "%s(%d)" % (type(self).__name__, self.device_id)


@functools.lru_cache(maxsize=None)
def _devices_by_platform(platform):
    import jax

    if platform is None:
        return tuple(jax.devices())
    try:
        return tuple(jax.devices(platform))
    except RuntimeError:
        return ()


class CPUPlace(Place):
    _kind = "cpu"
    _jax_platform = "cpu"


class TPUPlace(Place):
    _kind = "tpu"
    _jax_platform = "tpu"


# Alias kept so code written against the reference API keeps working; on this
# framework "the accelerator place" is a TPU.
CUDAPlace = TPUPlace


def default_place():
    """Accelerator if present, else CPU (cf. reference get_device logic)."""
    import jax

    d = jax.devices()[0]
    if d.platform == "cpu":
        return CPUPlace(0)
    return TPUPlace(0)


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


def tpu_device_count():
    import jax

    return len([d for d in jax.devices() if d.platform != "cpu"])
