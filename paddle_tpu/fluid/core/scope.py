"""Scope: hierarchical name -> value store for persistable variables.

Capability parity: reference `paddle/fluid/framework/scope.h:46` (NewScope /
FindVar with parent fallback) and `variable.h:26`.  In the TPU build, only
*persistable* state (parameters, optimizer accumulators, running stats) lives
in a Scope between runs — intermediates never materialize by name because the
whole block compiles into one XLA computation.  Values are jax Arrays (or
numpy on feed).
"""

from __future__ import annotations


class Scope:
    def __init__(self, parent: "Scope" = None):
        self._vars = {}
        self._parent = parent
        self._kids = []

    def new_scope(self) -> "Scope":
        s = Scope(self)
        self._kids.append(s)
        return s

    def var(self, name):
        """Find-or-declare a slot in THIS scope (cf. Scope::Var)."""
        if name not in self._vars:
            self._vars[name] = None
        return name

    def set(self, name, value):
        self._vars[name] = value

    def find_var(self, name):
        """Lookup with parent fallback (cf. Scope::FindVar). None if absent."""
        s = self
        while s is not None:
            if name in s._vars:
                return s._vars[name]
            s = s._parent
        return None

    def has(self, name):
        s = self
        while s is not None:
            if name in s._vars and s._vars[name] is not None:
                return True
            s = s._parent
        return False

    def erase(self, name):
        self._vars.pop(name, None)

    def local_names(self):
        return list(self._vars)

    def drop_kids(self):
        self._kids = []


_global_scope = Scope()


def global_scope() -> Scope:
    """cf. python/paddle/fluid/executor.py:41 global_scope()."""
    return _global_scope


def _reset_global_scope_for_tests():
    global _global_scope
    _global_scope = Scope()
    return _global_scope
