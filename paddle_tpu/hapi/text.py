"""hapi.text: text encoders + classification heads (cf. reference
`incubate/hapi/text/text.py` — BasicLSTMCell/BasicGRUCell/RNN encoders,
CNNEncoder, BOWEncoder — plus the large pretrained models re-exported
from the zoo).

Each encoder is a dygraph Layer mapping padded id batches [B, T]
(+ optional seq_lens) to a fixed-size representation [B, D]; the
`TextClassifier` head composes any encoder with an MLP classifier — the
reference's sentiment / pairwise-matching model skeletons."""

from __future__ import annotations

import numpy as np

from ..fluid import dygraph, layers
from ..fluid.layer_helper import ParamAttr
from ..models.bert import BertConfig, BertForPretraining, BertModel
from ..models.transformer import Transformer, TransformerConfig

__all__ = [
    "BOWEncoder", "CNNEncoder", "GRUEncoder", "LSTMEncoder",
    "TextClassifier",
    "BertConfig", "BertModel", "BertForPretraining",
    "Transformer", "TransformerConfig",
]


def _mask(ids, pad_id):
    m = layers.cast(layers.not_equal(
        ids, layers.fill_constant_batch_size_like(
            ids, [-1, 1], "int64", pad_id)), "float32")
    return layers.unsqueeze(m, [2])            # [B, T, 1]


class BOWEncoder(dygraph.Layer):
    """Bag-of-words: masked mean of embeddings (cf. reference
    BOWEncoder)."""

    def __init__(self, vocab_size, emb_dim, pad_id=0):
        super().__init__()
        self.emb = dygraph.Embedding([vocab_size, emb_dim])
        self.pad_id = pad_id
        self.output_dim = emb_dim

    def forward(self, ids, seq_lens=None):
        e = self.emb(ids)                       # [B, T, E]
        m = _mask(ids, self.pad_id)
        summed = layers.reduce_sum(e * m, dim=1)
        denom = layers.reduce_sum(m, dim=1) + 1e-6
        return summed / denom


class CNNEncoder(dygraph.Layer):
    """Conv-over-time + max pool (cf. reference CNNEncoder: one Conv2D
    over the [B, 1, T, E] view with a full-width kernel)."""

    def __init__(self, vocab_size, emb_dim, num_filters=64, filter_size=3,
                 pad_id=0):
        super().__init__()
        self.emb = dygraph.Embedding([vocab_size, emb_dim])
        self.conv = dygraph.Conv2D(
            1, num_filters, (filter_size, emb_dim),
            padding=(filter_size // 2, 0))
        self.pad_id = pad_id
        self.output_dim = num_filters

    def forward(self, ids, seq_lens=None):
        e = self.emb(ids)                       # [B, T, E]
        m = _mask(ids, self.pad_id)
        e = e * m
        h = self.conv(layers.unsqueeze(e, [1]))  # [B, F, T', 1]
        h = layers.relu(h)
        return layers.reduce_max(h, dim=[2, 3])  # [B, F]


class GRUEncoder(dygraph.Layer):
    """Embedding -> projection -> dynamic GRU, last state (cf. reference
    DynamicGRU-based encoders)."""

    def __init__(self, vocab_size, emb_dim, hidden, pad_id=0):
        super().__init__()
        self.emb = dygraph.Embedding([vocab_size, emb_dim])
        self.proj = dygraph.Linear(emb_dim, 3 * hidden, bias_attr=False)
        self.hidden = hidden
        self.pad_id = pad_id
        self.output_dim = hidden
        h = hidden
        std = 1.0 / np.sqrt(h)
        from ..fluid.initializer import UniformInitializer

        self.w = self.create_parameter(
            [h, 3 * h],
            attr=ParamAttr(initializer=UniformInitializer(-std, std)))
        self.b = self.create_parameter([1, 3 * h], is_bias=True)

    def forward(self, ids, seq_lens=None):
        from ..fluid.layers.common import append_simple_op

        x = self.proj(self.emb(ids))            # [B, T, 3H]
        ins = {"Input": x, "Weight": self.w, "Bias": self.b}
        if seq_lens is not None:
            ins["SeqLens"] = seq_lens
        hidden, last = append_simple_op(
            "gru", ins, {}, out_slots=("Hidden", "LastH"))
        return last


class LSTMEncoder(dygraph.Layer):
    """Embedding -> projection -> LSTM, last hidden (cf. reference
    BasicLSTMCell/RNN encoder)."""

    def __init__(self, vocab_size, emb_dim, hidden, pad_id=0):
        super().__init__()
        self.emb = dygraph.Embedding([vocab_size, emb_dim])
        self.proj = dygraph.Linear(emb_dim, 4 * hidden, bias_attr=False)
        self.hidden = hidden
        self.pad_id = pad_id
        self.output_dim = hidden
        h = hidden
        std = 1.0 / np.sqrt(h)
        from ..fluid.initializer import UniformInitializer

        self.w = self.create_parameter(
            [h, 4 * h],
            attr=ParamAttr(initializer=UniformInitializer(-std, std)))
        self.b = self.create_parameter([1, 4 * h], is_bias=True)

    def forward(self, ids, seq_lens=None):
        from ..fluid.layers.common import append_simple_op

        x = self.proj(self.emb(ids))            # [B, T, 4H]
        ins = {"Input": x, "Weight": self.w, "Bias": self.b}
        if seq_lens is not None:
            ins["SeqLens"] = seq_lens
        hidden, cell, last_h, last_c = append_simple_op(
            "lstm", ins, {}, out_slots=("Hidden", "Cell", "LastH", "LastC"))
        return last_h


class TextClassifier(dygraph.Layer):
    """Encoder + MLP head (cf. reference hapi text model skeletons:
    sentiment classifier over any encoder)."""

    def __init__(self, encoder, num_classes, hidden=None):
        super().__init__()
        self.encoder = encoder
        d = encoder.output_dim
        h = hidden or max(d // 2, num_classes * 2)
        self.fc1 = dygraph.Linear(d, h, act="relu")
        self.fc2 = dygraph.Linear(h, num_classes)

    def forward(self, ids, seq_lens=None):
        rep = self.encoder(ids, seq_lens)
        return self.fc2(self.fc1(rep))
