"""hapi.text: text model zoo exposure (cf. reference
`incubate/hapi/text/` bert/transformer modules)."""

from ..models.bert import BertConfig, BertForPretraining, BertModel
from ..models.transformer import Transformer, TransformerConfig

__all__ = ["BertConfig", "BertModel", "BertForPretraining",
           "Transformer", "TransformerConfig"]
