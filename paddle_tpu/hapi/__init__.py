"""High-level API: Keras-like Model trainer.

Capability parity: reference `python/paddle/incubate/hapi/` — `model.py`
(Model.fit/evaluate/predict with static+dygraph adapters), `callbacks.py`.
"""

from . import datasets, text, vision  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback,
    CSVLogger,
    EarlyStopping,
    LRSchedulerCallback,
    ModelCheckpoint,
    ProgBarLogger,
    ReduceLROnPlateau,
)
from .model import Input, Model, summary  # noqa: F401
