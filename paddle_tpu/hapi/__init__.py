"""High-level API: Keras-like Model trainer.

Capability parity: reference `python/paddle/incubate/hapi/` — `model.py`
(Model.fit/evaluate/predict with static+dygraph adapters), `callbacks.py`.
"""

from .callbacks import Callback, ModelCheckpoint, ProgBarLogger  # noqa: F401
from .model import Model  # noqa: F401
