"""Training callbacks (cf. reference incubate/hapi/callbacks.py)."""

from __future__ import annotations


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    """cf. reference ProgBarLogger: periodic loss/metric printing."""

    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(
                "%s: %.4f" % (k, v) for k, v in (logs or {}).items()
            )
            print("epoch %d step %d - %s" % (self._epoch, step, items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = ", ".join(
                "%s: %.4f" % (k, v) for k, v in (logs or {}).items()
            )
            print("epoch %d end - %s" % (epoch, items))


class ModelCheckpoint(Callback):
    """cf. reference ModelCheckpoint: save every N epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            import os

            self.model.save(os.path.join(self.save_dir, str(epoch)))
