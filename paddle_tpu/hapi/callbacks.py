"""Training callbacks (cf. reference incubate/hapi/callbacks.py)."""

from __future__ import annotations


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    """cf. reference ProgBarLogger: periodic loss/metric printing."""

    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(
                "%s: %.4f" % (k, v) for k, v in (logs or {}).items()
            )
            print("epoch %d step %d - %s" % (self._epoch, step, items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = ", ".join(
                "%s: %.4f" % (k, v) for k, v in (logs or {}).items()
            )
            print("epoch %d end - %s" % (epoch, items))


class ModelCheckpoint(Callback):
    """cf. reference ModelCheckpoint: save every N epochs.

    Default layout is the legacy one (`<save_dir>/<epoch>.pdparams`).
    Passing `max_num_checkpoints` (retention) and/or `async_save` routes
    saves through `paddle_tpu.incubate.checkpoint`: atomically-committed
    `checkpoint_<n>/` dirs with CRC metadata, written off the training
    thread — `load_latest(model)` resumes from the newest committed one.
    """

    def __init__(self, save_freq=1, save_dir=None,
                 max_num_checkpoints=None, async_save=False):
        self.save_freq = save_freq
        self.save_dir = save_dir
        self.max_num_checkpoints = max_num_checkpoints
        self.async_save = async_save
        self._async = None

    @property
    def _use_saver(self):
        return self.max_num_checkpoints is not None or self.async_save

    def _make_saver(self):
        from ..incubate.checkpoint.checkpoint_saver import (
            AsyncCheckpointSaver,
            CheckpointSaver,
        )

        saver = CheckpointSaver(
            root=self.save_dir,
            max_num_checkpoints=self._retention)
        return AsyncCheckpointSaver(saver) if self.async_save else saver

    @property
    def _retention(self):
        # None -> default 3; an explicit 0 means KEEP ALL (CheckpointSaver
        # retention semantics), so `or 3` would be wrong
        return 3 if self.max_num_checkpoints is None \
            else self.max_num_checkpoints

    def on_epoch_end(self, epoch, logs=None):
        if not self.save_dir or epoch % self.save_freq != 0:
            return
        import os

        if not self._use_saver:
            self.model.save(os.path.join(self.save_dir, str(epoch)))
            return
        from ..incubate.checkpoint.checkpoint_saver import StateSnapshot

        snap = StateSnapshot(self.model.get_weights())
        if self._async is None:
            self._async = self._make_saver()
        if self.async_save:
            self._async.save_async([snap], epoch=epoch)
        else:
            self._async.save_checkpoint([snap], epoch=epoch)

    def on_train_end(self, logs=None):
        # drain the in-flight save so a completed fit() is fully durable
        # (and any background failure surfaces here, not silently)
        if self.async_save and self._async is not None:
            self._async.wait()

    def load_latest(self, model=None):
        """Restore the newest committed checkpoint's weights into the
        model; returns its meta dict (or None if none committed)."""
        from ..incubate.checkpoint.checkpoint_saver import (
            CheckpointSaver,
            StateSnapshot,
        )

        model = model or getattr(self, "model", None)
        if model is None:
            raise ValueError(
                "load_latest needs a model: pass one, or attach the "
                "callback via set_model/fit first")
        snap = StateSnapshot()
        meta = CheckpointSaver(
            root=self.save_dir,
            max_num_checkpoints=self._retention,
        ).load_checkpoint([snap])
        if meta is None:
            return None
        model.set_weights(snap.arrays)
        return meta


class EarlyStopping(Callback):
    """cf. reference (2.0) EarlyStopping: stop fit() when a monitored
    value stops improving; optionally restore the best weights."""

    def __init__(self, monitor="loss", mode="min", patience=0,
                 min_delta=0.0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best = save_best_model
        if mode not in ("min", "max"):
            mode = "min"
        self.mode = mode
        self.stopped_epoch = None

    def on_train_begin(self, logs=None):
        import numpy as np

        self.wait = 0
        self.best = (np.inf if self.mode == "min" else -np.inf) \
            if self.baseline is None else self.baseline
        self._best_state = None
        self.model.stop_training = False

    def _improved(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur)
        if self._improved(cur):
            self.best = cur
            self.wait = 0
            if self.save_best:
                self._best_state = self.model.get_weights()
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True
                self.stopped_epoch = epoch

    def on_train_end(self, logs=None):
        if self.save_best and self._best_state is not None:
            self.model.set_weights(self._best_state)


class LRSchedulerCallback(Callback):
    """Step an LR schedule (callable epoch -> lr) on each epoch end."""

    def __init__(self, schedule):
        self.schedule = schedule

    def on_epoch_end(self, epoch, logs=None):
        lr = float(self.schedule(epoch))
        opt = self.model._optimizer
        if hasattr(opt, "set_lr"):
            opt.set_lr(lr)
        else:
            opt._learning_rate = lr


class ReduceLROnPlateau(Callback):
    """cf. reference (2.0) ReduceLROnPlateau: shrink the LR by `factor`
    when the monitored value plateaus for `patience` epochs."""

    def __init__(self, monitor="loss", factor=0.1, patience=3,
                 min_delta=1e-4, min_lr=0.0, mode="min", verbose=0):
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = int(patience)
        self.min_delta = abs(min_delta)
        self.min_lr = float(min_lr)
        self.mode = mode if mode in ("min", "max") else "min"
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        import numpy as np

        self.best = np.inf if self.mode == "min" else -np.inf
        self.wait = 0

    def _improved(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def _get_lr(self, opt):
        lr = getattr(opt, "_learning_rate", None)
        return float(lr) if isinstance(lr, (int, float)) else None

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        if self._improved(float(cur)):
            self.best = float(cur)
            self.wait = 0
            return
        self.wait += 1
        if self.wait > self.patience:
            opt = self.model._optimizer
            lr = self._get_lr(opt)
            if lr is not None and lr > self.min_lr:
                new_lr = max(lr * self.factor, self.min_lr)
                if hasattr(opt, "set_lr"):
                    opt.set_lr(new_lr)
                else:
                    opt._learning_rate = new_lr
                if self.verbose:
                    print("ReduceLROnPlateau: lr %.2e -> %.2e"
                          % (lr, new_lr))
            self.wait = 0


class CSVLogger(Callback):
    """Append per-epoch logs to a CSV file (VisualDL-callback capability
    without the dashboard dependency)."""

    def __init__(self, path, append=False):
        self.path = path
        self.append = append
        self._keys = None

    def on_train_begin(self, logs=None):
        if not self.append:
            open(self.path, "w").close()
            self._keys = None

    def on_epoch_end(self, epoch, logs=None):
        import os

        logs = logs or {}
        if self._keys is None:
            self._keys = sorted(logs.keys())
            try:
                need_header = os.path.getsize(self.path) == 0
            except OSError:
                need_header = True
            if need_header:
                with open(self.path, "a") as f:
                    f.write(",".join(["epoch"] + self._keys) + "\n")
        with open(self.path, "a") as f:
            f.write(",".join(
                [str(epoch)] + ["%g" % float(logs.get(k, float("nan")))
                                for k in self._keys]) + "\n")
