"""Training callbacks (cf. reference incubate/hapi/callbacks.py)."""

from __future__ import annotations


class Callback:
    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class ProgBarLogger(Callback):
    """cf. reference ProgBarLogger: periodic loss/metric printing."""

    def __init__(self, log_freq=10, verbose=1):
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self._epoch = epoch

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = ", ".join(
                "%s: %.4f" % (k, v) for k, v in (logs or {}).items()
            )
            print("epoch %d step %d - %s" % (self._epoch, step, items))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            items = ", ".join(
                "%s: %.4f" % (k, v) for k, v in (logs or {}).items()
            )
            print("epoch %d end - %s" % (epoch, items))


class ModelCheckpoint(Callback):
    """cf. reference ModelCheckpoint: save every N epochs."""

    def __init__(self, save_freq=1, save_dir=None):
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            import os

            self.model.save(os.path.join(self.save_dir, str(epoch)))


class EarlyStopping(Callback):
    """cf. reference (2.0) EarlyStopping: stop fit() when a monitored
    value stops improving; optionally restore the best weights."""

    def __init__(self, monitor="loss", mode="min", patience=0,
                 min_delta=0.0, baseline=None, save_best_model=True):
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.save_best = save_best_model
        if mode not in ("min", "max"):
            mode = "min"
        self.mode = mode
        self.stopped_epoch = None

    def on_train_begin(self, logs=None):
        import numpy as np

        self.wait = 0
        self.best = (np.inf if self.mode == "min" else -np.inf) \
            if self.baseline is None else self.baseline
        self._best_state = None
        self.model.stop_training = False

    def _improved(self, cur):
        if self.mode == "min":
            return cur < self.best - self.min_delta
        return cur > self.best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            return
        cur = float(cur)
        if self._improved(cur):
            self.best = cur
            self.wait = 0
            if self.save_best:
                import jax.numpy as jnp

                self._best_state = {
                    k: jnp.asarray(v.data)
                    for k, v in self.model.network.state_dict().items()
                }
        else:
            self.wait += 1
            if self.wait > self.patience:
                self.model.stop_training = True
                self.stopped_epoch = epoch

    def on_train_end(self, logs=None):
        if self.save_best and self._best_state is not None:
            sd = self.model.network.state_dict()
            for k, v in self._best_state.items():
                sd[k].data = v


class LRSchedulerCallback(Callback):
    """Step an LR schedule (callable epoch -> lr) on each epoch end."""

    def __init__(self, schedule):
        self.schedule = schedule

    def on_epoch_end(self, epoch, logs=None):
        lr = float(self.schedule(epoch))
        opt = self.model._optimizer
        if hasattr(opt, "set_lr"):
            opt.set_lr(lr)
        else:
            opt._learning_rate = lr
