"""hapi.Model: fit/evaluate/predict over a dygraph Layer.

Capability parity: reference `incubate/hapi/model.py` — Model wraps a
network + optimizer + loss + metrics; fit() iterates a DataLoader (or
arrays), runs train steps, drives callbacks; evaluate()/predict();
save()/load() of params + optimizer state.

TPU-first: the dygraph path IS the jit path (lowerings are traceable), so
one adapter serves both modes; large-scale training goes through
distributed.ShardedTrainStep with the same Layer.
"""

from __future__ import annotations

import numpy as np

from ..fluid import dygraph, layers
from ..fluid.dygraph import to_variable
from .callbacks import Callback, ProgBarLogger


def _to_batches(data, batch_size, shuffle=False, seed=None):
    """Accept a DataLoader-like iterable or (x, y) arrays."""
    if hasattr(data, "__iter__") and not isinstance(data, (tuple, list)):
        yield from data
        return
    xs, ys = data
    n = len(xs)
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    for i in range(0, n, batch_size):
        j = idx[i:i + batch_size]
        yield xs[j], ys[j]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False  # set by EarlyStopping

    def prepare(self, optimizer=None, loss_function=None, metrics=None):
        """cf. reference Model.prepare(optimizer, loss, metrics)."""
        self._optimizer = optimizer
        self._loss = loss_function
        self._metrics = list(metrics or [])
        return self

    # -- steps ----------------------------------------------------------
    @staticmethod
    def _wrap_inputs(inputs):
        """A network may take one array or a list of feature arrays."""
        if isinstance(inputs, (list, tuple)):
            return [to_variable(np.asarray(a)) for a in inputs]
        return [to_variable(np.asarray(inputs))]

    def train_batch(self, inputs, labels):
        xs = self._wrap_inputs(inputs)
        y = to_variable(np.asarray(labels))
        self.network.train()
        pred = self.network(*xs)
        loss = self._loss(pred, y)
        loss.backward()
        self._optimizer.minimize(loss, parameter_list=self.network.parameters())
        self.network.clear_gradients()
        return float(loss.numpy()), pred.numpy()

    def eval_batch(self, inputs, labels):
        self.network.eval()
        with dygraph.no_grad():
            pred = self.network(*self._wrap_inputs(inputs))
            loss = self._loss(pred, to_variable(np.asarray(labels)))
        return float(loss.numpy()), pred.numpy()

    def predict_batch(self, inputs):
        self.network.eval()
        with dygraph.no_grad():
            return self.network(*self._wrap_inputs(inputs)).numpy()

    # -- loops ----------------------------------------------------------
    def fit(self, train_data, eval_data=None, batch_size=32, epochs=1,
            eval_freq=1, verbose=1, callbacks=None, shuffle=True,
            log_freq=10):
        """cf. reference Model.fit: epochs over train_data with eval every
        `eval_freq` epochs, callbacks driving logging/checkpoint/early
        stop (reference model.py fit + callbacks.py)."""
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.append(ProgBarLogger(log_freq=log_freq, verbose=verbose))
        for c in cbs:
            c.set_model(self)
            c.on_train_begin()
        self.stop_training = False
        history = {"loss": []}
        for epoch in range(epochs):
            for c in cbs:
                c.on_epoch_begin(epoch)
            losses = []
            for step, (bx, by) in enumerate(
                _to_batches(train_data, batch_size, shuffle, seed=epoch)
            ):
                for c in cbs:
                    c.on_train_batch_begin(step)
                loss, pred = self.train_batch(bx, by)
                losses.append(loss)
                self._update_metrics(pred, by)
                for c in cbs:
                    c.on_train_batch_end(step, {"loss": loss})
            logs = {"loss": float(np.mean(losses))}
            logs.update(self._eval_metrics())
            if eval_data is not None and (
                    epoch % max(eval_freq, 1) == 0 or epoch == epochs - 1):
                logs["eval_loss"] = self.evaluate(
                    eval_data, batch_size=batch_size, verbose=0
                )["loss"]
            history["loss"].append(logs["loss"])
            for c in cbs:
                c.on_epoch_end(epoch, logs)
            if self.stop_training:
                break
        for c in cbs:
            c.on_train_end()
        return history

    def evaluate(self, eval_data, batch_size=32, verbose=0):
        losses = []
        for m in self._metrics:
            m.reset()
        for bx, by in _to_batches(eval_data, batch_size):
            loss, pred = self.eval_batch(bx, by)
            losses.append(loss)
            self._update_metrics(pred, by)
        out = {"loss": float(np.mean(losses))}
        out.update(self._eval_metrics())
        return out

    def predict(self, test_data, batch_size=32):
        outs = []
        n = len(test_data)
        for i in range(0, n, batch_size):
            outs.append(self.predict_batch(test_data[i:i + batch_size]))
        return np.concatenate(outs, axis=0)

    # -- metrics --------------------------------------------------------
    def _update_metrics(self, pred, labels):
        from ..fluid.metrics import Accuracy

        for m in self._metrics:
            if isinstance(m, Accuracy):
                acc = float(
                    (np.argmax(pred, -1).ravel()
                     == np.asarray(labels).ravel()).mean()
                )
                m.update(acc, len(pred))
            else:
                m.update(pred, labels)

    def _eval_metrics(self):
        out = {}
        for m in self._metrics:
            try:
                out[m._name] = m.eval()
            except ValueError:
                pass  # metric saw no batches
        return out

    # -- persistence ----------------------------------------------------
    def save(self, path):
        dygraph.save_dygraph(self.network.state_dict(), path)

    def load(self, path):
        params, _ = dygraph.load_dygraph(path)
        self.network.set_state_dict(params)
