"""hapi.Model: fit/evaluate/predict over a network, static OR dygraph.

Capability parity: reference `incubate/hapi/model.py` — Model wraps a
network + optimizer + loss + metrics with TWO adapters chosen by the
execution mode at prepare() time (reference StaticGraphAdapter /
DynamicGraphAdapter, model.py:156,594): under `dygraph.guard()` batches
run eagerly; otherwise prepare() builds train/eval/predict Programs from
the declared `Input` specs (eval program cloned for_test BEFORE minimize,
the reference's clone discipline) and fit() drives an Executor.
fit()/evaluate()/predict() and the callback stream are adapter-agnostic.
"""

from __future__ import annotations

import numpy as np

from ..fluid import dygraph
from ..fluid.dygraph import to_variable
from .callbacks import Callback, ProgBarLogger


class Input:
    """cf. reference hapi.Input: a feed-var spec (shape with None/-1
    batch dims, dtype, name)."""

    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = list(shape or [])
        self.dtype = dtype
        self.name = name

    def _to_feed_var(self, default_name):
        from ..fluid import layers

        shape = [(-1 if s in (None, -1) else int(s)) for s in self.shape]
        return layers.data(self.name or default_name, shape=shape,
                           dtype=self.dtype, append_batch_size=False)


def _to_batches(data, batch_size, shuffle=False, seed=None):
    """Accept a DataLoader-like iterable or (x, y) arrays."""
    if hasattr(data, "__iter__") and not isinstance(data, (tuple, list)):
        yield from data
        return
    xs, ys = data
    n = len(xs)
    idx = np.arange(n)
    if shuffle:
        np.random.RandomState(seed).shuffle(idx)
    for i in range(0, n, batch_size):
        j = idx[i:i + batch_size]
        yield xs[j], ys[j]


class _NullStepCtx:
    """No-op stand-in for StepTimer.step() when telemetry is off."""

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


def _as_array(a):
    """Host lists -> numpy; anything already array-like (numpy OR a
    device-resident jax.Array from io.DevicePrefetcher) passes through —
    np.asarray on a device array would be a host round trip."""
    return a if hasattr(a, "dtype") else np.asarray(a)


class _DygraphAdapter:
    """Eager per-batch execution (reference DynamicGraphAdapter)."""

    def __init__(self, model):
        self.m = model

    def train_batch(self, inputs, labels):
        m = self.m
        xs = _wrap_vars(inputs)
        y = to_variable(_as_array(labels))
        m.network.train()
        pred = m.network(*xs)
        loss = m._loss(pred, y)
        loss.backward()
        m._optimizer.minimize(loss, parameter_list=m.network.parameters())
        m.network.clear_gradients()
        return float(loss.numpy()), pred.numpy()

    def eval_batch(self, inputs, labels):
        m = self.m
        m.network.eval()
        with dygraph.no_grad():
            pred = m.network(*_wrap_vars(inputs))
            loss = m._loss(pred, to_variable(_as_array(labels)))
        return float(loss.numpy()), pred.numpy()

    def predict_batch(self, inputs):
        self.m.network.eval()
        with dygraph.no_grad():
            return self.m.network(*_wrap_vars(inputs)).numpy()

    def save(self, path):
        dygraph.save_dygraph(self.m.network.state_dict(), path)
        if self.m._optimizer is not None and hasattr(
                self.m._optimizer, "state_dict"):
            try:
                dygraph.save_dygraph(self.m._optimizer.state_dict(), path)
            except Exception:
                pass

    def load(self, path):
        params, _ = dygraph.load_dygraph(path)
        self.m.network.set_state_dict(params)


class _StaticGraphAdapter:
    """Program-building execution (reference StaticGraphAdapter,
    model.py:156): one train program (forward + loss + optimizer), an
    eval clone taken BEFORE minimize, and a predict program; all three
    share the startup program / scope so parameters are common."""

    def __init__(self, model):
        import paddle_tpu.fluid as fluid
        from ..fluid import layers

        self.m = model
        m = model
        if not m._inputs:
            raise ValueError(
                "static-graph Model needs inputs=[hapi.Input(...)] specs "
                "(reference Model(network, inputs, labels) contract)")
        # the network's Layers created their parameter VARS in the
        # default main program (and init ops in the default startup) at
        # construction time — CLONE both so this model's forward/loss/
        # optimizer ops live in a private program and a second static
        # Model in the same process cannot collide
        self.main = fluid.default_main_program().clone()
        self.startup = fluid.default_startup_program().clone()
        self.scope = fluid.Scope()
        with fluid.program_guard(self.main, self.startup):
            in_vars = [
                spec._to_feed_var("hapi_x%d" % i)
                for i, spec in enumerate(m._inputs)
            ]
            label_vars = [
                spec._to_feed_var("hapi_y%d" % i)
                for i, spec in enumerate(m._labels or [])
            ]
            pred = m.network(*in_vars)
            self._pred_name = pred.name
            self._feed_names = [v.name for v in in_vars]
            self._label_names = [v.name for v in label_vars]
            # predict/eval program: forward only, cloned before backward
            self.test_prog = self.main.clone(for_test=True)
            if m._loss is not None:
                loss = m._loss(pred, *label_vars)
                self._loss_name = loss.name
                # eval clone WITH loss but before optimizer ops
                self.eval_prog = self.main.clone(for_test=True)
                if m._optimizer is not None:
                    m._optimizer.minimize(loss)
        self.exe = fluid.Executor()
        with fluid.scope_guard(self.scope):
            self.exe.run(self.startup)

    def _feed(self, inputs, labels=None):
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        feed = {n: _as_array(a) for n, a in zip(self._feed_names, ins)}
        if labels is not None:
            labs = labels if isinstance(labels, (list, tuple)) else [labels]
            feed.update({
                n: _as_array(a) for n, a in zip(self._label_names, labs)
            })
        return feed

    def train_batch(self, inputs, labels):
        import paddle_tpu.fluid as fluid

        with fluid.scope_guard(self.scope):
            loss, pred = self.exe.run(
                self.main, feed=self._feed(inputs, labels),
                fetch_list=[self._loss_name, self._pred_name])
        return float(np.mean(loss)), np.asarray(pred)

    def eval_batch(self, inputs, labels):
        import paddle_tpu.fluid as fluid

        with fluid.scope_guard(self.scope):
            loss, pred = self.exe.run(
                self.eval_prog, feed=self._feed(inputs, labels),
                fetch_list=[self._loss_name, self._pred_name])
        return float(np.mean(loss)), np.asarray(pred)

    def predict_batch(self, inputs):
        import paddle_tpu.fluid as fluid

        with fluid.scope_guard(self.scope):
            (pred,) = self.exe.run(
                self.test_prog, feed=self._feed(inputs),
                fetch_list=[self._pred_name])
        return np.asarray(pred)

    def save(self, path):
        state = {
            n: np.asarray(self.scope.find_var(n))
            for n in self.scope.local_names()
            if self.scope.has(n)
        }
        np.savez(path + ".pdparams.npz", **state)

    def load(self, path):
        data = np.load(path + ".pdparams.npz")
        for n in data.files:
            self.scope.set(n, data[n])


def _wrap_vars(inputs):
    """A network may take one array or a list of feature arrays."""
    if isinstance(inputs, (list, tuple)):
        return [to_variable(_as_array(a)) for a in inputs]
    return [to_variable(_as_array(inputs))]


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = _as_spec_list(inputs)
        self._labels = _as_spec_list(labels)
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self._adapter = None
        self.stop_training = False  # set by EarlyStopping
        self.io_stats = None        # io.PipelineStats when device_prefetch
        self.step_timer = None      # observability.StepTimer (set by fit)

    @property
    def mode(self):
        return "dygraph" if isinstance(self._adapter, _DygraphAdapter) \
            else "static"

    def prepare(self, optimizer=None, loss_function=None, metrics=None):
        """cf. reference Model.prepare(optimizer, loss, metrics); picks
        the adapter from the CURRENT execution mode (in_dygraph_mode)."""
        from ..fluid import framework

        self._optimizer = optimizer
        self._loss = loss_function
        self._metrics = list(metrics or [])
        if framework.in_dygraph_mode():
            self._adapter = _DygraphAdapter(self)
        else:
            self._adapter = _StaticGraphAdapter(self)
        return self

    def _ensure_prepared(self):
        if self._adapter is None:
            raise RuntimeError("call Model.prepare(...) before training")

    # -- steps ----------------------------------------------------------
    def train_batch(self, inputs, labels):
        self._ensure_prepared()
        return self._adapter.train_batch(inputs, labels)

    def eval_batch(self, inputs, labels):
        self._ensure_prepared()
        return self._adapter.eval_batch(inputs, labels)

    def predict_batch(self, inputs):
        self._ensure_prepared()
        return self._adapter.predict_batch(inputs)

    # -- loops ----------------------------------------------------------
    def fit(self, train_data, eval_data=None, batch_size=32, epochs=1,
            eval_freq=1, verbose=1, callbacks=None, shuffle=True,
            log_freq=10, device_prefetch=False, prefetch_depth=2,
            telemetry=True, scalar_log=None):
        """cf. reference Model.fit: epochs over train_data with eval every
        `eval_freq` epochs, callbacks driving logging/checkpoint/early
        stop (reference model.py fit + callbacks.py).

        `device_prefetch=True` routes batches through
        `io.DevicePrefetcher` (depth `prefetch_depth`): host collation
        and the H2D copy of batch N+1 overlap the train step of batch N,
        and pipeline wait/copy metrics accumulate in
        `self.io_stats` (an `io.PipelineStats`).  Loaders exposing
        `set_epoch` get it called once per epoch (sharded determinism
        contract).

        `telemetry=True` (default) arms an `observability.StepTimer` as
        `self.step_timer`: every train step gets a component budget —
        data_wait (blocked on next(batch)) + compile (XLA compilations,
        detected via jax hooks + executor lowering) + compute (dispatch,
        device execution, fetch) + host_overhead (residual) ≈ step_time
        — recorded in always-on registry histograms
        (`train_*_ms{loop="hapi.fit"}`) and kept in
        `self.step_timer.history` / `.last_breakdown`.  `scalar_log`
        (a path or `observability.ScalarWriter`) additionally streams
        every step's scalars as JSONL."""
        self._ensure_prepared()
        if telemetry:
            from ..observability import StepTimer

            self.step_timer = StepTimer(name="hapi.fit",
                                        scalar_writer=scalar_log)
        else:
            self.step_timer = None
        if device_prefetch:
            from ..io import DevicePrefetcher, PipelineStats

            if self.io_stats is None:
                self.io_stats = PipelineStats(name="hapi.fit")
            if isinstance(train_data, DevicePrefetcher):
                self.io_stats = train_data.stats  # metrics live there
            elif hasattr(train_data, "__iter__") and \
                    not isinstance(train_data, (tuple, list)):
                # wrap the LOADER itself (not the per-epoch generator) so
                # a stateful loader keeps its delivered-batch alignment
                # and early-break rewind guarantees
                train_data = DevicePrefetcher(
                    train_data, depth=prefetch_depth, stats=self.io_stats)
        cbs = list(callbacks or [])
        if verbose and not any(isinstance(c, ProgBarLogger) for c in cbs):
            cbs.append(ProgBarLogger(log_freq=log_freq, verbose=verbose))
        for c in cbs:
            c.set_model(self)
            c.on_train_begin()
        self.stop_training = False
        history = {"loss": []}
        # in dygraph mode no Executor.run fills the compile/compute
        # components; fit itself diffs the thread compile accumulator
        # and the train_batch wall clock instead
        eager = isinstance(self._adapter, _DygraphAdapter)
        try:
            for epoch in range(epochs):
                for c in cbs:
                    c.on_epoch_begin(epoch)
                losses = []
                for m in self._metrics:
                    m.reset()
                if hasattr(train_data, "set_epoch"):
                    train_data.set_epoch(epoch)
                batches = _to_batches(train_data, batch_size, shuffle,
                                      seed=epoch)
                if device_prefetch:
                    from ..io import DevicePrefetcher

                    if not isinstance(train_data, DevicePrefetcher):
                        # (x, y) array input: the per-epoch generator is
                        # stateless, wrapping it loses nothing
                        batches = DevicePrefetcher(
                            batches, depth=prefetch_depth,
                            stats=self.io_stats)
                # explicit next() so the time blocked on the input
                # pipeline is measured as the step's data_wait component
                import time as _time

                from ..observability import trace as _trace

                tracer = _trace.default_tracer()
                it = iter(batches)
                step = 0
                while True:
                    ctx = self.step_timer.step() if self.step_timer \
                        else _NullStepCtx()
                    with ctx as rec:
                        t_fetch = _time.perf_counter()
                        try:
                            bx, by = next(it)
                        except StopIteration:
                            if rec is not None:
                                rec.cancel()
                            break
                        t_got = _time.perf_counter()
                        if rec is not None:
                            rec.add("data_wait", t_got - t_fetch)
                        if tracer.enabled:
                            tracer.complete("data_wait", t_fetch, t_got,
                                            cat="train")
                        for c in cbs:
                            c.on_train_batch_begin(step)
                        if rec is not None and eager:
                            from ..observability import step_timer as _st

                            t_tb = _time.perf_counter()
                            comp0 = _st.thread_compile_seconds()
                            loss, pred = self.train_batch(bx, by)
                            t_tb1 = _time.perf_counter()
                            wall = t_tb1 - t_tb
                            dcomp = min(
                                _st.thread_compile_seconds() - comp0, wall)
                            rec.add("compile", dcomp)
                            rec.add("compute", max(wall - dcomp, 0.0))
                            if tracer.enabled:
                                # dygraph has no Executor.run span: the
                                # eager train_batch is the compute leg
                                tracer.complete(
                                    "train_batch", t_tb, t_tb1, cat="train",
                                    args={"compile_ms":
                                          round(dcomp * 1e3, 3)})
                        else:
                            loss, pred = self.train_batch(bx, by)
                        losses.append(loss)
                        self._update_metrics(pred, by)
                        for c in cbs:
                            c.on_train_batch_end(step, {"loss": loss})
                    step += 1
                logs = {"loss": float(np.mean(losses))}
                logs.update(self._eval_metrics())
                if eval_data is not None and (
                        epoch % max(eval_freq, 1) == 0
                        or epoch == epochs - 1):
                    logs["eval_loss"] = self.evaluate(
                        eval_data, batch_size=batch_size, verbose=0
                    )["loss"]
                history["loss"].append(logs["loss"])
                for c in cbs:
                    c.on_epoch_end(epoch, logs)
                if self.stop_training:
                    break
            for c in cbs:
                c.on_train_end()
        finally:
            if self.step_timer is not None:
                # flush/close the scalar log even on a mid-train crash:
                # the steps leading up to a failure are the ones a
                # post-mortem needs
                self.step_timer.close()
        return history

    def evaluate(self, eval_data, batch_size=32, verbose=0):
        self._ensure_prepared()
        losses = []
        for m in self._metrics:
            m.reset()
        for bx, by in _to_batches(eval_data, batch_size):
            loss, pred = self.eval_batch(bx, by)
            losses.append(loss)
            self._update_metrics(pred, by)
        out = {"loss": float(np.mean(losses))}
        out.update(self._eval_metrics())
        return out

    def predict(self, test_data, batch_size=32):
        self._ensure_prepared()
        outs = []
        n = len(test_data)
        for i in range(0, n, batch_size):
            outs.append(self.predict_batch(test_data[i:i + batch_size]))
        return np.concatenate(outs, axis=0)

    # -- metrics --------------------------------------------------------
    def _update_metrics(self, pred, labels):
        from ..fluid.metrics import Accuracy

        for m in self._metrics:
            if isinstance(m, Accuracy):
                acc = float(
                    (np.argmax(pred, -1).ravel()
                     == np.asarray(labels).ravel()).mean()
                )
                m.update(acc, len(pred))
            else:
                m.update(pred, labels)

    def _eval_metrics(self):
        out = {}
        for m in self._metrics:
            name = getattr(m, "name", None) or getattr(m, "_name", "metric")
            try:
                val = (m.accumulate() if hasattr(m, "accumulate")
                       else m.eval())
            except ValueError:
                continue  # metric saw no batches
            out[name if isinstance(name, str) else "metric"] = val
        return out

    def get_weights(self):
        """Mode-agnostic snapshot of all parameter arrays (used by
        EarlyStopping best-weight restore)."""
        self._ensure_prepared()
        if isinstance(self._adapter, _DygraphAdapter):
            return {k: np.asarray(v.data)
                    for k, v in self.network.state_dict().items()}
        sc = self._adapter.scope
        return {n: np.asarray(sc.find_var(n))
                for n in sc.local_names() if sc.has(n)}

    def set_weights(self, weights):
        self._ensure_prepared()
        if isinstance(self._adapter, _DygraphAdapter):
            sd = self.network.state_dict()
            for k, v in weights.items():
                if k in sd:
                    import jax.numpy as jnp

                    sd[k].data = jnp.asarray(v)
            return
        for n, v in weights.items():
            self._adapter.scope.set(n, v)

    def summary(self, input_shapes=None):
        """Per-layer parameter table (reference Model.summary)."""
        return summary(self.network, input_shapes)

    # -- persistence ----------------------------------------------------
    def save(self, path):
        self._ensure_prepared()
        self._adapter.save(path)

    def load(self, path):
        self._ensure_prepared()
        self._adapter.load(path)


def _as_spec_list(specs):
    if specs is None:
        return []
    if isinstance(specs, Input):
        return [specs]
    return list(specs)


def summary(network, input_shapes=None):
    """cf. reference (2.0) paddle.summary / hapi Model.summary: per-layer
    parameter table + totals for a dygraph Layer tree."""
    rows = []
    total = 0
    trainable = 0

    def visit(layer, prefix):
        nonlocal total, trainable
        own = 0
        for name, p in layer._parameters.items() if hasattr(
                layer, "_parameters") else []:
            n = int(np.prod(p.shape))
            own += n
            total += n
            if not getattr(p, "stop_gradient", False):
                trainable += n
        rows.append((prefix or type(layer).__name__,
                     type(layer).__name__, own))
        for name, sub in getattr(layer, "_sub_layers", {}).items():
            visit(sub, "%s/%s" % (prefix, name) if prefix else name)

    visit(network, "")
    lines = ["%-40s %-20s %12s" % ("Layer (path)", "Type", "Params"),
             "-" * 74]
    for path, ty, n in rows:
        lines.append("%-40s %-20s %12d" % (path[:40], ty[:20], n))
    lines.append("-" * 74)
    lines.append("Total params: %d" % total)
    lines.append("Trainable params: %d" % trainable)
    text = "\n".join(lines)
    print(text)
    return {"total_params": total, "trainable_params": trainable,
            "layers": len(rows)}
