"""hapi.vision: model zoo + transforms exposure (cf. reference
`incubate/hapi/vision/models/` lenet/resnet/vgg/mobilenet and
`vision/transforms/`)."""

from ..models.lenet import LeNet5
from ..models.mobilenet import MobileNetV1, mobilenet_v1
from ..models.resnet import ResNet, resnet18, resnet34, resnet50, resnet101
from ..models.vgg import VGG, vgg16, vgg19

LeNet = LeNet5  # reference hapi name

__all__ = ["LeNet", "LeNet5", "ResNet", "resnet18", "resnet34",
           "resnet50", "resnet101", "VGG", "vgg16", "vgg19",
           "MobileNetV1", "mobilenet_v1", "transforms"]


class transforms:
    """Minimal functional transforms (cf. hapi/vision/transforms):
    compose, normalize, resize over numpy batches."""

    @staticmethod
    def normalize(x, mean, std):
        import numpy as np

        mean = np.asarray(mean, np.float32).reshape(1, -1, 1, 1)
        std = np.asarray(std, np.float32).reshape(1, -1, 1, 1)
        return (np.asarray(x, np.float32) - mean) / std

    @staticmethod
    def resize(x, size):
        import jax
        import numpy as np

        x = np.asarray(x, np.float32)
        n, c = x.shape[:2]
        return np.asarray(jax.image.resize(
            x, (n, c, size[0], size[1]), method="linear"))

    class Compose:
        def __init__(self, fns):
            self.fns = list(fns)

        def __call__(self, x):
            for f in self.fns:
                x = f(x)
            return x
