"""hapi.vision: model zoo + a real transforms pipeline (cf. reference
`incubate/hapi/vision/models/` lenet/resnet/vgg/mobilenet and
`incubate/hapi/vision/transforms/transforms.py`).

Transforms are CLASS pipelines over per-sample numpy images — CHW float
arrays (the repo-wide layout) — composable with `Compose`; the legacy
batch-functional helpers (`normalize`/`resize` staticmethods) remain for
back-compat.
"""

from __future__ import annotations

import numpy as np

from ..models.lenet import LeNet5
from ..models.mobilenet import MobileNetV1, mobilenet_v1
from ..models.resnet import ResNet, resnet18, resnet34, resnet50, resnet101
from ..models.vgg import VGG, vgg16, vgg19

LeNet = LeNet5  # reference hapi name

__all__ = ["LeNet", "LeNet5", "ResNet", "resnet18", "resnet34",
           "resnet50", "resnet101", "VGG", "vgg16", "vgg19",
           "MobileNetV1", "mobilenet_v1", "transforms"]


def _chw(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[None]
    return img


class _Transform:
    def __call__(self, img):
        raise NotImplementedError

    def __repr__(self):
        return type(self).__name__


class Compose(_Transform):
    """cf. reference transforms.Compose."""

    def __init__(self, fns):
        self.fns = list(fns)

    def __call__(self, img):
        for f in self.fns:
            img = f(img)
        return img


class Resize(_Transform):
    """Bilinear resize to (h, w) (cf. transforms.Resize)."""

    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax

        img = _chw(img).astype(np.float32)
        c = img.shape[0]
        return np.asarray(jax.image.resize(
            img, (c,) + self.size, method="linear"))


class CenterCrop(_Transform):
    """cf. transforms.CenterCrop."""

    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        img = _chw(img)
        h, w = img.shape[1:]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return img[:, i:i + th, j:j + tw]


class RandomCrop(_Transform):
    """cf. transforms.RandomCrop (optional zero padding first)."""

    def __init__(self, size, padding=0, seed=None):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = int(padding)
        self._rng = np.random.RandomState(seed)

    def __call__(self, img):
        img = _chw(img)
        if self.padding:
            p = self.padding
            img = np.pad(img, ((0, 0), (p, p), (p, p)))
        h, w = img.shape[1:]
        th, tw = self.size
        i = self._rng.randint(0, max(h - th, 0) + 1)
        j = self._rng.randint(0, max(w - tw, 0) + 1)
        return img[:, i:i + th, j:j + tw]


class RandomHorizontalFlip(_Transform):
    """cf. transforms.RandomHorizontalFlip."""

    def __init__(self, prob=0.5, seed=None):
        self.prob = float(prob)
        self._rng = np.random.RandomState(seed)

    def __call__(self, img):
        img = _chw(img)
        if self._rng.rand() < self.prob:
            return img[:, :, ::-1].copy()
        return img


class RandomVerticalFlip(_Transform):
    def __init__(self, prob=0.5, seed=None):
        self.prob = float(prob)
        self._rng = np.random.RandomState(seed)

    def __call__(self, img):
        img = _chw(img)
        if self._rng.rand() < self.prob:
            return img[:, ::-1, :].copy()
        return img


class BrightnessTransform(_Transform):
    """cf. transforms.BrightnessTransform: scale by U[max(0,1-v), 1+v]."""

    def __init__(self, value, seed=None):
        self.value = float(value)
        self._rng = np.random.RandomState(seed)

    def __call__(self, img):
        a = self._rng.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return _chw(img).astype(np.float32) * a


class ContrastTransform(_Transform):
    """cf. transforms.ContrastTransform: blend with the mean."""

    def __init__(self, value, seed=None):
        self.value = float(value)
        self._rng = np.random.RandomState(seed)

    def __call__(self, img):
        img = _chw(img).astype(np.float32)
        a = self._rng.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return img * a + img.mean() * (1 - a)


class ColorJitter(_Transform):
    """Brightness + contrast jitter (cf. transforms.ColorJitter, minus
    the HSV hue/saturation legs which need color-space conversion)."""

    def __init__(self, brightness=0.0, contrast=0.0, seed=None):
        self._t = Compose([
            BrightnessTransform(brightness, seed=seed),
            ContrastTransform(
                contrast, seed=None if seed is None else seed + 1),
        ])

    def __call__(self, img):
        return self._t(img)


class Normalize(_Transform):
    """cf. transforms.Normalize: per-channel (x - mean) / std."""

    def __init__(self, mean, std):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        return (_chw(img).astype(np.float32) - self.mean) / self.std


class Permute(_Transform):
    """HWC -> CHW (cf. transforms.Permute)."""

    def __call__(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            return img[None].astype(np.float32)
        return np.transpose(img, (2, 0, 1)).astype(np.float32)


ToTensor = Permute  # 2.0 name: HWC uint8/float -> CHW float


class transforms:
    """Namespace matching `hapi.vision.transforms`: the transform classes
    above plus the legacy batch-functional helpers."""

    Compose = Compose
    Resize = Resize
    CenterCrop = CenterCrop
    RandomCrop = RandomCrop
    RandomHorizontalFlip = RandomHorizontalFlip
    RandomVerticalFlip = RandomVerticalFlip
    BrightnessTransform = BrightnessTransform
    ContrastTransform = ContrastTransform
    ColorJitter = ColorJitter
    Normalize = Normalize
    Permute = Permute
    ToTensor = ToTensor

    @staticmethod
    def normalize(x, mean, std):
        mean = np.asarray(mean, np.float32).reshape(1, -1, 1, 1)
        std = np.asarray(std, np.float32).reshape(1, -1, 1, 1)
        return (np.asarray(x, np.float32) - mean) / std

    @staticmethod
    def resize(x, size):
        import jax

        x = np.asarray(x, np.float32)
        n, c = x.shape[:2]
        return np.asarray(jax.image.resize(
            x, (n, c, size[0], size[1]), method="linear"))
