"""hapi.datasets: map-style datasets over the reader-creator corpus
modules (cf. reference `incubate/hapi/datasets/` MNIST/Flowers/IMDB —
each wraps the legacy paddle.dataset readers into indexable datasets)."""

from __future__ import annotations

import numpy as np


class _ArrayDataset:
    """Indexable (x, y) dataset; also iterable as (x, y) batches source
    for Model.fit via the (xs, ys) tuple protocol."""

    def __init__(self, xs, ys):
        self.xs = np.asarray(xs)
        self.ys = np.asarray(ys)

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return self.xs[i], self.ys[i]

    def as_arrays(self):
        return self.xs, self.ys


class MNIST(_ArrayDataset):
    """cf. hapi/datasets/mnist.py: mode train|test, images [N,1,28,28]."""

    def __init__(self, mode="train", n=None):
        from ..dataset import mnist

        reader = mnist.train() if mode == "train" else mnist.test()
        xs, ys = [], []
        for img, label in reader():
            xs.append(np.asarray(img, np.float32).reshape(1, 28, 28))
            ys.append(int(label))
            if n is not None and len(xs) >= n:
                break
        super().__init__(np.stack(xs), np.asarray(ys, np.int64))


class Cifar10(_ArrayDataset):
    def __init__(self, mode="train", n=None):
        from ..dataset import cifar

        reader = cifar.train10() if mode == "train" else cifar.test10()
        xs, ys = [], []
        for img, label in reader():
            xs.append(np.asarray(img, np.float32).reshape(3, 32, 32))
            ys.append(int(label))
            if n is not None and len(xs) >= n:
                break
        super().__init__(np.stack(xs), np.asarray(ys, np.int64))


class Imdb:
    """cf. hapi/datasets/imdb.py: padded id sequences + labels."""

    def __init__(self, mode="train", seq_len=64, n=None):
        from ..dataset import imdb

        reader = imdb.train() if mode == "train" else imdb.test()
        xs, ys = [], []
        for seq, label in reader():
            arr = np.zeros(seq_len, np.int64)
            arr[: min(len(seq), seq_len)] = seq[:seq_len]
            xs.append(arr)
            ys.append(int(label))
            if n is not None and len(xs) >= n:
                break
        self.xs = np.stack(xs)
        self.ys = np.asarray(ys, np.int64)

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return self.xs[i], self.ys[i]

    def as_arrays(self):
        return self.xs, self.ys
