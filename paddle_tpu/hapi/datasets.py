"""hapi.datasets: map-style datasets over the reader-creator corpus
modules (cf. reference `incubate/hapi/datasets/` MNIST/Flowers/IMDB —
each wraps the legacy paddle.dataset readers into indexable datasets)."""

from __future__ import annotations

import numpy as np


class _ArrayDataset:
    """Indexable (x, y) dataset; also iterable as (x, y) batches source
    for Model.fit via the (xs, ys) tuple protocol."""

    def __init__(self, xs, ys, transform=None):
        self.xs = np.asarray(xs)
        self.ys = np.asarray(ys)
        self.transform = transform

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        x = self.xs[i]
        if self.transform is not None:
            x = self.transform(x)
        return x, self.ys[i]

    def as_arrays(self):
        return self.xs, self.ys


class MNIST(_ArrayDataset):
    """cf. hapi/datasets/mnist.py: mode train|test, images [N,1,28,28];
    `transform` applies per sample at __getitem__ (reference dataset
    transform contract)."""

    def __init__(self, mode="train", n=None, transform=None):
        from ..dataset import mnist

        reader = mnist.train() if mode == "train" else mnist.test()
        xs, ys = [], []
        for img, label in reader():
            xs.append(np.asarray(img, np.float32).reshape(1, 28, 28))
            ys.append(int(label))
            if n is not None and len(xs) >= n:
                break
        super().__init__(np.stack(xs), np.asarray(ys, np.int64),
                         transform=transform)


class Cifar10(_ArrayDataset):
    def __init__(self, mode="train", n=None, transform=None):
        from ..dataset import cifar

        reader = cifar.train10() if mode == "train" else cifar.test10()
        xs, ys = [], []
        for img, label in reader():
            xs.append(np.asarray(img, np.float32).reshape(3, 32, 32))
            ys.append(int(label))
            if n is not None and len(xs) >= n:
                break
        super().__init__(np.stack(xs), np.asarray(ys, np.int64),
                         transform=transform)


class WMT14:
    """cf. hapi-era translation dataset: padded (src, tgt_in, tgt_out)
    triples over the dataset.wmt14 reader."""

    def __init__(self, dict_size=30, mode="train", src_len=12, trg_len=12,
                 n=None):
        from ..dataset import wmt14

        reader = (wmt14.train(dict_size) if mode == "train"
                  else wmt14.test(dict_size))
        srcs, tins, touts = [], [], []
        for s, ti, to in reader():
            srcs.append(_pad(s, src_len))
            tins.append(_pad(ti, trg_len))
            touts.append(_pad(to, trg_len))
            if n is not None and len(srcs) >= n:
                break
        self.src = np.stack(srcs)
        self.tgt_in = np.stack(tins)
        self.tgt_out = np.stack(touts)

    def __len__(self):
        return len(self.src)

    def __getitem__(self, i):
        return self.src[i], self.tgt_in[i], self.tgt_out[i]


def _pad(seq, n, pad=0):
    a = np.full(n, pad, np.int64)
    a[: min(len(seq), n)] = seq[:n]
    return a


class Imdb:
    """cf. hapi/datasets/imdb.py: padded id sequences + labels."""

    def __init__(self, mode="train", seq_len=64, n=None):
        from ..dataset import imdb

        reader = imdb.train() if mode == "train" else imdb.test()
        xs, ys = [], []
        for seq, label in reader():
            arr = np.zeros(seq_len, np.int64)
            arr[: min(len(seq), seq_len)] = seq[:seq_len]
            xs.append(arr)
            ys.append(int(label))
            if n is not None and len(xs) >= n:
                break
        self.xs = np.stack(xs)
        self.ys = np.asarray(ys, np.int64)

    def __len__(self):
        return len(self.xs)

    def __getitem__(self, i):
        return self.xs[i], self.ys[i]

    def as_arrays(self):
        return self.xs, self.ys
