"""Incubating subsystems (cf. reference `python/paddle/fluid/incubate/`):
capabilities that are production-real but whose API may still move."""

from . import checkpoint  # noqa: F401
from . import complex  # noqa: F401
from . import data_generator  # noqa: F401
from . import fault  # noqa: F401
