"""Dataset feed authoring (reference parity:
`python/paddle/fluid/incubate/data_generator/__init__.py` —
MultiSlotDataGenerator et al., VERDICT #4's last parity gap).

A DataGenerator turns RAW log lines into the MultiSlot line protocol
the native Dataset channel engine (`native/dataset.cpp` +
`fluid.dataset`) parses: per sample line, for every declared slot,
``<count> v1 .. v<count>`` — ints for id slots, floats for value
slots.  Deployment modes:

* ``run_from_stdin()`` — the classic pslib shape: the generator script
  becomes the dataset's ``pipe_command`` ("python my_gen.py"), and the
  engine pipes every raw file through it at load/stream time;
* ``run_from_files(files, out_dir)`` — offline materialization: write
  protocol files once, point ``set_filelist`` at them.

Author by subclassing and implementing ``generate_sample(line)``,
which returns an ITERATOR (usually a generator function) over samples;
each sample is a list of ``(slot_name, values)`` pairs in the SLOT
ORDER the dataset declares via ``set_use_var``.  ``generate_batch``
may override cross-sample processing (negative sampling, shuffling a
local buffer) — the default passes samples through one by one.
"""

from __future__ import annotations

import os
import sys

__all__ = ["DataGenerator", "MultiSlotDataGenerator"]


class DataGenerator:
    """Base authoring class: line in -> protocol line(s) out."""

    def __init__(self):
        self.batch_size_ = 1
        self._line_str = None

    # -- reference surface ----------------------------------------------
    def set_batch(self, batch_size):
        """Samples per `generate_batch` call (reference parity; the
        TPU-native engine batches again on the consumer side, so this
        only scopes cross-sample hooks like negative sampling)."""
        self.batch_size_ = max(int(batch_size), 1)

    def generate_sample(self, line):
        """Return an iterator over samples for one raw line; each
        sample is ``[(slot_name, values), ...]`` in declared slot
        order.  Must be implemented by the author."""
        raise NotImplementedError(
            "implement generate_sample(line) -> iterator of "
            "[(slot_name, values), ...]")

    def generate_batch(self, samples):
        """Cross-sample hook: receives `batch_size_` samples, yields
        (possibly transformed) samples.  Default: passthrough."""
        for s in samples:
            yield s

    # -- protocol --------------------------------------------------------
    def _convert_to_line(self, sample):
        raise NotImplementedError

    def _iter_samples(self, lines):
        buf = []
        for line in lines:
            it = self.generate_sample(line)
            if it is None:
                continue
            for sample in it:
                if sample is None:
                    continue
                buf.append(sample)
                if len(buf) >= self.batch_size_:
                    for out in self.generate_batch(buf):
                        yield out
                    buf = []
        if buf:
            for out in self.generate_batch(buf):
                yield out

    def process(self, lines):
        """Protocol lines (with trailing newline) for raw `lines`."""
        for sample in self._iter_samples(lines):
            yield self._convert_to_line(sample)

    # -- runners ---------------------------------------------------------
    def run_from_stdin(self, stdin=None, stdout=None):
        """The pipe_command entry point: raw lines on stdin, protocol
        lines on stdout."""
        stdin = stdin if stdin is not None else sys.stdin
        stdout = stdout if stdout is not None else sys.stdout
        for out in self.process(stdin):
            stdout.write(out)

    def run_from_files(self, filelist, out_dir, suffix=".slot"):
        """Materialize protocol files; returns the written paths (feed
        them to ``dataset.set_filelist``)."""
        os.makedirs(out_dir, exist_ok=True)
        written = []
        for path in filelist:
            out_path = os.path.join(
                out_dir, os.path.basename(path) + suffix)
            with open(path) as fin, open(out_path, "w") as fout:
                for out in self.process(fin):
                    fout.write(out)
            written.append(out_path)
        return written


def _fmt(v):
    """Ints stay ints (id slots are parsed as int64); floats use repr
    (round-trips float32 text exactly enough for the engine's parse)."""
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


class MultiSlotDataGenerator(DataGenerator):
    """The MultiSlot text schema writer: per sample, for every slot,
    ``<count> v...`` — exactly what `fluid.dataset`'s engine parses."""

    def _convert_to_line(self, sample):
        parts = []
        for name, values in sample:
            try:
                vals = list(values)
            except TypeError:
                vals = [values]
            if not vals:
                raise ValueError(
                    "slot %r produced zero values — the MultiSlot "
                    "protocol needs at least one value per slot per "
                    "sample" % (name,))
            parts.append(str(len(vals)))
            parts.extend(_fmt(v) for v in vals)
        return " ".join(parts) + "\n"
