"""The fault-injection primitives behind `paddle_tpu.incubate.fault`.

Design rules:

  * deterministic — every fault fires at a declared (rank, step) or a
    declared occurrence count, never at random, so a failing drill
    reproduces byte-for-byte;
  * side-channel free — the plan serializes to JSON and rides the
    $PADDLE_TPU_FAULT_PLAN environment variable into drill workers;
  * injection points are the REAL seams: the `fluid.fs` FS object the
    CheckpointSaver writes through (transient errors, mid-commit
    crashes), the heartbeat update loop (stale heartbeats), and the
    training step (rank kills via real SIGKILL).
"""

from __future__ import annotations

import errno
import json
import os
import signal
import time

from ...fluid.fs import LocalFS

FAULT_PLAN_ENV = "PADDLE_TPU_FAULT_PLAN"

__all__ = ["FaultPlan", "FaultyFS", "HeartbeatStaller",
           "transient_os_error", "FAULT_PLAN_ENV"]


def transient_os_error(op=""):
    """The canonical injectable transient failure: EIO, the error a
    flaky NFS/FUSE mount surfaces."""
    return OSError(errno.EIO, "injected transient I/O failure", op)


class FaultPlan:
    """A declarative schedule of faults for one drill.

    Event kinds (all fields integers unless noted):

      {"kind": "kill", "rank": r, "step": s}
          rank r SIGKILLs itself before running global step s.
      {"kind": "stall_heartbeat", "rank": r, "step": s}
          rank r stops pinging its heartbeat from step s on (process
          stays alive and keeps training — the silent-rank case).
      {"kind": "hang", "rank": r, "step": s}
          rank r stops heartbeating AND stops making progress at step s
          (process alive, sleeping, shrugging off SIGTERM — the
          hung-rank case only the watchdog can see and only SIGKILL
          can clear).
      {"kind": "fs_error", "rank": r, "op": "mv", "times": k}
          the first k calls of FS op (serialize/commit seam) on rank r
          raise a transient OSError(EIO).
      {"kind": "fs_error", ..., "fatal": true}
          same, but a NON-transient error (PermissionError) — must NOT
          be retried.
      {"kind": "fs_slow", "rank": r, "seconds": 0.5}
          every intercepted FS op on rank r stalls `seconds` (float) —
          the slow-NFS case async saves must ride out off the train
          step.
      {"kind": "crash", "rank": r, "op": "mv", "nth": i}
          rank r dies by SIGKILL inside the i-th call of FS op — with
          op "mv" that is the mid-commit crash (tmp dir fully written,
          rename never happens).
      {"kind": "kill_replica", "replica": i, "request": n}
          serving drill: replica i of a `paddle_tpu.serving` fleet dies
          while serving its n-th request (1-based).  Process-level
          workers die by REAL SIGKILL mid-request
          (`maybe_kill_replica`); in-process replicas surface the same
          schedule as a `ReplicaDeadError` (`replica_kill_request`) —
          either way the router must detect the death and re-queue the
          in-flight group exactly once.  Replica events are addressed
          by replica INDEX, independent of this process's rank.
      {"kind": "stall_replica", "replica": i, "step": n,
       "seconds": 0.2}
          serving latency drill: generation replica i stalls ONCE for
          `seconds` (float) before decode step n (1-based) — the
          injected tail-latency event the SLO engine must catch (ITL
          alert fires) and clear once clean traffic resumes
          (`replica_stall`).
      {"kind": "lock_delay", "rank": r, "lock": "serving.router.cond",
       "seconds": 0.05, "times": k}
          concurrency drill: the named registry lock
          (`observability.locks`) sleeps `seconds` right after each of
          its next `k` acquisitions on rank r — deterministically
          widening a race window so ordering bugs that need an unlucky
          interleaving reproduce every run (`arm_lock_delays`).  The
          injected sleep bypasses the sanitizer's blocking-under-lock
          check: the delay is the drill, not a finding.

    Every event also takes `"gen": g` (default 0): it fires only in
    that elastic generation, so a drill's fault does not re-fire in
    every recovered group.
    """

    def __init__(self, events=None, rank=None, generation=None):
        self.events = [dict(e) for e in (events or [])]
        if rank is None:
            rank = int(os.getenv("PADDLE_TRAINER_ID", "0"))
        if generation is None:
            generation = int(os.getenv("PADDLE_ELASTIC_GENERATION", "0"))
        self.rank = int(rank)
        self.generation = int(generation)

    # -- construction -----------------------------------------------------
    @classmethod
    def from_env(cls, rank=None, generation=None):
        raw = os.getenv(FAULT_PLAN_ENV, "")
        if not raw:
            return cls([], rank=rank, generation=generation)
        return cls(json.loads(raw), rank=rank, generation=generation)

    def to_env(self, env=None):
        """Serialize into an env dict for a drill worker subprocess."""
        env = dict(env if env is not None else {})
        env[FAULT_PLAN_ENV] = json.dumps(self.events)
        return env

    def add(self, kind, **fields):
        self.events.append({"kind": kind, **fields})
        return self

    def _mine(self, kind):
        """Events of `kind` addressed to this rank AND this elastic
        generation (default generation 0: a drill's fault fires in the
        faulted generation, not again in every recovered one)."""
        return [
            e for e in self.events
            if e.get("kind") == kind
            and int(e.get("rank", -1)) == self.rank
            and int(e.get("gen", 0)) == self.generation
        ]

    # -- step-seam faults -------------------------------------------------
    def maybe_kill(self, step):
        """Call at the top of every training step: dies by REAL SIGKILL
        (no atexit, no finally — the preemption model) when the plan
        says this (rank, step)."""
        for e in self._mine("kill"):
            if int(e.get("step", -1)) == int(step):
                os.kill(os.getpid(), signal.SIGKILL)

    def heartbeat_stall_step(self):
        """The step this rank's heartbeat goes silent at (None: never)."""
        ev = self._mine("stall_heartbeat")
        return int(ev[0]["step"]) if ev else None

    def maybe_hang(self, step, monitor=None):
        """Call per step: when the plan hangs this (rank, step), stop
        the heartbeat and sleep forever (only SIGKILL ends it)."""
        for e in self._mine("hang"):
            if int(step) >= int(e.get("step", -1)):
                if monitor is not None:
                    monitor.stop()
                while True:         # PEP 475: SIGTERM handlers that
                    time.sleep(3600)   # return do not break the sleep

    # -- serving-replica faults -------------------------------------------
    def replica_kill_request(self, replica_index):
        """The 1-based request count at which serving replica
        `replica_index` dies (None: never).  Addressed by replica index,
        NOT rank — one serving process hosts many replicas."""
        for e in self.events:
            if (e.get("kind") == "kill_replica"
                    and int(e.get("replica", -1)) == int(replica_index)
                    and int(e.get("gen", 0)) == self.generation):
                return int(e.get("request", 1))
        return None

    def maybe_kill_replica(self, replica_index, request_count):
        """Call per served request in a process-level serving worker:
        dies by REAL SIGKILL mid-request when the plan says so (the
        router sees a dead pipe, never a reply)."""
        n = self.replica_kill_request(replica_index)
        if n is not None and int(request_count) >= n:
            os.kill(os.getpid(), signal.SIGKILL)

    def replica_stall(self, replica_index):
        """The ``(decode_step, seconds)`` at which generation replica
        `replica_index` stalls once (None: never) — the injected-
        latency SLO drill (`serving.generation.GenerationReplica`
        sleeps in its step hook)."""
        for e in self.events:
            if (e.get("kind") == "stall_replica"
                    and int(e.get("replica", -1)) == int(replica_index)
                    and int(e.get("gen", 0)) == self.generation):
                return (int(e.get("step", 1)),
                        float(e.get("seconds", 0.1)))
        return None

    # -- lock-seam faults -------------------------------------------------
    def lock_delays(self):
        """This rank's ``lock_delay`` events, normalized for
        ``observability.locks.install_delays``."""
        return [
            {"lock": str(e.get("lock", "")),
             "seconds": float(e.get("seconds", 0.0)),
             "times": int(e.get("times", 1))}
            for e in self._mine("lock_delay")
        ]

    def arm_lock_delays(self, registry=None):
        """Arm this plan's ``lock_delay`` events on the named-lock
        registry (the process-wide default unless given).  Returns the
        armed event count."""
        events = self.lock_delays()
        if events:
            from ...observability import locks

            (registry or locks.registry()).install_delays(events)
        return len(events)

    # -- FS-seam faults ---------------------------------------------------
    def wrap_fs(self, fs=None):
        """An FS object with this plan's fs_error/crash/fs_slow events
        armed (passthrough when the plan has none for this rank)."""
        fs_events = self._mine("fs_error") + self._mine("crash")
        slow = max((float(e.get("seconds", 0.0))
                    for e in self._mine("fs_slow")), default=0.0)
        base = fs or LocalFS()
        if not fs_events and not slow:
            return base
        return FaultyFS(base, fs_events, slow_s=slow)


class FaultyFS(LocalFS):
    """A LocalFS whose declared operations fail or crash on schedule.

    Subclasses LocalFS (not FS) on purpose: CheckpointSaver's
    `_is_local` check must keep routing through the local atomic-rename
    commit path — the faults land INSIDE that path, which is the code
    under test."""

    def __init__(self, base=None, events=(), slow_s=0.0):
        self._base = base or LocalFS()
        self._events = [dict(e) for e in events]
        self._counts = {}
        self._slow_s = float(slow_s)

    def _intercept(self, op):
        self._counts[op] = n = self._counts.get(op, 0) + 1
        if self._slow_s:
            time.sleep(self._slow_s)
        for e in self._events:
            if e.get("op") != op:
                continue
            if e.get("kind") == "crash":
                if n == int(e.get("nth", 1)):
                    os.kill(os.getpid(), signal.SIGKILL)
            elif e.get("kind") == "fs_error":
                if n <= int(e.get("times", 1)):
                    if e.get("fatal"):
                        raise PermissionError(
                            errno.EACCES, "injected non-transient failure",
                            op)
                    raise transient_os_error(op)

    def calls(self, op):
        """How many times `op` was attempted (retry assertions)."""
        return self._counts.get(op, 0)

    # intercepted ops: the serialize/commit seams CheckpointSaver uses
    def mkdirs(self, path):
        self._intercept("mkdirs")
        return self._base.mkdirs(path)

    def mv(self, src, dst):
        self._intercept("mv")
        return self._base.mv(src, dst)

    def delete(self, path):
        self._intercept("delete")
        return self._base.delete(path)

    def touch(self, path):
        self._intercept("touch")
        return self._base.touch(path)

    # passthrough reads
    def ls_dir(self, path):
        return self._base.ls_dir(path)

    def is_dir(self, path):
        return self._base.is_dir(path)

    def is_file(self, path):
        return self._base.is_file(path)

    def is_exist(self, path):
        return self._base.is_exist(path)

    def upload(self, local_path, fs_path):
        self._intercept("upload")
        return self._base.upload(local_path, fs_path)

    def download(self, fs_path, local_path):
        return self._base.download(fs_path, local_path)


class HeartbeatStaller:
    """Freeze a rank's heartbeat from a declared step on.

    Wraps a HeartBeatMonitor: `step(global_step)` arms the stall when
    the plan's step is reached — the monitor's background ping loop is
    stopped, the file's mtime ages, and the watchdog sees LOST while the
    process itself keeps computing (the hung-rank failure mode)."""

    def __init__(self, monitor, stall_step):
        self._monitor = monitor
        self._stall_step = stall_step
        self.stalled = False

    def step(self, global_step):
        if (not self.stalled and self._stall_step is not None
                and int(global_step) >= int(self._stall_step)):
            self._monitor.stop()
            self.stalled = True
        return self.stalled
