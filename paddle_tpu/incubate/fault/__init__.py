"""Deterministic fault injection for elasticity drills.

Every recovery path in `distributed.elastic` is exercised by a test
that INJECTS the fault rather than asserting the behavior in prose:
rank kills, slow/failing filesystems, stale heartbeats, and mid-commit
crashes, all driven by one declarative `FaultPlan` that serializes
through an environment variable so subprocess drill workers replay the
exact same schedule every run.
"""

from .injection import (  # noqa: F401
    FAULT_PLAN_ENV,
    FaultPlan,
    FaultyFS,
    HeartbeatStaller,
    transient_os_error,
)
