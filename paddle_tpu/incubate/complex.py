"""Complex-tensor namespace on native JAX complex dtypes.

Capability parity: reference `python/paddle/incubate/complex/`
(`tensor/math.py` kron/matmul/elementwise ops, `tensor/manipulation.py`
reshape/transpose, `helper.py`) — there a ComplexVariable pairs two real
tensors because the framework has no complex dtype; here XLA has native
complex64/complex128, so each wrapper is the plain jnp op with the
reference's calling convention (transpose_x/transpose_y on matmul,
perm-list transpose) and VarBase in/out so dygraph code composes.

All functions accept dygraph VarBase, numpy, or jax arrays; the result
is a VarBase when any input was one (eager idiom preserved), else a jax
array.  Real inputs are accepted everywhere — mixing real and complex
operands promotes like numpy.  complex128 keeps full precision only
under ``JAX_ENABLE_X64`` (otherwise jax canonicalizes it to complex64,
its standard dtype policy).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = [
    "elementwise_add",
    "elementwise_div",
    "elementwise_mul",
    "elementwise_sub",
    "is_complex",
    "kron",
    "matmul",
    "reshape",
    "transpose",
]


def _unwrap(x):
    """(array, was_varbase) for VarBase / numpy / jax inputs."""
    from ..fluid.dygraph.varbase import VarBase

    if isinstance(x, VarBase):
        return jnp.asarray(x.data), True
    return jnp.asarray(x), False


def _wrap(val, wrapped):
    if not wrapped:
        return val
    from ..fluid.dygraph.varbase import VarBase

    return VarBase(val)


def is_complex(x):
    """True when `x` holds a complex dtype (complex64/complex128)."""
    arr, _ = _unwrap(x)
    return jnp.issubdtype(arr.dtype, jnp.complexfloating)


def _binary(x, y, fn):
    ax, wx = _unwrap(x)
    ay, wy = _unwrap(y)
    return _wrap(fn(ax, ay), wx or wy)


def elementwise_add(x, y):
    """Complex elementwise add (cf. incubate/complex/tensor/math.py)."""
    return _binary(x, y, jnp.add)


def elementwise_sub(x, y):
    return _binary(x, y, jnp.subtract)


def elementwise_mul(x, y):
    return _binary(x, y, jnp.multiply)


def elementwise_div(x, y):
    return _binary(x, y, jnp.divide)


def matmul(x, y, transpose_x=False, transpose_y=False):
    """Complex matmul with the reference's transpose flags: operands
    with ndim > 1 transpose their last two axes first."""
    ax, wx = _unwrap(x)
    ay, wy = _unwrap(y)
    if transpose_x and ax.ndim > 1:
        ax = jnp.swapaxes(ax, -1, -2)
    if transpose_y and ay.ndim > 1:
        ay = jnp.swapaxes(ay, -1, -2)
    return _wrap(jnp.matmul(ax, ay), wx or wy)


def kron(x, y):
    """Kronecker product (cf. incubate/complex/tensor/math.py kron)."""
    return _binary(x, y, jnp.kron)


def reshape(x, shape):
    ax, wx = _unwrap(x)
    return _wrap(jnp.reshape(ax, tuple(shape)), wx)


def transpose(x, perm):
    """Axis permutation (the reference's perm-list convention; complex
    values move untouched — no conjugation)."""
    ax, wx = _unwrap(x)
    return _wrap(jnp.transpose(ax, tuple(perm)), wx)
