"""Auto-checkpoint: resume-transparent epoch loops keyed by program hash.

Capability parity: reference
`python/paddle/fluid/incubate/checkpoint/auto_checkpoint.py` —
`train_epoch_range` wraps the user's epoch loop; a restarted process
silently fast-forwards to the first epoch after the last COMMITTED
checkpoint of the same job (`_get_running_key` = hash of the program),
with the checkpoint dir coming from the environment so user code does
not change between a fresh run and a resume.

TPU-first deltas from the reference: saves are asynchronous by default
(`AsyncCheckpointSaver` — the train step never blocks on FS I/O), a
checkpoint is only trusted if its CRC manifest verifies (torn writes
from a preemption are skipped, falling back to the previous commit),
and multi-host runs barrier through `distributed/monitor.py` with only
rank 0 committing metadata.

Usage::

    exe.run(startup)
    for epoch in acp.train_epoch_range(30, checkpoint_dir=root):
        train_one_epoch(...)
    # SIGKILL any time; rerunning the same script resumes after the
    # last committed epoch.
"""

from __future__ import annotations

import os
import sys

from .checkpoint_saver import (
    AsyncCheckpointSaver,
    CheckpointSaver,
    SerializableBase,
    StateSnapshot,
    program_hash,
)

CHECKPOINT_DIR_ENV = "PADDLE_TPU_CHECKPOINT_DIR"

# reference parity: at most one acp range may be live at a time
# (g_train_epoch_range in the reference)
_g_train_epoch_range = None


class TrainEpochRange:
    """The resume-aware epoch iterator behind `train_epoch_range`."""

    def __init__(self, max_epoch_num, name=None, checkpoint_dir=None,
                 main_program=None, scope=None, fs=None,
                 save_checkpoint_inter=1, max_num_checkpoints=3,
                 async_save=True, trainer_id=None, num_trainers=None,
                 barrier=None, extra_serializables=None, data_loaders=None,
                 verbose=False, retry_attempts=0, retry_backoff_s=0.5,
                 fence=None):
        from ...fluid import framework
        from ...fluid.core.scope import global_scope

        self._max_epoch_num = int(max_epoch_num)
        self._program = main_program or framework.default_main_program()
        self._scope = scope or global_scope()
        self._inter = max(int(save_checkpoint_inter), 1)
        self._verbose = verbose
        self._hash = program_hash(self._program)
        self.name = name or "acp_%s" % self._hash[:16]

        root = checkpoint_dir or os.getenv(CHECKPOINT_DIR_ENV)
        if root is None:
            # no directory configured: plain range(), no checkpointing
            # (reference _can_auto_checkpoint degrades the same way)
            self._saver = None
            self._async = None
            self._start_epoch = 0
            self.restored_from = -1
            self.restored_step = None
            self.restored_no = None
            return

        trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0")
                         if trainer_id is None else trainer_id)
        num_trainers = int(os.getenv("PADDLE_TRAINERS_NUM", "1")
                           if num_trainers is None else num_trainers)
        if num_trainers > 1 and barrier is None:
            from ...distributed.monitor import BarrierMonitor

            barrier = BarrierMonitor(
                os.path.join(root, self.name), trainer_id, num_trainers)

        self._rank = trainer_id
        # dense program state is replicated across DP ranks: rank 0 alone
        # writes payload.npz (concurrent ranks writing one filename would
        # tear it); sharded extras (host-embedding tables etc.) carry
        # rank-distinct filenames and save on every rank
        self._snap = StateSnapshot.from_program(self._program, self._scope)
        extras = list(extra_serializables or [])
        # data loaders (paddle_tpu.io state_dict/load_state_dict contract)
        # ride as per-rank extras: the iteration cursor commits atomically
        # WITH the parameters, which is what makes mid-epoch resume exact.
        # Multiple loaders must advance epochs in lockstep (same batch
        # count): a shorter loader that already crossed into epoch e+1
        # when a mid-epoch save lands would be rewound by the caller's
        # set_epoch(e) on resume and replay its whole epoch
        if data_loaders is not None:
            from ...io.resumable import DataLoaderCheckpoint

            if not isinstance(data_loaders, (list, tuple)):
                data_loaders = [data_loaders]
            for i, dl in enumerate(data_loaders):
                if isinstance(dl, DataLoaderCheckpoint):
                    extras.append(dl)
                else:
                    extras.append(DataLoaderCheckpoint(
                        dl, name="dataloader%d" % i, trainer_id=trainer_id))
        self._serializables = [self._snap] + extras
        self._save_serializables = (
            self._serializables if trainer_id == 0 else extras)
        self._nranks = num_trainers
        self._saver = CheckpointSaver(
            root=os.path.join(root, self.name), fs=fs,
            max_num_checkpoints=max_num_checkpoints,
            trainer_id=trainer_id, num_trainers=num_trainers,
            barrier=barrier, retry_attempts=retry_attempts,
            retry_backoff_s=retry_backoff_s, fence=fence)
        self._async = AsyncCheckpointSaver(self._saver) if async_save \
            else None
        self._restore()

    # -- resume ----------------------------------------------------------
    def _restore(self):
        skipped = []
        meta = self._saver.load_checkpoint(
            self._serializables, expect_program_hash=self._hash,
            on_skip=lambda n, why: skipped.append((n, why)))
        for n, why in skipped:
            print("auto_checkpoint[%s]: skipping checkpoint_%d (%s)"
                  % (self.name, n, why), file=sys.stderr)
        if meta is None:
            self._start_epoch = 0
            self.restored_from = -1
            self.restored_step = None
            self.restored_no = None
            return
        self._serializables[0].restore_to_scope(self._scope)
        self.restored_from = int(meta.get("epoch", -1))
        self.restored_step = meta.get("step")
        self.restored_no = meta.get("no")
        if self.restored_step is not None:
            # mid-epoch checkpoint (saved via save_checkpoint(epoch, step)
            # with a data loader attached): RE-ENTER the same epoch — the
            # restored loader cursor positions iteration at the first
            # unconsumed batch, so the epoch's remainder (and nothing
            # else) gets trained.  Exception: a save landing exactly on
            # the epoch's last batch restores a cursor already in the
            # NEXT epoch; re-entering would retrain nothing but a
            # set_epoch(e) call could rewind it — skip ahead instead.
            loader_epochs = [
                w.restored_epoch() for w in self._serializables
                if hasattr(w, "restored_epoch")
            ]
            loader_epochs = [e for e in loader_epochs if e is not None]
            if not loader_epochs:
                # no loader cursor restored (none attached, or the
                # checkpoint predates attachment): re-entering the epoch
                # would retrain batches 0..step — skip to the next epoch
                # instead (the pre-loader semantics)
                self._start_epoch = self.restored_from + 1
            elif min(loader_epochs) > self.restored_from:
                self._start_epoch = self.restored_from + 1
            else:
                self._start_epoch = self.restored_from
        else:
            self._start_epoch = self.restored_from + 1
        if self._verbose:
            print("auto_checkpoint[%s]: resumed after epoch %d%s"
                  % (self.name, self.restored_from,
                     "" if self.restored_step is None
                     else " step %s (mid-epoch)" % self.restored_step),
                  file=sys.stderr)

    @property
    def start_epoch(self):
        return self._start_epoch

    @property
    def step_timer(self):
        """Lazily-created `observability.StepTimer` (loop="acp") for the
        user's inner loop::

            for epoch in r:
                for batch in loader:
                    with r.step_timer.step():
                        exe.run(...)   # compile/compute split recorded

        Epoch-level histograms (`train_epoch_ms{loop="acp"}`) are always
        on; this adds the per-step breakdown when the inner loop opts
        in."""
        if getattr(self, "_step_timer", None) is None:
            from ...observability import StepTimer

            self._step_timer = StepTimer(name="acp")
        return self._step_timer

    # -- save ------------------------------------------------------------
    def save_checkpoint(self, epoch, step=None):
        extra = {"program_hash": self._hash, "name": self.name}
        # the topology manifest makes elastic resharding deterministic:
        # record how this group partitioned every rank-dependent layout
        try:
            from ...distributed.elastic.manifest import TopologyManifest

            extra.update(TopologyManifest.from_serializables(
                getattr(self, "_nranks", 1) or 1,
                self._serializables,
                generation=int(os.getenv("PADDLE_ELASTIC_GENERATION", "0")),
            ).to_meta())
        except Exception:
            pass   # manifest is advisory; a save must never fail on it
        if self._async is not None:
            return self._async.save_async(
                self._save_serializables, epoch=epoch, step=step,
                extra_meta=extra)
        return self._saver.save_checkpoint(
            self._save_serializables, epoch=epoch, step=step,
            extra_meta=extra)

    def wait(self):
        """Barrier on the in-flight async save (re-raises its error)."""
        if self._async is not None:
            return self._async.wait()

    # -- the loop --------------------------------------------------------
    def get(self):
        import time

        from ...observability.metrics import default_registry

        reg = default_registry()
        h_epoch = reg.histogram(
            "train_epoch_ms", "Wall time of one training epoch (ms)",
            labelnames=("loop",)).labels("acp")
        g_epoch = reg.gauge(
            "train_epoch", "Current epoch of the acp training loop",
            labelnames=("loop",)).labels("acp")
        from ...observability import trace as _trace

        tracer = _trace.default_tracer()
        global _g_train_epoch_range
        _g_train_epoch_range = self
        try:
            for epoch in range(self._start_epoch, self._max_epoch_num):
                g_epoch.set(epoch)
                t0 = time.perf_counter()
                yield epoch
                t1 = time.perf_counter()
                h_epoch.observe((t1 - t0) * 1e3)
                if tracer.enabled:
                    tracer.complete("epoch", t0, t1, cat="train",
                                    args={"loop": "acp", "epoch": epoch})
                if self._saver is not None and (
                        epoch % self._inter == self._inter - 1
                        or epoch == self._max_epoch_num - 1):
                    self.save_checkpoint(epoch)
                    if tracer.enabled:
                        tracer.instant(
                            "checkpoint_saved", cat="checkpoint",
                            args={"epoch": epoch, "loop": "acp"})
        finally:
            _g_train_epoch_range = None
            # drain the in-flight save on EVERY exit (normal end, break,
            # exception): the last issued checkpoint must be durable and
            # a background save failure must never be swallowed
            self.wait()

    def __iter__(self):
        return self.get()


def train_epoch_range(max_epoch_num, save_checkpoint_inter=1, **kw):
    """Reference-parity entry point: iterate epochs with transparent
    checkpoint/resume.  `checkpoint_dir` (or $PADDLE_TPU_CHECKPOINT_DIR)
    enables persistence; without it this is a plain range."""
    r = TrainEpochRange(
        max_epoch_num, save_checkpoint_inter=save_checkpoint_inter, **kw)
    return r.get()


def current_train_epoch_range():
    """The live TrainEpochRange, if an acp loop is running (reference
    g_train_epoch_range accessor)."""
    return _g_train_epoch_range
