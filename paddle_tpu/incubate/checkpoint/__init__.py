"""Fault-tolerant checkpointing (cf. reference
`python/paddle/fluid/incubate/checkpoint/` — `auto_checkpoint.py`,
`checkpoint_saver.py`).

One engine serves every training style in the framework:

* `CheckpointSaver` — write-to-tmp + atomic-rename commits over the
  `fluid/fs.py` FS abstraction, per-checkpoint metadata (epoch/step,
  program hash, payload CRC32), retention and GC of stale/partial dirs;
* `AsyncCheckpointSaver` — the save off the critical path: the
  device->host snapshot is synchronous (cheap), serialization + FS I/O
  run in a background thread with at most one save in flight;
* `train_epoch_range` / `TrainEpochRange` — auto-checkpoint keyed by
  the program hash, so a restarted run silently resumes from the last
  *committed* checkpoint and corrupted/partial checkpoints are skipped.
"""

from .auto_checkpoint import (  # noqa: F401
    CHECKPOINT_DIR_ENV,
    TrainEpochRange,
    current_train_epoch_range,
    train_epoch_range,
)
from .checkpoint_saver import (  # noqa: F401
    AsyncCheckpointSaver,
    CheckpointLoadError,
    CheckpointSaveError,
    CheckpointSaver,
    HostEmbeddingCheckpoint,
    PaddleModel,
    SerializableBase,
    StateSnapshot,
    program_hash,
)

__all__ = [
    "AsyncCheckpointSaver",
    "CheckpointLoadError",
    "CheckpointSaveError",
    "CheckpointSaver",
    "HostEmbeddingCheckpoint",
    "PaddleModel",
    "SerializableBase",
    "StateSnapshot",
    "TrainEpochRange",
    "current_train_epoch_range",
    "program_hash",
    "train_epoch_range",
]
