"""Crash-safe checkpoint engine: atomic commits, CRC integrity, async I/O.

Capability parity: reference
`python/paddle/fluid/incubate/checkpoint/checkpoint_saver.py`
(`SerializableBase`, `PaddleModel`, `CheckpointSaver` over the fleet FS
clients — numbered dirs, `_serial_to_path`, cache-then-upload for remote
FS) — extended with the crash-safety the reference leaves to HDFS
semantics: every save lands in a `.tmp` directory and becomes visible
only through one atomic rename, `meta.json` carries a CRC32 per payload
file so a torn write is detected and skipped at load time, and stale
tmp/corrupt directories are garbage-collected.

Async design (cf. Orbax async checkpointing; Check-N-Run, NSDI '22):
the device->host snapshot is taken synchronously on the training thread
(cheap — bytes already exist on host after fetch), then serialization +
FS writes run on a background thread with at most ONE save in flight.
Errors surface on the next `save_async`/`wait` — a checkpoint failure
must never be silent, but it also must not crash the train step that
happened to overlap it.

Multi-host discipline: every rank serializes its own shard files into
the shared tmp directory and drops a per-rank manifest; rank 0 merges
the manifests into `meta.json` and performs the commit rename; other
ranks wait on the barrier (`distributed/monitor.py` machinery) so no
rank can observe (or GC) a half-written checkpoint.
"""

from __future__ import annotations

import errno
import json
import os
import threading
import time
import uuid
import zlib

import numpy as np

from ...fluid.fs import LocalFS
from ...observability import locks as _locks

META_FILE = "meta.json"
_TMP_PREFIX = ".tmp_checkpoint_"
_ATTEMPT_PREFIX = ".attempt_"
_CKPT_PREFIX = "checkpoint_"


class CheckpointSaveError(RuntimeError):
    """A (possibly asynchronous) checkpoint save failed."""


class StaleGenerationError(CheckpointSaveError):
    """A rank from a superseded elastic generation tried to commit.

    Raised by a generation fence (distributed.elastic.GenerationFence)
    wired into the saver: once the controller bumps the generation, a
    straggler from the old group can serialize all it wants but can
    never make a checkpoint visible to the new group."""


class CheckpointLoadError(RuntimeError):
    """No loadable checkpoint: every candidate was corrupt/partial."""


_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name)
    for name in ("EIO", "EAGAIN", "EINTR", "EBUSY", "ESTALE", "ETIMEDOUT",
                 "ECONNRESET", "ECONNABORTED", "ENETDOWN", "ENETUNREACH",
                 "EREMOTEIO", "ENOBUFS")
    if hasattr(errno, name)
)


def default_is_transient(exc):
    """The retry policy's default verdict: I/O flakes a shared or
    network filesystem recovers from (EIO, timeouts, dropped
    connections) retry; everything else — including logic errors like
    FileExistsError/PermissionError and any StaleGenerationError —
    raises immediately."""
    if isinstance(exc, StaleGenerationError):
        return False
    if isinstance(exc, (TimeoutError, InterruptedError, ConnectionError)):
        return True
    return isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS


def program_hash(program):
    """Stable identity of a Program's structure (auto-checkpoint key —
    a restarted run only resumes from checkpoints of the SAME graph)."""
    import hashlib

    return hashlib.md5(program.to_json().encode("utf-8")).hexdigest()


def _crc_file(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            crc = zlib.crc32(b, crc)
    return crc & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# Serializables
# ---------------------------------------------------------------------------


class SerializableBase:
    """What CheckpointSaver saves/restores (reference parity interface).

    `snapshot()` runs synchronously on the caller's thread (device->host
    materialization); `serialize(path)` may run on a background thread
    and returns the list of file names it wrote under `path` (they enter
    the CRC manifest)."""

    def snapshot(self):
        pass

    def serialize(self, path):
        raise NotImplementedError

    def deserialize(self, path):
        raise NotImplementedError


class StateSnapshot(SerializableBase):
    """A name -> host-array dict captured at snapshot time.

    The constructor copies nothing; `snapshot()` materializes every
    value via np.asarray (device->host), so an in-flight async write
    never races the training step mutating the scope."""

    def __init__(self, values=None, filename="payload.npz"):
        self._source = values or {}
        self.arrays = None
        self.filename = filename

    @classmethod
    def from_scope(cls, scope, names=None, filename="payload.npz"):
        names = list(names) if names is not None else scope.local_names()
        snap = cls({}, filename=filename)
        snap._scope = scope
        snap._names = names
        return snap

    @classmethod
    def from_program(cls, program, scope, filename="payload.npz"):
        names = [
            v.name for v in program.list_vars()
            if v.persistable and not v.is_data and scope.has(v.name)
        ]
        return cls.from_scope(scope, names, filename=filename)

    def snapshot(self):
        src = self._source
        if getattr(self, "_scope", None) is not None:
            src = {
                n: self._scope.find_var(n)
                for n in self._names
                if self._scope.has(n)
            }
        self.arrays = {n: np.asarray(v) for n, v in src.items()}

    def serialize(self, path):
        if self.arrays is None:
            self.snapshot()
        np.savez(os.path.join(path, self.filename), **self.arrays)
        return [self.filename]

    def deserialize(self, path):
        with np.load(os.path.join(path, self.filename),
                     allow_pickle=False) as data:
            self.arrays = {n: data[n] for n in data.files}
        return self.arrays

    def restore_to_scope(self, scope, device_put=True):
        put = _device_put if device_put else (lambda a: a)
        for n, a in (self.arrays or {}).items():
            scope.set(n, put(a))


def _device_put(arr):
    import jax

    return jax.device_put(arr)


class PaddleModel(SerializableBase):
    """The persistables of a static program (reference parity class)."""

    def __init__(self, exe, program, scope=None):
        from ...fluid.core.scope import global_scope

        self._exe = exe
        self._program = program
        self._scope = scope or global_scope()
        self._snap = StateSnapshot.from_program(program, self._scope,
                                                filename="params.npz")

    def snapshot(self):
        self._snap.snapshot()

    def serialize(self, path):
        return self._snap.serialize(path)

    def deserialize(self, path):
        self._snap.deserialize(path)
        self._snap.restore_to_scope(self._scope)


class HostEmbeddingCheckpoint(SerializableBase):
    """Host-resident embedding tables save SHARDED: each rank persists
    only the rows it owns (`hostemb_<table>_rank<r>.npz`), the exact
    layout `fluid/host_embedding.py` keeps them in — no gather, no
    table-sized network traffic (the pslib sparse-table save model)."""

    def __init__(self, tables, trainer_id=0):
        # tables: iterable of HostEmbedding (or program._host_embeddings
        # mapping name -> (table, ids_slot))
        if isinstance(tables, dict):
            tables = [t if not isinstance(t, tuple) else t[0]
                      for t in tables.values()]
        self._tables = list(tables)
        self._rank = int(trainer_id)

    def snapshot(self):
        # a hot-row device cache holds the newest values for cached
        # rows; flush so _rows is the full truth before copying
        for t in self._tables:
            flush = getattr(t, "flush_cache", None)
            if flush is not None:
                flush()
        # rows live on host already; copy so the optimizer's in-place
        # push during an async write can't tear the payload
        self._shards = [
            (t, t._rows.copy(),
             getattr(t, "_accum", np.zeros(0)).copy())
            for t in self._tables
        ]

    def _fname(self, table):
        return "hostemb_%s_rank%d.npz" % (table.name, self._rank)

    def serialize(self, path):
        if not hasattr(self, "_shards"):
            self.snapshot()
        names = []
        for t, rows, accum in self._shards:
            fname = self._fname(t)
            np.savez(os.path.join(path, fname), rows=rows, accum=accum,
                     meta=np.asarray([t.num_rows, t.dim, self._rank,
                                      t.nproc]))
            names.append(fname)
        return names

    def layout(self):
        """Manifest fragment describing this save's table layout."""
        return {
            t.name: {"num_rows": t.num_rows, "dim": t.dim,
                     "nranks": t.nproc}
            for t in self._tables
        }

    def deserialize(self, path):
        import sys as _sys

        from ...distributed.elastic.reshard import rank_shard_paths

        for t in self._tables:
            own = os.path.join(path, self._fname(t))
            saved_nproc = None
            if os.path.exists(own):
                with np.load(own) as d:
                    if "meta" in d.files:
                        saved_nproc = int(d["meta"][3])
            if saved_nproc in (None, t.nproc) and os.path.exists(own):
                t.load(own)
                continue
            # world size changed (or this rank is new): gather the old
            # group's complete shard set and re-slice the row layout
            shard_paths = rank_shard_paths(path, "hostemb", t.name)
            if not shard_paths:
                raise CheckpointLoadError(
                    "checkpoint carries no shards for host-embedding "
                    "table %r" % t.name)
            print(
                "HostEmbeddingCheckpoint[%s]: resharding %d-rank shards "
                "for nproc=%d" % (t.name, len(shard_paths), t.nproc),
                file=_sys.stderr)
            t.load_resharded(shard_paths)


# ---------------------------------------------------------------------------
# The saver
# ---------------------------------------------------------------------------


class CheckpointSaver:
    """Numbered atomic checkpoints under one root directory.

    Layout::

        root/checkpoint_<n>/          committed (rename is the commit)
            meta.json                 {"no", "epoch", "step",
                                       "program_hash", "files": {..crc..}}
            <payload files>
        root/.tmp_checkpoint_<n>.<token>/   in-progress (GC'd)

    `fs` is the fluid FS abstraction. A non-local FS (HDFSClient) gets
    the reference's cache-then-upload flow: serialize into
    `local_cache_path`, upload to a remote tmp dir, remote-rename to
    commit.
    """

    def __init__(self, root, fs=None, max_num_checkpoints=3,
                 trainer_id=0, num_trainers=1, barrier=None,
                 local_cache_path=None, retry_attempts=0,
                 retry_backoff_s=0.5, retry_max_backoff_s=8.0,
                 is_transient=None, fence=None):
        """`retry_attempts`: extra single-rank save attempts on TRANSIENT
        I/O failures (`is_transient`, default `default_is_transient`),
        with exponential backoff from `retry_backoff_s` capped at
        `retry_max_backoff_s`.  Each attempt starts a fresh tmp dir, so a
        commit stays all-or-nothing across retries.  Multi-rank saves
        are never retried here — re-issuing the collective save is the
        elastic controller's job (the barrier tokens scope each attempt).

        `fence`: an object whose `check()` raises StaleGenerationError
        when this process belongs to a superseded elastic generation;
        consulted at save start and again immediately before the commit
        rename, so a stale rank cannot publish into the new group."""
        self._fs = fs or LocalFS()
        self._root = root
        self._max_num = (int(max_num_checkpoints)
                         if max_num_checkpoints else 0)
        self._rank = int(trainer_id)
        self._nranks = int(num_trainers)
        self._barrier = barrier
        self._retry_attempts = max(int(retry_attempts), 0)
        self._retry_backoff_s = float(retry_backoff_s)
        self._retry_max_backoff_s = float(retry_max_backoff_s)
        self._is_transient = is_transient or default_is_transient
        self._fence = fence
        self._cache = local_cache_path or os.path.join(
            root if self._is_local else ".", ".checkpoint_cache")
        if self._nranks > 1 and barrier is None:
            raise ValueError(
                "multi-trainer CheckpointSaver needs a barrier (e.g. "
                "distributed.monitor.BarrierMonitor) so non-zero ranks "
                "wait for rank 0's commit")
        if self._nranks > 1 and not self._is_local:
            raise ValueError(
                "multi-trainer checkpointing requires a shared-mounted "
                "(LocalFS-addressable) root so every rank can write its "
                "shard into one tmp dir; mount the DFS locally or save "
                "per-rank roots")

    @property
    def _is_local(self):
        return isinstance(self._fs, LocalFS)

    # -- directory bookkeeping ------------------------------------------
    def _ckpt_dir(self, n):
        return os.path.join(self._root, _CKPT_PREFIX + "%d" % n)

    def _numbers(self):
        dirs, _files = self._fs.ls_dir(self._root)
        out = []
        for name in dirs:
            if name.startswith(_CKPT_PREFIX):
                tail = name[len(_CKPT_PREFIX):]
                if tail.isdigit():
                    out.append(int(tail))
        return sorted(out)

    def get_checkpoint_no(self):
        """Largest COMMITTED-and-valid checkpoint number, or -1."""
        for n in reversed(self._numbers()):
            if self._read_valid_meta(n) is not None:
                return n
        return -1

    def list_checkpoints(self):
        """[(n, meta)] for every committed checkpoint with readable
        meta, oldest first (payload CRCs are re-verified at load, not
        here — this is the fast listing the streaming delta-chain
        restore walks)."""
        out = []
        for n in self._numbers():
            meta = self._read_valid_meta(n)
            if meta is not None:
                out.append((n, meta))
        return out

    def last_checkpoint_dir_no(self):
        """Largest checkpoint_<n> dir present, valid or not (numbering
        must advance past a corrupt tail, never overwrite it)."""
        nums = self._numbers()
        return nums[-1] if nums else -1

    # -- integrity -------------------------------------------------------
    def _read_valid_meta(self, n, verify_payload=False):
        """meta dict if checkpoint n is committed and consistent, else
        None.  verify_payload=True re-CRCs every payload file (load
        path); False trusts the committed meta (fast listing path)."""
        d = self._ckpt_dir(n)
        meta_path = os.path.join(d, META_FILE)
        if not self._fs.is_exist(meta_path):
            return None
        try:
            if self._is_local:
                with open(meta_path) as f:
                    meta = json.load(f)
            else:
                tmp = os.path.join(self._cache, "meta_%d.json" % n)
                os.makedirs(self._cache, exist_ok=True)
                self._fs.download(meta_path, tmp)
                with open(tmp) as f:
                    meta = json.load(f)
        except (ValueError, OSError):
            return None
        if verify_payload and not self._verify_payload(d, meta):
            return None
        return meta

    def _verify_payload(self, d, meta):
        if not self._is_local:
            # remote payloads are verified after download, per file
            return True
        return self._verify_local_payload(d, meta)

    def _barrier_wait(self, tag):
        """BarrierMonitor ids are one-shot (markers persist).  Save tags
        are scoped by the per-attempt token (_agree_tmp_name), so a dead
        attempt's markers can never collide with or satisfy a live one;
        this wrapper is the backstop for the remaining self-collision
        (this rank's OWN marker surviving a failure whose withdraw
        didn't run): clear it and re-wait instead of wedging."""
        try:
            self._barrier.wait(tag)
        except ValueError:
            reset = getattr(self._barrier, "reset", None)
            if reset is None:
                raise
            reset(tag)
            self._barrier.wait(tag)

    def _agree_tmp_name(self, n, timeout_s=120.0, poll_s=0.05):
        """Rank 0 picks a fresh per-attempt token and publishes the tmp
        dir name through an atomically-renamed pointer file; other ranks
        poll it.  The token scopes the tmp dir AND the barrier tags to
        THIS attempt, so a dead attempt's leftover markers/fragments can
        never satisfy this attempt's barriers or enter its manifest
        merge — after a double crash the worst case is a loud barrier
        timeout (a rank that grabbed the stale pointer), never a
        silently mixed commit."""
        pointer = os.path.join(self._root, "%s%d.ptr" % (_ATTEMPT_PREFIX, n))
        if self._rank == 0:
            name = "%s%d.%s" % (_TMP_PREFIX, n, uuid.uuid4().hex[:8])
            self._fs.mkdirs(self._root)
            with open(pointer + ".w", "w") as f:
                f.write(name)
            os.replace(pointer + ".w", pointer)
            return name
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(pointer):
                with open(pointer) as f:
                    name = f.read().strip()
                if name:
                    return name
            time.sleep(poll_s)
        raise CheckpointSaveError(
            "rank %d: rank 0 never published an attempt token for "
            "checkpoint_%d (pointer %r)" % (self._rank, n, pointer))

    def _check_fence(self):
        if self._fence is not None:
            self._fence.check()

    # -- save ------------------------------------------------------------
    def save_checkpoint(self, slists, epoch=None, step=None,
                        extra_meta=None, no=None, snapshot=True):
        """Serialize `slists` into checkpoint_<n>; returns n.

        Atomicity: everything lands in a tmp dir; the rename to
        checkpoint_<n> is the commit point.  Multi-trainer: all ranks
        serialize, rank 0 merges manifests + commits, everyone barriers
        on both sides.  Single-rank transient I/O failures retry with
        backoff when `retry_attempts` is configured (each retry restarts
        from a fresh tmp dir — the snapshot is reused, so the retried
        commit is the SAME state, all-or-nothing)."""
        slists = list(slists)
        if snapshot:
            for s in slists:
                s.snapshot()
        attempts = self._retry_attempts if self._nranks == 1 else 0
        backoff = self._retry_backoff_s
        for attempt in range(attempts + 1):
            try:
                return self._save_attempt(slists, epoch=epoch, step=step,
                                          extra_meta=extra_meta, no=no)
            except BaseException as e:
                if attempt >= attempts or not self._is_transient(e):
                    raise
                try:
                    from ...observability.metrics import default_registry

                    default_registry().counter(
                        "checkpoint_save_retries_total",
                        "Checkpoint save attempts retried after a "
                        "transient I/O failure").inc()
                except Exception:
                    pass
                import sys as _sys

                print("CheckpointSaver: transient save failure (%r), "
                      "retry %d/%d in %.2fs"
                      % (e, attempt + 1, attempts, backoff),
                      file=_sys.stderr)
                time.sleep(backoff)
                backoff = min(backoff * 2, self._retry_max_backoff_s)

    def _save_attempt(self, slists, epoch=None, step=None, extra_meta=None,
                      no=None):
        t_save = time.perf_counter()
        commit_secs = None
        self._check_fence()
        n = (self.last_checkpoint_dir_no() + 1) if no is None else int(no)

        if self._nranks > 1:
            # the tmp dir must be AGREED across ranks yet UNIQUE per
            # attempt: rank 0 picks a fresh token and publishes it
            tmp_name = self._agree_tmp_name(n)
            token = tmp_name.rsplit(".", 1)[1]
        else:
            token = uuid.uuid4().hex[:8]
            tmp_name = "%s%d.%s" % (_TMP_PREFIX, n, token)

        if self._is_local:
            tmp = os.path.join(self._root, tmp_name)
            self._fs.mkdirs(tmp)
            write_dir = tmp
        else:
            os.makedirs(self._cache, exist_ok=True)
            write_dir = os.path.join(self._cache, tmp_name)
            os.makedirs(write_dir, exist_ok=True)

        manifest = {}
        committed = False
        try:
            for s in slists:
                for fname in s.serialize(write_dir):
                    full = os.path.join(write_dir, fname)
                    manifest[fname] = {
                        "crc32": _crc_file(full),
                        "size": os.path.getsize(full),
                    }
            if self._nranks > 1:
                # per-rank manifest fragment; rank 0 merges after the
                # serialization barrier
                frag = os.path.join(write_dir,
                                    "manifest_rank%d.json" % self._rank)
                with open(frag, "w") as f:
                    json.dump(manifest, f)
                self._barrier_wait("ckpt_ser_%d.%s" % (n, token))
                if self._rank != 0:
                    self._barrier_wait("ckpt_commit_%d.%s" % (n, token))
                    if not self._fs.is_exist(self._ckpt_dir(n)):
                        raise CheckpointSaveError(
                            "rank 0 released the commit barrier but "
                            "checkpoint_%d was not committed" % n)
                    committed = True
                    return n
                manifest = {}
                for r in range(self._nranks):
                    fp = os.path.join(write_dir, "manifest_rank%d.json" % r)
                    with open(fp) as f:
                        manifest.update(json.load(f))
                    os.remove(fp)

            meta = {
                "no": n,
                "epoch": epoch,
                "step": step,
                "time": time.time(),
                "files": manifest,
            }
            meta.update(extra_meta or {})
            with open(os.path.join(write_dir, META_FILE), "w") as f:
                json.dump(meta, f)

            final = self._ckpt_dir(n)
            t_commit = time.perf_counter()
            # last exit before the commit becomes visible: a rank from a
            # superseded elastic generation must not publish
            self._check_fence()
            # a committed checkpoint is immutable: shutil.move onto an
            # existing dir would NEST the tmp inside it and report
            # success while committing nothing
            if self._fs.is_exist(final):
                raise CheckpointSaveError(
                    "checkpoint_%d already exists under %r — refusing to "
                    "overwrite a committed checkpoint" % (n, self._root))
            if self._is_local:
                self._fs.mv(write_dir, final)        # THE commit
                committed = True
            else:
                remote_tmp = os.path.join(self._root, tmp_name)
                self._fs.mkdirs(self._root)
                self._fs.upload(write_dir, remote_tmp)
                self._fs.mv(remote_tmp, final)       # remote commit
                committed = True
                # cache cleanup AFTER the commit flag: a flaky delete
                # must not report (or retry-and-duplicate) a save whose
                # checkpoint is already durable
                try:
                    LocalFS().delete(write_dir)
                except OSError:
                    pass
            commit_secs = time.perf_counter() - t_commit
        except BaseException:
            # never leave a half-commit that a reader could mistake for
            # a checkpoint; tmp dirs are invisible to the load path by
            # name, but delete eagerly anyway
            if self._nranks <= 1:
                (LocalFS() if not self._is_local else self._fs).delete(
                    write_dir)
            raise
        finally:
            if self._nranks > 1:
                if committed and self._rank == 0:
                    self._barrier_wait("ckpt_commit_%d.%s" % (n, token))
                if not committed:
                    # a FAILED attempt withdraws its own barrier markers
                    # (the token already isolates attempts; this just
                    # keeps the barrier workspace from accumulating)...
                    reset = getattr(self._barrier, "reset", None)
                    if reset is not None:
                        reset("ckpt_ser_%d.%s" % (n, token))
                        reset("ckpt_commit_%d.%s" % (n, token))
                    # ...and rank 0 withdraws the attempt pointer so a
                    # retrying peer can't grab this dead attempt's token
                    # (it would time out loudly waiting for barriers no
                    # one serves)
                    if self._rank == 0:
                        self._fs.delete(os.path.join(
                            self._root,
                            "%s%d.ptr" % (_ATTEMPT_PREFIX, n)))
            # always-on checkpoint telemetry (observability registry):
            # save = serialize + barriers + commit end to end; commit =
            # the rename that makes the checkpoint durable (rank 0)
            try:
                from ...observability.metrics import default_registry

                reg = default_registry()
                reg.histogram(
                    "checkpoint_save_ms",
                    "CheckpointSaver.save_checkpoint wall time (ms)"
                ).observe((time.perf_counter() - t_save) * 1e3)
                if committed:
                    reg.counter("checkpoint_saves_total",
                                "Committed checkpoint saves").inc()
                    if commit_secs is not None:
                        reg.histogram(
                            "checkpoint_commit_ms",
                            "Atomic-rename commit wall time (ms)"
                        ).observe(commit_secs * 1e3)
                else:
                    reg.counter("checkpoint_save_failures_total",
                                "Failed checkpoint save attempts").inc()
            except Exception:
                pass  # telemetry must never break a save's error path

        # post-commit housekeeping is BEST-EFFORT: the checkpoint is
        # already durable, so a flaky delete must neither fail the save
        # nor (via the transient-retry loop above) re-run the attempt
        # and commit a duplicate checkpoint_<n+1>
        if self._rank == 0:
            try:
                if self._nranks > 1:
                    # every rank is past the commit barrier; the attempt
                    # pointer has served its purpose
                    self._fs.delete(os.path.join(
                        self._root, "%s%d.ptr" % (_ATTEMPT_PREFIX, n)))
                self.clean_redundant_checkpoints()
                self.gc_stale_tmp()
            except OSError as e:
                import sys as _sys

                print("CheckpointSaver: post-commit cleanup failed (%r); "
                      "checkpoint_%d is committed, cleanup will be "
                      "retried on the next save" % (e, n),
                      file=_sys.stderr)
        return n

    # -- load ------------------------------------------------------------
    def load_checkpoint(self, slists, no=None, expect_program_hash=None,
                        on_skip=None):
        """Deserialize the newest VALID checkpoint into `slists`.

        Walks checkpoint numbers newest-first; a checkpoint with a
        missing/torn meta, a CRC mismatch, or (when
        `expect_program_hash` is given) a different program hash is
        skipped — `on_skip(no, reason)` observes each skip.  Returns the
        meta dict, or None when the root holds no checkpoint at all.
        Raises CheckpointLoadError when checkpoints exist but ALL are
        unusable (silently training from scratch would be data loss).
        """
        nums = self._numbers() if no is None else [int(no)]
        any_seen = False
        for n in reversed(nums):
            any_seen = True
            meta = self._read_valid_meta(n, verify_payload=True)
            if meta is None:
                if on_skip:
                    on_skip(n, "missing/corrupt meta or payload CRC "
                               "mismatch")
                continue
            if (expect_program_hash is not None
                    and meta.get("program_hash") not in (
                        None, expect_program_hash)):
                if on_skip:
                    on_skip(n, "program hash mismatch")
                continue
            d = self._ckpt_dir(n)
            if not self._is_local:
                local = os.path.join(self._cache, "restore_%d" % n)
                LocalFS().delete(local)
                self._fs.download(d, local)
                d = local
                if not self._verify_local_payload(d, meta):
                    if on_skip:
                        on_skip(n, "payload CRC mismatch after download")
                    continue
            for s in slists:
                s.deserialize(d)
            return meta
        if any_seen and nums:
            raise CheckpointLoadError(
                "checkpoints exist under %r but none is loadable "
                "(all corrupt/partial or wrong program)" % (self._root,))
        return None

    def _verify_local_payload(self, d, meta):
        for fname, rec in (meta.get("files") or {}).items():
            path = os.path.join(d, fname)
            if (not os.path.isfile(path)
                    or os.path.getsize(path) != rec.get("size", -1)
                    or _crc_file(path) != rec.get("crc32")):
                return False
        return True

    # -- retention & GC ---------------------------------------------------
    def delete_checkpoint(self, n):
        """Remove one committed checkpoint (the streaming delta-chain
        retention deletes whole superseded chains; the numeric GC below
        cannot know chain boundaries)."""
        self._fs.delete(self._ckpt_dir(int(n)))

    def clean_redundant_checkpoints(self, reserved_num=None):
        """Keep the newest `reserved_num` (default max_num_checkpoints)
        VALID checkpoints; also delete any committed-but-corrupt dirs
        older than the newest valid one (they can never be loaded)."""
        reserved = self._max_num if reserved_num is None else int(
            reserved_num)
        if reserved <= 0:
            return
        nums = self._numbers()
        valid = [n for n in nums if self._read_valid_meta(n) is not None]
        keep = set(valid[-reserved:])
        newest_valid = valid[-1] if valid else -1
        for n in nums:
            if n in keep:
                continue
            if n in valid or n < newest_valid:
                self._fs.delete(self._ckpt_dir(n))

    def gc_stale_tmp(self, min_age_s=3600.0):
        """Remove leftover `.tmp_checkpoint_*` dirs from crashed saves.

        Age-gated: a live save from another rank/process must not lose
        its tmp dir under it.  On a non-local FS the mtime is not
        observable, so nothing is deleted — remote leftovers are an
        operator cleanup, never an automated data-loss risk."""
        if not self._is_local:
            return
        dirs, files = self._fs.ls_dir(self._root)
        now = time.time()
        stale_tmp = [d for d in dirs if d.startswith(_TMP_PREFIX)]
        stale_ptr = [f for f in files if f.startswith(_ATTEMPT_PREFIX)]
        for name in stale_tmp + stale_ptr:
            path = os.path.join(self._root, name)
            try:
                age = now - os.path.getmtime(path)
            except OSError:
                continue
            if age > min_age_s:
                self._fs.delete(path)


# ---------------------------------------------------------------------------
# Async wrapper
# ---------------------------------------------------------------------------


class AsyncCheckpointSaver:
    """Keeps checkpoint I/O off the train step.

    `save_async` synchronously snapshots (device->host), then runs
    serialization + FS writes on a daemon thread.  At most one save is
    in flight: a second `save_async` first waits out the previous one.
    A background failure is re-raised (as CheckpointSaveError) from the
    NEXT save_async/wait call — never swallowed, never crashing the
    training thread mid-step.
    """

    def __init__(self, saver: CheckpointSaver):
        self.saver = saver
        self._thread = None
        self._error = None
        self._last_no = None
        self._lock = _locks.named_lock("checkpoint.async_state")

    @property
    def in_flight(self):
        t = self._thread
        return t is not None and t.is_alive()

    def save_async(self, slists, epoch=None, step=None, extra_meta=None):
        """Snapshot now, write later; returns the checkpoint number the
        save WILL commit as."""
        from ...observability.metrics import default_registry

        reg = default_registry()
        self.wait()                      # one in flight; surfaces errors
        slists = list(slists)
        t_snap = time.perf_counter()
        for s in slists:
            s.snapshot()
        # the ONLY part of an async save the train step waits on: the
        # device->host state snapshot
        reg.histogram(
            "checkpoint_snapshot_ms",
            "Synchronous device->host snapshot time of an async save (ms)"
        ).observe((time.perf_counter() - t_snap) * 1e3)
        g_inflight = reg.gauge("checkpoint_save_in_flight",
                               "Background checkpoint saves running")
        no = self.saver.last_checkpoint_dir_no() + 1

        def run():
            try:
                self.saver.save_checkpoint(
                    slists, epoch=epoch, step=step, extra_meta=extra_meta,
                    no=no, snapshot=False)
            except BaseException as e:   # surfaced on next save/wait
                with self._lock:
                    self._error = e
            finally:
                g_inflight.dec()

        g_inflight.inc()
        self._thread = threading.Thread(
            target=run, name="ckpt-save-%s" % no, daemon=True)
        self._thread.start()
        self._last_no = no
        return no

    def wait(self):
        """Barrier: block until the in-flight save (if any) committed;
        re-raise any background failure."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise CheckpointSaveError(
                "asynchronous checkpoint save failed: %r" % (err,)
            ) from err
        return self._last_no
