"""KV caches: the decode step's working set, dense and PAGED.

`KVCache` (PR 15) is the dense layout — ``[L, slots, max_len, H, D]``
per array, every slot paying ``max_len`` HBM whether its sequence is 20
tokens or 2000.  PERF.md round 13 proved the decode step is KV-read
memory-bound, which makes those idle bytes the top perf lever left on
the table (ROADMAP item 1).

`PagedKVCache` rebuilds the store as a BLOCK POOL:

* device arrays ``[L, num_blocks, block_size, H, D]`` (k and v) — a
  fixed-shape pool every slot draws from, so the compiled decode
  executable never changes as blocks migrate between requests;
* a host-side per-slot block table ``[slots, max_blocks_per_slot]``
  int32 mapping logical block j to a physical pool block.  The table
  is passed to the jitted step as DATA;
* `BlockPool` — the refcounted allocator.  Block 0 is the reserved
  garbage block: inactive slots' table rows point at it, so the
  batched decode step's dead-row writes land somewhere nobody reads;
* `PrefixCache` — refcounted FULL-block reuse keyed by a token-chain
  hash (vLLM's prefix caching): two requests sharing a system prompt
  share the physical blocks, and the second skips that prefill
  entirely.  Only full blocks are ever shared, so the writable tail is
  always private and copy-on-write never arises;
* optional int8 storage (``kv_dtype="int8"``): pools hold int8 rows
  plus per-row per-head f32 scales — halving (vs f32: quartering) the
  KV bytes the memory-bound step streams, under the documented-
  tolerance opt-in policy (`PADDLE_TPU_FLASH_ACC` discipline).

Capacity math: dense charges ``slots * max_len`` rows; the pool charges
``num_blocks * block_size`` rows — provisioned to the MEAN sequence
length rather than the max (``analysis.perf.decode_step_cost`` prices
both).  When the pool runs dry the engine preempts, requeues, and
retries — admission is measured, not provisioned-for-worst-case.
"""

from __future__ import annotations

import hashlib
import heapq

import jax.numpy as jnp
import numpy as np

__all__ = ["BlockPool", "KVCache", "PagedKVCache", "PoolExhausted",
           "PrefixCache"]


class KVCache:
    """Dense host-side handle (see module doc) — the PR-15 layout, kept
    as the paged engine's A/B baseline and the draft model's cache."""

    def __init__(self, num_layers, slots, max_len, num_heads, head_dim,
                 dtype=jnp.float32):
        self.num_layers = int(num_layers)
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        shape = (self.num_layers, self.slots, self.max_len,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    @property
    def shape(self):
        return tuple(self.k.shape)

    @property
    def nbytes(self):
        return int(2 * np.prod(self.shape) * self.dtype.itemsize)

    def arrays(self):
        return self.k, self.v

    def update(self, k, v):
        """Adopt the arrays a donated prefill/decode call returned (the
        old handles are invalid once donated — never keep them)."""
        self.k, self.v = k, v

    def describe(self):
        return {
            "layers": self.num_layers, "slots": self.slots,
            "max_len": self.max_len, "heads": self.num_heads,
            "head_dim": self.head_dim, "dtype": str(self.dtype),
            "bytes": self.nbytes, "paged": False,
        }


class PoolExhausted(RuntimeError):
    """No free block — the engine's preempt/requeue trigger."""


class BlockPool:
    """Refcounted allocator over the pool's block axis (host-side).

    Deterministic: allocation always hands out the LOWEST free block id
    (a heap), so a fixed request schedule produces a fixed block
    layout — the exactness drills rely on nothing, but debuggability
    does.  Block 0 is reserved (the garbage block) and never leaves the
    pool."""

    def __init__(self, num_blocks):
        if num_blocks < 2:
            raise ValueError("pool needs >= 2 blocks (block 0 is "
                             "reserved), got %d" % num_blocks)
        self.num_blocks = int(num_blocks)
        self._ref = np.zeros(self.num_blocks, np.int32)
        self._ref[0] = 1                       # garbage block, pinned
        self._free = list(range(1, self.num_blocks))
        heapq.heapify(self._free)

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def used_blocks(self):
        return self.num_blocks - 1 - len(self._free)

    def alloc(self, n):
        """n fresh blocks (refcount 1 each) or `PoolExhausted` — the
        caller decides whether to evict, preempt, or shed."""
        if n > len(self._free):
            raise PoolExhausted(
                "need %d blocks, %d free of %d"
                % (n, len(self._free), self.num_blocks))
        ids = [heapq.heappop(self._free) for _ in range(n)]
        for b in ids:
            self._ref[b] = 1
        return ids

    def incref(self, ids):
        for b in ids:
            if self._ref[b] <= 0:
                raise ValueError("incref on free block %d" % b)
            self._ref[b] += 1

    def decref(self, ids):
        """Drop one reference per id; blocks hitting zero return to the
        free list.  Returns the freed ids (the leak drill's assert)."""
        freed = []
        for b in ids:
            if b == 0:
                raise ValueError("decref on the reserved garbage block")
            if self._ref[b] <= 0:
                raise ValueError("double free of block %d" % b)
            self._ref[b] -= 1
            if self._ref[b] == 0:
                heapq.heappush(self._free, b)
                freed.append(b)
        return freed

    def refcount(self, block_id):
        return int(self._ref[block_id])


class PrefixCache:
    """Refcounted full-block prefix reuse keyed by a token-chain hash.

    Key of block j = H(key_{j-1} || tokens of block j) — a chain, so a
    lookup walks the prompt's full blocks until the first miss and
    every hit is an EXACT token-prefix match (hash collisions aside;
    sha1 over the literal token bytes).  The registry holds one pool
    reference per cached block; each slot using a block holds another —
    a shared block frees only when the last user AND the registry let
    go.  Eviction is LRU over chains with no registry children and no
    outside users, triggered by allocation pressure."""

    def __init__(self, pool, block_size):
        self.pool = pool
        self.block_size = int(block_size)
        # key -> [block_id, parent_key, last_use, n_child]
        self._entries = {}
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.hit_tokens = 0

    def __len__(self):
        return len(self._entries)

    @staticmethod
    def _key(parent, tokens):
        h = hashlib.sha1()
        h.update(parent.encode() if parent else b"root")
        h.update(np.asarray(tokens, np.int64).tobytes())
        return h.hexdigest()

    def _chain_keys(self, prompt_ids, max_tokens):
        """Keys of the full blocks covering <= max_tokens prompt
        tokens, in order."""
        bs = self.block_size
        keys, parent = [], ""
        for j in range(max_tokens // bs):
            parent = self._key(parent, prompt_ids[j * bs:(j + 1) * bs])
            keys.append(parent)
        return keys

    def lookup(self, prompt_ids):
        """Longest cached prefix of ``prompt_ids``, capped one token
        short of the full prompt (a hit must still leave >= 1 token to
        prefill — its logits seed generation).  Returns
        ``(n_tokens, block_ids)`` with one pool reference taken per
        returned block (the caller's to decref on release)."""
        keys = self._chain_keys(prompt_ids, len(prompt_ids) - 1)
        blocks = []
        for key in keys:
            ent = self._entries.get(key)
            if ent is None:
                break
            self._clock += 1
            ent[2] = self._clock
            blocks.append(ent[0])
        if blocks:
            self.pool.incref(blocks)
            self.hits += 1
            self.hit_tokens += len(blocks) * self.block_size
        else:
            self.misses += 1
        return len(blocks) * self.block_size, blocks

    def register(self, prompt_ids, block_ids):
        """Publish a freshly prefilled prompt's FULL blocks.  The
        registry increfs what it adopts; already-registered prefixes
        (including the ones this request was served from) are left
        alone."""
        keys = self._chain_keys(prompt_ids, len(prompt_ids))
        parent = ""
        for j, key in enumerate(keys):
            if key not in self._entries:
                self._clock += 1
                self.pool.incref([block_ids[j]])
                self._entries[key] = [block_ids[j], parent,
                                      self._clock, 0]
                if parent:
                    self._entries[parent][3] += 1
            parent = key

    def evict(self, n_blocks_needed):
        """Free LRU chains (leaf-first, registry-only references) until
        ``n_blocks_needed`` blocks are free or nothing evictable is
        left.  Returns the number of blocks actually freed."""
        freed = 0
        while self.pool.free_blocks < n_blocks_needed:
            victims = [
                (ent[2], key) for key, ent in self._entries.items()
                if ent[3] == 0 and self.pool.refcount(ent[0]) == 1
            ]
            if not victims:
                break
            _, key = min(victims)
            ent = self._entries.pop(key)
            if ent[1]:
                self._entries[ent[1]][3] -= 1
            freed += len(self.pool.decref([ent[0]]))
        return freed

    def stats(self):
        total = self.hits + self.misses
        return {
            "entries": len(self._entries),
            "hits": self.hits, "misses": self.misses,
            "hit_rate": (self.hits / total) if total else 0.0,
            "hit_tokens": self.hit_tokens,
        }


class PagedKVCache:
    """Host-side handle of the paged device pool (see module doc).

    ``num_blocks`` INCLUDES block 0 (the reserved garbage block); the
    usable capacity is ``(num_blocks - 1) * block_size`` token rows."""

    def __init__(self, num_layers, num_blocks, block_size, num_heads,
                 head_dim, slots, max_len, dtype=jnp.float32,
                 kv_dtype=None):
        self.num_layers = int(num_layers)
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.max_blocks_per_slot = -(-self.max_len // self.block_size)
        self.dtype = jnp.dtype(dtype)
        if kv_dtype not in (None, "int8"):
            raise ValueError("kv_dtype must be None or 'int8', got %r"
                             % (kv_dtype,))
        self.kv_dtype = kv_dtype
        self.quantized = kv_dtype == "int8"
        store = jnp.int8 if self.quantized else self.dtype
        shape = (self.num_layers, self.num_blocks, self.block_size,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, store)
        self.v = jnp.zeros(shape, store)
        if self.quantized:
            sshape = shape[:-1]
            self.k_scale = jnp.zeros(sshape, jnp.float32)
            self.v_scale = jnp.zeros(sshape, jnp.float32)
        else:
            self.k_scale = self.v_scale = None
        self.pool = BlockPool(self.num_blocks)
        self.block_tables = np.zeros(
            (self.slots, self.max_blocks_per_slot), np.int32)

    @property
    def shape(self):
        return tuple(self.k.shape)

    @property
    def nbytes(self):
        store = jnp.int8 if self.quantized else self.dtype
        n = int(2 * np.prod(self.shape) * jnp.dtype(store).itemsize)
        if self.quantized:
            n += int(2 * np.prod(self.k_scale.shape) * 4)
        return n

    @property
    def capacity_tokens(self):
        return (self.num_blocks - 1) * self.block_size

    def arrays(self):
        """The donated operands, in the engine's argument order."""
        if self.quantized:
            return self.k, self.v, self.k_scale, self.v_scale
        return self.k, self.v

    def update(self, *arrays):
        """Adopt donated-call outputs (order of `arrays`)."""
        if self.quantized:
            self.k, self.v, self.k_scale, self.v_scale = arrays
        else:
            self.k, self.v = arrays

    # -- slot bookkeeping (host) ------------------------------------------
    def blocks_for(self, n_tokens):
        return -(-int(n_tokens) // self.block_size)

    def table_row(self, slot):
        return self.block_tables[slot]

    def assign(self, slot, logical_index, block_id):
        self.block_tables[slot, logical_index] = block_id

    def clear_slot(self, slot):
        """Zero the table row — every entry points back at the garbage
        block.  Reference bookkeeping is the ENGINE's job (it knows
        which entries were shared); this only kills the indirection."""
        self.block_tables[slot, :] = 0

    def describe(self):
        return {
            "layers": self.num_layers, "slots": self.slots,
            "max_len": self.max_len, "heads": self.num_heads,
            "head_dim": self.head_dim, "dtype": str(self.dtype),
            "bytes": self.nbytes, "paged": True,
            "block_size": self.block_size,
            "num_blocks": self.num_blocks,
            "capacity_tokens": self.capacity_tokens,
            "kv_dtype": self.kv_dtype or str(self.dtype),
            "blocks_used": self.pool.used_blocks,
            "blocks_free": self.pool.free_blocks,
        }
