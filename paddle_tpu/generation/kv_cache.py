"""Fixed-shape slot KV cache: the decode step's working set.

Two stacked device arrays, ``k``/``v`` of shape
``[layers, slots, max_len, heads, head_dim]`` (slot-major rows, BSHD
within a slot so prefill's flash K/V copy straight in), plus per-slot
length counters living HOST-side in the engine.  The shape never
changes — slot count and max_len are the engine's compile-time
identity — so the decode executable is built once and every step
after that is a cache-donated re-invocation: XLA writes the updated
cache into the same HBM buffers instead of allocating a second copy
of what is by far the largest inference allocation
(``2 * L * slots * T * H * D * itemsize`` bytes; see
``analysis.perf.decode_step_cost`` for what streaming it costs per
token).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

__all__ = ["KVCache"]


class KVCache:
    """Host-side handle of the device cache arrays (see module doc)."""

    def __init__(self, num_layers, slots, max_len, num_heads, head_dim,
                 dtype=jnp.float32):
        self.num_layers = int(num_layers)
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.num_heads = int(num_heads)
        self.head_dim = int(head_dim)
        self.dtype = jnp.dtype(dtype)
        shape = (self.num_layers, self.slots, self.max_len,
                 self.num_heads, self.head_dim)
        self.k = jnp.zeros(shape, self.dtype)
        self.v = jnp.zeros(shape, self.dtype)

    @property
    def shape(self):
        return tuple(self.k.shape)

    @property
    def nbytes(self):
        return int(2 * np.prod(self.shape) * self.dtype.itemsize)

    def arrays(self):
        return self.k, self.v

    def update(self, k, v):
        """Adopt the arrays a donated prefill/decode call returned (the
        old handles are invalid once donated — never keep them)."""
        self.k, self.v = k, v

    def describe(self):
        return {
            "layers": self.num_layers, "slots": self.slots,
            "max_len": self.max_len, "heads": self.num_heads,
            "head_dim": self.head_dim, "dtype": str(self.dtype),
            "bytes": self.nbytes,
        }
